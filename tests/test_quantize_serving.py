"""Quantized & mixed-precision serving (ISSUE 8 tentpole).

End-to-end coverage of the post-training-quantization serving path:

- offline archive quantization (per-channel int8 weights, calibrated input
  scales, sidecar dtype-policy manifest) and first-class restore through
  ``ModelSerializer.restore_model``;
- quantized archive load through ``ModelRegistry`` with the dtype policy's
  (bucket, replica, dtype) pairs pre-warmed — zero on-traffic compiles —
  and a manifest-prewarmed RESTART that stays compile-free and
  bit-identical;
- per-bucket dtype policy honored under concurrent mixed f32/int8 load
  (separate pad-buffer pools, separate AOT executables, quantized traffic
  counted and latency-split);
- the accuracy gate: a passing deploy hot-swaps in, a failing deploy
  raises and provably leaves the f32 version serving (the PR 2 rollback
  guarantee);
- the ``serving.quantize.calibrate`` chaos point: corrupt/truncated
  calibration data degrades to a REFUSED deploy (no archive, no policy),
  never a silently wrong scale;
- a fleet of workers all serving one quantized archive bit-identically
  through the router.

All tier-1 (CPU mesh, in-process workers).
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.models.serializer import ModelSerializer
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.runtime.chaos import (ChaosController, CorruptBytes,
                                              FailNth)
from deeplearning4j_tpu.serving import (FleetRouter, ModelRegistry,
                                        ModelServer, StaticFleet)
from deeplearning4j_tpu.serving.manifest import WarmupManifest
from deeplearning4j_tpu.serving.quantize import (AccuracyGate,
                                                 AccuracyGateFailed,
                                                 CalibrationError,
                                                 DtypePolicy, QuantizedModel,
                                                 calibrate_inputs,
                                                 policy_path,
                                                 quantize_archive,
                                                 quantize_requests)
from deeplearning4j_tpu.train import Sgd

RNG = np.random.default_rng(42)
X = RNG.normal(size=(16, 8)).astype(np.float32)
CALIB = RNG.normal(size=(64, 8)).astype(np.float32)
BATCHER_KW = dict(max_batch_size=4, buckets=[1, 4], batch_timeout_ms=1.0,
                  pipeline_depth=1)


def _conf(seed=7):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.feed_forward(8))
            .build())


@pytest.fixture(scope="module")
def archives(tmp_path_factory):
    """One f32 archive + its quantized twin (+ policy sidecar)."""
    td = tmp_path_factory.mktemp("quant")
    src, dst = str(td / "model.zip"), str(td / "model.int8.zip")
    net = MultiLayerNetwork(_conf()).init()
    net.save(src)
    policy, report = quantize_archive(src, dst, CALIB)
    return src, dst, policy, report


def _pad_rows(x, bucket):
    return np.concatenate(
        [x, np.zeros((bucket - x.shape[0],) + x.shape[1:], x.dtype)], axis=0)


# ========================================================== archive round trip
def test_quantize_archive_restore_and_report(archives):
    src, dst, policy, report = archives
    # sidecar policy written and loadable
    assert os.path.exists(policy_path(dst))
    side = DtypePolicy.load(policy_path(dst))
    assert side.label() == policy.label()
    assert side.inputs.keys() == policy.inputs.keys()
    # both dense kernels quantized, byte budget shrank
    assert report["weights_quantized"] == 2
    assert report["params_bytes_quantized"] < report["params_bytes_f32"]
    # restore dispatches to QuantizedModel via the standard entry point
    qm = ModelSerializer.restore_model(dst)
    assert isinstance(qm, QuantizedModel)
    # close to the f32 net on both request dtypes (NOT bit-equal — int8)
    f32 = MultiLayerNetwork.load(src, load_updater=False)
    ref = np.asarray(f32.output(X))
    assert np.abs(np.asarray(qm.output(X)) - ref).max() < 0.05
    qx = quantize_requests(X, policy)
    assert qx.dtype == np.int8
    assert np.abs(np.asarray(qm.output(qx)) - ref).max() < 0.05


def test_double_quantization_refused(archives):
    _, dst, _, _ = archives
    with pytest.raises(ValueError, match="already a quantized archive"):
        quantize_archive(dst, dst + ".again", CALIB)


# ============================================== registry load + restart replay
def test_quantized_load_and_manifest_prewarmed_restart(archives, tmp_path):
    _, dst, policy, _ = archives
    qx = quantize_requests(X, policy)
    reg = ModelRegistry()
    try:
        served = reg.load("q", dst, warmup_example=X[:1], **BATCHER_KW)
        assert served.batcher.dtype_policy is not None  # embedded policy won
        warmed = served.batcher.compile_count()
        # policy warms BOTH dtype worlds: buckets x replicas x 2
        assert warmed == 2 * len(served.batcher.buckets) \
            * served.batcher.replica_count
        out_q = np.asarray(reg.predict("q", qx[:3]))
        out_f = np.asarray(reg.predict("q", X[:3]))
        assert served.batcher.compile_count() == warmed, \
            "mixed f32/int8 traffic minted a compile after warmup"
        # the manifest records the int8 pairs and the policy
        man = served.batcher.warmup_manifest()
        assert {"float32", "int8"} <= {p[2] for p in man.pairs}
        assert man.policy is not None
        assert man.policy["inputs"].keys() == policy.inputs.keys()
    finally:
        reg.shutdown()  # graceful: persists the manifest next to dst
    assert WarmupManifest.load_for_archive(dst) is not None

    # restart: a fresh registry replays the manifest — READY without a
    # single on-traffic compile, bit-identical to the previous process
    reg2 = ModelRegistry()
    try:
        served2 = reg2.load("q", dst)
        ready = served2.batcher.compile_count()
        out_q2 = np.asarray(reg2.predict("q", qx[:3]))
        out_f2 = np.asarray(reg2.predict("q", X[:3]))
        assert served2.batcher.compile_count() == ready, \
            "restart minted a compile on live traffic"
        assert np.array_equal(out_q, out_q2)
        assert np.array_equal(out_f, out_f2)
    finally:
        reg2.shutdown()


def test_per_bucket_policy_restricts_prewarm(archives):
    """quantized_buckets=[4]: only bucket 4 is pre-warmed at int8; other
    buckets still SERVE quantized traffic (minting on first use)."""
    _, dst, _, _ = archives
    qm = ModelSerializer.restore_model(dst)
    qm.dtype_policy.quantized_buckets = [4]
    qx = quantize_requests(X, qm.dtype_policy)
    reg = ModelRegistry()
    try:
        served = reg.register("q", qm, warmup_example=X[:1], **BATCHER_KW)
        b = served.batcher
        warmed = b.compile_count()
        n_buckets, n_reps = len(b.buckets), b.replica_count
        assert warmed == (n_buckets + 1) * n_reps  # f32 all + int8 only @4
        int8_pairs = [p for p in b._warmed_pairs if p[2] == "int8"]
        assert {p[0] for p in int8_pairs} == {4}
        # a bucket-4 int8 request stays compile-free...
        np.asarray(reg.predict("q", qx[:3]))
        assert b.compile_count() == warmed
        # ...and a bucket-1 int8 request still serves (one minted compile)
        np.asarray(reg.predict("q", qx[:1]))
        assert b.compile_count() == warmed + 1
    finally:
        reg.shutdown()


# ==================================================== concurrent mixed load
def test_mixed_dtype_concurrent_load_bit_identical(archives):
    """8 threads of interleaved f32 and int8 traffic: every response is
    bit-identical to the model's own output at the padded bucket shape,
    no compile is minted after warmup (per-dtype executables + per-dtype
    pad-buffer pools), and the quantized share of traffic is counted."""
    _, dst, policy, _ = archives
    qm = ModelSerializer.restore_model(dst)
    qx_all = quantize_requests(X, policy)
    reg = ModelRegistry()
    try:
        served = reg.register("q", qm, warmup_example=X[:1], **BATCHER_KW)
        b = served.batcher
        warmed = b.compile_count()
        # per-bucket per-dtype references through the model's own trace
        refs = {}
        for n in (1, 2, 3):
            bucket = 1 if n <= 1 else 4
            refs[("f32", n)] = np.asarray(
                qm.output(_pad_rows(X[:n], bucket)))[:n]
            refs[("int8", n)] = np.asarray(
                qm.output(_pad_rows(qx_all[:n], bucket)))[:n]
        failures = []

        def client(tid):
            rng = np.random.default_rng(tid)
            for k in range(25):
                n = int(rng.integers(1, 4))
                quantized = bool((tid + k) % 2)
                x = qx_all[:n] if quantized else X[:n]
                out = np.asarray(reg.predict("q", x, timeout_ms=30000))
                ref = refs[("int8" if quantized else "f32", n)]
                if not np.array_equal(out, ref):
                    failures.append((tid, k, quantized, n))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures, f"non-bit-identical responses: {failures[:5]}"
        assert b.compile_count() == warmed, \
            "mixed-dtype load minted executables after warmup"
        snap = served.metrics.snapshot()
        assert snap["requests_total"] == 8 * 25
        assert snap["quantized_requests_total"] == 8 * 25 // 2
        assert snap["quant_responses"] + snap["float_responses"] \
            == snap["responses_total"]
        assert snap["dtype_policy"] == policy.label()
        # the profiler surfaces the same split
        from deeplearning4j_tpu.runtime import profiler
        split = profiler.quant_split_stats()["q"]
        assert split["quantized_requests_total"] == 8 * 25 // 2
        assert split["latency_quant_p50_s"] is not None
    finally:
        reg.shutdown()


# ======================================================== accuracy gate
def test_accuracy_gate_pass_deploys_quantized(archives):
    src, dst, _, _ = archives
    reg = ModelRegistry()
    try:
        reg.load("m", src, warmup_example=X[:1], **BATCHER_KW)
        served = reg.deploy_quantized("m", dst, eval_inputs=CALIB,
                                      **BATCHER_KW)
        assert served.version == 2
        assert isinstance(served.model, QuantizedModel)
        assert served.gate_report["passed"] is True
        assert served.gate_report["accuracy_delta"] \
            <= served.gate_report["max_delta"]
        # quantized traffic now serves
        qx = quantize_requests(X, served.model.dtype_policy)
        np.asarray(reg.predict("m", qx[:2]))
        assert served.metrics.snapshot()["quantized_requests_total"] == 1
    finally:
        reg.shutdown()


def test_accuracy_gate_fail_leaves_f32_serving(archives):
    """The rollback drill: a deploy that fails its gate raises BEFORE the
    hot-swap — same version keeps serving, outputs bit-identical to
    before, zero quantized requests ever counted."""
    src, dst, _, _ = archives
    reg = ModelRegistry()
    try:
        reg.load("m", src, warmup_example=X[:1], **BATCHER_KW)
        before = np.asarray(reg.predict("m", X[:2]))
        v1 = reg.get("m")
        # a gate no quantization can clear: delta must be <= -1
        with pytest.raises(AccuracyGateFailed) as ei:
            reg.deploy_quantized("m", dst, eval_inputs=CALIB,
                                 gate=AccuracyGate(max_delta=-1.0),
                                 **BATCHER_KW)
        assert ei.value.report["passed"] is False
        served = reg.get("m")
        assert served is v1 and served.version == 1, \
            "failed gate took traffic"
        assert not isinstance(served.model, QuantizedModel)
        after = np.asarray(reg.predict("m", X[:2]))
        assert np.array_equal(before, after)
        assert served.metrics.snapshot().get(
            "quantized_requests_total", 0) == 0
    finally:
        reg.shutdown()


def test_gate_chaos_fault_also_rolls_back(archives):
    """A fault INSIDE the gate evaluation (injected at
    ``serving.quantize.gate``) must behave like a failed gate: raised to
    the caller, f32 keeps serving."""
    src, dst, _, _ = archives
    reg = ModelRegistry()
    try:
        reg.load("m", src, warmup_example=X[:1], **BATCHER_KW)
        with ChaosController(seed=5) as c:
            c.on("serving.quantize.gate", FailNth(1))
            with pytest.raises(Exception):
                reg.deploy_quantized("m", dst, eval_inputs=CALIB,
                                     **BATCHER_KW)
        assert reg.get("m").version == 1
        np.asarray(reg.predict("m", X[:2]))  # still serving
    finally:
        reg.shutdown()


# ==================================================== calibration chaos
def test_corrupt_calibration_refuses_deploy(archives, tmp_path):
    """The ``serving.quantize.calibrate`` drill: flipped calibration bytes
    fail the CRC check -> CalibrationError, and NO archive or policy is
    left behind (refused deploy, never a silently wrong scale)."""
    src, _, _, _ = archives
    out = str(tmp_path / "corrupt.int8.zip")
    with ChaosController(seed=3) as c:
        c.on("serving.quantize.calibrate", CorruptBytes(n_bytes=4,
                                                        mode="flip"))
        with pytest.raises(CalibrationError, match="CRC"):
            quantize_archive(src, out, CALIB)
        assert any(ev[0] == "serving.quantize.calibrate" for ev in c.events)
    assert not os.path.exists(out)
    assert not os.path.exists(policy_path(out))


def test_truncated_calibration_refuses_deploy(archives, tmp_path):
    src, _, _, _ = archives
    out = str(tmp_path / "trunc.int8.zip")
    with ChaosController(seed=4) as c:
        c.on("serving.quantize.calibrate", CorruptBytes(mode="truncate"))
        with pytest.raises(CalibrationError):
            quantize_archive(src, out, CALIB)
    assert not os.path.exists(out)
    assert not os.path.exists(policy_path(out))


def test_nonfinite_and_empty_calibration_refused():
    bad = CALIB.copy()
    bad[3, 2] = np.nan
    with pytest.raises(CalibrationError, match="non-finite"):
        calibrate_inputs(bad)
    with pytest.raises(CalibrationError, match="empty"):
        calibrate_inputs(np.zeros((0, 8), np.float32))


# ========================================================== fleet router
def test_fleet_router_serves_quantized_bit_identically(archives):
    """Three workers all loading ONE quantized archive behind the router:
    every worker's answer for the same int8 request is bit-identical (and
    equals a direct QuantizedModel oracle), and the routed path preserves
    it — the fleet tier needs no changes to carry quantized models."""
    _, dst, policy, _ = archives
    qm_oracle = ModelSerializer.restore_model(dst)
    qx = quantize_requests(X, policy)
    oracle = np.asarray(qm_oracle.output(_pad_rows(qx[:2], 4)))[:2]

    servers, endpoints = [], {}
    for i in range(3):
        reg = ModelRegistry()
        reg.load("m", dst, warmup_example=X[:1], **BATCHER_KW)
        srv = ModelServer(reg, worker_id=f"w{i}")
        endpoints[f"w{i}"] = f"127.0.0.1:{srv.start(0)}"
        servers.append(srv)
    body = json.dumps({"inputs": qx[:2].tolist(), "dtype": "int8",
                       "timeout_ms": 30000}).encode()

    def post(address):
        req = urllib.request.Request(
            f"http://{address}/v1/models/m/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return np.asarray(json.loads(r.read())["outputs"], np.float32)

    router = FleetRouter(StaticFleet(endpoints), probe_interval_s=0.05,
                         hedge_initial_ms=2000.0)
    port = router.start(0)
    try:
        # direct to every worker: all bit-identical to the oracle
        for wid, address in endpoints.items():
            got = post(address)
            assert np.array_equal(got, oracle.astype(np.float32)), \
                f"worker {wid} diverged on the quantized request"
        # and through the router
        for _ in range(6):
            got = post(f"127.0.0.1:{port}")
            assert np.array_equal(got, oracle.astype(np.float32))
    finally:
        router.stop()
        for srv in servers:
            srv.stop(shutdown_registry=True)


# =============================================== review-hardening regressions
def test_plain_integer_rows_are_not_dequantized(archives):
    """Only rows in the policy's EXACT wire dtype carry codes: a plain
    int64/int32 feature request must pass through untouched (same result
    as the equivalent float rows), not get the affine map applied as if
    it were int8 codes."""
    _, dst, policy, _ = archives
    qm = ModelSerializer.restore_model(dst)
    xi = RNG.integers(-3, 4, size=(4, 8))
    for dt in (np.int64, np.int32):
        got = np.asarray(qm.output(xi.astype(dt)))
        want = np.asarray(qm.output(xi.astype(np.float32)))
        assert np.array_equal(got, want), \
            f"{np.dtype(dt)} rows were treated as quantized codes"


def test_server_rejects_non_numeric_dtype(archives):
    """The request ``dtype`` field is client-controlled: ``object`` (which
    would defeat the ragged-row guard and fail inside the model, feeding
    the breaker) and other non-numeric dtypes must be a 400, before
    anything is queued."""
    _, dst, _, _ = archives
    reg = ModelRegistry()
    try:
        served = reg.load("m", dst, warmup_example=X[:1], **BATCHER_KW)
        srv = ModelServer(reg)
        for bad in ("object", "str", "datetime64[s]"):
            code, body, _ = srv._handle_predict(
                "m", json.dumps({"inputs": [[1.0], [1.0, 2.0]],
                                 "dtype": bad}).encode())
            assert code == 400, (bad, code, body)
            assert "dtype" in body["error"]
        assert served.breaker.snapshot()["failures_in_window"] == 0
        assert served.metrics.snapshot()["requests_total"] == 0
    finally:
        reg.shutdown()


def test_quant_metrics_detached_on_undeploy_swap_and_shutdown(archives):
    """attach_quant_metrics must be paired with detach everywhere a
    quantized model stops serving — undeploy, a hot-swap to a plain f32
    model, and registry shutdown — so the profiler neither pins the dead
    batcher nor reports a removed model as live."""
    from deeplearning4j_tpu.runtime import profiler
    src, dst, _, _ = archives
    reg = ModelRegistry()
    try:
        reg.load("gone", dst, warmup_example=X[:1], **BATCHER_KW)
        reg.load("swapped", dst, warmup_example=X[:1], **BATCHER_KW)
        reg.load("stays", dst, warmup_example=X[:1], **BATCHER_KW)
        assert {"gone", "swapped", "stays"} <= profiler.quant_split_stats().keys()
        reg.undeploy("gone")
        # hot-swap to a plain f32 model under the same name
        reg.load("swapped", src, warmup_example=X[:1], **BATCHER_KW)
        stats = profiler.quant_split_stats()
        assert "gone" not in stats
        assert "swapped" not in stats
        assert "stays" in stats
    finally:
        reg.shutdown()
    assert "stays" not in profiler.quant_split_stats()
