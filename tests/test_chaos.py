"""Chaos engineering layer tests (ISSUE 2 tentpole): deterministic fault
injection, circuit-broken serving, crash-safe checkpoints, supervised
auto-resume.

All tier-1 (CPU mesh, no ``slow`` marker). The acceptance criteria
exercised here: a seeded fault schedule replays deterministically, the
breaker opens and recovers, no request ever returns a wrong (non-exact)
answer, a corrupted newest checkpoint is detected and training resumes
from the previous valid one, and the supervisor stops retrying once the
restart budget is exhausted.
"""

import os
import threading
import time
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.data import NumpyDataSetIterator
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.runtime import chaos
from deeplearning4j_tpu.runtime.chaos import (AddLatency, ChaosCancelled,
                                              ChaosController, ChaosError,
                                              CorruptBytes, FailNth,
                                              FailWithProbability,
                                              HangUntilCancelled)
from deeplearning4j_tpu.serving import (CircuitBreaker, CircuitOpen,
                                        CircuitState, HealthState,
                                        ModelRegistry, ModelServer,
                                        RetryPolicy)
from deeplearning4j_tpu.train import (Adam, CollectScoresListener,
                                      FaultTolerantTrainer, Sgd,
                                      TrainingFailure)
from deeplearning4j_tpu.train.checkpoint import (CheckpointListener,
                                                 atomic_save_model,
                                                 load_manifest,
                                                 verify_checkpoint)


def _mln_conf(seed=7, n_in=8, n_out=4):
    return (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax"))
            .set_input_type(InputType.feed_forward(n_in)).build())


def _data(n=64, seed=0, dim=8):
    rng = np.random.default_rng(seed)
    return rng.normal(0, 1, (n, dim)).astype(np.float32)


def _train_conf():
    return (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(8)).build())


def _train_iter(n=96):
    rng = np.random.default_rng(0)
    y = rng.integers(0, 3, n)
    x = (np.eye(3)[y] @ rng.normal(0, 1, (3, 8)) * 2
         + rng.normal(0, 0.3, (n, 8))).astype(np.float32)
    return NumpyDataSetIterator(x, np.eye(3, dtype=np.float32)[y],
                                batch_size=32)


# ------------------------------------------------------- chaos framework
def test_noop_fast_path_and_scoping():
    assert not chaos.active()
    chaos.inject("anything")  # no controller: must be a silent no-op
    data = b"payload"
    assert chaos.transform_bytes("anything", data) is data
    outer = ChaosController(seed=1).on("p", AddLatency(0.0))
    with outer:
        assert chaos.active()
        inner = ChaosController(seed=2)
        with inner:
            # nesting: the inner controller shadows the outer one
            chaos.inject("p")
            assert outer.count("p") == 0, "outer must be shadowed"
        chaos.inject("p")  # inner exited: outer is active again
        assert outer.count("p") == 1
    assert not chaos.active()


def test_fail_nth_and_every_nth():
    with ChaosController() as c:
        c.on("pt", FailNth(3))
        chaos.inject("pt")
        chaos.inject("pt")
        with pytest.raises(ChaosError, match="call #3"):
            chaos.inject("pt")
        chaos.inject("pt")  # only the 3rd fails
    with ChaosController() as c:
        c.on("pt", FailNth(2, every=True))
        chaos.inject("pt")
        with pytest.raises(ChaosError):
            chaos.inject("pt")
        chaos.inject("pt")
        with pytest.raises(ChaosError):
            chaos.inject("pt")


def test_seeded_probability_schedule_replays_deterministically():
    def run(seed):
        fired = []
        with ChaosController(seed=seed) as c:
            c.on("pt", FailWithProbability(0.4))
            for i in range(50):
                try:
                    chaos.inject("pt")
                except ChaosError:
                    fired.append(i)
            return fired, list(c.events)

    fired_a, events_a = run(11)
    fired_b, events_b = run(11)
    assert fired_a == fired_b, "same seed must replay the same schedule"
    assert events_a == events_b
    assert 0 < len(fired_a) < 50, "p=0.4 over 50 calls: some, not all"
    fired_c, _ = run(12)
    assert fired_a != fired_c, "different seed must give a different schedule"


def test_latency_and_corrupt_bytes_policies():
    with ChaosController(seed=3) as c:
        c.on("lat", AddLatency(0.02))
        t0 = time.monotonic()
        chaos.inject("lat")
        assert time.monotonic() - t0 >= 0.02
        c.on("bytes.flip", CorruptBytes(n_bytes=4, mode="flip"))
        c.on("bytes.cut", CorruptBytes(mode="truncate"))
        c.on("bytes.third", CorruptBytes(mode="flip", nth=3))
        data = bytes(range(256)) * 4
        flipped = chaos.transform_bytes("bytes.flip", data)
        assert flipped != data and len(flipped) == len(data)
        cut = chaos.transform_bytes("bytes.cut", data)
        assert len(cut) < len(data)
        assert chaos.transform_bytes("bytes.third", data) is data  # call 1
        assert chaos.transform_bytes("bytes.third", data) is data  # call 2
        assert chaos.transform_bytes("bytes.third", data) != data  # call 3
    # replay: the same seed corrupts identically
    with ChaosController(seed=3) as c:
        c.on("bytes.flip", CorruptBytes(n_bytes=4, mode="flip"))
        assert chaos.transform_bytes("bytes.flip", data) == flipped


def test_hang_until_cancelled_releases_on_scope_exit():
    released = {}

    def victim(controller):
        try:
            chaos.inject("hang")
        except ChaosCancelled:
            released["cancelled"] = True

    c = ChaosController().on("hang", HangUntilCancelled(timeout_s=30))
    with c:
        t = threading.Thread(target=victim, args=(c,), daemon=True)
        t.start()
        time.sleep(0.1)
        assert t.is_alive(), "victim must be hanging"
    # scope exit cancels the hang
    t.join(timeout=5)
    assert not t.is_alive() and released.get("cancelled")


# -------------------------------------------------------- circuit breaker
def test_breaker_open_half_open_close_transitions():
    now = [0.0]
    b = CircuitBreaker(failure_threshold=3, window_s=10.0,
                       reset_timeout_s=5.0, clock=lambda: now[0])
    assert b.state is CircuitState.CLOSED and b.allow()
    b.record_failure()
    b.record_failure()
    b.record_success()  # success clears the consecutive window
    b.record_failure()
    b.record_failure()
    assert b.state is CircuitState.CLOSED
    b.record_failure()  # third consecutive -> OPEN
    assert b.state is CircuitState.OPEN
    assert not b.allow() and b.opens_total == 1
    now[0] = 4.9
    assert not b.allow(), "reset timeout not yet elapsed"
    now[0] = 5.1
    assert b.state is CircuitState.HALF_OPEN
    assert b.allow(), "half-open must admit a probe"
    assert not b.allow(), "only half_open_probes probes admitted"
    b.record_failure()  # probe failed -> OPEN again, timer restarts
    assert b.state is CircuitState.OPEN and b.opens_total == 2
    now[0] = 10.3
    assert b.allow()  # half-open probe again
    b.record_success()  # probe succeeded -> CLOSED
    assert b.state is CircuitState.CLOSED and b.allow()


def test_half_open_probe_slot_returned_on_admission_rejection():
    """Review regression: an admission rejection (not a model outcome)
    during HALF_OPEN must return the probe slot — otherwise the breaker
    wedges in a permanent shedding state on a healthy model."""
    now = [0.0]
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0,
                       clock=lambda: now[0])
    b.record_failure()  # OPEN
    now[0] = 1.5
    assert b.allow()  # the half-open probe slot is consumed
    b.record_discard()  # …but the request was shed at admission
    assert b.allow(), "probe slot must be available again"
    b.record_success()
    assert b.state is CircuitState.CLOSED


def test_checkpoint_counter_resumes_past_existing_archives(tmp_path):
    """Review regression: a fresh listener over an existing directory
    (supervisor restart) must continue the counter, not reuse index 0 —
    reuse would overwrite the OLDEST archive with the NEWEST state while
    newest-by-counter ordering still preferred the stale high indices."""
    net = MultiLayerNetwork(_mln_conf()).init()
    first = CheckpointListener(str(tmp_path), every_n_iterations=1)
    for it in range(1, 3):
        first.iteration_done(net, it, 0, 0.0)
    second = CheckpointListener(str(tmp_path), every_n_iterations=1)
    second.iteration_done(net, 3, 0, 0.0)
    zips = sorted(f for f in os.listdir(tmp_path) if f.endswith(".zip"))
    assert zips == ["checkpoint_0_iter1.zip", "checkpoint_1_iter2.zip",
                    "checkpoint_2_iter3.zip"]
    assert CheckpointListener.last_checkpoint_in(str(tmp_path)) == \
        os.path.join(tmp_path, "checkpoint_2_iter3.zip")


def test_breaker_window_expires_old_failures():
    now = [0.0]
    b = CircuitBreaker(failure_threshold=2, window_s=1.0,
                       clock=lambda: now[0])
    b.record_failure()
    now[0] = 2.0  # first failure ages out of the window
    b.record_failure()
    assert b.state is CircuitState.CLOSED


def test_retry_policy_full_jitter_bounds_and_determinism():
    r1 = RetryPolicy(max_attempts=5, base_delay_s=0.01, max_delay_s=0.05,
                     seed=9)
    r2 = RetryPolicy(max_attempts=5, base_delay_s=0.01, max_delay_s=0.05,
                     seed=9)
    d1 = [r1.delay_for(a) for a in range(5)]
    d2 = [r2.delay_for(a) for a in range(5)]
    assert d1 == d2, "seeded retry delays must replay"
    for a, d in enumerate(d1):
        assert 0.0 <= d <= min(0.05, 0.01 * 2 ** a)
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)


# ------------------------------------------------- serving under chaos
def test_registry_warmup_failure_rolls_back_to_old_version():
    """Satellite regression: an injected warmup failure during hot-swap
    must leave the OLD version serving — never an unregistered name or a
    half-swapped pair."""
    reg = ModelRegistry()
    x = _data(16)
    net1 = MultiLayerNetwork(_mln_conf(seed=1)).init()
    net2 = MultiLayerNetwork(_mln_conf(seed=2)).init()
    try:
        reg.register("m", net1, warmup_example=x[:1], max_batch_size=8)
        y1 = np.asarray(reg.predict("m", x[:2]))
        with ChaosController() as c:
            c.on("serving.batcher.warmup", FailNth(1))
            with pytest.raises(ChaosError):
                reg.register("m", net2, warmup_example=x[:1],
                             max_batch_size=8)
        served = reg.get("m")
        assert served.version == 1 and served.model is net1
        assert served.health is HealthState.READY
        y_after = np.asarray(reg.predict("m", x[:2]))
        assert (y_after == y1).all(), "old version must keep serving"
        # and a later clean re-register still hot-swaps normally
        served2 = reg.register("m", net2, warmup_example=x[:1],
                               max_batch_size=8)
        assert served2.version == 2
    finally:
        reg.shutdown()


def test_retry_absorbs_transient_forward_failure():
    reg = ModelRegistry()
    net = MultiLayerNetwork(_mln_conf()).init()
    ref = MultiLayerNetwork(_mln_conf()).init()
    x = _data(8)
    try:
        served = reg.register(
            "m", net, warmup_example=x[:1], max_batch_size=8,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, seed=1))
        with ChaosController() as c:
            # warmup already done; the FIRST live forward fails once
            c.on("serving.batcher.forward", FailNth(1))
            got = np.asarray(reg.predict("m", x[:2]))
        np.testing.assert_allclose(got, np.asarray(ref.output(x[:2])),
                                   rtol=1e-5)
        snap = served.metrics.snapshot()
        assert snap["retries_total"] == 1
        assert snap["errors_total"] == 1  # the failed attempt was recorded
        assert served.breaker.state is CircuitState.CLOSED
    finally:
        reg.shutdown()


def test_breaker_opens_sheds_and_recovers():
    reg = ModelRegistry()
    net = MultiLayerNetwork(_mln_conf()).init()
    x = _data(8)
    try:
        served = reg.register(
            "m", net, warmup_example=x[:1], max_batch_size=8,
            breaker=CircuitBreaker(failure_threshold=3, window_s=30.0,
                                   reset_timeout_s=0.2),
            retry=RetryPolicy(max_attempts=1))
        with ChaosController() as c:
            c.on("serving.batcher.forward", FailNth(1, every=True))
            for _ in range(3):  # trip the breaker
                with pytest.raises(ChaosError):
                    reg.predict("m", x[:1])
            assert served.breaker.state is CircuitState.OPEN
            assert served.health is HealthState.DEGRADED
            # while OPEN: requests shed instantly with CircuitOpen, the
            # model never runs (no new forward calls recorded)
            before = c.count("serving.batcher.forward")
            with pytest.raises(CircuitOpen):
                reg.predict("m", x[:1])
            assert c.count("serving.batcher.forward") == before
        # chaos gone; after the reset timeout a half-open probe closes it
        time.sleep(0.25)
        got = np.asarray(reg.predict("m", x[:2]))
        assert got.shape == (2, 4)
        assert served.breaker.state is CircuitState.CLOSED
        assert served.health is HealthState.READY
        snap = served.metrics.snapshot()
        assert snap["rejected_circuit"] == 1
        assert snap["breaker_opens_total"] == 1
        assert snap["breaker_state"] == "CLOSED"
    finally:
        reg.shutdown()


def test_readyz_and_breaker_metrics_on_http_server():
    import json
    import urllib.error
    import urllib.request

    reg = ModelRegistry()
    srv = ModelServer(reg)
    port = srv.start(0)
    base = f"http://127.0.0.1:{port}"
    net = MultiLayerNetwork(_mln_conf()).init()
    x = _data(8)
    try:
        # empty registry: alive but NOT ready
        assert json.loads(urllib.request.urlopen(
            f"{base}/healthz").read())["status"] == "ok"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/readyz")
        assert ei.value.code == 503

        served = reg.register(
            "m", net, warmup_example=x[:1], max_batch_size=8,
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
            retry=RetryPolicy(max_attempts=1))
        ready = json.loads(urllib.request.urlopen(f"{base}/readyz").read())
        assert ready == {"ready": True, "models": {"m": "ready"}}

        # trip the breaker -> DEGRADED -> /readyz 503, predict 503 circuit
        with ChaosController() as c:
            c.on("serving.batcher.forward", FailNth(1, every=True))
            body = json.dumps({"inputs": x[:1].tolist()}).encode()
            req = urllib.request.Request(f"{base}/v1/models/m/predict",
                                         data=body)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 500  # the failure itself
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 503  # now shed by the open breaker
            assert json.loads(ei.value.read())["reason"] == "circuit_open"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/readyz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["models"]["m"] == "degraded"
        metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert 'serving_breaker_state{model="m"} 2' in metrics
        assert 'serving_breaker_opens_total{model="m"} 1' in metrics
        assert ('serving_rejected_total{model="m",reason="circuit_open"} 1'
                in metrics)
        assert 'serving_retries_total{model="m"} 0' in metrics
        assert served.describe()["health"] == "degraded"
    finally:
        srv.stop(shutdown_registry=True)


# ------------------------------------------------ crash-safe checkpoints
def test_keep_every_decides_before_saving(tmp_path, monkeypatch):
    """Satellite: a keep_every-skipped checkpoint must never be written
    (the seed saved the archive, then immediately unlinked it)."""
    net = MultiLayerNetwork(_mln_conf()).init()
    writes = []
    orig_save = type(net).save

    def counting_save(self, path, save_updater=True):
        writes.append(path)
        return orig_save(self, path, save_updater=save_updater)

    monkeypatch.setattr(type(net), "save", counting_save)
    lst = CheckpointListener(str(tmp_path), every_n_iterations=1,
                             keep_every=3)
    for it in range(1, 7):
        lst.iteration_done(net, it, 0, 0.0)
    # 6 triggers, keep_every=3 -> exactly 2 archives written, 2 on disk
    assert len(writes) == 2
    zips = sorted(f for f in os.listdir(tmp_path) if f.endswith(".zip"))
    assert len(zips) == 2
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


def test_atomic_save_and_manifest(tmp_path):
    net = MultiLayerNetwork(_mln_conf()).init()
    lst = CheckpointListener(str(tmp_path), every_n_iterations=1,
                             keep_last=2)
    for it in range(1, 4):
        lst.iteration_done(net, it, 0, 0.0)
    zips = sorted(f for f in os.listdir(tmp_path) if f.endswith(".zip"))
    assert len(zips) == 2  # keep_last retention
    manifest = load_manifest(str(tmp_path))
    assert sorted(manifest) == zips  # retention also prunes the manifest
    for f in zips:
        path = os.path.join(tmp_path, f)
        assert verify_checkpoint(path, manifest[f])
        with zipfile.ZipFile(path) as zf:
            assert zf.testzip() is None


def test_corrupt_newest_checkpoint_falls_back_to_valid(tmp_path, caplog):
    net = MultiLayerNetwork(_mln_conf()).init()
    lst = CheckpointListener(str(tmp_path), every_n_iterations=1)
    lst.iteration_done(net, 1, 0, 0.0)
    with ChaosController(seed=5) as c:
        # torn write on the SECOND (newest) archive only
        c.on("train.checkpoint.bytes", CorruptBytes(mode="truncate", nth=1))
        lst.iteration_done(net, 2, 0, 0.0)
    zips = sorted(f for f in os.listdir(tmp_path) if f.endswith(".zip"))
    assert len(zips) == 2
    newest = os.path.join(tmp_path, "checkpoint_1_iter2.zip")
    manifest = load_manifest(str(tmp_path))
    assert not verify_checkpoint(newest, manifest[os.path.basename(newest)])
    import logging
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        best = CheckpointListener.last_checkpoint_in(str(tmp_path))
    assert best == os.path.join(tmp_path, "checkpoint_0_iter1.zip")
    assert any("Skipping unreadable/corrupt" in r.message
               for r in caplog.records)
    # the fallback checkpoint actually restores
    restored = MultiLayerNetwork.load(best)
    x = _data(4)
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), rtol=1e-5)


def test_truncated_zip_without_manifest_is_skipped(tmp_path):
    """Even with no manifest (e.g. pre-upgrade checkpoint dir), a
    truncated archive must be skipped via the zip's own structure."""
    net = MultiLayerNetwork(_mln_conf()).init()
    p0 = str(tmp_path / "checkpoint_0_iter1.zip")
    p1 = str(tmp_path / "checkpoint_1_iter2.zip")
    atomic_save_model(net, p0)
    atomic_save_model(net, p1)
    with open(p1, "rb") as f:
        data = f.read()
    with open(p1, "wb") as f:
        f.write(data[:len(data) // 2])  # crash mid-write
    assert not os.path.exists(
        os.path.join(tmp_path, "checkpoint_manifest.json"))
    assert CheckpointListener.last_checkpoint_in(str(tmp_path)) == p0


def test_all_checkpoints_corrupt_returns_none(tmp_path):
    net = MultiLayerNetwork(_mln_conf()).init()
    p0 = str(tmp_path / "checkpoint_0_iter1.zip")
    atomic_save_model(net, p0)
    with open(p0, "wb") as f:
        f.write(b"not a zip at all")
    assert CheckpointListener.last_checkpoint_in(str(tmp_path)) is None


# ------------------------------------------- supervised trainer under chaos
def test_supervised_resume_matches_uninterrupted_trajectory(tmp_path):
    """Mid-epoch crash + restore: the resumed run's loss trajectory must
    match an uninterrupted run iteration-for-iteration (exact-resume
    checkpoints + batch skipping on restart)."""
    epochs = 4

    # ---- uninterrupted reference run
    ref_scores = CollectScoresListener()

    def make_ref():
        net = MultiLayerNetwork(_train_conf()).init()
        net.set_listeners(ref_scores)
        return net

    FaultTolerantTrainer(make_ref, str(tmp_path / "ref"),
                         every_n_iterations=1).fit(_train_iter(),
                                                   epochs=epochs)

    # ---- chaotic run: killed at iteration 5 (mid-epoch 1; 3 batches per
    # epoch). ChaosListener runs FIRST so the score of the killed
    # iteration is never recorded and the newest checkpoint is iteration
    # 4 — the resume re-trains iteration 5 from the iter-4 state exactly.
    scores = CollectScoresListener()

    def make_net():
        net = MultiLayerNetwork(_train_conf()).init()
        net.set_listeners(chaos.ChaosListener(), scores)
        return net

    trainer = FaultTolerantTrainer(make_net, str(tmp_path / "ckpt"),
                                   every_n_iterations=1, max_restarts=2)
    with ChaosController() as c:
        c.on("train.iteration", FailNth(5))
        net = trainer.fit(_train_iter(), epochs=epochs)
    assert trainer.restarts == 1
    assert net._epoch == epochs

    # iteration numbering must be gapless and duplicate-free across the
    # crash (restore to iter 4 + skip the epoch's already-trained batch),
    # and every post-resume loss must bit-match the uninterrupted run
    assert [i for i, _ in scores.scores] == [i for i, _ in ref_scores.scores]
    got = [s for _, s in scores.scores]
    ref = [s for _, s in ref_scores.scores]
    np.testing.assert_allclose(got, ref, rtol=0, atol=0)


def test_restart_budget_window_exhaustion(tmp_path):
    it = _train_iter()

    def make_net():
        net = MultiLayerNetwork(_train_conf()).init()
        net.set_listeners(chaos.ChaosListener())
        return net

    trainer = FaultTolerantTrainer(make_net, str(tmp_path / "ckpt"),
                                   every_n_iterations=2, max_restarts=2,
                                   restart_window_s=60.0)
    with ChaosController() as c:
        c.on("train.iteration", FailNth(1, every=True))  # every iteration
        with pytest.raises(TrainingFailure, match="giving up after 2 "
                                                  "restarts in 60s"):
            trainer.fit(it, epochs=2)
    assert trainer.restarts == 3  # budget + the exhausting attempt


def test_hung_training_detected_and_abandoned(tmp_path):
    """A HANG (not an exception) must be caught by the heartbeat watchdog:
    the supervisor abandons the stalled worker and the restart budget
    escalates (the hang persists) as TrainingFailure."""
    it = _train_iter()

    def make_net():
        return MultiLayerNetwork(_train_conf()).init()

    trainer = FaultTolerantTrainer(make_net, str(tmp_path / "ckpt"),
                                   every_n_iterations=2, max_restarts=1,
                                   heartbeat_timeout_s=0.3)
    with ChaosController() as c:
        c.on("train.epoch", HangUntilCancelled(timeout_s=30))
        t0 = time.monotonic()
        with pytest.raises(TrainingFailure, match="giving up"):
            trainer.fit(it, epochs=2)
        elapsed = time.monotonic() - t0
    assert trainer.restarts == 2
    assert elapsed < 10, "watchdog must abandon the hang, not wait it out"


def test_hang_recovers_when_fault_clears(tmp_path):
    """Hang on the FIRST epoch attempt only; the supervisor abandons it,
    restarts, and training completes normally."""
    it = _train_iter()

    def make_net():
        return MultiLayerNetwork(_train_conf()).init()

    class HangOnce(HangUntilCancelled):
        def apply(self, point, index, rng, controller):
            if index == 1:
                return super().apply(point, index, rng, controller)
            return None

    # timeout generous enough that the first step's jit compile on a
    # fresh net is not misread as a hang
    trainer = FaultTolerantTrainer(make_net, str(tmp_path / "ckpt"),
                                   every_n_iterations=2, max_restarts=2,
                                   heartbeat_timeout_s=5.0)
    with ChaosController() as c:
        c.on("train.epoch", HangOnce(timeout_s=60))
        net = trainer.fit(it, epochs=2)
    assert trainer.restarts == 1
    assert net._epoch == 2
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.8


# ---------------------------------------------------------------------------
# ISSUE 14: every REGISTERED_POINTS entry must be exercised by a drill —
# these four points existed in code but had no test firing them (the
# analysis lint now fails the suite if one regresses to untested).

def test_chaos_point_batcher_submit_is_explicit_error():
    from deeplearning4j_tpu.serving import ContinuousBatcher
    net = MultiLayerNetwork(_mln_conf()).init()
    b = ContinuousBatcher(net, max_batch_size=4, batch_timeout_ms=1.0)
    x = _data(2)
    try:
        with ChaosController(seed=3) as c:
            c.on("serving.batcher.submit", FailNth(1))
            with pytest.raises(ChaosError):
                b.submit(x)
        # the fault was one admission, not the batcher: next request serves
        got = np.asarray(b.submit(x))
        np.testing.assert_array_equal(got, np.asarray(net.output(x)))
    finally:
        b.shutdown()


def test_chaos_points_registry_register_and_deploy(tmp_path):
    reg = ModelRegistry()
    net = MultiLayerNetwork(_mln_conf()).init()
    try:
        with ChaosController(seed=3) as c:
            c.on("serving.registry.register", FailNth(1))
            with pytest.raises(ChaosError):
                reg.register("m", net, warmup_example=_data(1))
        assert "m" not in reg.names()
        # registration succeeds once the fault clears
        reg.register("m", net, warmup_example=_data(1))
        assert "m" in reg.names()
        # deploy_quantized faults BEFORE the gate/build: old version intact
        with ChaosController(seed=3) as c:
            c.on("serving.registry.deploy_quantized", FailNth(1))
            with pytest.raises(ChaosError):
                reg.deploy_quantized("m", str(tmp_path / "none.zip"),
                                     eval_inputs=[_data(2)])
        assert reg.get("m").model is net
    finally:
        reg.shutdown()


def test_chaos_point_checkpoint_write_fails_cleanly(tmp_path):
    net = MultiLayerNetwork(_mln_conf()).init()
    path = tmp_path / "ckpt.zip"
    with ChaosController(seed=3) as c:
        c.on("train.checkpoint.write", FailNth(1))
        with pytest.raises(ChaosError):
            atomic_save_model(net, str(path))
    # the faulted write left nothing behind — no archive, no tmp litter
    assert not path.exists()
    assert [p for p in os.listdir(tmp_path) if not p.startswith(".")] == []
    # and the next write lands atomically as usual
    entry = atomic_save_model(net, str(path))
    assert path.exists() and verify_checkpoint(str(path), entry)
