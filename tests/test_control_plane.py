"""Replicated control plane tests (ISSUE 12): versioned shared fleet
config, file-lease leader election, router-tier supervision, client-side
router failover, predictive autoscaling signals, and multi-router
consistency.

Layers, cheapest first:

- **Pure units** — ``FleetConfig`` atomics/versioning/exactly-once
  claims, ``LeaseElection`` acquire/heartbeat/takeover/release,
  ``forecast_rate`` trend math, ``SLOMonitor.recent_counts``.
- **Chaos** — ``serving.router.config_load`` (corrupt/stale config
  degrades to the last-valid snapshot with a loud counter, never a
  crash) and ``serving.autoscale.lease`` (a hung heartbeat yields
  leadership within one lease window).
- **In-process routers over stub workers** — breaker warm-start from the
  first ``/v1/metricsz`` scrape, idempotent config-versioned rolling
  deploys (two routers, one applied deploy), multi-router consistency
  (identical ``ranked_workers`` orders, shed-window agreement within one
  probe interval, bit-identical responses for the same request stream).
- **Subprocess router tier** — ``RouterSupervisor`` + ``router_main``
  processes over the shared config: SIGKILL a router mid-load through a
  ``MultiRouterClient`` with ZERO client-visible errors, watchdog
  relaunch within budget, peering visible from the survivor.
"""

import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from deeplearning4j_tpu.runtime.chaos import (AddLatency, ChaosController,
                                              CorruptBytes, FailNth)
from deeplearning4j_tpu.serving.autoscale import (AutoscalerConfig,
                                                  SLOAutoscaler,
                                                  forecast_rate)
from deeplearning4j_tpu.serving.control_plane import (FleetConfig,
                                                      LeaseElection,
                                                      MultiRouterClient,
                                                      RouterSpec,
                                                      RouterSupervisor)
from deeplearning4j_tpu.serving.resilience import CircuitState
from deeplearning4j_tpu.serving.router import FleetRouter, StaticFleet
from deeplearning4j_tpu.serving.slo import SLOMonitor, SLOTarget


def _wait_until(pred, timeout_s=10.0, interval=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ==========================================================================
# stub worker: scripted, no jax (same idiom as test_router)
class _StubWorker:
    """A fake worker: ``/readyz`` 200, predict scripted via ``mode``
    ("ok" | "shed"), optional ``/v1/metricsz`` breaker payload (the
    warm-start seam)."""

    def __init__(self, body=b'{"outputs": [[1.0]], "version": 1}',
                 metricsz=None):
        self.mode = "ok"
        self.body = body
        self.retry_after_ms = 500.0
        self.metricsz = metricsz
        self.hits = 0
        self.lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, payload, extra=None):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if self.path == "/readyz":
                    self._send(200, b'{"ready": true}')
                elif self.path == "/v1/metricsz" and stub.metricsz \
                        is not None:
                    self._send(200, json.dumps(stub.metricsz).encode())
                else:
                    self._send(404, b'{}')

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                with stub.lock:
                    stub.hits += 1
                    mode = stub.mode
                if mode == "shed":
                    self._send(503, json.dumps(
                        {"error": "overloaded",
                         "retry_after_ms": stub.retry_after_ms}).encode(),
                        extra={"Retry-After-Ms":
                               f"{stub.retry_after_ms:.0f}"})
                else:
                    self._send(200, stub.body)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def address(self):
        return f"127.0.0.1:{self.port}"

    def stop(self):
        self.httpd.shutdown()


# ==========================================================================
# FleetConfig
def test_fleet_config_versioned_atomic_roundtrip(tmp_path):
    p = str(tmp_path / "fleet.json")
    cfg = FleetConfig(p)
    assert cfg.version == 0 and cfg.endpoints() == {}
    cfg.set_workers({"w0": "127.0.0.1:1", "w1": "127.0.0.1:2"})
    assert cfg.version == 1
    # a second process (fresh object) sees the same roster + version
    other = FleetConfig(p)
    assert other.version == 1
    assert other.endpoints() == {"w0": "127.0.0.1:1", "w1": "127.0.0.1:2"}
    # unchanged roster writes nothing (no version churn)
    cfg.set_workers({"w1": "127.0.0.1:2", "w0": "127.0.0.1:1"})
    assert cfg.version == 1
    # router roster round-trips too
    cfg.set_router("r0", "127.0.0.1:9")
    assert other.routers() == {"r0": "127.0.0.1:9"}
    cfg.remove_router("r0")
    assert other.routers() == {}


def test_fleet_config_try_claim_exactly_once_across_instances(tmp_path):
    p = str(tmp_path / "fleet.json")
    a, b = FleetConfig(p), FleetConfig(p)
    assert a.try_claim("deploy:v2", {"router": "a"}) is True
    assert b.try_claim("deploy:v2", {"router": "b"}) is False
    assert b.applied("deploy:v2")["router"] == "a"
    assert a.try_claim("deploy:v3") is True


def test_fleet_config_concurrent_mutations_all_land(tmp_path):
    """N threads x M mutations through two instances: the lock file
    serializes them, so the version advances by exactly N*M and every
    key lands."""
    p = str(tmp_path / "fleet.json")
    configs = [FleetConfig(p), FleetConfig(p)]
    n_threads, per_thread = 4, 8

    def run(tid):
        for k in range(per_thread):
            def fn(cfg, tid=tid, k=k):
                cfg["models"][f"t{tid}-{k}"] = {"v": k}
            configs[tid % 2].mutate(fn)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    final = FleetConfig(p).snapshot()
    assert final["version"] == n_threads * per_thread
    assert len(final["models"]) == n_threads * per_thread


def test_fleet_config_corrupt_and_stale_degrade_to_last_valid(tmp_path):
    p = str(tmp_path / "fleet.json")
    cfg = FleetConfig(p)
    cfg.set_workers({"w0": "127.0.0.1:1"})
    good = cfg.endpoints()
    # torn write: readers keep the last-valid snapshot, loudly
    with open(p, "w") as f:
        f.write('{"format": "dl4j-fleet-config-v1", "version": ')
    assert cfg.endpoints() == good
    assert cfg.counters()["load_failures_total"] == 1
    # a blind overwrite that REGRESSES the version is stale, not truth
    with open(p, "w") as f:
        json.dump({"format": "dl4j-fleet-config-v1", "version": 0,
                   "workers": {}}, f)
    assert cfg.endpoints() == good
    assert cfg.counters()["load_failures_total"] == 2
    # a good write recovers without a restart
    cfg.set_workers({"w9": "127.0.0.1:9"})
    assert FleetConfig(p).endpoints() == {"w9": "127.0.0.1:9"}


def test_fleet_config_chaos_load_fault_and_corruption(tmp_path):
    """The ``serving.router.config_load`` chaos point: an injected load
    fault or byte corruption degrades to the last-valid snapshot with
    the counter bumped — never a raise on the read path."""
    p = str(tmp_path / "fleet.json")
    cfg = FleetConfig(p)
    cfg.set_workers({"w0": "127.0.0.1:1"})
    good = cfg.endpoints()
    with ChaosController(seed=3) as c:
        c.on("serving.router.config_load", FailNth(1, every=True))
        # force a reload: the file changes under an always-failing point
        FleetConfig(p).set_workers({"w0": "127.0.0.1:1",
                                    "w1": "127.0.0.1:2"})
        assert cfg.endpoints() == good  # degraded, not crashed
        assert cfg.counters()["load_failures_total"] >= 1
    # corruption flavour: bytes mangled between disk and parse
    with ChaosController(seed=4) as c:
        c.on("serving.router.config_load",
             CorruptBytes(n_bytes=16, mode="truncate"))
        fresh = FleetConfig(p, create=False)
        assert fresh.endpoints() == {}  # nothing valid ever loaded...
        assert fresh.counters()["load_failures_total"] >= 1
    # ...and the same object recovers on the next clean read
    fresh.set_router("r0", "127.0.0.1:5")  # mutate re-reads + rewrites
    assert fresh.endpoints() == {"w0": "127.0.0.1:1", "w1": "127.0.0.1:2"}


# ==========================================================================
# LeaseElection
def test_lease_acquire_heartbeat_takeover_release(tmp_path):
    lease = str(tmp_path / "lease")
    a = LeaseElection(lease, "r0", lease_s=0.4)
    b = LeaseElection(lease, "r1", lease_s=0.4)
    assert a.ensure() == "leader"
    assert b.ensure() == "follower"
    assert b.holder() == "r0"
    # heartbeats keep the lease across a full window
    for _ in range(4):
        time.sleep(0.15)
        assert a.ensure() == "leader"
    assert b.ensure() == "follower"
    # the leader dies (stops heartbeating): takeover after one window,
    # with the fencing seq bumped
    seq0 = b.snapshot()["seq"]
    time.sleep(0.55)
    assert b.ensure() == "leader"
    assert b.snapshot()["seq"] == seq0 + 1
    # the old leader observes the loss and steps down (never utimes the
    # new holder's lease)
    assert a.ensure() == "follower"
    assert a.snapshot()["holder"] == "r1"
    # voluntary release frees the lease immediately
    b.release()
    assert a.ensure() == "leader"
    roles = [e["role"] for e in a.elections]
    assert roles[-1] == "leader" and "follower" in roles


def test_lease_release_by_follower_never_revokes_leader(tmp_path):
    lease = str(tmp_path / "lease")
    a = LeaseElection(lease, "r0", lease_s=5.0)
    b = LeaseElection(lease, "r1", lease_s=5.0)
    assert a.ensure() == "leader"
    assert b.ensure() == "follower"
    b.release()  # not the holder: must be a no-op
    assert a.ensure() == "leader"
    assert a.holder() == "r0"


def test_lease_chaos_hung_heartbeat_yields_leadership(tmp_path):
    """The ``serving.autoscale.lease`` chaos point: a heartbeat delayed
    past the lease window (the hung-leader drill) lets a follower take
    over; when the hung beat finally returns, the old leader re-reads
    the lease, sees the new holder, and steps down WITHOUT touching the
    file."""
    lease = str(tmp_path / "lease")
    a = LeaseElection(lease, "ra", lease_s=0.4)
    b = LeaseElection(lease, "rb", lease_s=0.4)
    assert a.ensure() == "leader"
    assert b.ensure() == "follower"
    with ChaosController(seed=1) as c:
        c.on("serving.autoscale.lease", AddLatency(0.8))
        done = threading.Event()

        def hung_beat():
            a.ensure()  # sleeps 0.8s inside the chaos point
            done.set()

        t = threading.Thread(target=hung_beat, daemon=True)
        t.start()
        assert _wait_until(lambda: b.ensure() == "leader", timeout_s=3.0), \
            "follower never took over from the hung leader"
        assert done.wait(5.0)
        t.join(5.0)
    # the old leader lost: stepped down, and rb's lease survived intact
    assert a.role == "follower"
    assert b.ensure() == "leader"
    assert b.holder() == "rb"
    assert any(e["reason"] == "lease_lost" for e in a.elections)


def test_lease_heartbeat_thread_lifecycle(tmp_path):
    lease = str(tmp_path / "lease")
    a = LeaseElection(lease, "r0", lease_s=0.5)
    with a:
        assert _wait_until(a.is_leader, timeout_s=3.0)
    # stop() released: the file is gone and the thread joined (the
    # conftest lease-election thread guard watches the name prefix)
    assert a.holder() is None


# ==========================================================================
# forecast + recent_counts
def test_forecast_rate_trends():
    # empty / flat / too-short: no trend
    assert forecast_rate([], 10.0) == (0.0, 0.0, 0.0)
    pred, slope, now = forecast_rate([5, 5, 5], 10.0)
    assert slope == 0.0 and now == 5.0
    pred, slope, now = forecast_rate([4.0] * 20, 15.0)
    assert abs(slope) < 1e-9 and abs(pred - 4.0) < 1e-6
    # a ramp extrapolates ahead of the current rate
    ramp = [float(i) for i in range(20)]
    pred, slope, now = forecast_rate(ramp, 15.0)
    assert slope == pytest.approx(1.0)
    assert pred == pytest.approx(19 + 15.0)
    assert now == pytest.approx(np.mean(ramp[-5:]))
    # a 10x step: positive slope, prediction well above current capacity
    step = [1.0] * 15 + [10.0] * 5
    pred, slope, now = forecast_rate(step, 15.0)
    assert slope > 0 and now == pytest.approx(10.0) and pred > now
    # a decaying series never predicts negative traffic
    pred, slope, _ = forecast_rate([20.0 - i for i in range(20)], 60.0)
    assert slope < 0 and pred == 0.0


def test_slo_recent_counts_per_second_history():
    clock = {"t": 1000.0}
    mon = SLOMonitor(windows_s=(10, 60), now_fn=lambda: clock["t"])
    for sec, n in ((1000, 2), (1001, 5), (1003, 1)):
        clock["t"] = float(sec)
        for _ in range(n):
            mon.record("m", ok=True, latency_s=0.001)
    clock["t"] = 1004.0
    # seconds 999..1003 (current partial second 1004 excluded)
    assert mon.recent_counts("m", 5) == [0, 2, 5, 0, 1]
    assert mon.recent_counts("ghost", 5) == [0, 0, 0, 0, 0]
    # clamped to the ring horizon, zero-padded on the old side
    counts = mon.recent_counts("m", 600)
    assert len(counts) == 60 and sum(counts) == 8


# ==========================================================================
# autoscaler: leadership + predictive signals (unit: fake router)
class _FakeView:
    def __init__(self, wid):
        self.worker_id = wid
        self.address = "127.0.0.1:1"

    def admittable(self, now=None):
        return True


class _FakeRouter:
    def __init__(self, slo):
        self.slo = slo
        self.view = _FakeView("w0")
        self.autoscaler = None

    def ranked_workers(self, model):
        return [self.view]

    def workers(self):
        return {"w0": self.view}

    def attach_autoscaler(self, a):
        self.autoscaler = a


def _capacity(replicas=1, queue_depth=0, queue_headroom=256,
              busy_fraction=0.2):
    # the fleet-aggregated schema fleet_capacity() produces
    return {"workers": {"w0": {
                "models": {"m": {"param_bytes": 100,
                                 "model_state_bytes": 0,
                                 "replicas": replicas,
                                 "utilization": {"busy_fraction":
                                                 busy_fraction},
                                 "queue": {"depth": queue_depth,
                                           "headroom_requests":
                                           queue_headroom}}},
                "totals": {"device_bytes": 100 * replicas},
                "process": {"device_budget_bytes": None}}},
            "models": {"m": {"param_bytes": 100, "replicas": replicas,
                             "queue_depth": queue_depth,
                             "queue_headroom_requests": queue_headroom,
                             "busy_fraction": busy_fraction}},
            "process": {}}


def _controller(tmp_path=None, holder="r0", election=None, **cfg_kw):
    clock = {"t": 1000.0}
    slo = SLOMonitor(target=SLOTarget(availability=0.999, latency_ms=50.0,
                                      latency_target=0.9),
                     windows_s=(10, 60), now_fn=lambda: clock["t"])
    router = _FakeRouter(slo)
    state = {"replicas": 1, "levers": [],
             "capacity": _capacity()}

    def replica_lever(view, model, delta, span):
        state["levers"].append(("delta", delta))
        state["replicas"] = max(1, state["replicas"] + delta)
        return True, {"replicas": state["replicas"]}

    defaults = dict(fast_window_s=10, slow_window_s=60, up_burn=2.0,
                    confirm_burn=1.0, down_burn=0.5, up_cooldown_s=5.0,
                    down_cooldown_s=30.0, min_requests=4, max_replicas=4)
    defaults.update(cfg_kw)
    auto = SLOAutoscaler(router, config=AutoscalerConfig(**defaults),
                         capacity_fn=lambda: state["capacity"],
                         replica_lever=replica_lever,
                         election=election,
                         now_fn=lambda: clock["t"])
    return auto, slo, state, clock


def _feed(slo, n, ok=True, slow=False):
    for _ in range(n):
        slo.record("m", ok=ok, latency_s=0.2 if slow else 0.001)


def test_follower_shadow_computes_but_never_acts(tmp_path):
    lease = str(tmp_path / "lease")
    ea = LeaseElection(lease, "ra", lease_s=30.0)
    eb = LeaseElection(lease, "rb", lease_s=30.0)
    auto_a, slo_a, state_a, _ = _controller(election=ea)
    auto_b, slo_b, state_b, _ = _controller(election=eb)
    for slo in (slo_a, slo_b):  # both see the same breach
        _feed(slo, 20, ok=False)
    da = auto_a.tick()
    db = auto_b.tick()
    # the leader scaled; every one of its decisions says so
    assert [d["action"] for d in da] == ["scale_up_replica"]
    assert da[0]["role"] == "leader" and da[0]["ok"]
    assert state_a["levers"] == [("delta", 1)]
    # the follower shadow-computed the SAME pressure but touched nothing
    assert [d["action"] for d in db] == ["follower_scale_up"]
    assert db[0]["role"] == "follower" and not db[0]["ok"]
    assert state_b["levers"] == []
    assert auto_a.report()["role"] == "leader"
    assert auto_b.report()["role"] == "follower"
    assert auto_b.report()["election"]["holder"] == "ra"


def test_takeover_moves_the_acting_autoscaler(tmp_path):
    lease = str(tmp_path / "lease")
    ea = LeaseElection(lease, "ra", lease_s=0.3)
    eb = LeaseElection(lease, "rb", lease_s=0.3)
    auto_a, slo_a, state_a, _ = _controller(election=ea)
    auto_b, slo_b, state_b, _ = _controller(election=eb)
    _feed(slo_a, 20, ok=False)
    _feed(slo_b, 20, ok=False)
    assert [d["action"] for d in auto_a.tick()] == ["scale_up_replica"]
    assert [d["action"] for d in auto_b.tick()] == ["follower_scale_up"]
    # the leader dies (no more heartbeats); the follower's next tick
    # past the lease window takes over and ACTS
    time.sleep(0.45)
    _feed(slo_b, 20, ok=False)
    db = auto_b.tick()
    assert state_b["levers"] == [("delta", 1)]
    assert [d["action"] for d in db] == ["scale_up_replica"]
    assert db[0]["role"] == "leader"
    # the election itself is on the record
    actions = [d["action"] for d in auto_b.report()["decisions"]]
    assert "election_leader" in actions
    assert auto_b.report()["election"]["role"] == "leader"


def test_predictive_queue_pressure_scales_before_breach():
    auto, slo, state, _ = _controller(queue_pressure=0.5)
    # healthy traffic, zero burn — but the admission queue is backing up
    _feed(slo, 20, ok=True)
    state["capacity"] = _capacity(queue_depth=40, queue_headroom=24)
    decisions = auto.tick()
    assert [d["action"] for d in decisions] == ["scale_up_replica"]
    d = decisions[0]
    assert d["predictive"]["signal"] == "queue"
    assert d["burn"]["burn_fast"] < auto.config.up_burn  # pre-breach
    assert state["levers"] == [("delta", 1)]


def test_predictive_forecast_scales_on_traffic_ramp():
    auto, slo, state, clock = _controller(forecast_window_s=20,
                                          forecast_horizon_s=15.0,
                                          forecast_margin=1.2)
    state["capacity"] = _capacity(busy_fraction=0.9)
    # 15 s of 1 rps, then a 100x step over the last 5 s — all healthy
    for sec in range(15):
        clock["t"] = 1000.0 + sec
        _feed(slo, 1)
    for sec in range(15, 20):
        clock["t"] = 1000.0 + sec
        _feed(slo, 100)
    clock["t"] = 1020.0
    decisions = auto.tick()
    assert [d["action"] for d in decisions] == ["scale_up_replica"]
    sig = decisions[0]["predictive"]
    assert sig["signal"] == "forecast"
    assert sig["predicted_rate"] > sig["serveable_rate"] * 1.2
    assert decisions[0]["burn"]["burn_fast"] < auto.config.up_burn


def test_predictive_scheduled_window_needs_no_traffic():
    now = time.time()
    auto, slo, state, _ = _controller(
        schedules=[{"model": "m", "start_ts": now - 1,
                    "end_ts": now + 60}])
    _feed(slo, 1)  # the model must exist in the report; no real traffic
    decisions = auto.tick()
    assert [d["action"] for d in decisions] == ["scale_up_replica"]
    assert decisions[0]["predictive"]["signal"] == "schedule"


def test_predictive_quiet_fleet_does_not_scale():
    auto, slo, state, _ = _controller()
    _feed(slo, 20, ok=True)  # healthy, no queue, flat traffic
    assert auto.tick() == []
    assert state["levers"] == []


# ==========================================================================
# breaker warm-start (satellite: a fresh router adopts the worker's verdict)
def test_fresh_router_warm_starts_breaker_from_metricsz():
    sick = _StubWorker(metricsz={"worker": "w0", "models": {
        "m": {"breaker": {"state": "OPEN", "opens_total": 3},
              "counters": {}}}})
    healthy = _StubWorker(metricsz={"worker": "w1", "models": {
        "m": {"breaker": {"state": "CLOSED", "opens_total": 0},
              "counters": {}}}})
    bare = _StubWorker()  # no metricsz at all (stub/old payload)
    try:
        router = FleetRouter(StaticFleet({"w0": sick.address,
                                          "w1": healthy.address,
                                          "w2": bare.address}),
                             hedge_enabled=False)
        router._probe_cycle()
        views = router.workers()
        assert views["w0"].breaker.state is CircuitState.OPEN
        assert views["w1"].breaker.state is CircuitState.CLOSED
        assert views["w2"].breaker.state is CircuitState.CLOSED
        # warm-start is one-shot: the verdict was adopted, not subscribed
        assert all(v.breaker_warmed for v in views.values())
        # the isolated worker is not admittable until its breaker's own
        # half-open probe path re-admits it
        assert not views["w0"].admittable()
        assert views["w1"].admittable()
    finally:
        for s in (sick, healthy, bare):
            s.stop()


# ==========================================================================
# idempotent, config-versioned rolling deploys
class _FakeDeployFleet:
    def __init__(self, endpoints):
        self._e = dict(endpoints)
        self.restarts = []

    def endpoints(self):
        return dict(self._e)

    def worker_ids(self):
        return sorted(self._e)

    def restart_worker(self, wid, archive=None, version=None):
        self.restarts.append((wid, archive, version))


def test_rolling_deploy_applies_exactly_once_across_routers(tmp_path):
    stub = _StubWorker()
    try:
        cfg_path = str(tmp_path / "fleet.json")
        config_a, config_b = FleetConfig(cfg_path), FleetConfig(cfg_path)
        fleet_a = _FakeDeployFleet({"w0": stub.address})
        fleet_b = _FakeDeployFleet({"w0": stub.address})
        ra = FleetRouter(fleet_a, hedge_enabled=False, router_id="ra")
        rb = FleetRouter(fleet_b, hedge_enabled=False, router_id="rb")
        ra.attach_config(config_a)
        rb.attach_config(config_b)
        report_a = ra.rolling_deploy("model-v2.zip", version=2,
                                     ready_timeout_s=10)
        assert fleet_a.restarts == [("w0", "model-v2.zip", 2)]
        assert "skipped" not in report_a
        # the same deploy through the OTHER router: claimed already —
        # skipped, no worker touched, the applier named
        report_b = rb.rolling_deploy("model-v2.zip", version=2,
                                     ready_timeout_s=10)
        assert report_b["skipped"] is True
        assert report_b["applied_by"]["router"] == "ra"
        assert fleet_b.restarts == []
        # the completed deploy state is in the shared config for all
        assert config_b.snapshot()["deploy"]["archive"] == "model-v2.zip"
        # a DIFFERENT version is a different action: it applies
        report_b2 = rb.rolling_deploy("model-v3.zip", version=3,
                                      ready_timeout_s=10)
        assert "skipped" not in report_b2
        assert fleet_b.restarts == [("w0", "model-v3.zip", 3)]
    finally:
        stub.stop()


# ==========================================================================
# multi-router consistency (satellite: shared-nothing routers agree)
def test_two_routers_rank_identically_and_agree_on_shed(tmp_path):
    stubs = [_StubWorker() for _ in range(4)]
    try:
        endpoints = {f"w{i}": s.address for i, s in enumerate(stubs)}
        probe_s = 0.05
        ra = FleetRouter(StaticFleet(endpoints), hedge_enabled=False,
                         probe_interval_s=probe_s, router_id="ra")
        rb = FleetRouter(StaticFleet(endpoints), hedge_enabled=False,
                         probe_interval_s=probe_s, router_id="rb")
        pa, pb = ra.start(0), rb.start(0)
        try:
            # rendezvous + placement determinism: identical orders for
            # every model name, computed independently
            for model in ("m", "alpha", "zoo/bert", "x" * 40):
                assert [v.worker_id for v in ra.ranked_workers(model)] == \
                       [v.worker_id for v in rb.ranked_workers(model)]
            # one worker sheds: each router learns from ITS OWN traffic,
            # and their shed windows agree within one probe interval
            victim = ra.ranked_workers("m")[0].worker_id
            stubs[int(victim[1:])].mode = "shed"
            body = json.dumps({"inputs": [[1.0]]}).encode()
            for port in (pa, pb):
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/models/m/predict",
                    data=body), timeout=10).read()
            now = time.monotonic()
            rem_a = ra.workers()[victim].shed_until - now
            rem_b = rb.workers()[victim].shed_until - now
            assert rem_a > 0 and rem_b > 0
            assert abs(rem_a - rem_b) <= probe_s + 0.25
            assert not ra.workers()[victim].admittable()
            assert not rb.workers()[victim].admittable()
        finally:
            ra.stop()
            rb.stop()
    finally:
        for s in stubs:
            s.stop()


def test_two_routers_serve_bit_identical_responses():
    """The same request stream through two independent routers over real
    workers returns byte-identical outputs (rendezvous agreement means
    the same worker concentration; bit-identity means a client cannot
    tell routers apart)."""
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer

    def conf():
        return (NeuralNetConfiguration.builder().seed(7).updater(None)
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=4, activation="softmax"))
                .set_input_type(InputType.feed_forward(8)).build())

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(8, 8)).astype(np.float32)
    kw = dict(max_batch_size=4, buckets=[1, 4], batch_timeout_ms=1.0,
              pipeline_depth=0)
    servers = []
    for wid in range(2):
        reg = ModelRegistry()
        reg.register("m", MultiLayerNetwork(conf()).init(),
                     warmup_example=xs[:1], **kw)
        srv = ModelServer(reg, worker_id=f"w{wid}")
        srv.start(0)
        servers.append(srv)
    endpoints = {f"w{i}": f"127.0.0.1:{s.port}"
                 for i, s in enumerate(servers)}
    ra = FleetRouter(StaticFleet(endpoints), hedge_enabled=False)
    rb = FleetRouter(StaticFleet(endpoints), hedge_enabled=False)
    pa, pb = ra.start(0), rb.start(0)
    try:
        for k in range(8):
            n, ofs = 1 + k % 4, k % 4
            outs = []
            for port in (pa, pb):
                body = json.dumps({"inputs": xs[ofs:ofs + n].tolist(),
                                   "timeout_ms": 10000}).encode()
                resp = urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/models/m/predict",
                    data=body), timeout=30)
                outs.append(np.asarray(
                    json.loads(resp.read())["outputs"], np.float32))
            assert np.array_equal(outs[0], outs[1]), \
                f"routers disagreed on request {k}"
    finally:
        ra.stop()
        rb.stop()
        for s in servers:
            s.stop(shutdown_registry=True)


# ==========================================================================
# MultiRouterClient failover (in-process routers)
def test_multi_router_client_round_robin_and_failover():
    stub = _StubWorker()
    ra = FleetRouter(StaticFleet({"w0": stub.address}),
                     hedge_enabled=False, probe_interval_s=0.05)
    rb = FleetRouter(StaticFleet({"w0": stub.address}),
                     hedge_enabled=False, probe_interval_s=0.05)
    pa, pb = ra.start(0), rb.start(0)
    client = MultiRouterClient(endpoints=[f"127.0.0.1:{pa}",
                                          f"127.0.0.1:{pb}"])
    try:
        for _ in range(6):
            status, payload = client.predict("m", [[1.0]],
                                             timeout_ms=5000)
            assert status == 200 and payload["outputs"] == [[1.0]]
        snap = client.snapshot()
        assert snap["failovers_total"] == 0
        assert set(snap["router_requests"]) == {f"127.0.0.1:{pa}",
                                                f"127.0.0.1:{pb}"}
        # one router dies: every request still lands, via failover
        ra.stop()
        for _ in range(6):
            status, payload = client.predict("m", [[1.0]],
                                             timeout_ms=5000)
            assert status == 200 and payload["outputs"] == [[1.0]]
        assert client.snapshot()["failovers_total"] >= 3
    finally:
        ra.stop()
        rb.stop()
        stub.stop()


# ==========================================================================
# subprocess router tier: SIGKILL drill through the supervisor
def test_router_supervisor_sigkill_drill_zero_client_errors(tmp_path):
    """The production topology, miniaturized: 2 supervised router
    PROCESSES over a shared config fronting stub workers. SIGKILL one
    router mid-load through a ``MultiRouterClient`` -> zero
    client-visible errors; the watchdog relaunches it within budget and
    it re-registers; the survivor's peering saw the death."""
    stubs = [_StubWorker() for _ in range(2)]
    cfg_path = str(tmp_path / "fleet.json")
    config = FleetConfig(cfg_path)
    config.set_workers({f"w{i}": s.address for i, s in enumerate(stubs)})
    specs = [RouterSpec(router_id=f"r{i}", config_path=cfg_path,
                        router_kw={"hedge_enabled": False,
                                   "probe_interval_s": 0.1})
             for i in range(2)]
    sup = RouterSupervisor(specs, run_dir=str(tmp_path / "run"),
                           max_restarts=4, heartbeat_timeout_s=60.0)
    try:
        sup.start()
        assert _wait_until(lambda: len(config.routers()) == 2,
                           timeout_s=30), "routers never registered"
        client = MultiRouterClient(config=config)
        outcomes = []
        stop = threading.Event()
        lock = threading.Lock()

        def client_loop():
            while not stop.is_set():
                try:
                    status, payload = client.predict("m", [[1.0]],
                                                     timeout_ms=8000)
                    rec = ("ok" if status == 200 and
                           payload.get("outputs") == [[1.0]]
                           else f"bad:{status}")
                except Exception as e:
                    rec = f"error:{type(e).__name__}"
                with lock:
                    outcomes.append(rec)
                time.sleep(0.01)

        threads = [threading.Thread(target=client_loop, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # steady state
        victim = sup.router_ids()[0]
        sup.kill_router(victim)
        time.sleep(1.5)  # sustained load across the death + failover
        # the watchdog relaunches the victim and it re-registers
        assert _wait_until(lambda: len(sup.endpoints()) == 2,
                           timeout_s=60), "router not relaunched"
        assert _wait_until(lambda: len(config.routers()) == 2,
                           timeout_s=30), "router never re-registered"
        sup.check()  # within the restart budget
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(30)
        bad = [o for o in outcomes if o != "ok"]
        assert outcomes and not bad, \
            f"{len(bad)}/{len(outcomes)} client-visible failures: {bad[:5]}"
        assert client.snapshot()["failovers_total"] >= 1
        # the survivor's peering observed the topology the whole time
        survivor = [r for r in sup.router_ids() if r != victim][0]
        addr = config.routers()[survivor]
        peers = json.loads(urllib.request.urlopen(
            f"http://{addr}/v1/peers", timeout=10).read())
        assert peers["router_id"] == survivor
        assert victim in peers["peers"]
    finally:
        sup.stop()
        for s in stubs:
            s.stop()
    # graceful stop deregistered both routers from the shared config
    assert _wait_until(lambda: config.routers() == {}, timeout_s=10)
