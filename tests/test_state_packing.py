"""Flat-buffer small-leaf state packing (runtime/state_packing.py).

The packed step must be bit-identical to the plain step: packing is pure
storage plumbing (the TPU analog of the reference's flat-params design —
upstream ``MultiLayerNetwork.init()`` flattening; SURVEY.md §3.1).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.nn import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.runtime.environment import get_environment
from deeplearning4j_tpu.runtime.state_packing import LeafPacker, PackedStepLoop
from deeplearning4j_tpu.train.updaters import Adam


def _tree_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _make_net(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(DenseLayer(n_out=24, activation="tanh"))
            .layer(OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12))
            .build())
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
    return MultiLayerNetwork(conf).init()


class TestLeafPacker:
    def test_roundtrip_identity(self):
        tree = {
            "a": {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.ones((7,))},
            "big": jnp.zeros((600, 600)),  # > 1 MB, stays standalone
            "c": [jnp.full((3,), 2, jnp.int32), jnp.float32(5.0)],
        }
        packer = LeafPacker(tree)
        packed = packer.pack(tree)
        _tree_equal(packer.unpack(packed), tree)
        # big leaf kept standalone; small ones packed per dtype
        assert packer.n_kept == 1
        assert packer.n_packed == 4

    def test_scalar_and_alignment(self):
        tree = {"s": jnp.int32(3), "v": jnp.arange(5.0)}
        packer = LeafPacker(tree, align=8)
        _tree_equal(packer.unpack(packer.pack(tree)), tree)

    def test_structure_mismatch_raises(self):
        tree = {"a": jnp.ones((3,))}
        packer = LeafPacker(tree)
        with pytest.raises(ValueError):
            packer.pack({"a": jnp.ones((3,)), "b": jnp.ones((2,))})

    def test_dtype_mismatch_raises(self):
        tree = {"a": jnp.ones((3,), jnp.float32)}
        packer = LeafPacker(tree)
        with pytest.raises(ValueError, match="rebuild the packer"):
            packer.pack({"a": jnp.ones((3,), jnp.bfloat16)})

    def test_handle_count_reduction(self):
        net = _make_net()
        packer = LeafPacker(net.train_state)
        packed = packer.pack(net.train_state)
        n_packed = len(jax.tree_util.tree_leaves(packed))
        n_plain = len(jax.tree_util.tree_leaves(net.train_state))
        assert n_packed < n_plain  # every small leaf collapsed into buffers


class TestPackedStepEquivalence:
    @pytest.mark.quick
    def test_packed_step_bit_identical(self):
        """N packed steps == N plain steps, bitwise, same seeds."""
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 16)]

        net_a = _make_net()
        net_b = _make_net()
        _tree_equal(net_a.train_state, net_b.train_state)

        step_a = net_a._jitted("train_step", net_a._make_train_step)
        step_b, packer = net_b._jitted_packed()
        ts = net_a.train_state
        pts = packer.pack_device(net_b.train_state)
        key = jax.random.PRNGKey(3)
        for i in range(4):
            k = jax.random.fold_in(key, i)
            ts, loss_a = step_a(ts, x, y, k, None, None)
            pts, loss_b = step_b(pts, x, y, k, None, None)
            assert float(loss_a) == float(loss_b)
        _tree_equal(ts, packer.unpack_device(pts))

    def test_fit_equivalence_packed_vs_unpacked(self):
        """fit() with packing on vs off: identical final params."""
        rng = np.random.default_rng(1)
        x = rng.normal(size=(32, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 32)]
        env = get_environment()
        prev = env.packed_state
        try:
            env.set_packed_state(True)
            net_on = _make_net().fit(x, y, epochs=3)
            env.set_packed_state(False)
            net_off = _make_net().fit(x, y, epochs=3)
        finally:
            env.packed_state = prev
        _tree_equal(net_on.train_state.params, net_off.train_state.params)
        _tree_equal(net_on.train_state.opt_state, net_off.train_state.opt_state)

    def test_fit_graph_packed(self):
        """ComputationGraph fit with packing: state stays consistent."""
        from deeplearning4j_tpu.nn.graph_vertices import ElementWiseVertex
        g = (NeuralNetConfiguration.builder()
             .seed(5)
             .updater(Adam(1e-2))
             .graph_builder()
             .add_inputs("in"))
        g.add_layer("d1", DenseLayer(n_out=16, activation="relu"), "in")
        g.add_layer("d2", DenseLayer(n_out=16, activation="relu"), "d1")
        g.add_vertex("add", ElementWiseVertex(op="add"), "d1", "d2")
        g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "add")
        g.set_outputs("out")
        from deeplearning4j_tpu.nn.inputs import InputType
        g.set_input_types(InputType.feed_forward(8))
        env = get_environment()
        prev = env.packed_state
        rng = np.random.default_rng(2)
        x = rng.normal(size=(20, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 20)]
        try:
            env.set_packed_state(True)
            from deeplearning4j_tpu.models.computation_graph import ComputationGraph
            cg_on = ComputationGraph(g.build()).init().fit(x, y, epochs=2)
            env.set_packed_state(False)
            cg_off = ComputationGraph(g.build()).init().fit(x, y, epochs=2)
        finally:
            env.packed_state = prev
        _tree_equal(cg_on.train_state.params, cg_off.train_state.params)

    def test_stateful_listener_disables_packing(self):
        from deeplearning4j_tpu.train.listeners import TrainingListener

        class Grabby(TrainingListener):
            def __init__(self):
                self.seen_steps = []

            def iteration_done(self, model, iteration, epoch, score):
                # must see a FRESH train_state every iteration
                self.seen_steps.append(int(model.train_state.step))

        net = _make_net()
        lst = Grabby()
        net.set_listeners(lst)
        rng = np.random.default_rng(3)
        x = rng.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)]
        net.fit(x, y, epochs=3)
        assert lst.seen_steps == [1, 2, 3]

    def test_stateful_listener_also_disables_grouping(self):
        """dispatch_unroll>1 + a state-reading listener: batches must still
        dispatch one at a time so iteration_done observes per-iteration
        state (grouping would show iteration 1 the weights of iteration K)."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        from deeplearning4j_tpu.train.listeners import TrainingListener

        class Grabby(TrainingListener):
            def __init__(self):
                self.seen_steps = []

            def iteration_done(self, model, iteration, epoch, score):
                self.seen_steps.append(int(model.train_state.step))

        env = get_environment()
        prev = env.dispatch_unroll
        rng = np.random.default_rng(7)
        x = rng.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)]
        try:
            env.set_dispatch_unroll(4)
            net = _make_net()
            lst = Grabby()
            net.set_listeners(lst)
            it = ListDataSetIterator([DataSet(x, y) for _ in range(4)],
                                     batch_size=8)
            net.fit(it, epochs=1)
        finally:
            env.dispatch_unroll = prev
        assert lst.seen_steps == [1, 2, 3, 4]

    def test_stateless_listener_keeps_packing(self):
        from deeplearning4j_tpu.train.listeners import CollectScoresListener
        net = _make_net()
        scores = CollectScoresListener()
        net.set_listeners(scores)
        rng = np.random.default_rng(4)
        x = rng.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)]
        net.fit(x, y, epochs=2)
        assert len(scores.scores) == 2
        # state is fresh after fit returns
        assert int(net.train_state.step) == 2


class TestPackedFitRobustness:
    def test_exception_mid_fit_preserves_progress(self):
        """An iterator error mid-fit must not lose completed packed steps."""
        from deeplearning4j_tpu.data.dataset import DataSet

        rng = np.random.default_rng(5)
        x = rng.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)]

        class ExplodingIterator:
            def __init__(self, n_good):
                self.n_good = n_good
                self._i = 0

            def reset(self):
                self._i = 0

            def __iter__(self):
                return self

            def __next__(self):
                if self._i >= self.n_good:
                    raise RuntimeError("data source died")
                self._i += 1
                return DataSet(x, y)

        net = _make_net()
        with pytest.raises(RuntimeError, match="data source died"):
            net.fit(ExplodingIterator(3), epochs=1)
        # the three completed steps survive the exception
        assert int(net.train_state.step) == 3


class TestDispatchUnroll:
    def _data(self, n_batches, seed=9):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        rng = np.random.default_rng(seed)
        batches = [DataSet(rng.normal(size=(8, 12)).astype(np.float32),
                           np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)])
                   for _ in range(n_batches)]
        return ListDataSetIterator(batches, batch_size=8)

    def test_unrolled_fit_bit_identical(self):
        """fit with dispatch_unroll=3 (incl. a partial tail group) must match
        the per-batch loop bitwise, including the listener loss sequence."""
        from deeplearning4j_tpu.train.listeners import CollectScoresListener
        env = get_environment()
        prev = env.dispatch_unroll
        try:
            nets, scores = [], []
            for k in (1, 3):
                env.set_dispatch_unroll(k)
                net = _make_net()
                coll = CollectScoresListener()
                net.set_listeners(coll)
                net.fit(self._data(7), epochs=2)  # 7 % 3 != 0: partial tail
                nets.append(net)
                scores.append([s for _, s in coll.scores])
        finally:
            env.dispatch_unroll = prev
        assert len(scores[0]) == len(scores[1]) == 14
        np.testing.assert_allclose(scores[0], scores[1], rtol=0, atol=0)
        _tree_equal(nets[0].train_state.params, nets[1].train_state.params)
        assert int(nets[1].train_state.step) == 14

    def test_exception_mid_fit_with_unroll_preserves_buffered(self):
        """Iterator death mid-epoch with dispatch_unroll>1: batches buffered
        before the exception must still train (flush in the finally)."""
        from deeplearning4j_tpu.data.dataset import DataSet

        rng = np.random.default_rng(6)
        x = rng.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)]

        class ExplodingIterator:
            def __init__(self, n_good):
                self.n_good, self._i = n_good, 0

            def reset(self):
                self._i = 0

            def __iter__(self):
                return self

            def __next__(self):
                if self._i >= self.n_good:
                    raise RuntimeError("died")
                self._i += 1
                return DataSet(x, y)

        env = get_environment()
        prev = env.dispatch_unroll
        try:
            env.set_dispatch_unroll(4)
            net = _make_net()
            with pytest.raises(RuntimeError, match="died"):
                net.fit(ExplodingIterator(3), epochs=1)  # 3 < unroll: all buffered
        finally:
            env.dispatch_unroll = prev
        assert int(net.train_state.step) == 3

    def test_unroll_with_packing_disabled_falls_back(self):
        env = get_environment()
        prev_u, prev_p = env.dispatch_unroll, env.packed_state
        try:
            env.set_dispatch_unroll(4)
            env.set_packed_state(False)
            net = _make_net().fit(self._data(5), epochs=1)
        finally:
            env.dispatch_unroll, env.packed_state = prev_u, prev_p
        assert int(net.train_state.step) == 5

    def test_raising_listener_does_not_double_train(self):
        """A listener that raises mid-group must not cause the finally-flush
        to re-dispatch already-executed batches (verified-by-execution bug:
        the group trained twice)."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        from deeplearning4j_tpu.train.listeners import TrainingListener

        class RaiseOnFirst(TrainingListener):
            needs_model_state = False

            def __init__(self):
                self.calls = 0

            def iteration_done(self, model, iteration, epoch, score):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("listener boom")

        rng = np.random.default_rng(8)
        x = rng.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)]
        env = get_environment()
        prev = env.dispatch_unroll
        try:
            env.set_dispatch_unroll(2)
            net = _make_net()
            lst = RaiseOnFirst()
            net.set_listeners(lst)
            it = ListDataSetIterator([DataSet(x, y) for _ in range(2)],
                                     batch_size=8)
            with pytest.raises(RuntimeError, match="listener boom"):
                net.fit(it, epochs=1)
        finally:
            env.dispatch_unroll = prev
        # the 2-batch group ran ONCE: step counter is 2, not 4
        assert int(net.train_state.step) == 2

    def test_graph_unrolled_fit_matches_single(self):
        """ComputationGraph fit with dispatch_unroll=3 == per-batch loop."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        from deeplearning4j_tpu.models.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.graph_vertices import ElementWiseVertex

        def build():
            g = (NeuralNetConfiguration.builder().seed(13).updater(Adam(1e-2))
                 .graph_builder().add_inputs("in"))
            g.add_layer("d1", DenseLayer(n_out=16, activation="relu"), "in")
            g.add_layer("d2", DenseLayer(n_out=16, activation="relu"), "d1")
            g.add_vertex("add", ElementWiseVertex(op="add"), "d1", "d2")
            g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "add")
            g.set_outputs("out")
            g.set_input_types(InputType.feed_forward(8))
            return ComputationGraph(g.build()).init()

        rng = np.random.default_rng(12)
        batches = [DataSet(rng.normal(size=(10, 8)).astype(np.float32),
                           np.eye(3, dtype=np.float32)[rng.integers(0, 3, 10)])
                   for _ in range(7)]
        env = get_environment()
        prev = env.dispatch_unroll
        try:
            nets = []
            for k in (1, 3):
                env.set_dispatch_unroll(k)
                net = build()
                net.fit(ListDataSetIterator(list(batches), batch_size=10),
                        epochs=2)
                nets.append(net)
        finally:
            env.dispatch_unroll = prev
        _tree_equal(nets[0].train_state.params, nets[1].train_state.params)
        assert int(nets[1].train_state.step) == 14
