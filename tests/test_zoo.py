"""Zoo instantiation + small-scale training tests (reference
``TestInstantiation`` pattern: build each model, check shapes/params, train a
step where cheap)."""

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.zoo import (Bert, Darknet19, InceptionResNetV1, LeNet,
                                    ResNet50, SimpleCNN, SqueezeNet,
                                    TextGenerationLSTM, TinyYOLO, UNet, VGG16,
                                    VGG19, Xception, YOLO2)


def test_lenet_trains():
    net = LeNet(num_classes=10).init()
    x = np.random.default_rng(0).normal(0, 1, (8, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[np.random.default_rng(1).integers(0, 10, 8)]
    net.fit(x, y, epochs=1)
    out = np.asarray(net.output(x))
    assert out.shape == (8, 10)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_resnet50_builds_and_forwards():
    net = ResNet50(num_classes=10, height=64, width=64).init()
    # bottleneck-block param sanity: 53 conv layers + bn + fc
    n = net.num_params()
    assert n > 2e7, f"ResNet50 param count too small: {n}"
    x = np.random.default_rng(0).normal(0, 1, (2, 64, 64, 3)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10)


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_resnet50_trains_a_step():
    net = ResNet50(num_classes=4, height=32, width=32).init()
    x = np.random.default_rng(0).normal(0, 1, (4, 32, 32, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[[0, 1, 2, 3]]
    net.fit(x, y, epochs=1)
    assert np.isfinite(net.score())


def test_simple_cnn_and_vgg_build():
    assert SimpleCNN(num_classes=5).init().num_params() > 1e5
    # VGG16 at reduced resolution to keep test cheap
    net = VGG16(num_classes=10, height=32, width=32).init()
    assert net.num_params() > 1e7


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_darknet_and_unet_build():
    net = Darknet19(num_classes=10, height=64, width=64).init()
    x = np.random.default_rng(0).normal(0, 1, (1, 64, 64, 3)).astype(np.float32)
    assert np.asarray(net.output(x)).shape == (1, 10)

    unet = UNet(height=32, width=32, base_filters=4, depth=2).init()
    xi = np.random.default_rng(0).normal(0, 1, (1, 32, 32, 3)).astype(np.float32)
    out = np.asarray(unet.output(xi))
    assert out.shape == (1, 32, 32, 1)


def test_textgen_lstm_tbptt():
    vocab = 20
    net = TextGenerationLSTM(vocab_size=vocab, hidden=32, layers=2,
                             tbptt_length=8).init()
    rng = np.random.default_rng(0)
    T = 24
    ids = rng.integers(0, vocab, (4, T + 1))
    x = np.eye(vocab, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(vocab, dtype=np.float32)[ids[:, 1:]]
    net.fit(x, y, epochs=1)
    assert np.isfinite(net.score())
    # stateful generation path
    step = np.asarray(net.rnn_time_step(x[:, :1]))
    assert step.shape == (4, 1, vocab)
    step2 = np.asarray(net.rnn_time_step(x[:, 1:2]))
    assert step2.shape == (4, 1, vocab)
    net.rnn_clear_previous_state()


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_bert_small_trains_with_mask():
    net = Bert.small().init()
    rng = np.random.default_rng(0)
    B, T = 4, 16
    tokens = rng.integers(0, 1000, (B, T)).astype(np.int32)
    labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, B)]
    fmask = np.ones((B, T), np.float32)
    fmask[:, 10:] = 0.0  # padding
    ds = DataSet(tokens, labels, features_mask=fmask)
    from deeplearning4j_tpu.data import ListDataSetIterator
    net.fit(ListDataSetIterator([ds]), epochs=2)
    out = np.asarray(net.output(tokens, mask=fmask))
    assert out.shape == (B, 2)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_vgg19_and_squeezenet_build():
    assert VGG19(num_classes=10, height=32, width=32).init().num_params() > 1e7
    net = SqueezeNet(num_classes=10, height=64, width=64).init()
    x = np.random.default_rng(0).normal(0, 1, (1, 64, 64, 3)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (1, 10)
    # squeezenet is small by design
    assert net.num_params() < 3e6


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_xception_builds_and_forwards():
    net = Xception(num_classes=7, height=64, width=64, middle_blocks=2).init()
    x = np.random.default_rng(0).normal(0, 1, (1, 64, 64, 3)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (1, 7)


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_inception_resnet_v1_builds_and_forwards():
    net = InceptionResNetV1(num_classes=5, height=96, width=96,
                            blocks_a=1, blocks_b=1, blocks_c=1).init()
    x = np.random.default_rng(0).normal(0, 1, (1, 96, 96, 3)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (1, 5)


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_tiny_yolo_and_yolo2():
    net = TinyYOLO(num_classes=3, height=128, width=128).init()
    x = np.random.default_rng(0).normal(0, 1, (1, 128, 128, 3)).astype(np.float32)
    out = np.asarray(net.output(x))
    # 128/32 = 4 grid, 5 anchors * (5 + 3 classes)
    assert out.shape == (1, 4, 4, 5 * 8)

    y2 = YOLO2(num_classes=3, height=128, width=128).init()
    out2 = np.asarray(y2.output(x))
    assert out2.shape == (1, 4, 4, 5 * 8)
    # train one step on a synthetic label tensor
    labels = np.zeros_like(out2)
    y2.fit(x, labels, epochs=1)
    assert np.isfinite(y2.score())


def test_remat_segments_match_plain_training_step():
    """env.remat_segments wraps single-cut DAG segments in jax.checkpoint;
    one training step must produce identical loss and parameters."""
    import jax.numpy as jnp
    import jax.random as jr
    from deeplearning4j_tpu.nn import (ActivationLayer, BatchNormalization,
                                       ConvolutionLayer, GlobalPoolingLayer,
                                       InputType, OutputLayer, PoolingType)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph_vertices import ElementWiseVertex
    from deeplearning4j_tpu.models import ComputationGraph
    from deeplearning4j_tpu.runtime.environment import get_environment

    def build():
        g = (NeuralNetConfiguration.builder().seed(3).graph_builder()
             .add_inputs("in"))
        g.add_layer("c1", ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                           convolution_mode="same",
                                           activation="identity"), "in")
        g.add_layer("b1", BatchNormalization(activation="relu"), "c1")
        g.add_layer("c2", ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                           convolution_mode="same",
                                           activation="identity"), "b1")
        g.add_vertex("add", ElementWiseVertex(op="add"), "c2", "b1")
        g.add_layer("relu", ActivationLayer(activation="relu"), "add")
        g.add_layer("pool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), "relu")
        g.add_layer("out", OutputLayer(n_out=3, activation="softmax"), "pool")
        conf = (g.set_outputs("out")
                 .set_input_types(InputType.convolutional(8, 8, 4)).build())
        return ComputationGraph(conf).init()

    x = np.random.default_rng(0).normal(0, 1, (2, 8, 8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.default_rng(1).integers(0, 3, 2)]
    env = get_environment()

    def one_step():
        net = build()
        # the residual 'b1' edge crosses the add, so cuts land after 'relu'
        assert any(len(s) > 1 for s in net._remat_segments())
        step = net._make_train_step()
        ts, loss = step(net.train_state, {"in": jnp.asarray(x)},
                        [jnp.asarray(y)], jr.PRNGKey(0), None)
        return float(loss), ts.params

    env.set_remat(False)
    l0, p0 = one_step()
    try:
        env.set_remat(True)
        l1, p1 = one_step()
    finally:
        env.set_remat(False)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_init_pretrained_loads_local_archive(tmp_path, monkeypatch):
    """`init_pretrained()` is offline-first (reference `initPretrained`
    downloads; here weights load from $DL4J_TPU_ZOO_DIR): a LeNet archive
    placed under the zoo dir restores with identical outputs, and a
    missing archive raises the documented FileNotFoundError."""
    from deeplearning4j_tpu.zoo import LeNet

    zoo = LeNet(num_classes=10)
    net = zoo.init()
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 784)).astype(np.float32)
    before = np.asarray(net.output(x))

    monkeypatch.setenv("DL4J_TPU_ZOO_DIR", str(tmp_path))
    with pytest.raises(FileNotFoundError, match="DL4J_TPU_ZOO_DIR"):
        LeNet(num_classes=10).init_pretrained()

    net.save(str(tmp_path / "lenet.zip"))
    net2 = LeNet(num_classes=10).init_pretrained()
    after = np.asarray(net2.output(x))
    np.testing.assert_allclose(before, after, rtol=1e-6)
