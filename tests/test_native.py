"""Native C++ host component tests (threshold codec, image pipeline)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.native import (ImagePipeline, ThresholdCodec,
                                       TreeCodec, get_lib)


def test_native_lib_builds():
    assert get_lib() is not None, "g++ toolchain expected in this environment"


def test_threshold_codec_roundtrip_and_residual():
    n = 1000
    codec = ThresholdCodec(n, threshold=0.1)
    rng = np.random.default_rng(0)
    grad = rng.normal(0, 0.05, n).astype(np.float32)  # mostly below threshold
    grad[:10] = 0.5
    grad[10:20] = -0.5
    encoded = codec.encode(grad)
    assert 20 <= len(encoded) <= n
    decoded = codec.decode(encoded)
    # every encoded position contributes exactly ±threshold
    assert set(np.unique(np.abs(decoded[decoded != 0]))) == {np.float32(0.1)}
    np.testing.assert_allclose(decoded[:10], 0.1)
    np.testing.assert_allclose(decoded[10:20], -0.1)
    # residual carries the remainder: 0.5 - 0.1 = 0.4
    np.testing.assert_allclose(codec.residual[:10], 0.4, rtol=1e-6)
    # repeated encoding of zeros drains the residual
    drained = decoded.copy()
    for _ in range(4):
        enc = codec.encode(np.zeros(n, np.float32))
        codec.decode(enc, drained)
    np.testing.assert_allclose(drained[:10], 0.5, rtol=1e-5)


def test_threshold_codec_matches_numpy_fallback():
    n = 512
    rng = np.random.default_rng(1)
    grad = rng.normal(0, 0.2, n).astype(np.float32)
    c_native = ThresholdCodec(n, 0.15)
    enc_native = c_native.encode(grad)
    # manual expected
    pos = grad >= 0.15
    neg = grad <= -0.15
    expected_idx = np.nonzero(pos | neg)[0]
    got_idx = np.abs(enc_native) - 1
    np.testing.assert_array_equal(np.sort(got_idx), expected_idx)


def test_bitmap_codec():
    n = 100
    codec = ThresholdCodec(n, 0.2)
    grad = np.zeros(n, np.float32)
    grad[3] = 1.0
    grad[7] = -1.0
    bm = codec.encode_bitmap(grad)
    assert bm.dtype == np.uint8 and len(bm) == 25
    out = codec.decode_bitmap(bm)
    assert out[3] == np.float32(0.2) and out[7] == np.float32(-0.2)
    assert np.count_nonzero(out) == 2


# --------------------------------------------------------- codec hardening
# ISSUE 6 satellite: hand-rolled property tests (no hypothesis in this
# environment) over seeded random cases and the edge shapes the issue
# names — empty, all-below-threshold, all-above, non-contiguous, f32/f64.

def _numpy_call(codec, method, *args):
    """Run a codec method with the native lib temporarily hidden, so the
    numpy fallback executes."""
    import deeplearning4j_tpu.native as native
    lib, native._lib = native._lib, None
    failed, native._build_failed = native._build_failed, True
    try:
        return getattr(codec, method)(*args)
    finally:
        native._lib, native._build_failed = lib, failed


_EDGE_CASES = []
for label, maker in [
    ("empty", lambda rng: np.empty(0, np.float32)),
    ("all_below", lambda rng: rng.uniform(-0.05, 0.05, 257).astype(np.float32)),
    ("all_above", lambda rng: np.where(rng.random(64) < 0.5, 1.0, -1.0)
                                .astype(np.float32)),
    ("mixed", lambda rng: rng.normal(0, 0.2, 1001).astype(np.float32)),
    ("f64", lambda rng: rng.normal(0, 0.2, 333)),  # float64 input
    ("noncontig", lambda rng: rng.normal(0, 0.2, (100, 6))
                                 .astype(np.float32)[:, ::2]),
]:
    _EDGE_CASES.append((label, maker))


@pytest.mark.parametrize("label,maker", _EDGE_CASES,
                         ids=[l for l, _ in _EDGE_CASES])
@pytest.mark.parametrize("threshold", [0.1, 0.0])
def test_codec_roundtrip_properties(label, maker, threshold):
    """Round-trip invariants on every edge shape, sparse AND bitmap, for
    both backends: (a) decoded mass + residual == input + prior residual
    (no gradient mass is created or destroyed), (b) every decoded entry
    is exactly ±threshold, (c) native and numpy backends agree bit-for-
    bit on encoding, residual and decode."""
    rng = np.random.default_rng(hash(label) % 2**31)
    grad = maker(rng)
    n = int(np.prod(grad.shape))
    as_f32 = np.ascontiguousarray(grad, np.float32).reshape(-1)

    c_nat = ThresholdCodec(n, threshold)
    c_np = ThresholdCodec(n, threshold)

    enc_nat = c_nat.encode(grad)
    enc_np = _numpy_call(c_np, "encode", grad)
    np.testing.assert_array_equal(enc_nat, enc_np)
    np.testing.assert_array_equal(c_nat.residual, c_np.residual)

    dec_nat = c_nat.decode(enc_nat)
    dec_np = _numpy_call(c_np, "decode", enc_np)
    np.testing.assert_array_equal(dec_nat, dec_np)
    # mass conservation: what was sent plus what stayed local is the input
    np.testing.assert_allclose(dec_nat + c_nat.residual, as_f32,
                               rtol=1e-6, atol=1e-6)
    sent = dec_nat[dec_nat != 0]
    if threshold > 0 and sent.size:
        assert set(np.unique(np.abs(sent))) == {np.float32(threshold)}

    # bitmap format: fresh codecs (encode mutates the residual), same
    # decoded result as the sparse format for the same input
    b_nat = ThresholdCodec(n, threshold)
    b_np = ThresholdCodec(n, threshold)
    bm_nat = b_nat.encode_bitmap(grad)
    bm_np = _numpy_call(b_np, "encode_bitmap", grad)
    np.testing.assert_array_equal(bm_nat, bm_np)
    np.testing.assert_array_equal(b_nat.residual, b_np.residual)
    np.testing.assert_array_equal(b_nat.residual, c_nat.residual)
    dbm_nat = b_nat.decode_bitmap(bm_nat)
    dbm_np = _numpy_call(b_np, "decode_bitmap", bm_np)
    np.testing.assert_array_equal(dbm_nat, dbm_np)
    np.testing.assert_array_equal(dbm_nat, dec_nat)


def test_codec_bound_bugs_rejected():
    """The hardening fixes: size-mismatched gradients, truncated bitmap
    buffers and wrong-dtype targets used to read/write out of bounds
    through the ctypes boundary — now they raise."""
    codec = ThresholdCodec(100, 0.1)
    with pytest.raises(ValueError):
        codec.encode(np.zeros(50, np.float32))      # short grad: OOB read
    with pytest.raises(ValueError):
        codec.encode(np.zeros(200, np.float32))     # long grad: silent drop
    with pytest.raises(ValueError):
        codec.encode_bitmap(np.zeros(99, np.float32))
    with pytest.raises(ValueError):
        codec.decode_bitmap(np.zeros(10, np.uint8))  # truncated buffer
    with pytest.raises(ValueError):
        codec.decode(np.asarray([1], np.int32),
                     target=np.zeros(100, np.float64))  # f64 reinterpret
    with pytest.raises(ValueError):
        codec.decode(np.asarray([1], np.int32),
                     target=np.zeros(50, np.float32))   # short target
    # invalid indices are IGNORED (C semantics), not wrapped: index 0 used
    # to decrement target[-1] through the numpy fallback
    out = _numpy_call(codec, "decode", np.asarray([0, 101, -101], np.int32))
    assert np.count_nonzero(out) == 0
    out_c = codec.decode(np.asarray([0, 101, -101], np.int32))
    np.testing.assert_array_equal(out_c, out)


def test_codec_residual_deterministic_across_processes():
    """ISSUE 6 satellite: the residual stream must be bit-deterministic
    across two FRESH processes — the property the distributed trainer's
    exact-resume and lockstep invariants stand on."""
    script = r"""
import json, sys
import numpy as np
from deeplearning4j_tpu.native import ThresholdCodec
rng = np.random.default_rng(42)
codec = ThresholdCodec(2000, 1e-3)
encs = []
for step in range(5):
    g = rng.normal(0, 0.003, 2000).astype(np.float32)
    encs.append(codec.encode(g).tolist())
print(json.dumps({"encs": encs,
                  "residual": codec.residual.tobytes().hex()}))
"""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo_root + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    outs = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        outs.append(json.loads(r.stdout))
    assert outs[0] == outs[1]


def test_tree_codec_flatten_roundtrip_and_formats():
    """TreeCodec (flat param-tree ergonomics): flatten/unflatten round-
    trips leaf shapes; the sparse-vs-bitmap choice follows the predicted
    wire size and both formats decode to the same contribution."""
    rng = np.random.default_rng(5)
    leaves = [rng.normal(0, 0.01, (64, 32)).astype(np.float32),
              rng.normal(0, 0.01, (32,)).astype(np.float32),
              rng.normal(0, 0.01, (32, 8)).astype(np.float32)]
    tc = TreeCodec(leaves, threshold=5e-3)
    flat = tc.flatten(leaves)
    assert flat.shape == (64 * 32 + 32 + 32 * 8,)
    back = tc.unflatten(flat)
    for a, b in zip(leaves, back):
        np.testing.assert_array_equal(a, b)

    # sparse wins when almost nothing clears the threshold
    sparse_grad = np.zeros(tc.size, np.float32)
    sparse_grad[:3] = 1.0
    assert tc.predicted_format(sparse_grad) == TreeCodec.FORMAT_SPARSE
    # bitmap wins when nearly everything does
    dense_grad = np.full(tc.size, 1.0, np.float32)
    tc2 = TreeCodec(leaves, threshold=5e-3)
    assert tc2.predicted_format(dense_grad) == TreeCodec.FORMAT_BITMAP

    fmt, payload = tc2.encode(dense_grad)
    assert fmt == TreeCodec.FORMAT_BITMAP
    assert len(payload) == tc2.codec.bitmap_nbytes()
    target = np.zeros(tc2.size, np.float32)
    tc2.decode_into(fmt, payload, target)
    assert np.all(target == np.float32(5e-3))
    with pytest.raises(ValueError):
        tc2.decode_into(99, payload, target)
    with pytest.raises(ValueError):
        tc.flatten(leaves[:2])


def test_image_pipeline_matches_numpy():
    pipe = ImagePipeline(n_threads=4)
    rng = np.random.default_rng(2)
    batch = rng.integers(0, 256, (8, 40, 40, 3), dtype=np.uint8)
    f = pipe.to_float(batch)
    np.testing.assert_allclose(f, batch.astype(np.float32) / 255.0, rtol=1e-6)

    mean = np.array([0.5, 0.4, 0.3], np.float32)
    std = np.array([0.2, 0.25, 0.3], np.float32)
    norm = pipe.normalize(batch, mean, std)
    expected = (batch.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(norm, expected, rtol=1e-5)


def test_random_crop_flip_deterministic():
    pipe = ImagePipeline(n_threads=2)
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, (6, 36, 36, 3), dtype=np.uint8)
    a = pipe.random_crop_flip(batch, 32, 32, seed=42)
    b = pipe.random_crop_flip(batch, 32, 32, seed=42)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (6, 32, 32, 3)
    c = pipe.random_crop_flip(batch, 32, 32, seed=43)
    assert not np.array_equal(a, c)
    # each output row must appear somewhere in the source image (crop of it)
    src_rows = {bytes(r) for r in batch[0].reshape(-1, 3 * 36)[:, :]}  # loose check
    assert a[0].shape == (32, 32, 3)
