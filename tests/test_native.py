"""Native C++ host component tests (threshold codec, image pipeline)."""

import numpy as np
import pytest

from deeplearning4j_tpu.native import ImagePipeline, ThresholdCodec, get_lib


def test_native_lib_builds():
    assert get_lib() is not None, "g++ toolchain expected in this environment"


def test_threshold_codec_roundtrip_and_residual():
    n = 1000
    codec = ThresholdCodec(n, threshold=0.1)
    rng = np.random.default_rng(0)
    grad = rng.normal(0, 0.05, n).astype(np.float32)  # mostly below threshold
    grad[:10] = 0.5
    grad[10:20] = -0.5
    encoded = codec.encode(grad)
    assert 20 <= len(encoded) <= n
    decoded = codec.decode(encoded)
    # every encoded position contributes exactly ±threshold
    assert set(np.unique(np.abs(decoded[decoded != 0]))) == {np.float32(0.1)}
    np.testing.assert_allclose(decoded[:10], 0.1)
    np.testing.assert_allclose(decoded[10:20], -0.1)
    # residual carries the remainder: 0.5 - 0.1 = 0.4
    np.testing.assert_allclose(codec.residual[:10], 0.4, rtol=1e-6)
    # repeated encoding of zeros drains the residual
    drained = decoded.copy()
    for _ in range(4):
        enc = codec.encode(np.zeros(n, np.float32))
        codec.decode(enc, drained)
    np.testing.assert_allclose(drained[:10], 0.5, rtol=1e-5)


def test_threshold_codec_matches_numpy_fallback():
    n = 512
    rng = np.random.default_rng(1)
    grad = rng.normal(0, 0.2, n).astype(np.float32)
    c_native = ThresholdCodec(n, 0.15)
    enc_native = c_native.encode(grad)
    # manual expected
    pos = grad >= 0.15
    neg = grad <= -0.15
    expected_idx = np.nonzero(pos | neg)[0]
    got_idx = np.abs(enc_native) - 1
    np.testing.assert_array_equal(np.sort(got_idx), expected_idx)


def test_bitmap_codec():
    n = 100
    codec = ThresholdCodec(n, 0.2)
    grad = np.zeros(n, np.float32)
    grad[3] = 1.0
    grad[7] = -1.0
    bm = codec.encode_bitmap(grad)
    assert bm.dtype == np.uint8 and len(bm) == 25
    out = codec.decode_bitmap(bm)
    assert out[3] == np.float32(0.2) and out[7] == np.float32(-0.2)
    assert np.count_nonzero(out) == 2


def test_image_pipeline_matches_numpy():
    pipe = ImagePipeline(n_threads=4)
    rng = np.random.default_rng(2)
    batch = rng.integers(0, 256, (8, 40, 40, 3), dtype=np.uint8)
    f = pipe.to_float(batch)
    np.testing.assert_allclose(f, batch.astype(np.float32) / 255.0, rtol=1e-6)

    mean = np.array([0.5, 0.4, 0.3], np.float32)
    std = np.array([0.2, 0.25, 0.3], np.float32)
    norm = pipe.normalize(batch, mean, std)
    expected = (batch.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(norm, expected, rtol=1e-5)


def test_random_crop_flip_deterministic():
    pipe = ImagePipeline(n_threads=2)
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, (6, 36, 36, 3), dtype=np.uint8)
    a = pipe.random_crop_flip(batch, 32, 32, seed=42)
    b = pipe.random_crop_flip(batch, 32, 32, seed=42)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (6, 32, 32, 3)
    c = pipe.random_crop_flip(batch, 32, 32, seed=43)
    assert not np.array_equal(a, c)
    # each output row must appear somewhere in the source image (crop of it)
    src_rows = {bytes(r) for r in batch[0].reshape(-1, 3 * 36)[:, :]}  # loose check
    assert a[0].shape == (32, 32, 3)
