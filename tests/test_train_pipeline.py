"""Overlapped training pipeline (ISSUE 4): sharded device prefetch, async
loss readback, step-time profiler — trajectory must stay bit-identical to
the synchronous loop, listeners must observe identical ordered callbacks,
and every background stage must die with the fit that started it."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data import NumpyDataSetIterator
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (AsyncDataSetIterator,
                                               ListDataSetIterator)
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import ParallelWrapper
from deeplearning4j_tpu.runtime.chaos import ChaosController, ChaosError, FailNth
from deeplearning4j_tpu.runtime.environment import get_environment
from deeplearning4j_tpu.train import (Adam, CollectScoresListener, Sgd,
                                      TrainingListener, TrainingProfiler)


def _conf(seed=7):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.feed_forward(8))
            .build())


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return x, y


def _params(net):
    return np.asarray(net.params()["layer_0"]["W"])


class _OrderListener(TrainingListener):
    """Records every callback with its arguments; deliberately slow in
    iteration_done so an ordering bug in the completion path would show."""

    needs_model_state = False

    def __init__(self):
        self.events = []

    def iteration_done(self, model, iteration, epoch, score):
        time.sleep(0.002)
        self.events.append(("iter", iteration, epoch, float(score)))

    def on_epoch_start(self, model, epoch):
        self.events.append(("start", epoch))

    def on_epoch_end(self, model, epoch):
        self.events.append(("end", epoch))


# --------------------------------------------------------- bit-identity
def test_mln_prefetched_fit_bit_identical():
    """MLN fit with DevicePrefetcher + async readback reproduces the
    synchronous loop's loss trajectory and final params EXACTLY."""
    x, y = _data()
    cs, cp = CollectScoresListener(), CollectScoresListener()

    ns = MultiLayerNetwork(_conf()).init()
    ns.set_listeners(cs)
    ns.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=3)

    prof = TrainingProfiler()
    np_ = MultiLayerNetwork(_conf()).init()
    np_.set_listeners(cp)
    np_.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=3,
            prefetch_buffer=3, profiler=prof)

    assert cs.scores == cp.scores  # float-exact trajectory
    assert (_params(ns) == _params(np_)).all()
    r = prof.report()
    assert r["iterations"] == 12
    assert 0.0 <= r["data_wait_fraction"] <= 1.0


def test_parallel_wrapper_prefetched_fit_bit_identical():
    """ParallelWrapper with the sharded device prefetch (builder knob) and
    async completion matches its own synchronous feed path bit-for-bit."""
    x, y = _data()
    n0 = MultiLayerNetwork(_conf()).init()
    (ParallelWrapper.builder(n0).strategy("data_parallel")
     .prefetch_buffer(0).build()
     .fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=3))

    n2 = MultiLayerNetwork(_conf()).init()
    prof = TrainingProfiler()
    (ParallelWrapper.builder(n2).strategy("data_parallel")
     .prefetch_buffer(3).build()
     .fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=3,
          profiler=prof))

    assert (_params(n0) == _params(n2)).all()
    assert prof.report()["iterations"] == 6


def test_parallel_wrapper_unrolled_dispatch_bit_identical():
    """env.dispatch_unroll > 1 routes ParallelWrapper through the unrolled
    SHARDED step (make_unrolled_step) — same trajectory as single steps."""
    x, y = _data()
    n1 = MultiLayerNetwork(_conf()).init()
    ParallelWrapper.builder(n1).build().fit(
        NumpyDataSetIterator(x, y, batch_size=32), epochs=4)

    env = get_environment()
    env.set_dispatch_unroll(2)
    try:
        n2 = MultiLayerNetwork(_conf()).init()
        ParallelWrapper.builder(n2).build().fit(
            NumpyDataSetIterator(x, y, batch_size=32), epochs=4)
    finally:
        env.set_dispatch_unroll(1)
    assert (_params(n1) == _params(n2)).all()


def test_parallel_wrapper_composes_with_async_dataset_iterator():
    """Two-stage feed: AsyncDataSetIterator (host ETL) under the
    DevicePrefetcher (device staging) — still bit-identical."""
    x, y = _data()
    n1 = MultiLayerNetwork(_conf()).init()
    (ParallelWrapper.builder(n1).prefetch_buffer(0).build()
     .fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=3))

    n2 = MultiLayerNetwork(_conf()).init()
    ait = AsyncDataSetIterator(
        NumpyDataSetIterator(x, y, batch_size=32), queue_size=2)
    try:
        (ParallelWrapper.builder(n2).prefetch_buffer(2).build()
         .fit(ait, epochs=3))
    finally:
        ait.close()
    assert (_params(n1) == _params(n2)).all()


def test_computation_graph_prefetched_fit_bit_identical():
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph

    def conf():
        return (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_out=32, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "h")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(12))
                .build())

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 12)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    cs, cp = CollectScoresListener(), CollectScoresListener()

    g1 = ComputationGraph(conf()).init()
    g1.set_listeners(cs)
    g1.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=3)
    g2 = ComputationGraph(conf()).init()
    g2.set_listeners(cp)
    g2.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=3,
           prefetch_buffer=2)

    assert cs.scores == cp.scores
    assert (np.asarray(g1.params()["h"]["W"])
            == np.asarray(g2.params()["h"]["W"])).all()


# ------------------------------------------------- async listener delivery
def test_listener_ordering_identical_under_async_readback():
    """Every callback (iteration_done / epoch start / epoch end), its
    arguments, and its ORDER must match the synchronous loop exactly, even
    with a slow listener that syncs on the score."""
    x, y = _data()
    ls, la = _OrderListener(), _OrderListener()

    ns = MultiLayerNetwork(_conf()).init()
    ns.set_listeners(ls)
    ns.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=2)

    na = MultiLayerNetwork(_conf()).init()
    na.set_listeners(la)
    na.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=2,
           prefetch_buffer=2)

    assert ls.events == la.events
    # sanity on the shape of the stream: start, 4 iters, end, per epoch
    assert ls.events[0] == ("start", 0)
    assert [e[0] for e in ls.events].count("iter") == 8


def test_listener_exception_propagates_from_async_delivery():
    """A listener raising on the completion thread must fail fit() (and
    leave no worker behind — covered by the conftest guard)."""

    class Boom(TrainingListener):
        needs_model_state = False

        def iteration_done(self, model, iteration, epoch, score):
            if iteration == 3:
                raise ValueError("listener boom")

    x, y = _data()
    net = MultiLayerNetwork(_conf()).init()
    net.set_listeners(Boom())
    with pytest.raises(ValueError, match="listener boom"):
        net.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=5,
                prefetch_buffer=2)


def test_stateful_listener_forces_synchronous_delivery():
    """A listener with needs_model_state=True must observe ITS iteration's
    post-step state — delivery happens before the next dispatch."""

    class StateReader(TrainingListener):
        needs_model_state = True  # default, explicit for the test

        def __init__(self):
            self.steps = []

        def iteration_done(self, model, iteration, epoch, score):
            self.steps.append(int(model.train_state.step))

    x, y = _data()
    net = MultiLayerNetwork(_conf()).init()
    sr = StateReader()
    net.set_listeners(sr)
    net.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=2,
            prefetch_buffer=2)
    assert sr.steps == list(range(1, 9))


# ------------------------------------------------------------ chaos drill
def test_chaos_prefetch_fetch_fails_fit_cleanly():
    """An injected train.prefetch.fetch fault must fail the fit with the
    chaos error (not a hang, not a swallowed stop) and leave no prefetch
    or delivery thread alive."""
    x, y = _data()
    net = MultiLayerNetwork(_conf()).init()
    with ChaosController(seed=3) as c:
        c.on("train.prefetch.fetch", FailNth(3))
        with pytest.raises(ChaosError, match="train.prefetch.fetch"):
            net.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=2,
                    prefetch_buffer=2)
        assert c.count("train.prefetch.fetch") == 3
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        stray = [t for t in threading.enumerate()
                 if t.name.startswith(("train-prefetch",
                                       "train-listener-delivery"))]
        if not stray:
            break
        time.sleep(0.05)
    assert not stray, f"hung pipeline threads: {[t.name for t in stray]}"


def test_chaos_prefetch_fetch_fails_parallel_wrapper_cleanly():
    x, y = _data()
    net = MultiLayerNetwork(_conf()).init()
    pw = ParallelWrapper.builder(net).prefetch_buffer(2).build()
    with ChaosController(seed=3) as c:
        c.on("train.prefetch.fetch", FailNth(2))
        with pytest.raises(ChaosError, match="train.prefetch.fetch"):
            pw.fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=2)
    # the wrapper stays usable after the drill (fresh epoch, fresh worker)
    pw.fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=1)
    assert np.isfinite(net.score())


# ------------------------------------------- AsyncDataSetIterator repairs
class _CountingIter(ListDataSetIterator):
    """Counts (and slows) base pulls so a drain-on-reset is measurable."""

    def __init__(self, datasets):
        super().__init__(datasets)
        self.pulls = 0

    def next(self):
        self.pulls += 1
        time.sleep(0.005)
        return super().next()


def _batches(n=16):
    x, y = _data(n * 4)
    return [DataSet(x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
            for i in range(n)]


def test_async_iterator_reset_stops_worker_without_draining_base():
    """reset() signals the stop event instead of pulling every remaining
    batch of the base iterator through the queue (the old reset paid the
    whole epoch's ETL to throw it away)."""
    base = _CountingIter(_batches(16))
    ait = AsyncDataSetIterator(base, queue_size=2)
    try:
        assert ait.has_next()
        ait.next()
        ait.next()
        pulled = base.pulls
        ait.reset()
        # worker restarted for the new pass; the OLD pass pulled at most
        # consumed + queue depth + 1 in-flight, nowhere near all 16
        assert base.pulls <= pulled + 4, \
            f"reset drained the base iterator ({base.pulls} pulls)"
        n = 0
        while ait.has_next():
            ait.next()
            n += 1
        assert n == 16  # fresh full pass after reset
    finally:
        ait.close()


def test_async_iterator_error_surfaces_before_buffered_batches():
    """A mid-stream worker fault surfaces on the NEXT has_next()/next(),
    discarding batches buffered behind it — not after the sentinel."""

    class FailingIter(ListDataSetIterator):
        def __init__(self, datasets, fail_at):
            super().__init__(datasets)
            self.fail_at = fail_at
            self.n = 0

        def next(self):
            self.n += 1
            if self.n == self.fail_at:
                raise RuntimeError("etl boom")
            return super().next()

    ait = AsyncDataSetIterator(FailingIter(_batches(16), fail_at=3),
                               queue_size=8)
    got = 0
    with pytest.raises(RuntimeError, match="etl boom"):
        # let the worker run ahead into the fault with batches buffered
        time.sleep(0.2)
        while ait.has_next():
            ait.next()
            got += 1
    assert got <= 2, f"error only surfaced after {got} buffered batches"
    # after the raise the iterator reports exhausted, and reset() recovers
    assert not ait.has_next()
    ait.close()


def test_async_iterator_close_is_idempotent_and_restartable():
    base = _CountingIter(_batches(8))
    ait = AsyncDataSetIterator(base, queue_size=2)
    assert ait.has_next()
    ait.close()
    ait.close()
    # reset after close starts a fresh pass
    n = 0
    while ait.has_next():
        ait.next()
        n += 1
    assert n == 8
    ait.close()
