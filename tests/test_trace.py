"""Distributed tracing + SLO telemetry (ISSUE 9): the flight recorder.

Layers under test:

- **runtime/trace.py** in isolation: span-tree correctness under
  concurrent requests, tail sampling (flagged traces always kept, healthy
  dropped at rate 0), the disabled no-op fast path (singleton, zero
  allocations attributed to trace.py), ring-buffer memory cap, Chrome
  trace-event (Perfetto) export round-trip.
- **SLOMonitor** burn-rate math against hand-computed windows (injected
  clock — no sleeping).
- **Cross-process propagation over real HTTP**: router -> worker ->
  batcher spans merged into ONE tree via the router's ``/v1/traces``
  aggregation, with bucket/replica/AOT annotations and the winner's
  bit-identity checksum; fleet-wide ``/metrics`` aggregation (summed
  counters, bucket-merged histograms, SLO burn rates).
- **The acceptance drill** over real subprocess workers: a hedged fleet
  request under the straggler-chaos schedule (plus a SIGKILL) yields one
  merged trace showing both worker attempts (loser marked discarded),
  batcher stage spans, and the stamped chaos event.
"""

import json
import os
import threading
import time
import tracemalloc
import urllib.request

import hashlib
import numpy as np
import pytest

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.runtime import chaos, trace
from deeplearning4j_tpu.runtime.chaos import AddLatency, ChaosController
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
from deeplearning4j_tpu.serving.metrics import LatencyHistogram
from deeplearning4j_tpu.serving.router import FleetRouter, StaticFleet
from deeplearning4j_tpu.serving.slo import SLOMonitor, SLOTarget


def _conf(seed=7):
    return (NeuralNetConfiguration.builder().seed(seed).updater(None)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.feed_forward(8))
            .build())


RNG = np.random.default_rng(0)
X = RNG.normal(size=(16, 8)).astype(np.float32)
BATCHER_KW = dict(max_batch_size=4, buckets=[1, 4], batch_timeout_ms=1.0,
                  pipeline_depth=0)


@pytest.fixture(autouse=True)
def _trace_isolation(request):
    """Every test starts from a known tracing state with an empty
    collector and leaves no tracing state (or env knobs) behind. Tests
    sharing the module-scoped fleet keep tracing ON (the fixture's
    servers were started under it); everything else starts disabled."""
    if "traced_fleet" in request.fixturenames:
        trace.enable(rate=1.0, capacity=512)
    else:
        trace.disable()
        trace.collector().clear()
    yield
    trace.disable()
    trace.collector().clear()
    os.environ.pop("DL4J_TPU_ACCESS_LOG", None)
    os.environ.pop("DL4J_TPU_TRACE", None)


def _post(port, name="m", n=2, timeout_ms=10000, ofs=0):
    body = json.dumps({"inputs": X[ofs:ofs + n].tolist(),
                       "timeout_ms": timeout_ms}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}/predict", data=body)
    resp = urllib.request.urlopen(req, timeout=60)
    return resp.status, dict(resp.getheaders()), json.loads(resp.read())


def _spans_named(record, name):
    return [s for s in record["spans"] if s["name"] == name]


# ==========================================================================
# span trees
def test_span_tree_structure_and_annotations():
    trace.enable(rate=1.0, capacity=16)
    with trace.span("root") as r:
        r.set("model", "m")
        with trace.span("child-a") as a:
            a.event("mark", k=1)
        with trace.span("child-b"):
            pass
    recs = trace.collector().traces()
    assert len(recs) == 1
    rec = recs[0]
    assert all(s["trace_id"] == rec["trace_id"] for s in rec["spans"])
    roots = trace.span_tree(rec)
    assert len(roots) == 1 and roots[0]["name"] == "root"
    assert roots[0]["annotations"] == {"model": "m"}
    kids = [c["name"] for c in roots[0]["children"]]
    assert kids == ["child-a", "child-b"]  # start-time ordered
    assert roots[0]["children"][0]["events"][0]["name"] == "mark"
    for s in rec["spans"]:
        assert s["duration_s"] is not None and s["duration_s"] >= 0.0


def test_span_trees_intact_under_concurrent_requests():
    """8 threads each build their own trace; contextvar isolation must
    keep every tree intact — no span leaks into a foreign trace."""
    trace.enable(rate=1.0, capacity=64)
    n_threads, n_children = 8, 3

    def worker(i):
        with trace.span(f"root-{i}"):
            for j in range(n_children):
                with trace.span(f"child-{i}-{j}"):
                    time.sleep(0.001)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    recs = trace.collector().traces()
    assert len(recs) == n_threads
    seen_roots = set()
    for rec in recs:
        roots = trace.span_tree(rec)
        assert len(roots) == 1, f"trace {rec['trace_id']} has {len(roots)} roots"
        i = int(roots[0]["name"].split("-")[1])
        seen_roots.add(i)
        names = {c["name"] for c in roots[0]["children"]}
        assert names == {f"child-{i}-{j}" for j in range(n_children)}, \
            f"trace {i} contaminated: {names}"
    assert seen_roots == set(range(n_threads))


# ==========================================================================
# tail sampling + ring + no-op path
def test_tail_sampling_keeps_flagged_drops_healthy_at_rate_zero():
    trace.enable(rate=0.0, capacity=16)
    for _ in range(5):
        with trace.span("healthy"):
            pass
    assert trace.collector().traces() == []
    assert trace.collector().dropped == 5
    # a chaos-faulted trace is stamped by the injector and kept
    with ChaosController(seed=1) as c:
        c.on("drill.point", AddLatency(0.0))
        with trace.span("faulted"):
            chaos.inject("drill.point")
    # a hedged trace is kept
    with trace.span("routed") as s:
        s.flag("hedged")
    kept = trace.collector().traces()
    assert [r["spans"][0]["name"] for r in kept] == ["faulted", "routed"]
    assert kept[0]["flags"] == ["chaos"]
    ev = kept[0]["spans"][0]["events"][0]
    assert ev["name"] == "chaos" and ev["point"] == "drill.point"
    assert kept[1]["flags"] == ["hedged"]


def test_latency_threshold_flags_slow_traces():
    trace.enable(rate=0.0, latency_threshold_ms=5.0, capacity=8)
    with trace.span("fast"):
        pass
    with trace.span("slow"):
        time.sleep(0.02)
    kept = trace.collector().traces()
    assert len(kept) == 1 and kept[0]["flags"] == ["slow"]


def test_ring_buffer_caps_memory():
    trace.enable(rate=1.0, capacity=8)
    for i in range(50):
        with trace.span(f"t{i}"):
            pass
    recs = trace.collector().traces()
    assert len(recs) == 8  # bounded regardless of traffic
    assert trace.collector().kept == 50
    # the ring holds the MOST RECENT traces, oldest first even after
    # wraparound (slots carry their insertion sequence)
    assert [r["spans"][0]["name"] for r in recs] == \
        [f"t{i}" for i in range(42, 50)]


def test_disabled_path_is_singleton_and_allocation_free():
    """The rate-0/no-op contract the serving hot path relies on: span()
    returns THE shared no-op object and a dispatch-path-shaped loop
    attributes zero live allocations to trace.py."""
    trace.disable()
    assert trace.span("a") is trace.NOOP
    assert trace.span("b") is trace.NOOP
    assert trace.current_span() is None
    assert trace.current_trace_id() is None
    assert trace.NOOP.child("c") is trace.NOOP

    def hot_loop():
        for _ in range(500):
            with trace.span("batcher.dispatch") as sp:
                sp.set("bucket", 4)
                sp.event("x")
            trace.flag_current("shed")
            trace.annotate_current("aot", "hit")
            trace.stage_event("encode", 0.01)

    hot_loop()  # warm any lazy interpreter state
    tracemalloc.start()
    hot_loop()  # and once traced: specialization/bookkeeping one-offs
    before = tracemalloc.take_snapshot()
    hot_loop()
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    # the contract is zero PER-REQUEST allocations: any leak on the
    # dispatch path would show up 500x here; a handful of one-time
    # interpreter-internal allocations (bytecode specialization) do not
    # count against it
    grown = [st for st in after.compare_to(before, "lineno")
             if st.size_diff > 0 and st.count_diff >= 100 and st.traceback
             and any(fr.filename == trace.__file__ for fr in st.traceback)]
    assert not grown, f"per-call allocations attributed to trace.py: {grown}"


# ==========================================================================
# Perfetto / Chrome trace-event export
def test_perfetto_export_round_trips():
    trace.enable(rate=1.0, capacity=8)
    with trace.span("request") as r:
        r.set("bucket", 4)
        with trace.span("dispatch") as d:
            d.event("chaos", point="p", action="latency:0.1")
    recs = trace.collector().traces()
    exported = trace.to_chrome_trace(recs)
    parsed = json.loads(json.dumps(exported))  # the round trip
    events = parsed["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert {e["name"] for e in complete} == {"request", "dispatch"}
    assert [e["name"] for e in instants] == ["dispatch:chaos"]
    req = next(e for e in complete if e["name"] == "request")
    dis = next(e for e in complete if e["name"] == "dispatch")
    src = {s["name"]: s for s in recs[0]["spans"]}
    for name, ev in (("request", req), ("dispatch", dis)):
        assert ev["ts"] == pytest.approx(src[name]["start_ts"] * 1e6)
        assert ev["dur"] == pytest.approx(src[name]["duration_s"] * 1e6)
    # parentage survives in args; the dispatch nests inside the request
    assert dis["args"]["parent_id"] == req["args"]["span_id"]
    assert req["args"]["bucket"] == 4
    # nesting holds to wall-clock anchor jitter (ts is time.time()-based,
    # dur is monotonic — allow a few ms of skew)
    slack_us = 5000.0
    assert req["ts"] - slack_us <= dis["ts"]
    assert dis["ts"] + dis["dur"] <= req["ts"] + req["dur"] + slack_us


# ==========================================================================
# SLO burn-rate math
def test_slo_burn_rate_matches_hand_computed_windows():
    clock = {"t": 1000.0}
    mon = SLOMonitor(target=SLOTarget(availability=0.99, latency_ms=100.0,
                                      latency_target=0.9),
                     windows_s=(60, 600), now_fn=lambda: clock["t"])
    # hand-built window: 100 requests, 5 unavailable; of the 95 ok, 10
    # breach the 100 ms latency objective
    for i in range(95):
        mon.record("m", ok=True, latency_s=0.2 if i < 10 else 0.05)
    for _ in range(5):
        mon.record("m", ok=False)
    w = mon.report()["m"]["windows"]
    for name in ("60s", "600s"):
        assert w[name]["requests"] == 100
        assert w[name]["availability"] == pytest.approx(0.95)
        # burn = error_rate / budget = 0.05 / 0.01
        assert w[name]["availability_burn_rate"] == pytest.approx(5.0)
        assert w[name]["latency_attainment"] == pytest.approx(1 - 10 / 95,
                                                              abs=1e-6)
        # latency burn = slow_rate / budget = (10/95) / 0.1
        assert w[name]["latency_burn_rate"] == pytest.approx(
            (10 / 95) / 0.1, abs=1e-3)
    # 2 minutes later the fast window has emptied; the slow one has not
    clock["t"] += 120
    w = mon.report()["m"]["windows"]
    assert w["60s"]["requests"] == 0
    assert w["60s"]["availability_burn_rate"] == 0.0
    assert w["600s"]["requests"] == 100
    assert w["600s"]["availability_burn_rate"] == pytest.approx(5.0)
    text = mon.render_prometheus()
    assert 'slo_availability_burn_rate{model="m",window="600s"} 5.0' in text
    assert 'slo_target_availability{model="m"} 0.99' in text


def test_slo_monitor_caps_model_cardinality():
    """Client-sent names must not grow SLO state without bound: past
    ``max_models`` distinct names, new outcomes are dropped."""
    mon = SLOMonitor(now_fn=lambda: 1000.0, max_models=3)
    for i in range(10):
        mon.record(f"m{i}", ok=True, latency_s=0.01)
    rep = mon.report()
    assert sorted(rep) == ["m0", "m1", "m2"]
    # known names keep recording under the cap
    mon.record("m1", ok=False)
    assert mon.report()["m1"]["windows"]["60s"]["requests"] == 2


def test_slo_monitor_create_gate_blocks_never_served_names():
    """The router records with ``create=(status == 200)``: a junk name
    that never served must not occupy a slot, while a tracked model's
    failures count in full."""
    mon = SLOMonitor(now_fn=lambda: 1000.0, max_models=8)
    mon.record("junk", ok=False, create=False)
    assert "junk" not in mon.report()
    mon.record("real", ok=True, latency_s=0.01, create=True)
    mon.record("real", ok=False, create=False)
    w = mon.report()["real"]["windows"]["60s"]
    assert w["requests"] == 2 and w["availability"] == pytest.approx(0.5)


def test_hedge_flag_header_keeps_worker_half_at_rate_zero():
    """Tail sampling decides per process: the router's hedge attempt
    carries ``X-Trace-Flags: hedged`` so the worker's half of the trace
    self-keeps even at rate 0 with nothing locally wrong."""
    trace.enable(rate=0.0, capacity=16)
    reg = ModelRegistry()
    reg.register("m", MultiLayerNetwork(_conf()).init(),
                 warmup_example=X[:1], **BATCHER_KW)
    srv = ModelServer(reg, worker_id="whf")
    try:
        status, _, hdrs = srv._handle_predict(
            "m", json.dumps({"inputs": X[:2].tolist()}).encode(),
            headers={"X-Trace-Id": "t-hedge", "X-Parent-Span-Id": "p1",
                     "X-Trace-Flags": "hedged"})
        assert status == 200 and hdrs["X-Trace-Id"] == "t-hedge"
        # an un-flagged healthy request on the same server is dropped
        status, _, _ = srv._handle_predict(
            "m", json.dumps({"inputs": X[:2].tolist()}).encode())
        assert status == 200
    finally:
        reg.shutdown()
    kept = trace.collector().traces()
    assert len(kept) == 1 and kept[0]["trace_id"] == "t-hedge"
    assert kept[0]["flags"] == ["hedged"]
    assert trace.collector().dropped == 1


def test_latency_histogram_merge_is_bucketwise():
    a, b = LatencyHistogram(), LatencyHistogram()
    for v in (0.001, 0.002, 0.02):
        a.observe(v)
    for v in (0.002, 0.2, 1.5):
        b.observe(v)
    merged = LatencyHistogram.from_wire(a.to_wire()).merge(
        LatencyHistogram.from_wire(b.to_wire()))
    assert merged.count == 6
    assert merged.sum == pytest.approx(a.sum + b.sum)
    assert merged.max == pytest.approx(1.5)
    # bucket merge: percentiles come from combined counts, and a
    # reference histogram fed both streams agrees exactly
    ref = LatencyHistogram()
    for v in (0.001, 0.002, 0.02, 0.002, 0.2, 1.5):
        ref.observe(v)
    for p in (50, 90, 99):
        assert merged.percentile(p) == ref.percentile(p)
    with pytest.raises(ValueError):
        LatencyHistogram(lo=1e-3).merge(LatencyHistogram())


# ==========================================================================
# cross-process propagation over real HTTP (in-process workers)
@pytest.fixture(scope="module")
def traced_fleet():
    """Two real ModelServer workers (identically seeded nets) behind a
    router; tracing at rate 1 so every trace is kept."""
    cfg = trace.enable(rate=1.0, capacity=512)
    servers, endpoints = [], {}
    for i in range(2):
        reg = ModelRegistry()
        reg.register("m", MultiLayerNetwork(_conf()).init(),
                     warmup_example=X[:1], **BATCHER_KW)
        srv = ModelServer(reg, worker_id=f"tw{i}")
        endpoints[f"tw{i}"] = f"127.0.0.1:{srv.start(0)}"
        servers.append(srv)
    router = FleetRouter(StaticFleet(endpoints), probe_interval_s=0.05,
                         hedge_initial_ms=5000.0)  # no hedging here
    port = router.start(0)
    yield router, port
    router.stop()
    for srv in servers:
        srv.stop(shutdown_registry=True)
    trace.disable()
    del cfg


def test_cross_process_propagation_over_real_http(traced_fleet):
    router, port = traced_fleet
    status, headers, _ = _post(port, n=2)
    assert status == 200
    tid = headers["X-Trace-Id"]

    def fetch():
        merged = router.aggregate_traces(tid)
        if merged and len(_spans_named(merged[0], "batcher.complete")) >= 1:
            return merged[0]
        return None

    deadline = time.monotonic() + 10
    rec = fetch()
    while rec is None and time.monotonic() < deadline:
        time.sleep(0.05)
        rec = fetch()
    assert rec is not None, "merged trace never appeared"
    # one connected tree: router.request -> router.attempt ->
    # worker.predict -> batcher stage spans
    roots = trace.span_tree(rec)
    assert len(roots) == 1 and roots[0]["name"] == "router.request"
    (attempt,) = _spans_named(rec, "router.attempt")
    assert attempt["parent_id"] == roots[0]["span_id"]
    assert attempt["annotations"]["winner"] is True
    assert len(attempt["annotations"]["body_crc32"]) == 8
    (predict,) = _spans_named(rec, "worker.predict")
    assert predict["parent_id"] == attempt["span_id"]
    assert predict["annotations"]["bucket"] == 4
    assert predict["annotations"]["replica"] == 0
    (dispatch,) = _spans_named(rec, "batcher.dispatch")
    assert dispatch["parent_id"] == predict["span_id"]
    assert dispatch["annotations"]["bucket"] == 4
    assert dispatch["annotations"]["aot"] in ("hit", "miss")
    (complete,) = _spans_named(rec, "batcher.complete")
    assert complete["annotations"]["replica"] == dispatch["annotations"]["replica"]
    # the same merge is served over HTTP, and exports chrome JSON
    via_http = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/v1/traces?trace_id={tid}",
        timeout=10).read())
    assert via_http["traces"][0]["trace_id"] == tid
    chrome = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/v1/traces?trace_id={tid}&format=chrome",
        timeout=10).read())
    assert any(e["name"] == "worker.predict"
               for e in chrome["traceEvents"])


def test_router_metrics_aggregate_fleet_wide(traced_fleet):
    router, port = traced_fleet
    base = router.slo.report().get("m", {})
    n_before = (base.get("windows", {}).get("3600s", {}) or {}).get(
        "requests", 0)
    for k in range(6):
        assert _post(port, n=1 + k % 4, ofs=k % 8)[0] == 200
    text = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                  timeout=10).read().decode()
    # fleet-wide sums: requests recorded across the fleet equal the sum of
    # the per-worker labeled series
    fleet_total = per_worker_total = 0
    for line in text.splitlines():
        if line.startswith('fleet_serving_requests_total{model="m"}'):
            fleet_total = float(line.rsplit(" ", 1)[1])
        elif line.startswith('fleet_serving_requests_total{model="m",'):
            per_worker_total += float(line.rsplit(" ", 1)[1])
    assert fleet_total >= 6
    assert fleet_total == per_worker_total
    # merged-histogram percentiles and the SLO burn rates are rendered
    assert 'fleet_serving_latency_seconds{model="m",quantile="0.99"}' in text
    assert 'slo_availability_burn_rate{model="m",window="60s"} 0.0' in text
    # the router's own (fleet-wide) monitor saw exactly this traffic
    rep = router.slo.report()["m"]["windows"]["3600s"]
    assert rep["requests"] >= n_before + 6
    assert rep["availability"] == 1.0


# ==========================================================================
# access log + crash-report correlation
def test_access_log_line_and_crash_report_carry_trace_id(capfd):
    os.environ["DL4J_TPU_ACCESS_LOG"] = "1"
    trace.enable(rate=1.0, capacity=16)
    reg = ModelRegistry()
    reg.register("m", MultiLayerNetwork(_conf()).init(),
                 warmup_example=X[:1], **BATCHER_KW)
    srv = ModelServer(reg, worker_id="wlog")
    try:
        status, _, _ = srv._handle_predict(
            "m", json.dumps({"inputs": X[:2].tolist()}).encode())
        assert status == 200
    finally:
        reg.shutdown()
    line = next(ln for ln in capfd.readouterr().err.splitlines()
                if '"dl4j_tpu_access"' in ln)
    rec = json.loads(line)
    assert rec["model"] == "m" and rec["outcome"] == 200
    assert rec["worker"] == "wlog"
    assert rec["bucket"] == 4          # stamped by the batcher stage span
    assert rec["latency_ms"] > 0
    assert rec["trace_id"]
    # crash reports join the flight recorder via the active trace id
    from deeplearning4j_tpu.runtime.crash_reporting import CrashReportingUtil
    with trace.span("train.step") as sp:
        report = CrashReportingUtil.memory_report(
            error=RuntimeError("RESOURCE_EXHAUSTED"))
        assert f"trace: {sp.trace_id}" in report
    assert "trace: -" in CrashReportingUtil.memory_report()
    # off by default: no knob, no line
    os.environ.pop("DL4J_TPU_ACCESS_LOG")
    capfd.readouterr()
    trace.emit_access_log({"model": "m"})
    assert '"dl4j_tpu_access"' not in capfd.readouterr().err


# ==========================================================================
# training step spans
def test_train_step_span_carries_exchange_stage_events():
    from deeplearning4j_tpu.train import Sgd
    from deeplearning4j_tpu.train.distributed import (DistributedConfig,
                                                      DistributedTrainer)
    trace.enable(rate=1.0, capacity=16)
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
         .list()
         .layer(DenseLayer(n_out=8, activation="relu"))
         .layer(OutputLayer(n_out=4, activation="softmax"))
         .set_input_type(InputType.feed_forward(8)).build())).init()
    tr = DistributedTrainer(net, DistributedConfig(threshold=1e-3),
                            world=2, rank=None)
    x = X[:8]
    y = np.eye(4, dtype=np.float32)[np.arange(8) % 4]
    tr.step(x, y)
    recs = [r for r in trace.collector().traces()
            if r["spans"] and r["spans"][-1]["name"] == "train.step"]
    assert recs, "no train.step trace kept"
    root = trace.span_tree(recs[-1])[0]
    assert root["annotations"]["world"] == 2
    assert root["annotations"]["rank"] == "loopback"
    stages = [e["stage"] for e in root["events"] if e["name"] == "stage"]
    # the ExchangeStats hooks stamp the full pipeline split on the span
    for stage in ("encode", "exchange", "decode", "apply"):
        assert stage in stages, (stage, stages)


# ==========================================================================
# the acceptance drill: subprocess fleet, hedge + SIGKILL + chaos stamp
def _rendezvous(model, wids):
    def score(wid):
        h = hashlib.blake2b(f"{model}|{wid}".encode(), digest_size=8)
        return int.from_bytes(h.digest(), "big")
    return sorted(wids, key=score, reverse=True)


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_hedged_sigkill_drill_yields_one_merged_trace(tmp_path):
    """ISSUE 9 acceptance: a hedged fleet request under the chaos drill
    (deterministic straggler schedule on the primary worker; SIGKILL
    after) yields ONE merged trace tree over real subprocess workers:
    router attempt spans, BOTH worker attempts with the loser marked
    discarded (bit-identical body checksum recorded on both), batcher
    stage spans with bucket/replica/AOT annotations, and the chaos event
    stamped inside the straggling worker's span."""
    from deeplearning4j_tpu.runtime.environment import get_environment
    from deeplearning4j_tpu.serving.fleet import FleetSupervisor, WorkerSpec

    a1 = str(tmp_path / "model-v1.zip")
    cache = str(tmp_path / "cache")
    MultiLayerNetwork(_conf()).init().save(a1)
    get_environment().set_compile_cache(cache)
    reg = ModelRegistry()
    reg.load("m", a1, warmup_example=X[:1], **BATCHER_KW)
    reg.shutdown()  # persists the warmup manifest next to a1

    ids = [f"w{i}" for i in range(3)]
    ranked = _rendezvous("m", ids)
    straggler = ranked[0]  # the worker every "m" request is routed to
    sig = {"__single__": {"shape_tail": [8], "dtype": "float32"}}
    os.environ["DL4J_TPU_TRACE"] = "1"  # workers inherit: keep every trace
    specs = [WorkerSpec(
        worker_id=w, model_name="m", archive=a1, version=1,
        batcher_kw=dict(BATCHER_KW), cache_dir=cache, warmup_signature=sig,
        straggle=({"p": 1.0, "ms": 400.0, "seed": 5}
                  if w == straggler else None))
        for w in ids]
    trace.enable(rate=1.0, capacity=256)
    with FleetSupervisor(specs, run_dir=str(tmp_path / "run"),
                         max_restarts=4, heartbeat_timeout_s=60.0) as sup:
        router = FleetRouter(sup, probe_interval_s=0.1,
                             hedge_initial_ms=80.0,
                             hedge_warm_count=10**9)
        port = router.start(0)
        try:
            status, headers, _ = _post(port, n=2, timeout_ms=15000)
            assert status == 200
            tid = headers["X-Trace-Id"]
            assert router.metrics.snapshot()["hedges_total"] >= 1

            def fetch():
                merged = router.aggregate_traces(tid)
                if not merged:
                    return None
                rec = merged[0]
                # wait for the LATE loser: 2 attempts and 2 worker spans
                if (len(_spans_named(rec, "router.attempt")) >= 2
                        and len(_spans_named(rec, "worker.predict")) >= 2):
                    return rec
                return None

            deadline = time.monotonic() + 20
            rec = fetch()
            while rec is None and time.monotonic() < deadline:
                time.sleep(0.1)
                rec = fetch()
            assert rec is not None, "merged hedged trace never completed"

            # ONE tree rooted at the router's request span
            roots = trace.span_tree(rec)
            assert len(roots) == 1 and roots[0]["name"] == "router.request"
            assert "hedged" in rec["flags"] and "chaos" in rec["flags"]

            attempts = _spans_named(rec, "router.attempt")
            assert len(attempts) == 2
            loser = next(a for a in attempts
                         if a["annotations"].get("discarded"))
            winner = next(a for a in attempts
                          if a["annotations"].get("winner"))
            assert loser["annotations"]["worker"] == straggler
            assert winner["annotations"]["worker"] != straggler
            # the discarded duplicate WAS bit-identical to the winner
            assert (loser["annotations"]["body_crc32"]
                    == winner["annotations"]["body_crc32"])

            predicts = _spans_named(rec, "worker.predict")
            assert {p["annotations"]["worker"] for p in predicts} == \
                {straggler, winner["annotations"]["worker"]}
            # the chaos drill stamped the straggling worker's span
            strag_span = next(p for p in predicts
                              if p["annotations"]["worker"] == straggler)
            chaos_evs = [e for e in strag_span["events"]
                         if e["name"] == "chaos"]
            assert chaos_evs and chaos_evs[0]["point"] == \
                "serving.worker.predict"
            assert chaos_evs[0]["action"].startswith("latency:")

            # batcher stage spans with bucket/replica/AOT annotations,
            # parented under each worker's predict span
            dispatches = _spans_named(rec, "batcher.dispatch")
            assert len(dispatches) >= 2
            for d in dispatches:
                assert d["annotations"]["bucket"] == 4
                assert "replica" in d["annotations"]
                assert d["annotations"]["aot"] in ("hit", "miss")
                assert d["parent_id"] in {p["span_id"] for p in predicts}
            assert len(_spans_named(rec, "batcher.complete")) >= 2

            # ---- SIGKILL leg of the drill: kill the straggler under
            # traffic; the request is still served (failover/hedge), the
            # supervisor restarts the victim within budget
            sup.kill_worker(straggler)
            status2, headers2, _ = _post(port, n=1, timeout_ms=15000)
            assert status2 == 200
            merged2 = router.aggregate_traces(headers2["X-Trace-Id"])
            assert merged2 and any(
                a["annotations"].get("winner")
                for a in _spans_named(merged2[0], "router.attempt"))
            deadline = time.monotonic() + 90
            while len(sup.endpoints()) < 3 and time.monotonic() < deadline:
                time.sleep(0.2)
            assert len(sup.endpoints()) == 3
            sup.check()
        finally:
            router.stop()
