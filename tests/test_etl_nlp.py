"""ETL (DataVec-equivalent) and NLP tests."""

import io

import numpy as np
import pytest


# ------------------------------------------------------------------- ETL
def test_csv_reader_and_transform_process():
    from deeplearning4j_tpu.data.records import (
        CSVRecordReader, LocalTransformExecutor, Schema, TransformProcess)
    csv_data = [
        "5.1,3.5,setosa",
        "6.2,2.9,versicolor",
        "7.1,3.0,virginica",
        "4.9,3.1,setosa",
    ]
    rr = CSVRecordReader().initialize(csv_data)
    schema = (Schema.builder()
              .add_column_double("sepal_len", "sepal_wid")
              .add_column_categorical("species", ["setosa", "versicolor", "virginica"])
              .build())
    tp = (TransformProcess.builder(schema)
          .categorical_to_integer("species")
          .double_math_op("sepal_len", "subtract", 5.0)
          .filter(lambda row: row["sepal_wid"] < 3.0)
          .build())
    out = LocalTransformExecutor.execute(list(rr), tp)
    assert out == [[pytest.approx(0.1), 3.5, 0],
                   [pytest.approx(2.1), 3.0, 2],
                   [pytest.approx(-0.1), 3.1, 0]]
    final = tp.final_schema()
    assert final.names == ["sepal_len", "sepal_wid", "species"]
    assert final.column("species").type.value == "integer"


def test_one_hot_and_iterator_bridge():
    from deeplearning4j_tpu.data.records import (
        CollectionRecordReader, RecordReaderDataSetIterator)
    records = [[0.5, 1.5, 0], [0.1, 0.2, 1], [0.9, 0.8, 2], [0.4, 0.3, 1]]
    it = RecordReaderDataSetIterator(CollectionRecordReader(records),
                                     batch_size=2, label_index=2, num_classes=3)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].features.shape == (2, 2)
    assert batches[0].labels.shape == (2, 3)
    np.testing.assert_allclose(batches[0].labels[0], [1, 0, 0])


def test_training_from_csv_end_to_end():
    """CSV -> TransformProcess -> iterator -> fit (the DataVec bridge path)."""
    from deeplearning4j_tpu.data.records import (
        CollectionRecordReader, RecordReaderDataSetIterator)
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train import Adam
    rng = np.random.default_rng(0)
    records = []
    for _ in range(120):
        cls = int(rng.integers(0, 2))
        x = rng.normal(cls * 2.0, 0.5, 2)
        records.append([float(x[0]), float(x[1]), cls])
    it = RecordReaderDataSetIterator(CollectionRecordReader(records),
                                     batch_size=32, label_index=2, num_classes=2)
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(5e-2)).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(2)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=20)
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.9


# ------------------------------------------------------------------- NLP
_CORPUS = [
    "the king rules the castle",
    "the queen rules the castle",
    "the king and the queen sit on thrones",
    "dogs chase cats around the garden",
    "cats chase mice around the garden",
    "the dog and the cat play in the garden",
] * 30


def test_word2vec_learns_cooccurrence():
    from deeplearning4j_tpu.nlp import Word2Vec
    w2v = (Word2Vec.builder()
           .layer_size(32).window_size(3).min_word_frequency(2)
           .negative(4).epochs(12).seed(7).learning_rate(0.05)
           .build())
    w2v.fit(_CORPUS)
    assert w2v.has_word("king") and w2v.has_word("garden")
    # words from the same topical cluster should be closer than cross-cluster
    royal = w2v.similarity("king", "queen")
    cross = w2v.similarity("king", "garden")
    assert royal > cross, f"king~queen {royal} vs king~garden {cross}"
    assert len(w2v.words_nearest("king", 3)) == 3


def test_word_vector_serializer_roundtrip(tmp_path):
    from deeplearning4j_tpu.nlp import Word2Vec, WordVectorSerializer
    w2v = Word2Vec(layer_size=16, min_word_frequency=1, epochs=2, seed=3)
    w2v.fit(_CORPUS[:20])
    path = str(tmp_path / "vectors.txt")
    w2v.save(path)
    loaded = WordVectorSerializer.load_txt(path)
    v1 = w2v.get_word_vector("castle")
    v2 = loaded.get_word_vector("castle")
    np.testing.assert_allclose(v1, v2, atol=1e-5)


def test_paragraph_vectors():
    from deeplearning4j_tpu.nlp import ParagraphVectors
    docs = (["the cat sat on the mat the cat purred"] * 5
            + ["stock markets rallied as shares rose sharply"] * 5)
    pv = ParagraphVectors(layer_size=16, min_word_frequency=1, epochs=150,
                          learning_rate=0.1, seed=5)
    pv.fit(docs)
    # nearest docs to doc0 should be the other cat docs (indices 1-4)
    near = pv.docs_nearest(0, 3)
    assert all(j < 5 for j in near), near


def test_tokenizer_preprocess():
    from deeplearning4j_tpu.nlp.tokenization import (DefaultTokenizerFactory,
                                                     TokenPreProcess)
    tf = DefaultTokenizerFactory()
    tf.set_token_pre_processor(TokenPreProcess())
    toks = tf.create("Hello, World! (test)").get_tokens()
    assert toks == ["hello", "world", "test"]


def test_word2vec_grouped_dispatch_matches_single():
    """Word2Vec.fit with dispatch_unroll=4 (the fori-grouped _ns_step_group
    path, incl. a ragged tail batch) must produce the same tables as
    per-batch dispatch."""
    import numpy as np
    from deeplearning4j_tpu.nlp import Word2Vec
    from deeplearning4j_tpu.runtime.environment import get_environment

    sents = ["the quick brown fox jumps over the lazy dog",
             "pack my box with five dozen liquor jugs",
             "the five boxing wizards jump quickly"] * 6

    def run(unroll):
        env = get_environment()
        prev = env.dispatch_unroll
        try:
            env.set_dispatch_unroll(unroll)
            w2v = Word2Vec(layer_size=16, min_word_frequency=1, epochs=2,
                           seed=3, batch_size=32)
            w2v.fit(sents)
            return np.asarray(w2v.emb_in), np.asarray(w2v.emb_out)
        finally:
            env.dispatch_unroll = prev

    a_in, a_out = run(1)
    b_in, b_out = run(4)
    np.testing.assert_array_equal(a_in, b_in)
    np.testing.assert_array_equal(a_out, b_out)
