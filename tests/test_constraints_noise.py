"""Weight constraints + weight noise (reference LayerConstraint /
IWeightNoise-DropConnect; SURVEY §2.2 dl4j-nn configuration row)."""

import numpy as np

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (DenseLayer, DropConnect, InputType,
                                   MaxNormConstraint, NeuralNetConfiguration,
                                   NonNegativeConstraint, OutputLayer,
                                   UnitNormConstraint, WeightNoise)
from deeplearning4j_tpu.train import Adam


def _data(n=64):
    rng = np.random.default_rng(0)
    y = rng.integers(0, 3, n)
    x = (np.eye(3)[y] @ rng.normal(0, 1, (3, 8)) * 3
         + rng.normal(0, .3, (n, 8))).astype(np.float32)
    return x, np.eye(3, dtype=np.float32)[y]


def _fit(layer0, epochs=3):
    x, y = _data()
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(5e-2)).list()
            .layer(layer0)
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y, epochs=epochs)
    return net


def test_max_norm_constraint_enforced_after_updates():
    net = _fit(DenseLayer(n_out=16, activation="relu",
                          constraints=[MaxNormConstraint(0.5, axes=(0,))]))
    W = np.asarray(net.params()["layer_0"]["W"])
    col_norms = np.linalg.norm(W, axis=0)
    assert (col_norms <= 0.5 + 1e-5).all(), col_norms.max()


def test_unit_norm_and_nonnegative():
    net = _fit(DenseLayer(n_out=16, activation="relu",
                          constraints=[UnitNormConstraint(axes=(0,))],
                          bias_constraints=[NonNegativeConstraint()]))
    p = net.params()["layer_0"]
    np.testing.assert_allclose(np.linalg.norm(np.asarray(p["W"]), axis=0),
                               1.0, rtol=1e-5)
    assert (np.asarray(p["b"]) >= 0).all()


def test_dropconnect_trains_and_is_deterministic_at_inference():
    from deeplearning4j_tpu.data import NumpyDataSetIterator
    # 10 epochs: dropconnect halves the effective gradient signal, and with
    # this toolchain's mask draws 5 epochs stalls at ~0.72 accuracy while 10
    # reaches 1.0 (the no-noise control fits in 5)
    net = _fit(DenseLayer(n_out=16, activation="relu",
                          weight_noise=DropConnect(p=0.7)), epochs=10)
    x, y = _data()
    out1 = np.asarray(net.output(x[:8]))
    out2 = np.asarray(net.output(x[:8]))
    np.testing.assert_array_equal(out1, out2)  # noise is train-only
    acc = net.evaluate(NumpyDataSetIterator(x, y, batch_size=64)).accuracy()
    assert acc > 0.8, acc


def test_weight_noise_gaussian_changes_training_but_not_inference():
    net = _fit(DenseLayer(n_out=16, activation="relu",
                          weight_noise=WeightNoise(stddev=0.05)), epochs=2)
    x, _ = _data()
    np.testing.assert_array_equal(np.asarray(net.output(x[:4])),
                                  np.asarray(net.output(x[:4])))


def test_constraints_json_roundtrip():
    from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=4, constraints=[MaxNormConstraint(2.0)],
                              weight_noise=DropConnect(p=0.9)))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(3)).build())
    js = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(js)
    c = conf2.layers[0].constraints[0]
    assert type(c).__name__ == "MaxNormConstraint" and c.max_norm == 2.0
    assert conf2.layers[0].weight_noise.p == 0.9
    assert conf2.to_json() == js
