"""INDArray / Nd4j facade: factory, arithmetic, in-place rebind semantics,
indexing, reductions, and jit composability."""

import numpy as np
import pytest

from deeplearning4j_tpu.ndarray import INDArray, Nd4j, NDArrayIndex

pytestmark = pytest.mark.quick


def test_factories():
    assert Nd4j.zeros(2, 3).shape() == (2, 3)
    assert Nd4j.ones(4).sum().item() == 4.0
    assert Nd4j.eye(3).get_double(1, 1) == 1.0
    a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
    assert a.shape() == (2, 2) and a.get_double(1, 0) == 3.0
    # ints are a shape
    assert Nd4j.create(2, 5).shape() == (2, 5)
    assert Nd4j.linspace(0, 1, 5).length() == 5
    assert Nd4j.value_array_of((2, 2), 7.0).mean().item() == 7.0
    Nd4j.set_seed(12345)
    r1 = Nd4j.rand(3, 3).numpy()
    Nd4j.set_seed(12345)
    r2 = Nd4j.rand(3, 3).numpy()
    np.testing.assert_array_equal(r1, r2)


def test_arithmetic_and_inplace_rebind():
    a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
    b = a.add(1.0)
    assert b.get_double(0, 0) == 2.0
    assert a.get_double(0, 0) == 1.0  # pure op didn't touch a
    a.addi(10.0)
    assert a.get_double(0, 0) == 11.0  # in-place rebinds the wrapper
    a.subi(10.0).muli(2.0).divi(2.0)
    assert a.get_double(0, 0) == 1.0
    c = a.rsub(5.0)
    assert c.get_double(0, 0) == 4.0
    # operators
    d = (a * 2.0 + 1.0 - a) / 1.0
    assert d.get_double(0, 0) == 2.0
    assert (-a).get_double(0, 1) == -2.0


def test_mmul_gemm():
    a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
    b = Nd4j.eye(2)
    np.testing.assert_allclose(a.mmul(b).numpy(), a.numpy())
    g = Nd4j.gemm(a, a, transpose_b=True)
    np.testing.assert_allclose(g.numpy(), a.numpy() @ a.numpy().T)
    assert (a @ b).equals(a)


def test_row_column_vectors():
    a = Nd4j.zeros(3, 4)
    out = a.add_row_vector(Nd4j.create([1.0, 2.0, 3.0, 4.0]))
    np.testing.assert_allclose(out.numpy()[2], [1, 2, 3, 4])
    out2 = a.add_column_vector(Nd4j.create([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(out2.numpy()[:, 0], [1, 2, 3])


def test_reductions():
    a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
    assert a.sum().item() == 10.0
    np.testing.assert_allclose(a.sum(0).numpy(), [4.0, 6.0])
    np.testing.assert_allclose(a.mean(1).numpy(), [1.5, 3.5])
    assert a.max().item() == 4.0
    assert a.arg_max(1).numpy().tolist() == [1, 1]
    assert abs(a.norm2().item() - np.sqrt(30)) < 1e-5
    assert a.std().item() == pytest.approx(np.std([1, 2, 3, 4], ddof=1))


def test_indexing_get_put():
    a = Nd4j.arange(12).reshape(3, 4)
    sub = a.get(NDArrayIndex.interval(0, 2), NDArrayIndex.point(1))
    np.testing.assert_allclose(sub.numpy(), [1.0, 5.0])
    a.put_scalar((0, 0), 99.0)
    assert a.get_double(0, 0) == 99.0
    a.put_row(1, Nd4j.create([9.0, 9.0, 9.0, 9.0]))
    np.testing.assert_allclose(a.get_row(1).numpy(), [9, 9, 9, 9])
    a.put((NDArrayIndex.all(), NDArrayIndex.point(3)), Nd4j.create([7.0, 7.0, 7.0]))
    np.testing.assert_allclose(a.get_column(3).numpy(), [7, 7, 7])
    # functional: slices are copies, mutating the copy leaves parent intact
    row = a.get_row(0)
    row.addi(100.0)
    assert a.get_double(0, 1) != row.get_double(1)


def test_shape_ops():
    a = Nd4j.arange(24).reshape(2, 3, 4)
    assert a.permute(2, 0, 1).shape() == (4, 2, 3)
    assert a.swap_axes(0, 2).shape() == (4, 3, 2)
    assert a.ravel().shape() == (24,)
    assert a.slice(1).shape() == (3, 4)
    t = a.tensor_along_dimension(0, 1, 2)
    assert t.shape() == (3, 4)
    np.testing.assert_allclose(t.numpy(), a.numpy()[0])


def test_concat_stack_io(tmp_path):
    a, b = Nd4j.ones(2, 2), Nd4j.zeros(2, 2)
    assert Nd4j.vstack(a, b).shape() == (4, 2)
    assert Nd4j.hstack(a, b).shape() == (2, 4)
    assert Nd4j.concat(1, a, b).shape() == (2, 4)
    assert Nd4j.stack(0, a, b).shape() == (2, 2, 2)
    assert Nd4j.to_flattened(a, b).length() == 8
    p = str(tmp_path / "arr")
    Nd4j.write(a, p)
    back = Nd4j.read(p)
    assert back.equals(a)


def test_comparisons_where_sort():
    a = Nd4j.create([3.0, 1.0, 2.0])
    assert a.gt(1.5).numpy().tolist() == [True, False, True]
    w = Nd4j.where(a.gt(1.5), a, Nd4j.zeros(3))
    np.testing.assert_allclose(w.numpy(), [3.0, 0.0, 2.0])
    np.testing.assert_allclose(Nd4j.sort(a).numpy(), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(Nd4j.sort(a, ascending=False).numpy(), [3.0, 2.0, 1.0])


def test_jit_composability():
    """INDArray methods trace under jit — the facade never blocks compile."""
    import jax

    @jax.jit
    def f(x):
        a = INDArray(x)
        return a.mul(2.0).add(1.0).sum().array

    out = f(np.ones((4, 4), np.float32))
    assert float(out) == 4 * 4 * 2 + 16


def test_exec_named_op():
    a = Nd4j.create([[1.0, -2.0]])
    out = Nd4j.exec("relu", a)
    np.testing.assert_allclose(out.numpy(), [[1.0, 0.0]])


def test_transforms_and_boolean_indexing():
    """Reference Transforms / Conditions / BooleanIndexing API family."""
    from deeplearning4j_tpu.ndarray import (BooleanIndexing, Conditions,
                                            Nd4j, Transforms)
    a = Nd4j.create(np.array([[1.0, -2.0], [3.0, -4.0]], np.float32))
    np.testing.assert_allclose(Transforms.sigmoid(a).numpy(),
                               1 / (1 + np.exp(-a.numpy())), rtol=1e-6)
    np.testing.assert_allclose(Transforms.unit_vec(a).numpy(),
                               a.numpy() / np.linalg.norm(a.numpy()), rtol=1e-6)
    assert abs(Transforms.euclidean_distance(a.get_row(0), a.get_row(1))
               - np.linalg.norm([1 - 3, -2 + 4])) < 1e-6
    assert abs(Transforms.cosine_sim(a.get_row(0), a.get_row(0)) - 1.0) < 1e-6
    sims = Transforms.all_cosine_similarities(a, a.get_row(1)).numpy()
    assert abs(sims[1] - 1.0) < 1e-6

    b = a.dup()
    b.replace_where(0.0, Conditions.less_than(0))
    np.testing.assert_array_equal(b.numpy(), [[1, 0], [3, 0]])
    assert BooleanIndexing.or_(a, Conditions.less_than(-3))
    assert not BooleanIndexing.and_(a, Conditions.greater_than(0))


def test_number_reductions_and_misc():
    from deeplearning4j_tpu.ndarray import Nd4j
    a = Nd4j.create(np.array([[1.0, -2.0], [3.0, -4.0]], np.float32))
    assert a.max_number() == 3.0 and a.min_number() == -4.0
    assert a.sum_number() == -2.0 and abs(a.mean_number() + 0.5) < 1e-6
    assert a.amax().item() == 4.0 and a.arg_min().item() == 3
    assert a.norm_max_number() == 4.0
    np.testing.assert_array_equal(a.get_rows(1, 0).numpy(), [[3, -4], [1, -2]])
    np.testing.assert_array_equal(a.get_columns(1).numpy(), [[-2], [-4]])
    np.testing.assert_array_equal(a.is_nan().numpy(), [[False] * 2] * 2)
    assert a.like().sum_number() == 0.0
    np.testing.assert_array_equal(a.diag().numpy(), [1.0, -4.0])
    assert a.pad((1, 1), (0, 0)).shape() == (4, 2)
    assert a.to_int_vector() == [1, -2, 3, -4]


def test_round3_surface_tier():
    """Round-3 INDArray additions: in-place reshape family, predicates,
    vector-op completions, where-family, distances, index helpers."""
    a = Nd4j.create(np.arange(12, dtype=np.float32).reshape(3, 4))
    # in-place reshape family rebinds the wrapper
    b = a.dup().permutei(1, 0)
    assert b.shape() == (4, 3)
    assert a.dup().transposei().shape() == (4, 3)
    assert a.dup().reshapei(4, 3).shape() == (4, 3)
    assert a.dup().raveli().shape() == (12,)
    # predicates
    assert Nd4j.create(np.ones((1, 5), np.float32)).is_row_vector()
    assert Nd4j.create(np.ones((5, 1), np.float32)).is_column_vector()
    assert Nd4j.eye(3).is_square() and not a.is_square()
    assert a.ordering() == "c" and a.offset() == 0
    assert a.stride() == (4, 1)
    # broadcasting helpers
    assert a.get_row(0).broadcast_to(3, 4).shape() == (3, 4)
    assert a.repmat(2, 1).shape() == (6, 4)
    assert a.sub_array((1, 1), (2, 2)).shape() == (2, 2)
    np.testing.assert_allclose(a.sub_array((1, 1), (2, 2)).numpy(),
                               np.arange(12).reshape(3, 4)[1:3, 1:3])
    # where family
    w = a.dup().put_where(a.numpy() > 5, 0.0)
    assert w.numpy().max() == 5
    g = a.get_where(a.numpy() > 5, default=-1.0)
    assert (g.numpy() == -1).sum() == 6
    # row/col in-place completions
    r = np.array([1, 2, 3, 4], np.float32)
    np.testing.assert_allclose(a.dup().subi_row_vector(r).numpy(),
                               a.numpy() - r)
    np.testing.assert_allclose(a.dup().divi_row_vector(r).numpy(),
                               a.numpy() / r)
    np.testing.assert_allclose(a.dup().rsubi_row_vector(r).numpy(),
                               r - a.numpy())
    c = np.array([1, 2, 4], np.float32)
    np.testing.assert_allclose(a.dup().addi_column_vector(c).numpy(),
                               a.numpy() + c[:, None])
    np.testing.assert_allclose(a.dup().divi_column_vector(c).numpy(),
                               a.numpy() / c[:, None])
    # distances / stats
    z = Nd4j.zeros(3, 4)
    assert a.squared_distance(z) == pytest.approx((np.arange(12) ** 2).sum())
    assert a.distance1(z) == pytest.approx(np.arange(12).sum())
    assert a.median_number() == pytest.approx(5.5)
    assert a.percentile_number(50) == pytest.approx(5.5)
    assert a.norm_max().item() == 11
    # index helpers
    assert a.max_index() == 11 and a.min_index() == 0
    assert a.vectors_along_dimension(1) == 3
    assert a.tensors_along_dimension(0) == 4
    # misc
    np.testing.assert_allclose(a.dup().cumsumi(0).numpy(),
                               np.cumsum(a.numpy(), 0))
    np.testing.assert_allclose(a.cumprod(1).numpy(),
                               np.cumprod(a.numpy(), 1))
    assert (a.gt(5)).any() and not (a.gt(100)).any()
    assert a.gte(0).all() and a.gt(100).none()
    np.testing.assert_allclose(a.fmod(5.0).numpy(), np.fmod(a.numpy(), 5.0))
    assert a.detach() is a and a.leverage_to(None) is a


def test_round3_factory_tier():
    a = Nd4j.create(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert Nd4j.zeros_like(a).numpy().sum() == 0
    assert Nd4j.ones_like(a).numpy().sum() == 6
    assert (Nd4j.full((2, 2), 7.0).numpy() == 7).all()
    assert Nd4j.empty().length() == 0
    r = Nd4j.rand_int(10, 4, 5)
    assert r.shape() == (4, 5) and (r.numpy() >= 0).all() and (r.numpy() < 10).all()
    s = Nd4j.shuffle(a)
    assert sorted(map(tuple, s.numpy().tolist())) == sorted(map(tuple, a.numpy().tolist()))
    c = Nd4j.choice(a, 10)
    assert c.shape() == (10,) and set(c.numpy()) <= set(a.numpy().ravel())
    ap = Nd4j.append(a, 2, -1.0, axis=1)
    assert ap.shape() == (2, 5) and (ap.numpy()[:, 3:] == -1).all()
    pp = Nd4j.prepend(a, 1, 0.0, axis=0)
    assert pp.shape() == (3, 3) and (pp.numpy()[0] == 0).all()
    np.testing.assert_allclose(Nd4j.rot90(a).numpy(), np.rot90(a.numpy()))
    np.testing.assert_allclose(Nd4j.flip(a, 1).numpy(), a.numpy()[:, ::-1])
    np.testing.assert_allclose(Nd4j.diag(Nd4j.create(np.array([1.0, 2.0]))).numpy(),
                               np.diag([1.0, 2.0]))
    v = Nd4j.diag(a.get(NDArrayIndex.interval(0, 2), NDArrayIndex.interval(0, 2)))
    assert v.shape() == (2,)
    np.testing.assert_allclose(Nd4j.repeat(a, 2, axis=0).numpy(),
                               np.repeat(a.numpy(), 2, 0))
    assert Nd4j.tile(a, 2, 1).shape() == (4, 3)
    np.testing.assert_allclose(Nd4j.cumsum(a, 1).numpy(), np.cumsum(a.numpy(), 1))


def test_get_where_with_mask_and_eps():
    import numpy as np

    from deeplearning4j_tpu.ndarray import INDArray
    a = INDArray(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    mask = np.array([[1, 0], [0, 1]], np.float32)
    got = np.asarray(a.get_where_with_mask(mask, default=-1.0).array)
    np.testing.assert_array_equal(got, [[1.0, -1.0], [-1.0, 4.0]])
    b = np.array([[1.0 + 5e-6, 2.1], [3.0, 4.0 - 1e-7]], np.float32)
    e = np.asarray(a.eps(b).array)
    np.testing.assert_array_equal(e, [[1.0, 0.0], [1.0, 1.0]])
