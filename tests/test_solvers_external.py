"""Legacy solvers (LBFGS/CG/line-search — reference
org.deeplearning4j.optimize.solvers) and external-errors mode (reference
MultiLayerNetwork backpropGradient(epsilon) / feedForwardToLayer /
rnnActivateUsingStoredState)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (DenseLayer, InputType, LSTM, OutputLayer,
                                   RnnOutputLayer)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.train.updaters import Sgd


@pytest.mark.parametrize("algo,factor", [("LBFGS", 0.2),
                                         ("CONJUGATE_GRADIENT", 0.5),
                                         ("LINE_GRADIENT_DESCENT", 0.8)])
def test_second_order_solvers_reduce_loss(algo, factor):
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (64, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    conf = (NeuralNetConfiguration.builder().seed(0)
            .optimization_algo(algo).max_num_line_search_iterations(8).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(DataSet(x, y))
    for _ in range(5):
        net.fit(x, y)
    assert net.score(DataSet(x, y)) < s0 * factor


def test_external_errors_gradient_and_training():
    rng = np.random.default_rng(0)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(DenseLayer(n_out=4, activation="identity"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    x = jnp.asarray(rng.normal(0, 1, (16, 6)), jnp.float32)
    target = jnp.asarray(rng.normal(0, 1, (16, 4)), jnp.float32)

    out = net.output(x)
    eps = 2 * (out - target) / out.size
    _, gx = net.backprop_gradient(x, eps)

    def loss_of_x(xx):
        return jnp.mean((net.output(xx) - target) ** 2)
    gx_ref = jax.grad(loss_of_x)(x)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-5)

    l0 = float(loss_of_x(x))
    # 100 steps: plain SGD(0.1) from this init needs ~100 steps to halve the
    # loss (verified against a hand-rolled jax.grad SGD oracle, which
    # fit_external matches bit-for-bit step by step)
    for _ in range(100):
        out = net.output(x)
        net.fit_external(x, 2 * (out - target) / out.size)
    assert float(loss_of_x(x)) < l0 * 0.5


def test_feed_forward_to_layer_and_rnn_stored_state():
    rng = np.random.default_rng(0)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(DenseLayer(n_out=4, activation="identity"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    x = jnp.asarray(rng.normal(0, 1, (16, 6)), jnp.float32)
    acts = net.feed_forward_to_layer(0, x)
    assert len(acts) == 2 and acts[1].shape == (16, 8)

    conf2 = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.01)).list()
             .layer(LSTM(n_out=8, n_in=5))
             .layer(RnnOutputLayer(n_out=3))
             .set_input_type(InputType.recurrent(5, 4)).build())
    net2 = MultiLayerNetwork(conf2).init()
    xs = jnp.asarray(rng.normal(0, 1, (2, 4, 5)), jnp.float32)
    o1 = net2.rnn_activate_using_stored_state(xs)
    o2 = net2.rnn_activate_using_stored_state(xs)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)
    net2.rnn_activate_using_stored_state(xs, store_last_for_tbptt=True)
    o3 = net2.rnn_activate_using_stored_state(xs)
    assert not np.allclose(np.asarray(o1), np.asarray(o3))


def test_solver_respects_frozen_layers_and_updates_bn_state():
    from deeplearning4j_tpu.nn import BatchNormalization
    rng = np.random.default_rng(0)
    x = rng.normal(2.0, 3.0, (64, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    frozen_dense = DenseLayer(n_out=16, activation="tanh")
    frozen_dense.frozen = True
    conf = (NeuralNetConfiguration.builder().seed(0)
            .optimization_algo("LBFGS").list()
            .layer(frozen_dense)
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    import jax as _jax
    w0 = np.asarray(net.train_state.params["layer_0"]["W"])
    bn0 = np.asarray(net.train_state.model_state["layer_1"]["mean"])
    net.fit(x, y)
    w1 = np.asarray(net.train_state.params["layer_0"]["W"])
    bn1 = np.asarray(net.train_state.model_state["layer_1"]["mean"])
    np.testing.assert_array_equal(w0, w1)          # frozen layer untouched
    assert not np.allclose(bn0, bn1)               # BN running stats moved


def test_graph_solver_and_external_errors():
    from deeplearning4j_tpu.models import ComputationGraph
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (32, 5)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]

    # graph LBFGS
    g = (NeuralNetConfiguration.builder().seed(0)
         .optimization_algo("LBFGS").graph_builder().add_inputs("in"))
    g.add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax"), "d")
    conf = g.set_outputs("out").set_input_types(InputType.feed_forward(5)).build()
    net = ComputationGraph(conf).init()
    from deeplearning4j_tpu.data.dataset import DataSet
    s0 = net.score(DataSet(x, y))
    net.fit(x, y)
    assert net.score(DataSet(x, y)) < s0 * 0.5

    # graph external errors (no loss layer): LossLayer-free head
    g2 = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
          .graph_builder().add_inputs("in"))
    g2.add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "in")
    g2.add_layer("d2", DenseLayer(n_out=3, activation="identity"), "d1")
    conf2 = g2.set_outputs("d2").set_input_types(InputType.feed_forward(5)).build()
    net2 = ComputationGraph(conf2).init()
    target = jnp.asarray(rng.normal(0, 1, (32, 3)), jnp.float32)
    xj = jnp.asarray(x)

    def loss_now():
        return float(jnp.mean((net2.output(xj) - target) ** 2))

    out = net2.output(xj)
    eps = 2 * (out - target) / out.size
    gp, gin = net2.backprop_gradient({"in": xj}, [eps])
    gx_ref = jax.grad(lambda xx: jnp.mean((net2.output(xx) - target) ** 2))(xj)
    np.testing.assert_allclose(np.asarray(gin["in"]), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-5)

    l0 = loss_now()
    for _ in range(60):
        out = net2.output(xj)
        net2.fit_external({"in": xj}, [2 * (out - target) / out.size])
    assert loss_now() < l0 * 0.9
