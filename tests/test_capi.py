"""C language bindings (native/capi.cpp): a real C host program embeds the
Python runtime via the flat C API, loads a saved model, runs inference and
one fit step, and its outputs must match the in-process values.

Parity row: reference language bindings ([U] jumpy/ pydl4j/ nd4s) — bridges
between the JVM core and other languages; here the direction inverts
(C/C++ host -> Python/JAX core).
"""

import os
import shutil
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.serializer import ModelSerializer
from deeplearning4j_tpu.nn import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.train.updaters import Adam

C_CLIENT = r"""
#include <stdio.h>
#include <stdlib.h>
#include "dl4j_tpu_c.h"

int main(int argc, char **argv) {
  /* argv: model.zip  n_in  n_out */
  char err[512];
  if (dl4jtpu_init(NULL) != 0) {
    dl4jtpu_last_error(err, sizeof err);
    fprintf(stderr, "init failed: %s\n", err);
    return 2;
  }
  int h = dl4jtpu_load(argv[1]);
  if (h < 0) {
    dl4jtpu_last_error(err, sizeof err);
    fprintf(stderr, "load failed: %s\n", err);
    return 3;
  }
  int n_in = atoi(argv[2]), n_out = atoi(argv[3]);
  float *x = (float *)malloc(4 * n_in * sizeof(float));
  for (int i = 0; i < 4 * n_in; ++i) x[i] = (float)((i * 37 % 101) - 50) / 50.0f;
  int64_t shape[2] = {4, n_in};
  float *out = (float *)malloc(4 * n_out * sizeof(float));
  int64_t oshape[8]; int orank = 0;
  int64_t n = dl4jtpu_output(h, x, shape, 2, out, 4 * n_out, oshape, &orank);
  if (n != 4 * n_out) {
    dl4jtpu_last_error(err, sizeof err);
    fprintf(stderr, "output failed (%lld): %s\n", (long long)n, err);
    return 4;
  }
  printf("OUT");
  for (int i = 0; i < n; ++i) printf(" %.6f", out[i]);
  printf("\n");
  printf("OSHAPE %d %lld %lld\n", orank, (long long)oshape[0], (long long)oshape[1]);

  /* one fit step on a fixed batch */
  float *y = (float *)calloc(4 * n_out, sizeof(float));
  for (int i = 0; i < 4; ++i) y[i * n_out + (i % n_out)] = 1.0f;
  int64_t yshape[2] = {4, n_out};
  double score = dl4jtpu_fit(h, x, shape, 2, y, yshape, 2);
  if (score != score) {
    dl4jtpu_last_error(err, sizeof err);
    fprintf(stderr, "fit failed: %s\n", err);
    return 5;
  }
  printf("SCORE %.6f\n", score);
  if (dl4jtpu_save(h, argv[4]) != 0) {
    dl4jtpu_last_error(err, sizeof err);
    fprintf(stderr, "save failed: %s\n", err);
    return 6;
  }
  dl4jtpu_close(h);
  return 0;
}
"""


def _toolchain():
    return shutil.which("gcc") or shutil.which("g++")


@pytest.mark.skipif(_toolchain() is None, reason="no C toolchain")
def test_c_host_program_drives_model(tmp_path):
    from deeplearning4j_tpu.native import build_capi
    lib = build_capi()
    if lib is None:
        pytest.skip("C API build unavailable (no libpython dev files)")

    n_in, n_out = 6, 3
    conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    net = MultiLayerNetwork(conf).init()
    model_zip = str(tmp_path / "model.zip")
    ModelSerializer.write_model(net, model_zip)

    # compile the C client against the public header
    src = tmp_path / "client.c"
    src.write_text(C_CLIENT)
    exe = str(tmp_path / "client")
    hdr_dir = os.path.join(os.path.dirname(lib))
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    subprocess.run(
        [_toolchain(), "-o", exe, str(src), f"-I{hdr_dir}", lib,
         f"-Wl,-rpath,{hdr_dir}", f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True)

    # run it as a separate process (embedded interpreter, CPU backend)
    env = dict(os.environ)
    site = sysconfig.get_paths()["purelib"]  # the venv's site-packages
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__))), site,
         env.get("PYTHONPATH", "")])
    env["JAX_PLATFORMS"] = "cpu"
    # embedded interpreters need the BASE prefix (a venv prefix has no
    # stdlib); the venv's packages come in through PYTHONPATH above
    env["PYTHONHOME"] = sys.base_prefix
    saved_zip = str(tmp_path / "model_after_fit.zip")
    proc = subprocess.run([exe, model_zip, str(n_in), str(n_out), saved_zip],
                          capture_output=True, text=True, env=env, timeout=300)
    assert proc.returncode == 0, f"stderr: {proc.stderr[-2000:]}"
    lines = dict()
    for ln in proc.stdout.splitlines():
        k, _, rest = ln.partition(" ")
        lines[k] = rest
    assert "OUT" in lines and "SCORE" in lines

    # the C client's inference must match the in-process forward. Tolerance
    # note: this pytest process runs under --xla_force_host_platform_
    # device_count=8 while the embedded client compiles for the default CPU
    # topology; XLA partitions f32 reductions differently, giving ~1e-3
    # relative reduction-order drift (verified: a plain-python subprocess
    # without the flag matches the C client bit-for-bit).
    x = ((np.arange(4 * n_in) * 37 % 101) - 50).astype(np.float32) / 50.0
    x = x.reshape(4, n_in)
    expect = np.asarray(net.output(x)).ravel()
    got = np.asarray([float(v) for v in lines["OUT"].split()], np.float32)
    np.testing.assert_allclose(got, expect, rtol=5e-3, atol=1e-4)
    assert (got.reshape(4, n_out).argmax(-1)
            == expect.reshape(4, n_out).argmax(-1)).all()
    assert lines["OSHAPE"].split() == ["2", "4", str(n_out)]

    # its fit step must have moved the params: the saved archive differs
    # from the original and reloads into a working network
    net2 = ModelSerializer.restore_model(saved_zip)
    p_old = np.asarray(net.train_state.params["layer_0"]["W"])
    p_new = np.asarray(net2.train_state.params["layer_0"]["W"])
    assert not np.allclose(p_old, p_new)
    score = float(lines["SCORE"])
    assert np.isfinite(score) and score > 0
