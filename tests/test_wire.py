"""Binary wire transport (ISSUE 18): codec, negotiation, pools, shm.

Layers:

- **Codec** — frame round-trips (single/multi tensor, int8, fields and
  timeout carry), and the damage drills: corruption, truncation, and
  bit flips — manual and via the ``serving.wire.frame`` chaos byte
  point — are all counted :class:`WireProtocolError`s, never a tensor.
- **Negotiation matrix** — binary client ↔ JSON-only worker (router
  transcodes, caches the 415 verdict), JSON client ↔ binary worker,
  mid-stream downgrade when a worker stops speaking binary, and a
  hedged request whose two attempts ride different protocols yet the
  winner is bit-identical.
- **Pools** — keep-alive reuse, retry-once on a stale parked
  connection, breaker-open and worker-restart invalidation, and no fd
  leak under the conftest ``fd_guard``.
- **Zero-copy + shm** — binary rows land read-only in the batcher
  (``serving_zero_copy_rows_total``), the shared-memory fast path
  round-trips and releases its segments, and a chaos-corrupted shm
  frame is retried inline (``router_shm_fallbacks_total``) with a
  correct answer.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.runtime import chaos, journal
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer, wire
from deeplearning4j_tpu.serving.resilience import CircuitState
from deeplearning4j_tpu.serving.router import (FleetRouter, StaticFleet,
                                               _Attempt)


def _conf(seed=7):
    return (NeuralNetConfiguration.builder().seed(seed).updater(None)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.feed_forward(8))
            .build())


RNG = np.random.default_rng(0)
X = RNG.normal(size=(16, 8)).astype(np.float32)
BATCHER_KW = dict(max_batch_size=4, buckets=[1, 4], batch_timeout_ms=1.0,
                  pipeline_depth=0)


def _wait_ready(router, n, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        ws = router.workers()
        if len(ws) >= n and all(v.ready for v in ws.values()):
            return
        time.sleep(0.02)
    raise AssertionError("workers never became ready")


@pytest.fixture(scope="module")
def duo():
    """One wire-enabled and one JSON-only worker over identically seeded
    nets, plus the oracle output for X[:4] (bucket 4 = exact)."""
    servers, registries, endpoints = [], [], {}
    for i, wire_enabled in enumerate((True, False)):
        reg = ModelRegistry()
        reg.register("m", MultiLayerNetwork(_conf()).init(),
                     warmup_example=X[:1], **BATCHER_KW)
        srv = ModelServer(reg, worker_id=f"w{i}", wire_enabled=wire_enabled)
        endpoints[f"w{i}"] = f"127.0.0.1:{srv.start(0)}"
        servers.append(srv)
        registries.append(reg)
    ref = np.asarray(registries[0].predict("m", X[:4]))
    yield endpoints, registries, servers, ref
    for srv in servers:
        srv.stop(shutdown_registry=True)


def _predict_wire(pool, port, frame, timeout=60):
    return pool.request(f"127.0.0.1:{port}", "POST", "/v1/models/m/predict",
                        body=frame,
                        headers={"Content-Type": wire.CONTENT_TYPE},
                        timeout=timeout)


def _decode_any(headers, data):
    """Decode a predict response on either protocol into an f32 array."""
    ctype = next((v for k, v in headers.items()
                  if k.lower() == "content-type"), "")
    if ctype.split(";")[0].strip() == wire.CONTENT_TYPE:
        _, _, out, fr = wire.decode_predict_response(data)
        try:
            return np.array(out)
        finally:
            out = None
            fr.close()
    return np.asarray(json.loads(data)["outputs"], dtype=np.float32)


# ==========================================================================
# codec
def test_frame_roundtrip_single_multi_and_int8():
    x = X[:3]
    raw = wire.encode_predict_request(x, timeout_ms=1234,
                                      headers={"X-Request-Id": "r-1"})
    got, timeout_ms, fields, fr = wire.decode_predict_request(raw)
    assert timeout_ms == 1234
    assert fields["request_id"] == "r-1"
    assert got.dtype == np.float32 and got.tobytes() == x.tobytes()
    assert not got.flags.writeable        # zero-copy view over the frame
    fr.close()

    multi = {"a": X[:2], "b": (X[:2, :4] * 3).astype(np.int8)}
    raw = wire.encode_predict_request(multi)
    got, _, _, fr = wire.decode_predict_request(raw)
    assert set(got) == {"a", "b"}
    assert got["b"].dtype == np.int8
    assert got["a"].tobytes() == multi["a"].tobytes()
    assert got["b"].tobytes() == multi["b"].tobytes()
    fr.close()

    resp = wire.encode_predict_response("m", 3, X[:2],
                                        fields={"worker_id": "w9"})
    name, version, out, fr = wire.decode_predict_response(resp)
    assert (name, version) == ("m", 3)
    assert np.array(out).tobytes() == X[:2].tobytes()
    assert fr.meta["fields"]["worker_id"] == "w9"
    fr.close()


def test_damaged_frames_are_counted_protocol_errors_never_tensors():
    wire.reset_counters()
    raw = wire.encode_predict_request(X[:2], timeout_ms=500)
    cases = []
    flipped = bytearray(raw)
    flipped[len(raw) // 2] ^= 0x01        # one bit, mid-payload
    cases.append(bytes(flipped))
    cases.append(raw[: len(raw) - 3])     # truncated tail
    cases.append(b"NOPE" + raw[4:])       # bad magic
    cases.append(raw[:4] + b"\xff" + raw[5:])  # unknown version
    bad_meta = bytearray(raw)
    bad_meta[24] ^= 0xFF                  # corrupt the JSON meta block
    cases.append(bytes(bad_meta))
    for bad in cases:
        with pytest.raises(wire.WireProtocolError):
            wire.decode_frame(bad)
    assert wire.counters()["protocol_errors_total"] == len(cases)


def test_chaos_byte_point_drills_flip_and_truncate():
    """The registered ``serving.wire.frame`` point: chaos-mangled frames
    (bit rot and torn writes) decode to explicit protocol errors."""
    wire.reset_counters()
    for policy in (chaos.CorruptBytes(n_bytes=4, mode="flip"),
                   chaos.CorruptBytes(mode="truncate")):
        with chaos.ChaosController(seed=3) as c:
            c.on("serving.wire.frame", policy)
            raw = wire.encode_predict_request(X[:4])
            with pytest.raises(wire.WireProtocolError):
                got, _, _, fr = wire.decode_predict_request(raw)
                fr.close()                # pragma: no cover (must raise)
    assert wire.counters()["protocol_errors_total"] == 2
    # clean arm: no controller, the same encode/decode round-trips
    raw = wire.encode_predict_request(X[:4])
    got, _, _, fr = wire.decode_predict_request(raw)
    assert got.tobytes() == X[:4].tobytes()
    fr.close()
    assert wire.counters()["protocol_errors_total"] == 2


def test_header_field_mapping_roundtrip_and_case_insensitivity():
    headers = {k: f"v{i}" for i, k in enumerate(wire.HEADER_FIELDS)}
    fields = wire.headers_to_fields(headers)
    assert set(fields) == set(wire.HEADER_FIELDS.values())
    assert wire.fields_to_headers(fields) == headers
    # lower-cased spellings map to the canonical header; strangers drop
    assert wire.headers_to_fields({"x-request-id": "a", "X-Mystery": "b",
                                   "Content-Type": "c"}) \
        == {"request_id": "a"}
    assert wire.fields_to_headers({"request_id": "a", "mystery": "b"}) \
        == {"X-Request-Id": "a"}


def test_shm_frame_roundtrip_and_min_bytes_gate():
    raw = wire.encode_predict_request(X)   # 16*8*4 = 512 payload bytes
    small, seg = wire.frame_to_shm(raw, min_bytes=100000)
    assert small is raw and seg is None    # below the gate: untouched
    shm_raw, seg = wire.frame_to_shm(raw, min_bytes=128)
    assert seg is not None and len(shm_raw) < len(raw)
    try:
        got, _, _, fr = wire.decode_predict_request(shm_raw)
        assert got.tobytes() == X.tobytes()
        got = None
        fr.close()
    finally:
        wire.release_shm(seg)


# ==========================================================================
# connection pool
def test_pool_reuses_connections_and_bounds_idle(duo):
    endpoints, _, _, _ = duo
    address = endpoints["w0"]
    pool = wire.ConnectionPool(max_idle_per_endpoint=2)
    try:
        for _ in range(5):
            status, _, _ = pool.request(address, "GET", "/healthz",
                                        body=None, headers={}, timeout=30)
            assert status == 200
        snap = pool.snapshot()
        assert snap["created_total"] == 1
        assert snap["reused_total"] == 4
        assert pool.idle_count(address) == 1   # bounded LIFO park
        pool.invalidate(address)
        assert pool.idle_count(address) == 0
        assert pool.snapshot()["invalidated_total"] == 1
    finally:
        pool.close()


def test_pool_retry_once_on_stale_reused_connection(duo):
    """A parked keep-alive whose socket died underneath it is discarded
    and the request transparently retried once on a fresh connection —
    the caller never sees the stale socket."""
    endpoints, _, _, _ = duo
    address = endpoints["w0"]
    pool = wire.ConnectionPool()
    try:
        status, _, _ = pool.request(address, "GET", "/healthz",
                                    body=None, headers={}, timeout=30)
        assert status == 200 and pool.idle_count(address) == 1
        # kill the parked socket out from under the pool (the server-side
        # idle timeout / a silent peer reset does exactly this in prod)
        parked, _t = pool._idle[address][-1]
        parked.sock.close()
        status, _, _ = pool.request(address, "GET", "/healthz",
                                    body=None, headers={}, timeout=30)
        assert status == 200
        snap = pool.snapshot()
        assert snap["discarded_total"] == 1    # the stale conn, silently
        assert snap["created_total"] == 2      # original + the retry
        assert snap["reused_total"] == 1       # the attempt that failed
    finally:
        pool.close()


def test_breaker_open_and_restart_drop_pooled_connections(duo):
    endpoints, _, _, _ = duo

    class MutableFleet:
        def __init__(self, eps):
            self.eps = dict(eps)

        def endpoints(self):
            return dict(self.eps)

    fleet = MutableFleet({"w0": endpoints["w0"]})
    router = FleetRouter(fleet, probe_interval_s=3600.0)
    try:
        router._sync_views()
        view = router.workers()["w0"]
        # park a real keep-alive to the worker through the router's pool
        status, _, _ = router.pool.request(view.address, "GET", "/healthz",
                                           body=None, headers={},
                                           timeout=30)
        assert status == 200 and router.pool.idle_count(view.address) == 1
        # drive the breaker OPEN, then classify one more 5xx: the parked
        # connection must not outlive the verdict
        while view.breaker.state is not CircuitState.OPEN:
            view.breaker.record_failure()
        attempt = _Attempt(view, hedged=False)
        attempt.status = 500
        router._classify(attempt)
        assert router.pool.idle_count(view.address) == 0
        # worker restart = same id, new address: _sync_views drops the
        # old address's parked connections too
        status, _, _ = router.pool.request(view.address, "GET", "/healthz",
                                           body=None, headers={},
                                           timeout=30)
        assert router.pool.idle_count(view.address) == 1
        old_address = view.address
        fleet.eps["w0"] = endpoints["w1"]
        router._sync_views()
        assert router.pool.idle_count(old_address) == 0
        assert router.pool.snapshot()["invalidated_total"] >= 2
    finally:
        router.stop()


def test_pool_no_fd_leak(duo, fd_guard):
    endpoints, _, _, _ = duo
    pool = wire.ConnectionPool()
    for _ in range(6):
        pool.request(endpoints["w0"], "GET", "/healthz",
                     body=None, headers={}, timeout=30)
    pool.close()


# ==========================================================================
# negotiation matrix over real workers
def test_binary_end_to_end_bit_identical_and_zero_copy(duo):
    endpoints, registries, _, ref = duo
    router = FleetRouter(StaticFleet({"w0": endpoints["w0"]}),
                         probe_interval_s=0.05, hedge_initial_ms=2000.0)
    port = router.start(0)
    pool = wire.ConnectionPool()
    wire.reset_counters()
    zero_before = registries[0].get("m").metrics.snapshot()[
        "zero_copy_rows_total"]
    try:
        _wait_ready(router, 1)
        frame = wire.encode_predict_request(X[:4], timeout_ms=10000)
        for _ in range(3):
            status, headers, data = _predict_wire(pool, port, frame)
            assert status == 200
            out = _decode_any(headers, data)
            assert out.tobytes() == ref.tobytes()
        snap = router.metrics.snapshot()
        assert snap["wire_requests_total"] == 3
        assert snap["wire_downgrades_total"] == 0
        assert router.workers()["w0"].wire_ok is True
        assert wire.counters()["protocol_errors_total"] == 0
        zero_after = registries[0].get("m").metrics.snapshot()[
            "zero_copy_rows_total"]
        assert zero_after - zero_before == 3 * 4   # every row zero-copy
    finally:
        pool.close()
        router.stop()


def test_binary_client_json_only_worker_downgrades_bit_identical(duo):
    endpoints, _, _, ref = duo
    router = FleetRouter(StaticFleet({"w1": endpoints["w1"]}),
                         probe_interval_s=0.05, hedge_initial_ms=2000.0)
    port = router.start(0)
    pool = wire.ConnectionPool()
    journal.enable(capacity=2048)
    try:
        _wait_ready(router, 1)
        frame = wire.encode_predict_request(X[:4], timeout_ms=10000)
        for k in range(2):
            status, headers, data = _predict_wire(pool, port, frame)
            assert status == 200
            out = _decode_any(headers, data)    # JSON body: transcoded
            assert out.tobytes() == ref.tobytes()
        snap = router.metrics.snapshot()
        assert snap["wire_downgrades_total"] == 1   # 415 verdict cached
        assert router.workers()["w1"].wire_ok is False
        # the downgrade is a black-box event: one typed journal entry
        downs = journal.events(types=["router.wire_downgrade"])
        assert len(downs) == 1 and downs[0]["attrs"]["worker"] == "w1"
    finally:
        pool.close()
        router.stop()


def test_json_client_through_wire_enabled_fleet_unchanged(duo):
    endpoints, _, _, ref = duo
    router = FleetRouter(StaticFleet({"w0": endpoints["w0"]}),
                         probe_interval_s=0.05, hedge_initial_ms=2000.0)
    port = router.start(0)
    try:
        _wait_ready(router, 1)
        body = json.dumps({"inputs": X[:4].tolist(), "dtype": "float32",
                           "timeout_ms": 10000}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/m/predict", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            out = np.asarray(json.loads(r.read())["outputs"], np.float32)
        assert out.tobytes() == ref.tobytes()
        assert router.metrics.snapshot()["wire_requests_total"] == 0
    finally:
        router.stop()


def test_mid_stream_downgrade_when_worker_stops_speaking_binary(duo):
    endpoints, _, servers, ref = duo
    wire_srv = servers[0]
    router = FleetRouter(StaticFleet({"w0": endpoints["w0"]}),
                         probe_interval_s=0.05, hedge_initial_ms=2000.0)
    port = router.start(0)
    pool = wire.ConnectionPool()
    try:
        _wait_ready(router, 1)
        frame = wire.encode_predict_request(X[:4], timeout_ms=10000)
        status, headers, data = _predict_wire(pool, port, frame)
        assert status == 200
        assert router.workers()["w0"].wire_ok is True
        wire_srv.wire_enabled = False      # ops flipped the force-JSON lever
        status, headers, data = _predict_wire(pool, port, frame)
        assert status == 200               # 415 absorbed: transcode + retry
        out = _decode_any(headers, data)
        assert out.tobytes() == ref.tobytes()
        assert router.workers()["w0"].wire_ok is False
        assert router.metrics.snapshot()["wire_downgrades_total"] == 1
    finally:
        wire_srv.wire_enabled = True
        pool.close()
        router.stop()


def test_hedged_request_mixed_protocols_winner_bit_identical(duo):
    """Primary straggles; the hedge lands on the other worker. One view
    speaks binary, the other is JSON-only — whichever wins, the client
    sees exactly one bit-identical response."""
    endpoints, _, servers, ref = duo
    router = FleetRouter(StaticFleet(endpoints), probe_interval_s=0.05,
                         hedge_initial_ms=50.0)
    port = router.start(0)
    pool = wire.ConnectionPool()
    slowed = None
    try:
        _wait_ready(router, 2)
        primary = router.ranked_workers("m")[0].worker_id
        slowed = servers[0] if primary == "w0" else servers[1]
        orig = slowed._handle_predict

        def slow_predict(*args, **kw):
            time.sleep(0.4)
            return orig(*args, **kw)

        slowed._handle_predict = slow_predict
        frame = wire.encode_predict_request(X[:4], timeout_ms=10000)
        status, headers, data = _predict_wire(pool, port, frame)
        assert status == 200
        out = _decode_any(headers, data)
        assert out.tobytes() == ref.tobytes()
        snap = router.metrics.snapshot()
        assert snap["hedges_total"] >= 1
        assert snap["responses_total"] == 1    # exactly one delivered
    finally:
        if slowed is not None:
            slowed._handle_predict = orig
        pool.close()
        router.stop()


# ==========================================================================
# corrupt frames over HTTP + the shm retry drill
def test_corrupt_frame_is_503_protocol_error_at_router_and_worker(duo):
    endpoints, _, _, _ = duo
    router = FleetRouter(StaticFleet({"w0": endpoints["w0"]}),
                         probe_interval_s=0.05, hedge_initial_ms=2000.0)
    port = router.start(0)
    pool = wire.ConnectionPool()
    try:
        _wait_ready(router, 1)
        frame = bytearray(wire.encode_predict_request(X[:4]))
        frame[30] ^= 0xFF
        for target_port in (port, int(endpoints["w0"].rsplit(":", 1)[1])):
            status, headers, data = _predict_wire(pool, target_port,
                                                  bytes(frame))
            obj = json.loads(data)            # errors are ALWAYS JSON
            assert status == 503
            assert obj["reason"] == "wire_protocol_error"
    finally:
        pool.close()
        router.stop()


def test_chaos_corrupted_shm_frame_retries_inline_correct_answer(duo):
    """Damage on the shm re-encode (the router->worker hop) is a counted
    protocol error the router absorbs by resending inline — the client
    still gets the right tensor, never a wrong one."""
    endpoints, _, _, ref = duo
    router = FleetRouter(StaticFleet({"w0": endpoints["w0"]}),
                         probe_interval_s=0.05, hedge_initial_ms=2000.0,
                         shm_min_bytes=64)
    port = router.start(0)
    pool = wire.ConnectionPool()
    try:
        _wait_ready(router, 1)
        # encode the client frame OUTSIDE the controller so the router's
        # shm re-encode is the first encode the controller sees. Call
        # indices are 1-based and shared between inject/transform, and
        # every encode_frame consumes two (fire then transform) — so the
        # shm re-encode's TRANSFORM is call #2, and the worker's
        # response encode (#3/#4) stays clean
        frame = wire.encode_predict_request(X[:4], timeout_ms=10000)
        wire.reset_counters()
        with chaos.ChaosController(seed=11) as c:
            c.on("serving.wire.frame",
                 chaos.CorruptBytes(n_bytes=4, mode="flip", nth=2))
            status, headers, data = _predict_wire(pool, port, frame)
        assert status == 200
        out = _decode_any(headers, data)
        assert out.tobytes() == ref.tobytes()
        snap = router.metrics.snapshot()
        assert snap["shm_fallbacks_total"] == 1
        assert wire.counters()["protocol_errors_total"] >= 1
        # clean follow-up rides shm again
        status, headers, data = _predict_wire(pool, port, frame)
        assert status == 200
        assert _decode_any(headers, data).tobytes() == ref.tobytes()
        assert router.metrics.snapshot()["shm_hops_total"] >= 1
    finally:
        pool.close()
        router.stop()
