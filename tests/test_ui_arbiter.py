"""UI stats pipeline + hyperparameter search tests (reference TestVertxUI /
arbiter test patterns)."""

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu.arbiter import (ContinuousParameterSpace,
                                        DiscreteParameterSpace,
                                        EvaluationScoreFunction,
                                        GridSearchGenerator,
                                        LocalOptimizationRunner,
                                        RandomSearchGenerator)
from deeplearning4j_tpu.data import NumpyDataSetIterator
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (DenseLayer, InputType, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener, UIServer


def _data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n)
    x = (np.stack([y * 2.0, -y * 1.5], -1) + rng.normal(0, 0.4, (n, 2))).astype(np.float32)
    return x, np.eye(2, dtype=np.float32)[y]


def _conf(lr=1e-2, hidden=8):
    return (NeuralNetConfiguration.builder().seed(3).updater(Adam(lr)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(2)).build())


def test_stats_listener_and_ui_server():
    x, y = _data()
    it = NumpyDataSetIterator(x, y, batch_size=32)
    net = MultiLayerNetwork(_conf()).init()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, frequency=1))
    net.fit(it, epochs=3)
    recs = storage.records()
    assert len(recs) >= 9
    assert "score" in recs[0] and "params" in recs[0]
    assert "layer_0" in recs[0]["params"]
    assert recs[-1]["score"] < recs[0]["score"]

    server = UIServer.get_instance()
    server.attach(storage)
    port = server.start(port=0)
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/api/records") as r:
            data = json.loads(r.read())
        assert len(data) == len(recs)
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
            page = r.read().decode()
        assert "Training overview" in page
    finally:
        server.stop()


def test_random_search_finds_good_config():
    x, y = _data(128)
    train = NumpyDataSetIterator(x[:96], y[:96], batch_size=32)
    test = NumpyDataSetIterator(x[96:], y[96:], batch_size=32)
    space = {
        "lr": ContinuousParameterSpace(1e-4, 1e-1, log_scale=True),
        "hidden": DiscreteParameterSpace(4, 8, 16),
    }
    runner = LocalOptimizationRunner(
        lambda c: _conf(lr=c["lr"], hidden=c["hidden"]), space,
        RandomSearchGenerator(4, seed=2),
        score_function=EvaluationScoreFunction("accuracy"),
        train_iterator=train, eval_iterator=test, epochs=8)
    best = runner.execute()
    assert len(runner.results) == 4
    assert best.score >= 0.8
    assert runner.best_result().index == best.index


def test_grid_generator_covers_product():
    space = {"a": DiscreteParameterSpace(1, 2), "b": DiscreteParameterSpace("x", "y")}
    combos = list(GridSearchGenerator().candidates(space))
    assert len(combos) == 4
    assert {"a": 1, "b": "x"} in combos


def test_crash_report_contents():
    from deeplearning4j_tpu.runtime.crash_reporting import CrashReportingUtil
    net = MultiLayerNetwork(_conf()).init()
    report = CrashReportingUtil.memory_report(net)
    assert "parameter memory breakdown" in report
    assert "layer_0" in report and "TOTAL" in report


def test_ui_tabs_remote_storage_arbiter_and_tsne():
    """Tabbed UI endpoints: remote record POSTing (RemoteUIStatsStorage),
    arbiter results feed, and t-SNE upload all round-trip over HTTP."""
    import json
    import urllib.request
    from deeplearning4j_tpu.ui import RemoteUIStatsStorage, UIServer

    server = UIServer()  # separate instance; do not disturb the singleton
    port = server.start(port=0)
    base = f"http://127.0.0.1:{port}"
    try:
        remote = RemoteUIStatsStorage(base)
        # remote posting is opt-in (reference enableRemoteListener): 403 first
        try:
            remote.put_record({"iteration": 0, "score": 1.0})
            assert False, "expected HTTP 403 before enable_remote_listener()"
        except IOError as e:
            assert "403" in str(e)
        server.enable_remote_listener()
        remote.put_record({"iteration": 1, "score": 0.5})
        remote.put_record({"iteration": 2, "score": 0.25})
        recs = json.loads(urllib.request.urlopen(base + "/api/records").read())
        assert [r["score"] for r in recs] == [0.5, 0.25]

        class R:  # minimal OptimizationResult shape
            index, score, duration_s, candidate = 0, 0.9, 1.5, {"lr": 0.1}
        class Runner:
            listeners = []
        server.attach_arbiter(Runner)
        Runner.listeners[0](R)
        arb = json.loads(urllib.request.urlopen(base + "/api/arbiter").read())
        assert arb[0]["score"] == 0.9 and arb[0]["candidate"] == {"lr": 0.1}

        server.upload_tsne([[0.0, 1.0], [2.0, 3.0]], labels=[0, 1])
        ts = json.loads(urllib.request.urlopen(base + "/api/tsne").read())
        assert ts["points"] == [[0.0, 1.0], [2.0, 3.0]] and ts["labels"] == [0, 1]

        for tab in ("/", "/model", "/arbiter", "/tsne", "/system"):
            page = urllib.request.urlopen(base + tab).read().decode()
            assert "deeplearning4j_tpu training UI" in page
    finally:
        server.stop()


def test_resources_and_archive_utils(tmp_path):
    """DL4JResources base-dir + ArchiveUtils extraction with zip-slip guard."""
    import os
    import zipfile
    from deeplearning4j_tpu.runtime.resources import ArchiveUtils, DL4JResources, ResourceType

    old = DL4JResources._base
    try:
        DL4JResources.set_base_directory(str(tmp_path / "res"))
        d = DL4JResources.get_directory(ResourceType.DATASET, "mnist")
        assert d.endswith(os.path.join("res", "datasets", "mnist"))
        assert os.path.isdir(d)
    finally:
        DL4JResources._base = old

    z = tmp_path / "a.zip"
    with zipfile.ZipFile(z, "w") as f:
        f.writestr("dir/file.txt", "hello")
    out = ArchiveUtils.extract(str(z), str(tmp_path / "out"))
    assert open(out[0]).read() == "hello"
    assert ArchiveUtils.list_files(str(z)) == ["dir/file.txt"]

    evil = tmp_path / "evil.zip"
    with zipfile.ZipFile(evil, "w") as f:
        f.writestr("../escape.txt", "bad")
    import pytest as _pytest
    with _pytest.raises(ValueError, match="escapes"):
        ArchiveUtils.extract(str(evil), str(tmp_path / "out2"))


def test_arbiter_result_persistence(tmp_path):
    from deeplearning4j_tpu.arbiter.runner import (LocalOptimizationRunner,
                                                   OptimizationResult)

    class R:
        class SF:
            minimize = False
        score_function = SF()
        results = [OptimizationResult(0, {"lr": 0.1}, 0.8, 1.0),
                   OptimizationResult(1, {"lr": 0.01}, 0.9, 1.1)]
    path = str(tmp_path / "results.json")
    LocalOptimizationRunner.save_results(R, path)
    loaded = LocalOptimizationRunner.load_results(path)
    assert [r.score for r in loaded] == [0.8, 0.9]
    assert loaded[1].candidate == {"lr": 0.01}
    assert loaded.minimize is False and loaded.best().score == 0.9


def test_stats_listener_collects_histograms():
    """Reference StatsListener records param/update/activation histograms;
    ours computes them device-side (bincount) — verify they land in the
    stats records and are JSON-serializable for the UI."""
    import json
    import numpy as np
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train import Adam
    from deeplearning4j_tpu.ui import StatsListener

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    sl = StatsListener(frequency=1, collect_activations=True)
    net.set_listeners(sl)
    x = np.random.default_rng(0).normal(0, 1, (64, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.default_rng(1).integers(0, 3, 64)]
    net.fit(x, y, epochs=3)

    rec = sl.storage.records()[-1]
    h = rec["params"]["layer_0"]["W"]["hist"]
    assert len(h["counts"]) == 32 and sum(h["counts"]) == 8 * 16
    assert h["lo"] < h["hi"]
    uh = rec["updates"]["layer_0"]["W"]["hist"]
    assert sum(uh["counts"]) == 8 * 16
    assert len(rec["activations"]) == 2
    assert sum(rec["activations"][0]["hist"]["counts"]) == 64 * 16
    json.dumps(rec)  # UI transport
