"""Transfer learning on ComputationGraph: freeze ancestors, swap the head,
keep pretrained weights."""

import numpy as np

from deeplearning4j_tpu.models import ComputationGraph, FineTuneConfiguration, TransferLearning
from deeplearning4j_tpu.nn import (DenseLayer, InputType, NeuralNetConfiguration,
                                   OutputLayer)
from deeplearning4j_tpu.train import Adam


def _trained_graph():
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (48, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 48)]
    g = (NeuralNetConfiguration.builder().seed(0).updater(Adam(2e-2)).graph_builder()
         .add_inputs("in")
         .add_layer("feat1", DenseLayer(n_out=16, activation="tanh"), "in")
         .add_layer("feat2", DenseLayer(n_out=8, activation="tanh"), "feat1")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "feat2")
         .set_outputs("out"))
    g.set_input_types(InputType.feed_forward(5))
    net = ComputationGraph(g.build()).init()
    net.fit(x, y, epochs=5)
    return net, x


def test_graph_transfer_swap_head_keeps_features():
    net, x = _trained_graph()
    w_feat1 = np.asarray(net.params()["feat1"]["W"])

    net2 = (TransferLearning.graph_builder(net)
            .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-3)))
            .set_feature_extractor("feat2")
            .remove_vertex_and_connections("out")
            .add_layer("out2", OutputLayer(n_out=5, activation="softmax"), "feat2")
            .set_outputs("out2")
            .build())

    # pretrained feature weights carried over
    np.testing.assert_array_equal(np.asarray(net2.params()["feat1"]["W"]), w_feat1)
    # new head has the new width
    assert net2.params()["out2"]["W"].shape == (8, 5)
    # frozen flags on the feature extractor
    assert net2.conf.node("feat1").obj.frozen
    assert net2.conf.node("feat2").obj.frozen
    assert not net2.conf.node("out2").obj.frozen

    out = np.asarray(net2.output(x))
    assert out.shape == (48, 5)

    # training updates only the head
    y2 = np.eye(5, dtype=np.float32)[np.random.default_rng(1).integers(0, 5, 48)]
    net2.fit(x, y2, epochs=3)
    np.testing.assert_array_equal(np.asarray(net2.params()["feat1"]["W"]), w_feat1)
    assert not np.allclose(np.asarray(net2.params()["out2"]["W"]),
                           np.zeros((8, 5)))


def test_graph_transfer_removed_output_must_be_replaced():
    import pytest
    net, _ = _trained_graph()
    builder = (TransferLearning.graph_builder(net)
               .remove_vertex_and_connections("out"))
    with pytest.raises(ValueError, match="set_outputs"):
        builder.build()


def test_graph_transfer_downstream_removal():
    net, _ = _trained_graph()
    # removing feat2 also removes its dependent "out"
    b = TransferLearning.graph_builder(net).remove_vertex_and_connections("feat2")
    assert "out" in b._removed and "feat2" in b._removed and "feat1" not in b._removed


def test_transfer_keeps_batchnorm_running_stats():
    """Frozen feature extractors must carry their BN running stats, not
    reset to init (zeros/ones)."""
    from deeplearning4j_tpu.nn import BatchNormalization
    rng = np.random.default_rng(0)
    x = (rng.normal(3.0, 2.0, (64, 6))).astype(np.float32)  # non-unit stats
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
    g = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2)).graph_builder()
         .add_inputs("in")
         .add_layer("bn", BatchNormalization(), "in")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "bn")
         .set_outputs("out"))
    g.set_input_types(InputType.feed_forward(6))
    from deeplearning4j_tpu.models import ComputationGraph
    net = ComputationGraph(g.build()).init()
    net.fit(x, y, epochs=10)
    trained_mean = np.asarray(net.train_state.model_state["bn"]["mean"])
    # stats moved well away from init 0 toward the data mean 3.0
    # (running average with decay 0.9 over 10 updates ≈ (1-0.9^10)*3)
    assert trained_mean.mean() > 1.0

    net2 = (TransferLearning.graph_builder(net)
            .set_feature_extractor("bn")
            .remove_vertex_and_connections("out")
            .add_layer("out2", OutputLayer(n_out=4, activation="softmax"), "bn")
            .set_outputs("out2")
            .build())
    np.testing.assert_array_equal(
        np.asarray(net2.train_state.model_state["bn"]["mean"]), trained_mean)


def test_feature_extractor_typo_raises():
    import pytest
    net, _ = _trained_graph()
    b = TransferLearning.graph_builder(net).set_feature_extractor("nope")
    with pytest.raises(ValueError, match="nope"):
        b.build()


def test_graph_rnn_time_step_matches_full_forward():
    from deeplearning4j_tpu.models import ComputationGraph
    from deeplearning4j_tpu.nn import LSTM, RnnOutputLayer
    B, T, F = 2, 6, 4
    g = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2)).graph_builder()
         .add_inputs("in")
         .add_layer("lstm", LSTM(n_out=8), "in")
         .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax"), "lstm")
         .set_outputs("out"))
    g.set_input_types(InputType.recurrent(F, None))
    net = ComputationGraph(g.build()).init()
    x = np.random.default_rng(0).normal(0, 1, (B, T, F)).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    steps = [np.asarray(net.rnn_time_step(x[:, t:t + 1])) for t in range(T)]
    np.testing.assert_allclose(full[:, -1], steps[-1][:, -1], atol=2e-3)
    # clearing state restarts the sequence
    net.rnn_clear_previous_state()
    again = np.asarray(net.rnn_time_step(x[:, 0:1]))
    np.testing.assert_allclose(again, steps[0], atol=1e-5)


def test_graph_tbptt_training():
    """tBPTT on ComputationGraph: long sequence trained in carried chunks."""
    from deeplearning4j_tpu.models import ComputationGraph
    from deeplearning4j_tpu.nn import LSTM, RnnOutputLayer
    B, T, V = 4, 24, 6
    seq = np.tile(np.arange(V), (B, T // V + 2))[:, :T + 1]
    x = np.eye(V, dtype=np.float32)[seq[:, :-1]]
    y = np.eye(V, dtype=np.float32)[seq[:, 1:]]
    g = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2)).graph_builder()
         .add_inputs("in")
         .add_layer("lstm", LSTM(n_out=24), "in")
         .add_layer("out", RnnOutputLayer(n_out=V, activation="softmax"), "lstm")
         .set_outputs("out")
         .tbptt_fwd_length(8))
    g.set_input_types(InputType.recurrent(V, None))
    conf = g.build()
    assert conf.tbptt_fwd_length == 8
    net = ComputationGraph(conf).init()
    it0 = net._iteration
    net.fit(x, y, epochs=30)
    # 3 chunks per minibatch: iteration counter advanced accordingly
    assert (net._iteration - it0) == 30 * 3
    acc = (np.asarray(net.output(x)).argmax(-1) == seq[:, 1:]).mean()
    assert acc > 0.9
    # serde keeps the tbptt setting
    from deeplearning4j_tpu.models.computation_graph import ComputationGraphConfiguration
    back = ComputationGraphConfiguration.from_dict(conf.to_dict())
    assert back.tbptt_fwd_length == 8


def test_tbptt_with_integer_token_inputs():
    """(B, T) int token sequences must take the tBPTT path too, not silently
    full-BPTT."""
    from deeplearning4j_tpu.nn import EmbeddingSequenceLayer, LSTM, RnnOutputLayer
    B, T, V = 4, 20, 6
    seq = np.tile(np.arange(V), (B, T // V + 2))[:, :T + 1]
    toks = seq[:, :-1].astype(np.int32)
    y = np.eye(V, dtype=np.float32)[seq[:, 1:]]
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2)).list()
            .layer(EmbeddingSequenceLayer(n_in=V, n_out=8))
            .layer(LSTM(n_out=16))
            .layer(RnnOutputLayer(n_out=V, activation="softmax"))
            .tbptt_fwd_length(5)
            .set_input_type(InputType.recurrent(V, None)).build())
    from deeplearning4j_tpu.models import MultiLayerNetwork
    net = MultiLayerNetwork(conf).init()
    net.fit(toks, y, epochs=2)
    assert net._iteration == 2 * 4  # 4 chunks of length 5 per epoch
