"""Model-import tests: golden-file pattern (SURVEY.md §4) with the local TF
as the oracle — build a graph/model with TF, record its output, import into
this framework, compare."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")


@pytest.fixture(autouse=True)
def _isolate_lambda_registry():
    """The lambda registry is process-global; snapshot/restore around every
    test so registrations cannot leak across tests (and cannot silently
    satisfy another archive's Lambda names)."""
    from deeplearning4j_tpu.nn.misc_layers import _LAMBDA_REGISTRY
    saved = dict(_LAMBDA_REGISTRY)
    yield
    _LAMBDA_REGISTRY.clear()
    _LAMBDA_REGISTRY.update(saved)


def _frozen_graphdef(fn, input_specs):
    """Trace fn to a frozen (constant-folded) GraphDef."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    conc = tf.function(fn).get_concrete_function(*input_specs)
    frozen = convert_variables_to_constants_v2(conc)
    return frozen.graph.as_graph_def(), [t.name.split(":")[0] for t in frozen.inputs], \
        [t.name.split(":")[0] for t in frozen.outputs]


def test_tf_import_mlp():
    from deeplearning4j_tpu.imports import TFGraphMapper
    w1 = tf.constant(np.random.default_rng(0).normal(0, 1, (8, 16)).astype(np.float32))
    b1 = tf.constant(np.zeros(16, np.float32))
    w2 = tf.constant(np.random.default_rng(1).normal(0, 1, (16, 3)).astype(np.float32))

    def model(x):
        h = tf.nn.relu(tf.matmul(x, w1) + b1)
        return tf.nn.softmax(tf.matmul(h, w2))

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((None, 8), tf.float32, name="x")])
    sd = TFGraphMapper.import_graph(gd)
    x = np.random.default_rng(2).normal(0, 1, (4, 8)).astype(np.float32)
    expected = model(tf.constant(x)).numpy()
    got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_attention_block():
    """Mini transformer block — the BERT-shaped op set (batched matmul,
    layernorm primitives, gelu-via-erf, reshape/transpose/softmax)."""
    from deeplearning4j_tpu.imports import TFGraphMapper
    rng = np.random.default_rng(0)
    D, H = 16, 4
    wq = tf.constant(rng.normal(0, 0.1, (D, D)).astype(np.float32))
    wk = tf.constant(rng.normal(0, 0.1, (D, D)).astype(np.float32))
    wv = tf.constant(rng.normal(0, 0.1, (D, D)).astype(np.float32))
    gamma = tf.constant(np.ones(D, np.float32))
    beta = tf.constant(np.zeros(D, np.float32))

    def block(x):  # x: (B, T, D)
        B, T = tf.shape(x)[0], tf.shape(x)[1]
        q = tf.reshape(x @ wq, (2, 8, H, D // H))
        k = tf.reshape(x @ wk, (2, 8, H, D // H))
        v = tf.reshape(x @ wv, (2, 8, H, D // H))
        q = tf.transpose(q, (0, 2, 1, 3))
        k = tf.transpose(k, (0, 2, 1, 3))
        v = tf.transpose(v, (0, 2, 1, 3))
        s = tf.matmul(q, k, transpose_b=True) / tf.sqrt(float(D // H))
        a = tf.matmul(tf.nn.softmax(s, axis=-1), v)
        a = tf.reshape(tf.transpose(a, (0, 2, 1, 3)), (2, 8, D))
        y = x + a
        mean, var = tf.nn.moments(y, axes=[-1], keepdims=True)
        y = (y - mean) * tf.math.rsqrt(var + 1e-6) * gamma + beta
        # gelu via erf (BERT's formulation)
        return 0.5 * y * (1.0 + tf.math.erf(y / np.sqrt(2.0).astype(np.float32)))

    gd, inputs, outputs = _frozen_graphdef(
        block, [tf.TensorSpec((2, 8, D), tf.float32, name="x")])
    sd = TFGraphMapper.import_graph(gd)
    x = np.random.default_rng(3).normal(0, 1, (2, 8, D)).astype(np.float32)
    expected = block(tf.constant(x)).numpy()
    got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_keras_sequential_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12,)),
        tf.keras.layers.Dense(24, activation="relu"),
        tf.keras.layers.Dense(5, activation="softmax"),
    ])
    path = str(tmp_path / "model.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(0).normal(0, 1, (6, 12)).astype(np.float32)
    expected = km(x).numpy()
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_keras_cnn_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((16, 16, 3)),
        tf.keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Conv2D(16, 3, padding="valid", activation="relu"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(4, activation="softmax"),
    ])
    path = str(tmp_path / "cnn.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(0).normal(0, 1, (2, 16, 16, 3)).astype(np.float32)
    expected = km(x).numpy()
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_keras_functional_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    inp = tf.keras.layers.Input((10,), name="in0")
    a = tf.keras.layers.Dense(16, activation="relu")(inp)
    b = tf.keras.layers.Dense(16, activation="tanh")(inp)
    merged = tf.keras.layers.Add()([a, b])
    out = tf.keras.layers.Dense(3, activation="softmax")(merged)
    km = tf.keras.Model(inp, out)
    path = str(tmp_path / "func.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(0).normal(0, 1, (5, 10)).astype(np.float32)
    expected = km(x).numpy()
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_keras_conv1d_prelu_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((10, 6)),
        tf.keras.layers.Conv1D(8, 3, padding="same", activation="relu"),
        tf.keras.layers.Conv1D(8, 3, strides=2, padding="valid"),
        tf.keras.layers.PReLU(shared_axes=[1]),
        tf.keras.layers.GlobalAveragePooling1D(),
        tf.keras.layers.Dense(3, activation="softmax"),
    ])
    # non-zero PReLU alphas so the mapping is actually exercised
    prelu = km.layers[2]
    prelu.set_weights([np.full_like(prelu.get_weights()[0], 0.25)])
    path = str(tmp_path / "c1d.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(0).normal(0, 1, (4, 10, 6)).astype(np.float32)
    expected = km(x).numpy()
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_keras_crop_pad_upsample_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((9, 4)),
        tf.keras.layers.ZeroPadding1D(2),
        tf.keras.layers.Cropping1D((1, 1)),
        tf.keras.layers.UpSampling1D(2),
        tf.keras.layers.Conv1D(5, 3, padding="same", activation="tanh"),
        tf.keras.layers.GlobalMaxPooling1D(),
        tf.keras.layers.Dense(2),
    ])
    path = str(tmp_path / "cpu1d.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(1).normal(0, 1, (3, 9, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_conv3d_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6, 6, 6, 2)),
        tf.keras.layers.Conv3D(4, 3, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling3D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(3, activation="softmax"),
    ])
    path = str(tmp_path / "c3d.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(2).normal(0, 1, (2, 6, 6, 6, 2)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_functional_subtract_maximum(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    inp = tf.keras.layers.Input((8,))
    a = tf.keras.layers.Dense(8, activation="relu")(inp)
    b = tf.keras.layers.Dense(8, activation="relu")(inp)
    sub = tf.keras.layers.Subtract()([a, b])
    mx = tf.keras.layers.Maximum()([a, b])
    cat = tf.keras.layers.Concatenate()([sub, mx])
    out = tf.keras.layers.Dense(2)(cat)
    km = tf.keras.Model(inp, out)
    path = str(tmp_path / "fn.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(3).normal(0, 1, (5, 8)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_causal_conv1d_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12, 3)),
        tf.keras.layers.Conv1D(6, 3, padding="causal", dilation_rate=2,
                               activation="tanh"),
        tf.keras.layers.GlobalAveragePooling1D(),
        tf.keras.layers.Dense(2),
    ])
    path = str(tmp_path / "causal.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(4).normal(0, 1, (3, 12, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_lambda_layer_registry(tmp_path):
    """Reference KerasLayer.registerLambdaLayer: Lambda code is not in the
    .h5, so imports resolve the function by layer name from the registry."""
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((8,)),
        tf.keras.layers.Dense(6, activation="relu"),
        tf.keras.layers.Lambda(lambda t: t * 2.0 + 1.0, name="affine2x"),
        tf.keras.layers.Dense(3),
    ])
    path = str(tmp_path / "lam.keras")
    km.save(path)

    # without registration: a helpful error naming the missing lambdas
    with pytest.raises(NotImplementedError, match="affine2x"):
        KerasModelImport.import_keras_model_and_weights(path)

    import jax.numpy as jnp
    KerasModelImport.register_lambda_layer("affine2x", lambda t: t * 2.0 + 1.0)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(5).normal(0, 1, (4, 8)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_custom_layer_spi(tmp_path):
    """Reference KerasLayer.registerCustomLayer: a user-defined Keras class
    maps through a registered factory."""
    from deeplearning4j_tpu.imports import KerasModelImport
    from deeplearning4j_tpu.nn.misc_layers import LambdaLayer

    @tf.keras.utils.register_keras_serializable(package="test")
    class Scale3(tf.keras.layers.Layer):
        def call(self, t):
            return t * 3.0

    km = tf.keras.Sequential([
        tf.keras.layers.Input((5,)),
        tf.keras.layers.Dense(4),
        Scale3(),
    ])
    path = str(tmp_path / "custom.keras")
    km.save(path)

    KerasModelImport.register_custom_layer(
        "Scale3", lambda kl, cfg: LambdaLayer(fn=lambda t: t * 3.0,
                                              fn_name="scale3"))
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(6).normal(0, 1, (4, 5)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_lambda_unsafe_load_requires_all_names_registered(tmp_path):
    """Registering ONE lambda must not unlock unsafe deserialization of an
    archive whose Lambda names are NOT all registered."""
    from deeplearning4j_tpu.imports import KerasModelImport
    from deeplearning4j_tpu.imports.keras_import import _archive_lambda_names
    km = tf.keras.Sequential([
        tf.keras.layers.Input((4,)),
        tf.keras.layers.Lambda(lambda t: t + 1.0, name="unregistered_fn"),
        tf.keras.layers.Dense(2),
    ])
    path = str(tmp_path / "evil.keras")
    km.save(path)
    assert _archive_lambda_names(path) == ["unregistered_fn"]

    import pytest as _pytest
    KerasModelImport.register_lambda_layer("some_other_fn", lambda t: t)
    with _pytest.raises(NotImplementedError, match="unregistered_fn"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_tf_import_partitioned_call():
    """TF2 nested tf.function -> (Stateful)PartitionedCall nodes are inlined."""
    from deeplearning4j_tpu.imports import TFGraphMapper
    w = tf.constant(np.random.default_rng(0).normal(0, 1, (6, 4)).astype(np.float32))

    @tf.function
    def inner(t):
        return tf.nn.relu(tf.matmul(t, w))

    def model(x):
        return inner(x) + inner(x * 2.0)

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((3, 6), tf.float32, name="x")])
    has_call = any(n.op in ("PartitionedCall", "StatefulPartitionedCall")
                   for n in gd.node)
    sd = TFGraphMapper.import_graph(gd)
    x = np.random.default_rng(1).normal(0, 1, (3, 6)).astype(np.float32)
    expected = model(tf.constant(x)).numpy()
    got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_while_loop():
    """TF2 while_loop -> While/StatelessWhile op mapped to sd.while_loop."""
    from deeplearning4j_tpu.imports import TFGraphMapper

    def model(x):
        i = tf.constant(0)
        def cond(i, acc):
            return i < 5
        def body(i, acc):
            return i + 1, acc * 1.5 + 1.0
        _, out = tf.while_loop(cond, body, (i, x))
        return out

    # keep FUNCTIONAL control flow (freezing lowers While to TF1 frames,
    # which the importer deliberately rejects)
    conc = tf.function(model).get_concrete_function(
        tf.TensorSpec((2, 3), tf.float32, name="x"))
    gd = conc.graph.as_graph_def()
    inputs = [t.name.split(":")[0] for t in conc.inputs]
    outputs = [t.name.split(":")[0] for t in conc.outputs]
    assert any(n.op in ("While", "StatelessWhile") for n in gd.node), \
        [n.op for n in gd.node]
    sd = TFGraphMapper.import_graph(gd)
    x = np.random.default_rng(2).normal(0, 1, (2, 3)).astype(np.float32)
    expected = model(tf.constant(x)).numpy()
    got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_cond():
    """TF2 tf.cond -> If/StatelessIf mapped to sd.cond (lax.cond)."""
    from deeplearning4j_tpu.imports import TFGraphMapper

    def model(x):
        pred = tf.reduce_sum(x) > 0.0
        return tf.cond(pred, lambda: x * 2.0, lambda: x - 1.0)

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((2, 4), tf.float32, name="x")])
    # freezing LOWERS tf.cond to Switch/Merge — the TF1 dataflow form
    assert any(n.op == "Switch" for n in gd.node), [n.op for n in gd.node]
    sd = TFGraphMapper.import_graph(gd)
    for seed in (3, 4):
        x = np.random.default_rng(seed).normal(0.5, 1, (2, 4)).astype(np.float32)
        expected = model(tf.constant(x)).numpy()
        got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_cond_branch_heavy_golden():
    """Branch-heavy lowered tf.cond vs the TF oracle: multi-node branch
    subgraphs, shared external values, a value consumed both inside and
    outside the conditional (round-5: Switch/Merge now lowers onto
    sd.cond — lazy branch execution — instead of execute-both + where)."""
    from deeplearning4j_tpu.imports import TFGraphMapper

    scale = tf.constant(np.linspace(0.5, 2.0, 4).astype(np.float32))

    def model(x):
        base = x * scale                      # used by BOTH branches + tail
        pred = tf.reduce_sum(x) > 0.0

        def true_branch():
            h = tf.nn.relu(base) + tf.sin(x)
            return tf.reduce_mean(h, axis=1, keepdims=True) * base

        def false_branch():
            h = tf.nn.softplus(base - 1.0)
            return h * 0.25 + tf.cos(x)

        out = tf.cond(pred, true_branch, false_branch)
        return out + base * 0.125             # tail also reads base

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((3, 4), tf.float32, name="x")])
    assert any(n.op == "Switch" for n in gd.node)
    sd = TFGraphMapper.import_graph(gd)
    # the Merge lowered to a lazy callable (lax.cond), not a where-select
    merges = [n.name for n in gd.node if n.op == "Merge"]
    lowered = [o for o in sd.ops
               if o.op == "__callable__" and o.outputs[0] in merges]
    assert lowered, [o.op for o in sd.ops]
    for seed in (0, 1, 2, 9):  # both branch directions across seeds
        x = np.random.default_rng(seed).normal(0, 1, (3, 4)).astype(np.float32)
        expected = model(tf.constant(x)).numpy()
        got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_cond_static_fold_const_in_branch():
    """A branch op that static-folds its operand (Mean's axis, Reshape's
    shape) fed by a Const OUTSIDE the switch-gated region: the slice must
    inline the Const into the branch subgraph (a Placeholder there would
    break the fold) and still lower lazily."""
    from deeplearning4j_tpu.imports import TFGraphMapper

    def model(x):
        pred = tf.reduce_sum(x) > 0.0
        return tf.cond(pred,
                       lambda: tf.reduce_mean(x * 2.0, axis=1, keepdims=True),
                       lambda: tf.reshape(tf.reduce_sum(x - 1.0, axis=1),
                                          (3, 1)))

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((3, 4), tf.float32, name="x")])
    assert any(n.op == "Switch" for n in gd.node)
    sd = TFGraphMapper.import_graph(gd)
    merges = [n.name for n in gd.node if n.op == "Merge"]
    assert [o for o in sd.ops
            if o.op == "__callable__" and o.outputs[0] in merges], \
        "fell back to where-select"
    for seed in (0, 3):
        x = np.random.default_rng(seed).normal(0, 1, (3, 4)).astype(np.float32)
        expected = model(tf.constant(x)).numpy()
        got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_cond_eager_optout_serializable(tmp_path):
    """lazy_conditionals=False keeps the imported graph free of python
    callables so sd.save()/load round-trips (the lazy form trades that
    for taken-branch-only execution)."""
    from deeplearning4j_tpu.imports import TFGraphMapper
    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    def model(x):
        pred = tf.reduce_sum(x) > 0.0
        return tf.cond(pred, lambda: x * 2.0, lambda: x - 1.0)

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((2, 4), tf.float32, name="x")])
    assert any(n.op == "Switch" for n in gd.node)
    sd = TFGraphMapper.import_graph(gd, lazy_conditionals=False)
    path = str(tmp_path / "cond.sdz")
    sd.save(path)  # would raise on the lazy (callable) form
    sd2 = SameDiff.load(path)
    for seed in (3, 4):
        x = np.random.default_rng(seed).normal(0.5, 1, (2, 4)).astype(np.float32)
        expected = model(tf.constant(x)).numpy()
        got = np.asarray(sd2.output({inputs[0]: x}, outputs[0]))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_cond_untaken_branch_grad_clean():
    """The signature difference between lazy cond and execute-both+where:
    reverse-mode through `where` computes BOTH branch vjps, and an untaken
    sqrt-at-zero poisons the gradient with NaN (NaN * 0 = NaN). lax.cond
    runs only the taken branch's vjp, so the gradient stays finite."""
    from deeplearning4j_tpu.imports import TFGraphMapper
    from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
    from deeplearning4j_tpu.train.updaters import Adam

    cvals = np.zeros((2, 3), np.float32)  # sqrt'(0) = inf in the dead lane

    def model(x):
        c = tf.constant(cvals, name="w_const")
        pred = tf.reduce_sum(x) > 1e9      # always False at test inputs
        out = tf.cond(pred, lambda: tf.sqrt(c) * x, lambda: c * 3.0 + x)
        return tf.reduce_sum(out, axis=1)

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((2, 3), tf.float32, name="x")])
    assert any(n.op == "Switch" for n in gd.node)
    sd = TFGraphMapper.import_graph(gd)
    sd.convert_to_variable("w_const")
    loss = sd.invoke("reduce_sum", sd.vars[outputs[0]], name="probe_loss")
    sd.set_loss_variables(loss.name)
    x = np.random.default_rng(0).normal(0, 1, (2, 3)).astype(np.float32)
    grads = sd.calculate_gradients({inputs[0]: x}, "w_const")
    g = np.asarray(grads["w_const"])
    assert np.all(np.isfinite(g)), g      # where-form would be NaN here
    np.testing.assert_allclose(g, np.full_like(g, 3.0), rtol=1e-6)


def test_tf_import_saved_model(tmp_path):
    """SavedModel -> freeze serving signature -> import."""
    from deeplearning4j_tpu.imports import TFGraphMapper

    class M(tf.Module):
        def __init__(self):
            super().__init__()
            self.w = tf.Variable(
                np.random.default_rng(0).normal(0, 1, (5, 3)).astype(np.float32))

        @tf.function(input_signature=[tf.TensorSpec((None, 5), tf.float32)])
        def __call__(self, x):
            return tf.nn.softmax(tf.matmul(x, self.w))

    m = M()
    path = str(tmp_path / "sm")
    tf.saved_model.save(m, path)
    sd, inputs, outputs = TFGraphMapper.import_saved_model(path)
    x = np.random.default_rng(5).normal(0, 1, (4, 5)).astype(np.float32)
    expected = m(tf.constant(x)).numpy()
    got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf_import_functional_if():
    """Unlowered StatelessIf/If (tf.function graph) maps to sd.cond."""
    from deeplearning4j_tpu.imports import TFGraphMapper

    def model(x):
        pred = tf.reduce_sum(x) > 0.0
        return tf.cond(pred, lambda: x * 2.0, lambda: x - 1.0)

    conc = tf.function(model).get_concrete_function(
        tf.TensorSpec((2, 4), tf.float32, name="x"))
    gd = conc.graph.as_graph_def()
    inputs = [t.name.split(":")[0] for t in conc.inputs]
    outputs = [t.name.split(":")[0] for t in conc.outputs]
    assert any(n.op in ("If", "StatelessIf") for n in gd.node), \
        [n.op for n in gd.node]
    sd = TFGraphMapper.import_graph(gd)
    for seed in (3, 4):
        x = np.random.default_rng(seed).normal(0.5, 1, (2, 4)).astype(np.float32)
        expected = model(tf.constant(x)).numpy()
        got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_keras_separable_conv1d_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12, 6)),
        tf.keras.layers.SeparableConv1D(8, 3, padding="same", activation="relu",
                                        depth_multiplier=2),
        tf.keras.layers.GlobalAveragePooling1D(),
        tf.keras.layers.Dense(3),
    ])
    path = str(tmp_path / "sc1d.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(0).normal(0, 1, (4, 12, 6)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_locally_connected1d_matches_manual():
    """Keras 3 removed LocallyConnected*, so the mapper can only be hit by
    legacy archives — validate the LAYER against a manual unshared-conv
    reference instead."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import LocallyConnected1D
    from deeplearning4j_tpu.nn.base import GlobalConfig
    from deeplearning4j_tpu.nn.inputs import InputType

    B, T, F, K, O = 3, 10, 4, 3, 6
    layer = LocallyConnected1D(n_out=O, kernel_size=K, stride=1,
                               activation="identity")
    g = GlobalConfig()
    layer._g = g
    params, state = layer.init(jax.random.PRNGKey(0),
                               InputType.recurrent(F, T), g)
    x = np.random.default_rng(0).normal(0, 1, (B, T, F)).astype(np.float32)
    y, _ = layer.forward(params, state, jnp.asarray(x))
    W = np.asarray(params["W"])  # (T-K+1, 1, F*K, O)
    b = np.asarray(params["b"])
    expect = np.zeros((B, T - K + 1, O), np.float32)
    for t in range(T - K + 1):
        patch = x[:, t:t + K, :].transpose(0, 2, 1).reshape(B, F * K)
        expect[:, t, :] = patch @ W[t, 0] + b[t]
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


def test_keras_pooling1d_permute_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12, 6)),
        tf.keras.layers.MaxPooling1D(2),
        tf.keras.layers.AveragePooling1D(2),
        tf.keras.layers.Permute((2, 1)),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(2),
    ])
    path = str(tmp_path / "p1d.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(2).normal(0, 1, (3, 12, 6)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras_convlstm2d_import(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    km = tf.keras.Sequential([
        tf.keras.layers.Input((4, 8, 8, 3)),
        tf.keras.layers.ConvLSTM2D(5, 3, padding="valid", strides=2,
                                   return_sequences=False),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(2),
    ])
    path = str(tmp_path / "clstm.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(3).normal(0, 1, (2, 4, 8, 8, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-3, atol=1e-4)


def test_keras_functional_dot_minimum(tmp_path):
    from deeplearning4j_tpu.imports import KerasModelImport
    inp = tf.keras.layers.Input((8,))
    a = tf.keras.layers.Dense(8, activation="relu")(inp)
    b = tf.keras.layers.Dense(8, activation="relu")(inp)
    mn = tf.keras.layers.Minimum()([a, b])
    dt = tf.keras.layers.Dot(axes=-1)([a, b])
    cat = tf.keras.layers.Concatenate()([mn, dt])
    out = tf.keras.layers.Dense(2)(cat)
    km = tf.keras.Model(inp, out)
    path = str(tmp_path / "dm.keras")
    km.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(4).normal(0, 1, (5, 8)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), km(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_keras1_h5_dialect_import(tmp_path):
    """Keras 1.x H5 archives (nb_filter/border_mode/output_dim era) import
    through the legacy dialect parser — modern Keras refuses these files
    entirely, so the oracle is a manual numpy forward."""
    import h5py
    import json
    from deeplearning4j_tpu.imports import KerasModelImport

    rng = np.random.default_rng(0)
    W1 = rng.normal(0, 0.5, (6, 10)).astype(np.float32)
    b1 = rng.normal(0, 0.1, (10,)).astype(np.float32)
    W2 = rng.normal(0, 0.5, (10, 3)).astype(np.float32)
    b2 = np.zeros(3, np.float32)

    model_config = {
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense",
             "config": {"name": "dense_1", "output_dim": 10,
                        "activation": "relu", "batch_input_shape": [None, 6]}},
            {"class_name": "Dense",
             "config": {"name": "dense_2", "output_dim": 3,
                        "activation": "softmax"}},
        ],
    }
    path = str(tmp_path / "k1.h5")
    with h5py.File(path, "w") as f:
        f.attrs["keras_version"] = np.bytes_(b"1.2.2")
        f.attrs["model_config"] = np.bytes_(json.dumps(model_config).encode())
        mw = f.create_group("model_weights")
        g1 = mw.create_group("dense_1")
        g1.attrs["weight_names"] = [np.bytes_(b"dense_1_W"), np.bytes_(b"dense_1_b")]
        g1.create_dataset("dense_1_W", data=W1)
        g1.create_dataset("dense_1_b", data=b1)
        g2 = mw.create_group("dense_2")
        g2.attrs["weight_names"] = [np.bytes_(b"dense_2_W"), np.bytes_(b"dense_2_b")]
        g2.create_dataset("dense_2_W", data=W2)
        g2.create_dataset("dense_2_b", data=b2)

    net = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(0, 1, (5, 6)).astype(np.float32)
    h = np.maximum(x @ W1 + b1, 0)
    logits = h @ W2 + b2
    expected = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(net.output(x)), expected,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_tf_import_stock_mobilenetv2(tmp_path):
    """VERDICT r2 item 4: import a model the importer's authors did NOT
    build — a stock `tf.keras.applications.MobileNetV2` SavedModel (random
    weights; downloads are impossible offline). Activations must golden-
    match TF and a grafted fine-tune step must run."""
    tf = pytest.importorskip("tensorflow")
    import numpy as np
    from deeplearning4j_tpu.imports import TFGraphMapper

    tf.keras.utils.set_random_seed(0)
    model = tf.keras.applications.MobileNetV2(
        input_shape=(96, 96, 3), alpha=0.35, weights=None, classes=11)
    path = str(tmp_path / "mnv2")
    tf.saved_model.save(model, path)

    sd, inputs, outputs = TFGraphMapper.import_saved_model(path)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 96, 96, 3)).astype(np.float32)
    want = model(x, training=False).numpy()
    got = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)

    # fine-tune: graft a fresh head on the pre-softmax features and step
    from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
    from deeplearning4j_tpu.train.updaters import Adam
    sd.convert_to_variable(*sd.trainable_float_constants())
    labels = sd.placeholder("labels", (None, 11))
    out_v = sd.vars[outputs[0]]
    loss = sd.loss.softmax_cross_entropy("ft_loss", labels, out_v)
    sd.set_loss_variables("ft_loss")
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-4), data_set_feature_mapping=[inputs[0]],
        data_set_label_mapping=["labels"]))
    y = np.eye(11, dtype=np.float32)[rng.integers(0, 11, 2)]
    hist = sd.fit(x, y, epochs=2)
    assert np.isfinite(list(hist)).all()


def test_tf_import_einsum_deconv_resize_dynamic_shape(tmp_path):
    """Round-3 importer generality: Einsum, Conv2DBackpropInput (Keras
    Conv2DTranspose), DepthwiseConv2dNative, ResizeNearestNeighbor, and a
    Reshape whose shape operand is COMPUTED (tf.shape chain) all import and
    golden-match TF."""
    tf = pytest.importorskip("tensorflow")
    import numpy as np
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    from deeplearning4j_tpu.imports import TFGraphMapper

    rng = np.random.default_rng(0)
    B, H, W, C = 2, 8, 8, 4
    wd = rng.normal(0, 0.3, (3, 3, C, 2)).astype(np.float32)   # depthwise
    wt = rng.normal(0, 0.3, (3, 3, 6, C * 2)).astype(np.float32)  # deconv HWIO
    we = rng.normal(0, 0.3, (6, 5)).astype(np.float32)

    def model(x):
        d = tf.nn.depthwise_conv2d(x, wd, (1, 1, 1, 1), "SAME")      # (B,8,8,8)
        t = tf.nn.conv2d_transpose(d, wt, (B, 2 * H, 2 * W, 6), (1, 2, 2, 1),
                                   "SAME")                            # (B,16,16,6)
        r = tf.compat.v1.image.resize_nearest_neighbor(t, (H, W))     # (B,8,8,6)
        e = tf.einsum("bhwc,cd->bhwd", r, we)                         # (B,8,8,5)
        flat = tf.reshape(e, tf.stack([tf.shape(e)[0], -1]))          # computed shape
        return flat

    conc = tf.function(model).get_concrete_function(
        tf.TensorSpec((B, H, W, C), tf.float32, name="x"))
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    out_name = frozen.outputs[0].name.split(":")[0]

    x = rng.normal(0, 1, (B, H, W, C)).astype(np.float32)
    want = model(tf.constant(x)).numpy()
    sd = TFGraphMapper.import_graph(gd)
    got = np.asarray(sd.output({"x": x}, out_name))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras2_gru_reset_after_dual_bias_golden(tmp_path):
    """tf.keras GRU default (reset_after=True) has TWO bias sets; the
    recurrent one lives inside the reset product for the n gate. Import
    must golden-match, not sum the biases."""
    tf = pytest.importorskip("tensorflow")
    from deeplearning4j_tpu.imports import KerasModelImport
    tf.keras.utils.set_random_seed(3)
    model = tf.keras.Sequential([
        tf.keras.layers.Input((6, 5)),
        tf.keras.layers.GRU(7, return_sequences=True,
                            bias_initializer="glorot_uniform"),
        tf.keras.layers.Dense(3, activation="softmax"),
    ])
    path = str(tmp_path / "gru2.h5")
    model.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(0).normal(0, 1, (4, 6, 5)).astype(np.float32)
    want = model(x).numpy()
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras1_gru_reset_before_golden(tmp_path):
    """VERDICT r2 item 8: Keras-1 GRU (reset-BEFORE cell, hard_sigmoid
    gates, per-gate weight arrays) imports and matches a manual numpy
    forward of that exact cell — the refusal is gone."""
    import h5py
    import json
    from deeplearning4j_tpu.imports import KerasModelImport

    rng = np.random.default_rng(5)
    I, H = 5, 7
    Wz, Wr, Wh = (rng.normal(0, 0.4, (I, H)).astype(np.float32) for _ in range(3))
    Uz, Ur, Uh = (rng.normal(0, 0.4, (H, H)).astype(np.float32) for _ in range(3))
    bz, br, bh = (rng.normal(0, 0.1, (H,)).astype(np.float32) for _ in range(3))

    model_config = {
        "class_name": "Sequential",
        "config": [
            {"class_name": "GRU",
             "config": {"name": "gru_1", "output_dim": H,
                        "activation": "tanh", "inner_activation": "hard_sigmoid",
                        "return_sequences": True,
                        "batch_input_shape": [None, 6, I]}},
        ],
    }
    path = str(tmp_path / "k1gru.h5")
    names = ["gru_1_W_z", "gru_1_U_z", "gru_1_b_z",
             "gru_1_W_r", "gru_1_U_r", "gru_1_b_r",
             "gru_1_W_h", "gru_1_U_h", "gru_1_b_h"]
    arrs = [Wz, Uz, bz, Wr, Ur, br, Wh, Uh, bh]
    with h5py.File(path, "w") as f:
        f.attrs["keras_version"] = np.bytes_(b"1.2.2")
        f.attrs["model_config"] = np.bytes_(json.dumps(model_config).encode())
        mw = f.create_group("model_weights")
        g = mw.create_group("gru_1")
        g.attrs["weight_names"] = [np.bytes_(n.encode()) for n in names]
        for n, a in zip(names, arrs):
            g.create_dataset(n, data=a)

    net = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(0, 1, (3, 6, I)).astype(np.float32)

    def hard_sigmoid(v):
        return np.clip(0.2 * v + 0.5, 0.0, 1.0)

    h = np.zeros((3, H), np.float32)
    outs = []
    for t in range(6):
        xt = x[:, t]
        z = hard_sigmoid(xt @ Wz + h @ Uz + bz)
        r = hard_sigmoid(xt @ Wr + h @ Ur + br)
        hh = np.tanh(xt @ Wh + (r * h) @ Uh + bh)
        h = z * h + (1 - z) * hh
        outs.append(h)
    want = np.stack(outs, axis=1)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras2_bidirectional_gru_golden(tmp_path):
    """Bidirectional(GRU) goes through the shared _assign_rnn path — gate
    reorder + dual bias must apply there too."""
    tf = pytest.importorskip("tensorflow")
    from deeplearning4j_tpu.imports import KerasModelImport
    tf.keras.utils.set_random_seed(4)
    model = tf.keras.Sequential([
        tf.keras.layers.Input((5, 4)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.GRU(6, return_sequences=True,
                                bias_initializer="glorot_uniform")),
        tf.keras.layers.Dense(2, activation="softmax"),
    ])
    path = str(tmp_path / "bigru.h5")
    model.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = np.random.default_rng(0).normal(0, 1, (3, 5, 4)).astype(np.float32)
    want = model(x).numpy()
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tf_import_round3_simple_op_batch(tmp_path):
    """Round-3 simple-op mappings: trig/special tails, LeakyRelu, Cumsum,
    DepthToSpace, ReverseV2, TopKV2, matrix ops — golden vs TF."""
    tf = pytest.importorskip("tensorflow")
    import numpy as np
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    from deeplearning4j_tpu.imports import TFGraphMapper

    rng = np.random.default_rng(0)

    def model(x, img):
        a = tf.math.asinh(x) + tf.math.atan2(x, x + 2.0)
        a = tf.nn.leaky_relu(a, alpha=0.3) + tf.math.expm1(x * 0.1)
        a = tf.cumsum(a, axis=1) + tf.math.xdivy(x, tf.math.rint(x))
        a = tf.reverse(a, axis=[1])
        vals, idx = tf.math.top_k(a, k=2)
        d = tf.nn.depth_to_space(img, 2)
        return a, vals, tf.cast(idx, tf.float32), d

    conc = tf.function(model).get_concrete_function(
        tf.TensorSpec((3, 5), tf.float32, name="x"),
        tf.TensorSpec((2, 4, 4, 8), tf.float32, name="img"))
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    out_names = [t.name.split(":")[0] for t in frozen.outputs]

    x = rng.normal(0, 1, (3, 5)).astype(np.float32)
    img = rng.normal(0, 1, (2, 4, 4, 8)).astype(np.float32)
    wants = [t.numpy() for t in model(tf.constant(x), tf.constant(img))]
    sd = TFGraphMapper.import_graph(gd)
    feeds = {"x": x, "img": img}
    # outputs may share names with :N suffixes; fetch one by one
    for want, name in zip(wants, out_names):
        got = np.asarray(sd.output(feeds, name))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tf_import_training_dropout_active_in_fit():
    """A TF graph exported with dropout ACTIVE (training=True → stateful
    RandomUniform node) imports, and sd.fit applies a fresh mask per step:
    at lr=0 with constant data the loss varies across steps. Inference
    (sd.output) stays deterministic. (Round-3 bug: SameDiff training was
    silently dropout-free.)"""
    from deeplearning4j_tpu.imports import TFGraphMapper
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.train.updaters import Sgd
    w = tf.constant(
        np.random.default_rng(0).normal(0, 1, (8, 8)).astype(np.float32))

    def model(x):
        return tf.nn.dropout(tf.matmul(x, w), rate=0.5)

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((16, 8), tf.float32, name="x")])
    assert any(n.op == "RandomUniform" for n in gd.node)
    sd = TFGraphMapper.import_graph(gd)

    # inference: deterministic across calls (static-seed draw)
    x = np.random.default_rng(1).normal(0, 1, (16, 8)).astype(np.float32)
    o1 = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    o2 = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    np.testing.assert_array_equal(o1, o2)

    # training: per-step stochasticity
    pred = sd.vars[outputs[0]]
    labels = sd.placeholder("labels", (None, 8))
    sd.loss.mean_squared_error("loss", labels, pred)
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=Sgd(0.0), data_set_feature_mapping=[inputs[0]],
        data_set_label_mapping=["labels"]))
    y = np.zeros((16, 8), np.float32)
    losses = []
    for _ in range(3):
        losses.extend(sd.fit(x, y, epochs=1))
    assert len(set(np.round(losses, 10))) > 1, losses


def test_tf1_while_loop_frames_import():
    """TF1-style lowered while-loop frames (Enter/Merge/Switch/
    NextIteration/Exit) import and match the TF oracle — the last importer
    refusal deleted (round-3 VERDICT missing #1)."""
    from deeplearning4j_tpu.imports import TFGraphMapper

    tf.compat.v1.disable_control_flow_v2()
    g = tf.Graph()
    try:
      with g.as_default():
        with tf.compat.v1.Session() as sess:
            xin = tf.compat.v1.placeholder(tf.float32, (3, 4), name="x")
            # classic v1 control flow: frozen graphs of legacy models carry
            # these frames; tf.while_loop in compat.v1 graph mode lowers to
            # Enter/Merge/Switch/NextIteration/Exit
            w = tf.constant(np.full((4, 4), 0.5, np.float32))

            def cond(i, acc):
                return i < 5

            def body(i, acc):
                return i + 1, tf.tanh(acc @ w) + xin

            _, acc = tf.while_loop(cond, body, (tf.constant(0), xin))
            out = acc * 2.0
            gd = sess.graph.as_graph_def()
            out_name = out.name.split(":")[0]
            x_np = np.random.default_rng(0).normal(0, 1, (3, 4)).astype(np.float32)
            expected = sess.run(out, {xin: x_np})
    finally:
        tf.compat.v1.enable_control_flow_v2()
    assert any(n.op == "Enter" for n in gd.node), "graph has no v1 frames"
    sd = TFGraphMapper.import_graph(gd)
    got = np.asarray(sd.output({"x": x_np}, out_name))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_tf1_while_loop_invariant_and_multi_carry():
    """Frame with a loop-invariant Enter and two data carries."""
    from deeplearning4j_tpu.imports import TFGraphMapper
    tf.compat.v1.disable_control_flow_v2()
    g = tf.Graph()
    try:
      with g.as_default():
        with tf.compat.v1.Session() as sess:
            xin = tf.compat.v1.placeholder(tf.float32, (2, 3), name="x")
            scale = tf.constant(1.5, tf.float32)  # enters as invariant

            def cond(i, a, b):
                return i < 3

            def body(i, a, b):
                return i + 1, a + b * scale, b + 1.0

            _, a_fin, b_fin = tf.while_loop(
                cond, body, (tf.constant(0), xin, tf.ones_like(xin)))
            out = a_fin + b_fin
            gd = sess.graph.as_graph_def()
            out_name = out.name.split(":")[0]
            x_np = np.random.default_rng(1).normal(0, 1, (2, 3)).astype(np.float32)
            expected = sess.run(out, {xin: x_np})
    finally:
        tf.compat.v1.enable_control_flow_v2()
    assert any(n.op == "Enter" for n in gd.node), "graph has no v1 frames"
    sd = TFGraphMapper.import_graph(gd)
    got = np.asarray(sd.output({"x": x_np}, out_name))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_while_import_differentiable_with_max_iterations():
    """``import_graph(while_max_iterations=N)`` lowers imported While loops
    to the masked-scan form, so graphs containing loops can be FINE-TUNED
    (the default lax.while_loop lowering is forward-only)."""
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.imports import TFGraphMapper
    from deeplearning4j_tpu.train.updaters import Sgd
    w = tf.constant(np.full((4, 4), 0.1, np.float32))

    def model(x):
        def cond(i, acc):
            return i < 3

        def body(i, acc):
            return i + 1, tf.tanh(acc @ w) + x

        _, acc = tf.while_loop(cond, body, (tf.constant(0), x))
        return acc

    gd, inputs, outputs = _frozen_graphdef(
        model, [tf.TensorSpec((2, 4), tf.float32, name="x")])
    x_np = np.random.default_rng(0).normal(0, 1, (2, 4)).astype(np.float32)
    expected = model(tf.constant(x_np)).numpy()

    sd = TFGraphMapper.import_graph(gd, while_max_iterations=3)
    got = np.asarray(sd.output({inputs[0]: x_np}, outputs[0]))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)

    # fine-tune THROUGH the loop: convert the weight constant, fit, and
    # require the loss to move (gradients flow through the scanned body)
    out_v = sd.vars[outputs[0]]
    labels = sd.placeholder("labels", (None, 4))
    sd.loss.mean_squared_error("loss", labels, out_v)
    sd.set_loss_variables("loss")
    weights = sd.trainable_float_constants()
    assert weights, "no weight constants found"
    sd.convert_to_variable(*weights)
    sd.set_training_config(TrainingConfig(
        updater=Sgd(0.05), data_set_feature_mapping=[inputs[0]],
        data_set_label_mapping=["labels"]))
    y = np.zeros((2, 4), np.float32)
    losses = []
    for _ in range(8):
        losses.extend(sd.fit(x_np, y, epochs=1))
    assert losses[-1] < losses[0] * 0.9, losses


def test_tf1_nested_while_loops_import():
    """A v1 while INSIDE a v1 while (nested frames): the outer frame's
    body slice carries the whole inner frame, and the sub-importer lowers
    it recursively."""
    from deeplearning4j_tpu.imports import TFGraphMapper
    tf.compat.v1.disable_control_flow_v2()
    g = tf.Graph()
    try:
      with g.as_default():
        with tf.compat.v1.Session() as sess:
            xin = tf.compat.v1.placeholder(tf.float32, (2, 3), name="x")

            def outer_body(i, acc):
                def inner_body(j, a):
                    return j + 1, a * 0.5 + 1.0

                _, a_fin = tf.while_loop(
                    lambda j, a: j < 2, inner_body, (tf.constant(0), acc))
                return i + 1, a_fin + xin

            _, out = tf.while_loop(lambda i, a: i < 3, outer_body,
                                   (tf.constant(0), xin))
            gd = sess.graph.as_graph_def()
            out_name = out.name.split(":")[0]
            x_np = np.random.default_rng(2).normal(0, 1, (2, 3)).astype(np.float32)
            expected = sess.run(out, {xin: x_np})
    finally:
        tf.compat.v1.enable_control_flow_v2()
    assert sum(1 for n in gd.node if n.op == "Enter") > 4  # two frames
    sd = TFGraphMapper.import_graph(gd)
    got = np.asarray(sd.output({"x": x_np}, out_name))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
