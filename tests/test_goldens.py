"""Loss-curve regression goldens (BASELINE.md measurement plan item 2).

Deterministic seeded training runs whose per-step losses were recorded on
CPU and committed as fixtures. Any change to initialization draws, updater
math, loss conventions, RNG threading, or layer numerics shows up here as a
diff — the role the reference's loss-parity configs play (BASELINE configs
#1/#3/#4). Tolerances allow for XLA-version fusion drift, not semantic
change.
"""

import numpy as np

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import (ConvolutionLayer, DenseLayer, GravesLSTM,
                                   InputType, NeuralNetConfiguration,
                                   OutputLayer, RnnOutputLayer,
                                   SubsamplingLayer)
from deeplearning4j_tpu.train import Adam, CollectScoresListener, Sgd

# re-recorded 2026-08-03 on jax 0.4.37 (this repo's pinned toolchain), CPU
# backend, verified bit-identical across two fresh processes. The previous
# values (recorded on jax 0.9.0) were unreachable here: initialization /
# dropout draws differ across jax versions, so every curve diverged from
# step 1 and the goldens never provided regression signal on this
# toolchain. Goldens are environment-pinned fixtures — re-record (twice,
# diffing for determinism) whenever the jax pin moves.
LENET_GOLDEN = [2.309887, 2.272974, 2.253786, 2.242065, 2.193092,
                2.156597, 2.138206, 2.118122, 2.115263, 2.068008]
# (round 2: LSTM cell activation fixed to the reference's tanh default —
# was inheriting global identity)
LSTM_GOLDEN = [2.471995, 2.455743, 2.443324, 2.432385, 2.422121,
               2.412248, 2.402635, 2.393207]
# (round 3: dropout masks moved from threefry to the rbg generator —
# intentional perf change, BASELINE.md)
BERT_GOLDEN = [0.533299, 0.650245, 0.674123, 0.651878, 0.568803, 0.644421]

_TOL = dict(rtol=2e-3, atol=2e-3)


def test_lenet_loss_curve_golden():
    from deeplearning4j_tpu.data import MnistDataSetIterator
    conf = (NeuralNetConfiguration.builder().seed(123).updater(Adam(1e-3)).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1)).build())
    net = MultiLayerNetwork(conf).init()
    it = MnistDataSetIterator(batch_size=32, train=True, num_examples=160,
                              shuffle=False)
    if not it.synthetic:
        import pytest
        pytest.skip("real MNIST cache present; golden recorded on the "
                    "deterministic synthetic set")
    c = CollectScoresListener()
    net.set_listeners(c)
    net.fit(it, epochs=2)
    np.testing.assert_allclose([s for _, s in c.scores], LENET_GOLDEN, **_TOL)


def test_graves_lstm_loss_curve_golden():
    B, T, V = 8, 16, 12
    seq = np.tile(np.arange(V), (B, T // V + 2))[:, :T + 1]
    x = np.eye(V, dtype=np.float32)[seq[:, :-1]]
    y = np.eye(V, dtype=np.float32)[seq[:, 1:]]
    conf = (NeuralNetConfiguration.builder().seed(99).updater(Sgd(0.5)).list()
            .layer(GravesLSTM(n_out=16))
            .layer(RnnOutputLayer(n_out=V, activation="softmax"))
            .set_input_type(InputType.recurrent(V, T)).build())
    net = MultiLayerNetwork(conf).init()
    losses = []
    for _ in range(8):
        net.fit(x, y, epochs=1)
        losses.append(float(net.score()))
    np.testing.assert_allclose(losses, LSTM_GOLDEN, **_TOL)


def test_bert_loss_curve_golden():
    from deeplearning4j_tpu.zoo import Bert
    model = Bert(vocab_size=64, d_model=32, n_layers=2, n_heads=2, ffn_size=64,
                 max_len=16, num_classes=2, seed=5)
    net = model.init()
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 64, (8, 16)).astype(np.int32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    losses = []
    for _ in range(6):
        net.fit(toks, y, epochs=1)
        losses.append(float(net.score()))
    np.testing.assert_allclose(losses, BERT_GOLDEN, **_TOL)
