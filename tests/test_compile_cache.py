"""Cold-start & dispatch fast-path tests (ISSUE 5): persistent executable
cache (hit/miss accounting, corrupt-entry fallback via the
``runtime.compile_cache.load`` chaos point), warmup-manifest recording and
replay (compiles on replay <= recorded pairs), and AOT-dispatch
bit-identity vs the jit path for MLN / ComputationGraph / sd.fit /
ParallelWrapper / the serving batcher.

All tier-1 (CPU, no ``slow`` marker); the cache tests use a tmp_path cache
directory and detach it on the way out so the rest of the suite is
unaffected.
"""

import glob
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.runtime import chaos, compile_cache
from deeplearning4j_tpu.runtime.environment import get_environment
from deeplearning4j_tpu.serving import ContinuousBatcher, ModelRegistry
from deeplearning4j_tpu.serving.manifest import (WarmupManifest,
                                                 manifest_path)
from deeplearning4j_tpu.train import Sgd


# ------------------------------------------------------------ helpers
def _mln_conf(seed=7, n_in=8):
    return (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())


def _graph_conf(seed=5):
    return (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.05))
            .graph_builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_out=12, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "h")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(6))
            .build())


def _iterator(n=24, n_in=8, n_out=4, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return ListDataSetIterator([DataSet(x, y)], batch_size=batch)


def _probe_fn():
    """A fresh jit wrapper of the SAME program each call — forces the
    persistent-cache path (a new wrapper has no in-memory executable) with
    a stable cache key (same HLO)."""
    def cc_probe(x):
        return (x * 2.0 + 1.0) @ x.T
    return jax.jit(cc_probe)


@pytest.fixture
def cache_dir(tmp_path):
    d = compile_cache.enable(str(tmp_path / "executable-cache"))
    compile_cache.reset_stats()
    yield d
    compile_cache.disable()


@pytest.fixture
def aot_toggle():
    """Restore the process-wide AOT knob after a test flips it."""
    env = get_environment()
    before = env.aot_dispatch
    yield env
    env.aot_dispatch = before


# ----------------------------------------------------- persistent cache
def test_enable_is_framework_keyed_and_counts_hits_and_misses(cache_dir):
    assert compile_cache.FRAMEWORK_KEY in cache_dir
    assert f"jax{jax.__version__}" in cache_dir
    x = jnp.ones((32, 16))
    r1 = np.asarray(_probe_fn()(x))
    s1 = compile_cache.stats()
    assert s1["enabled"] and s1["misses"] >= 1
    assert glob.glob(cache_dir + "/*-cache"), "no entries persisted"
    hits_before = s1["hits"]
    r2 = np.asarray(_probe_fn()(x))  # same HLO, fresh wrapper -> cache hit
    s2 = compile_cache.stats()
    assert s2["hits"] > hits_before
    assert (r1 == r2).all(), "cached executable changed results"
    # the same counters ride the profiler facade and serving /metrics
    from deeplearning4j_tpu.runtime.profiler import compile_cache_stats
    assert compile_cache_stats()["hits"] == s2["hits"]


def test_corrupt_entry_falls_back_to_compile(cache_dir):
    x = jnp.ones((16, 8))
    r1 = np.asarray(_probe_fn()(x))
    for p in glob.glob(cache_dir + "/*-cache"):  # bit-rot every entry
        with open(p, "r+b") as f:
            f.write(b"\xff\x00garbage" * 4)
    r2 = np.asarray(_probe_fn()(x))
    s = compile_cache.stats()
    assert s["corrupt_entries"] >= 1, "corruption not detected/counted"
    assert (r1 == r2).all(), "fallback compile changed results"


def test_chaos_load_fault_falls_back_to_compile(cache_dir):
    x = jnp.ones((16, 8))
    r1 = np.asarray(_probe_fn()(x))  # populate the cache
    before = compile_cache.stats()["corrupt_entries"]
    with chaos.ChaosController(seed=3) as c:
        c.on("runtime.compile_cache.load", chaos.FailNth(1, every=True))
        r2 = np.asarray(_probe_fn()(x))
        assert c.count("runtime.compile_cache.load") >= 1
    assert compile_cache.stats()["corrupt_entries"] > before
    assert (r1 == r2).all(), "chaos fallback changed results"
    # controller gone: the next lookup is a clean hit again
    hits = compile_cache.stats()["hits"]
    np.asarray(_probe_fn()(x))
    assert compile_cache.stats()["hits"] > hits


# ----------------------------------------------------------- AOT cache
def test_aot_cache_bit_identity_and_signature_fallback(aot_toggle):
    aot_toggle.set_aot_dispatch(True)
    fitted = jax.jit(lambda s, x: (s + 1.0, (s @ x.T).sum()))
    s0 = jnp.full((4, 8), 2.0)
    x16 = jnp.ones((16, 8))
    aot = compile_cache.AotCache("test")
    got = aot.call("k16", fitted, s0, x16)
    ref = fitted(s0, x16)
    assert (np.asarray(got[0]) == np.asarray(ref[0])).all()
    assert float(got[1]) == float(ref[1])
    assert len(aot) == 1
    # a colliding key (different avals, same key) must fall back, not fail
    fb_before = compile_cache.stats()["aot_fallbacks"]
    x8 = jnp.ones((8, 8))
    got2 = aot.call("k16", fitted, s0, x8)
    assert float(got2[1]) == float(fitted(s0, x8)[1])
    assert compile_cache.stats()["aot_fallbacks"] > fb_before
    # knob off: no executables minted, jit path used
    aot_toggle.set_aot_dispatch(False)
    aot2 = compile_cache.AotCache("off")
    aot2.call("k", fitted, s0, x16)
    assert len(aot2) == 0


# ------------------------------------------------------------ manifests
def test_manifest_roundtrip_and_corrupt_tolerance(tmp_path):
    m = WarmupManifest.from_example(
        {"a": np.zeros((1, 3, 4), np.float32),
         "b": np.zeros((1, 2), np.int32)},
        buckets=[1, 2, 4], replicas=2,
        pairs=[(1, 0, "float32"), (1, 1, "float32")],
        max_batch_size=4, model="ComputationGraph")
    path = manifest_path(str(tmp_path / "model.zip"))
    m.save(path)
    back = WarmupManifest.load(path)
    assert back.buckets == [1, 2, 4] and back.replicas == 2
    assert back.max_batch_size == 4 and back.pairs == m.pairs
    ex = back.example(rows=4)
    assert ex["a"].shape == (4, 3, 4) and ex["a"].dtype == np.float32
    assert ex["b"].shape == (4, 2) and ex["b"].dtype == np.int32
    # corrupt manifest: load_for_archive degrades to None, never raises
    with open(path, "w") as f:
        f.write('{"format": "torn')
    assert WarmupManifest.load_for_archive(str(tmp_path / "model.zip")) is None
    assert WarmupManifest.load_for_archive(str(tmp_path / "no.zip")) is None


def test_registry_load_replays_manifest_compiles_bounded(tmp_path):
    archive = str(tmp_path / "model.zip")
    MultiLayerNetwork(_mln_conf()).init().save(archive)
    x = np.random.default_rng(0).normal(0, 1, (48, 8)).astype(np.float32)

    reg1 = ModelRegistry()
    served1 = reg1.load("m", archive, max_batch_size=8, batch_timeout_ms=1.0,
                        pipeline_depth=0,
                        warmup_example=x[:1])
    assert served1.metrics.snapshot()["warmup_seconds"] > 0
    base = np.asarray(served1.predict(x[:3]))
    oversized = np.asarray(served1.predict(x))  # 48 rows -> mints bucket 64
    minted_buckets = list(served1.batcher.buckets)
    assert 64 in minted_buckets
    reg1.shutdown()  # graceful: refreshes the manifest with the mint

    manifest = WarmupManifest.load(manifest_path(archive))
    assert manifest.buckets == minted_buckets
    assert manifest.max_batch_size == 8

    reg2 = ModelRegistry()
    served2 = reg2.load("m", archive, batch_timeout_ms=1.0, pipeline_depth=0)
    try:
        # replay: recorded buckets (incl. the traffic-minted 64) pre-warmed
        assert list(served2.batcher.buckets) == minted_buckets
        assert served2.batcher.max_batch_size == 8
        ready_compiles = served2.batcher.compile_count()
        assert ready_compiles <= len(manifest.pairs)
        # the restart serves the SAME traffic without minting a compile
        # and bit-identical to the recording process
        assert (np.asarray(served2.predict(x[:3])) == base).all()
        assert (np.asarray(served2.predict(x)) == oversized).all()
        assert served2.batcher.compile_count() == ready_compiles, \
            "manifest replay still compiled on live traffic"
    finally:
        reg2.shutdown()


def test_hot_swap_inherits_live_manifest(tmp_path):
    reg = ModelRegistry()
    x = np.random.default_rng(1).normal(0, 1, (40, 8)).astype(np.float32)
    reg.register("m", MultiLayerNetwork(_mln_conf()).init(),
                 max_batch_size=8, batch_timeout_ms=1.0, pipeline_depth=0,
                 warmup_example=x[:1])
    try:
        reg.predict("m", x)  # mints bucket 64 under live traffic
        v1_buckets = list(reg.get("m").batcher.buckets)
        assert 64 in v1_buckets
        # hot-swap with no explicit warmup: the replacement must inherit
        # the live bucket set, pre-warmed before it takes traffic
        served2 = reg.register("m", MultiLayerNetwork(_mln_conf(seed=9)).init())
        assert list(served2.batcher.buckets) == v1_buckets
        c0 = served2.batcher.compile_count()
        reg.predict("m", x)  # same oversized traffic: nothing new compiles
        assert served2.batcher.compile_count() == c0
    finally:
        reg.shutdown()


# -------------------------------------------- fast-path bit-identity
def _params_bytes(net):
    return b"".join(np.ascontiguousarray(np.asarray(l)).tobytes()
                    for l in jax.tree.leaves(net.train_state.params))


def _fit_mln(aot: bool, conf_fn=_mln_conf, **fit_kw):
    env = get_environment()
    before = env.aot_dispatch
    env.set_aot_dispatch(aot)
    try:
        net = MultiLayerNetwork(conf_fn()).init()
        net.fit(_iterator(), epochs=2, **fit_kw)
        return _params_bytes(net)
    finally:
        env.aot_dispatch = before


def test_mln_fit_fast_path_bit_identical_to_jit(aot_toggle):
    assert _fit_mln(True) == _fit_mln(False)
    assert compile_cache.stats()["aot_compiles"] > 0


def test_mln_fit_fast_path_bit_identical_grouped_dispatch(aot_toggle):
    env = get_environment()
    unroll = env.dispatch_unroll
    env.set_dispatch_unroll(2)
    try:
        assert _fit_mln(True) == _fit_mln(False)
    finally:
        env.dispatch_unroll = unroll


def test_cg_fit_fast_path_bit_identical_to_jit(aot_toggle):
    def fit(aot):
        env = get_environment()
        env.set_aot_dispatch(aot)
        net = ComputationGraph(_graph_conf()).init()
        rng = np.random.default_rng(3)
        x = rng.normal(0, 1, (24, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
        net.fit(ListDataSetIterator([DataSet(x, y)], batch_size=8), epochs=2)
        return _params_bytes(net)

    assert fit(True) == fit(False)


def test_sd_fit_fast_path_bit_identical_to_jit(aot_toggle):
    from deeplearning4j_tpu.autodiff.samediff import SameDiff, TrainingConfig

    def fit(aot):
        get_environment().set_aot_dispatch(aot)
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 6))
        w = sd.var("w", (6, 3))
        b = sd.var("b", (3,))
        logits = x @ w + b
        labels = sd.placeholder("labels", (None, 3))
        sd.loss.softmax_cross_entropy("loss", labels, logits)
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(
            updater=Sgd(0.1), data_set_feature_mapping=["x"],
            data_set_label_mapping=["labels"]))
        rng = np.random.default_rng(5)
        xs = rng.normal(0, 1, (24, 6)).astype(np.float32)
        ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
        hist = sd.fit(ListDataSetIterator([DataSet(xs, ys)], batch_size=8),
                      epochs=2)
        return (np.asarray(sd.arrays["w"]).tobytes(),
                np.asarray(sd.arrays["b"]).tobytes(),
                [float(v) for v in hist])

    w1, b1, h1 = fit(True)
    w2, b2, h2 = fit(False)
    assert w1 == w2 and b1 == b2 and h1 == h2


def test_parallel_wrapper_fast_path_bit_identical_to_jit(aot_toggle):
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    def fit(aot):
        get_environment().set_aot_dispatch(aot)
        net = MultiLayerNetwork(_mln_conf()).init()
        pw = ParallelWrapper.builder(net).workers(2).build()
        pw.fit(_iterator(n=32, batch=16), epochs=2)
        return _params_bytes(net)

    assert fit(True) == fit(False)


def test_batcher_fast_path_bit_identical_and_counted(aot_toggle):
    aot_toggle.set_aot_dispatch(True)
    net = MultiLayerNetwork(_mln_conf()).init()
    ref = MultiLayerNetwork(_mln_conf()).init()
    x = np.random.default_rng(2).normal(0, 1, (16, 8)).astype(np.float32)
    b = ContinuousBatcher(net, max_batch_size=16, batch_timeout_ms=1.0,
                          pipeline_depth=0, warmup_example=x[:1])
    try:
        assert b._pool.aot_count() == len(b.buckets)  # warmed through AOT
        assert b.compile_count() == len(b.buckets)
        for n in (1, 3, 8, 16):
            got = np.asarray(b.submit(x[:n]))
            bucket = min(bk for bk in b.buckets if bk >= n)
            pad = np.concatenate(
                [x[:n], np.zeros((bucket - n, 8), np.float32)])
            exp = np.asarray(ref.output(pad))[:n]
            assert (got == exp).all(), f"rows={n} not bit-identical"
        assert b.compile_count() == len(b.buckets)
    finally:
        b.shutdown()


def test_batcher_float64_request_mints_no_duplicate_executable(aot_toggle):
    """An f64 request (e.g. JSON via HTTP) lands on the SAME f32 program
    jit would canonicalize it onto — a raw-dtype AOT key would mint a
    duplicate executable and break the compiles <= buckets x replicas
    ledger (regression: examples/model_serving.py HTTP predict)."""
    aot_toggle.set_aot_dispatch(True)
    net = MultiLayerNetwork(_mln_conf()).init()
    x32 = np.random.default_rng(4).normal(0, 1, (4, 8)).astype(np.float32)
    b = ContinuousBatcher(net, max_batch_size=4, batch_timeout_ms=1.0,
                          pipeline_depth=0, warmup_example=x32[:1])
    try:
        warmed = b.compile_count()
        got64 = np.asarray(b.submit(x32[:2].astype(np.float64)))
        got32 = np.asarray(b.submit(x32[:2]))
        assert b.compile_count() == warmed, "f64 request minted a compile"
        assert (got64 == got32).all()
    finally:
        b.shutdown()


def test_parallel_wrapper_fsdp_sharding_drift_falls_back(aot_toggle):
    """FSDP state shardings evolve after the first step (XLA re-assigns
    replicated biases to sharded) — the AOT entry compiled at step 1 must
    fall back cleanly and re-lower, never crash the fit (regression:
    examples/model_sharding.py)."""
    from deeplearning4j_tpu.parallel.sharding import ShardingStrategy
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.runtime.mesh import create_mesh

    def fit(aot):
        get_environment().set_aot_dispatch(aot)
        net = MultiLayerNetwork(_mln_conf()).init()
        pw = ParallelWrapper(net, ShardingStrategy.fsdp(create_mesh()))
        pw.fit(_iterator(n=32, batch=16), epochs=2)
        return _params_bytes(net)

    assert fit(True) == fit(False)


def test_metrics_render_warmup_and_compile_cache(tmp_path):
    from deeplearning4j_tpu.serving import ModelServer
    import urllib.request

    reg = ModelRegistry()
    x = np.zeros((1, 8), np.float32)
    reg.register("m", MultiLayerNetwork(_mln_conf()).init(),
                 max_batch_size=4, batch_timeout_ms=1.0, warmup_example=x)
    srv = ModelServer(reg)
    port = srv.start(0)
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert 'serving_warmup_seconds{model="m"}' in text
        assert "compile_cache_hits_total" in text
        assert "compile_cache_corrupt_entries_total" in text
        assert "aot_dispatch_executables_total" in text
    finally:
        srv.stop(shutdown_registry=True)
