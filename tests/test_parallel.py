"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4 item 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import NumpyDataSetIterator
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import DenseLayer, InputType, NeuralNetConfiguration, OutputLayer
from deeplearning4j_tpu.parallel import ParallelInference, ParallelWrapper, ShardingStrategy
from deeplearning4j_tpu.parallel.ring_attention import sequence_parallel_attention
from deeplearning4j_tpu.runtime.mesh import SEQ_AXIS, create_mesh
from deeplearning4j_tpu.train import Adam, Sgd


def _conf(seed=7):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.feed_forward(8))
            .build())


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return x, y


def test_devices_available():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"


def test_dp_matches_single_device():
    """Sharded DP training must be numerically equivalent to single-device
    training (sync allreduce == the same global batch gradient)."""
    x, y = _data()
    it1 = NumpyDataSetIterator(x, y, batch_size=32)
    it2 = NumpyDataSetIterator(x, y, batch_size=32)

    net1 = MultiLayerNetwork(_conf()).init()
    net1.fit(it1, epochs=3)

    net2 = MultiLayerNetwork(_conf()).init()
    pw = ParallelWrapper.builder(net2).strategy("data_parallel").build()
    pw.fit(it2, epochs=3)

    w1 = np.asarray(net1.params()["layer_0"]["W"])
    w2 = np.asarray(net2.params()["layer_0"]["W"])
    np.testing.assert_allclose(w1, w2, rtol=2e-5, atol=2e-6)


def test_fsdp_trains():
    x, y = _data()
    it = NumpyDataSetIterator(x, y, batch_size=32)
    net = MultiLayerNetwork(_conf()).init()
    pw = ParallelWrapper.builder(net).strategy("fsdp").build()
    pw.fit(it, epochs=2)
    assert np.isfinite(net.score())


def test_computation_graph_through_parallel_wrapper():
    """ParallelWrapper wraps ComputationGraph too (reference parity; the
    CG step signature differs from MLN's — round-5 fix): DP-sharded CG
    training matches the CG's own single-context fit."""
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph

    def conf():
        return (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_out=32, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "h")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(12))
                .build())

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 12)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]

    net1 = ComputationGraph(conf()).init()
    net1.fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=3)

    net2 = ComputationGraph(conf()).init()
    pw = ParallelWrapper.builder(net2).strategy("data_parallel").build()
    pw.fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=3)

    w1 = np.asarray(net1.params()["h"]["W"])
    w2 = np.asarray(net2.params()["h"]["W"])
    np.testing.assert_allclose(w1, w2, rtol=2e-5, atol=2e-6)


@pytest.mark.slow  # wall-time tier-2 (ISSUE 19): heaviest tier-1 cases demoted so `not slow` finishes inside the 870 s budget
def test_tensor_parallel_builder_trains():
    """`.strategy("tensor_parallel").build()` must construct a mesh WITH a
    `model` axis itself (round-5 fix: the builder handed the TP strategy a
    data-only mesh and crashed with KeyError 'model') and train a
    transformer whose W_q/W_ff1 columns and W_o/W_ff2 rows shard over it."""
    from deeplearning4j_tpu.zoo import Bert
    net = Bert.small(vocab_size=100).init()
    pw = ParallelWrapper.builder(net).strategy("tensor_parallel").build()
    assert pw.strategy.mesh.shape["model"] == 8
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 100, (16, 8)).astype(np.int32)
    labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    it = NumpyDataSetIterator(ids, labels, batch_size=16)
    pw.fit(it, epochs=1)
    assert np.isfinite(net.score())


def test_batch_not_divisible_raises():
    from deeplearning4j_tpu.parallel.sharding import shard_batch
    strat = ShardingStrategy.data_parallel(create_mesh())
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch(strat, np.zeros((5, 3), np.float32))


def test_parallel_inference_batches():
    net = MultiLayerNetwork(_conf()).init()
    pi = ParallelInference(net, max_batch_size=16)
    x, _ = _data(24)
    direct = np.asarray(net.output(x[:8]))
    via_pi = pi.output(x[:8])
    np.testing.assert_allclose(direct, via_pi, rtol=1e-5)
    pi.shutdown()


def test_parallel_inference_computation_graph_multi_input():
    """ParallelInference over a multi-input ComputationGraph: dict batches
    coalesce per input name (the seed's bare ``np.concatenate(r.x)`` only
    handled single-array MLN inputs — ISSUE 1 satellite)."""
    import threading

    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.nn.graph_vertices import MergeVertex

    def conf():
        return (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in_a", "in_b")
                .add_layer("ha", DenseLayer(n_out=16, activation="relu"),
                           "in_a")
                .add_layer("hb", DenseLayer(n_out=16, activation="relu"),
                           "in_b")
                .add_vertex("m", MergeVertex(), "ha", "hb")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(12),
                                 InputType.feed_forward(6))
                .build())

    net = ComputationGraph(conf()).init()
    rng = np.random.default_rng(0)
    xa = rng.normal(size=(32, 12)).astype(np.float32)
    xb = rng.normal(size=(32, 6)).astype(np.float32)

    pi = ParallelInference(net, max_batch_size=8, batch_timeout_ms=5.0)
    try:
        results = {}

        def client(i, n):
            results[i] = pi.output({"in_a": xa[i:i + n], "in_b": xb[i:i + n]})

        threads = [threading.Thread(target=client, args=(i, 1 + i % 3))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(results) == 8
        for i in range(8):
            n = 1 + i % 3
            expect = np.asarray(net.output(xa[i:i + n], xb[i:i + n]))
            np.testing.assert_allclose(results[i], expect, rtol=1e-6)
    finally:
        pi.shutdown()


def test_parallel_inference_workers_are_device_replicas():
    """Reference ``Builder.workers(n)`` now means N real device replicas
    (ISSUE 3): device-resident parameter copies routed least-loaded, every
    replica's response bit-identical."""
    net = MultiLayerNetwork(_conf()).init()
    pi = (ParallelInference.builder(net)
          .workers(2).max_batch_size(16).batch_timeout_ms(1.0).build())
    try:
        assert pi.workers == 2
        x, _ = _data(24)
        outs = [np.asarray(pi.output(x[:4])) for _ in range(6)]
        for o in outs[1:]:
            assert (o == outs[0]).all(), \
                "responses differ across device replicas"
        counts = pi._batcher.metrics.snapshot()["replica_batches"]
        assert sorted(counts) == [0, 1], f"replica batch counts: {counts}"
        assert all(v >= 2 for v in counts.values()), f"unbalanced: {counts}"
        np.testing.assert_allclose(outs[0], np.asarray(net.output(x[:4])),
                                   rtol=1e-5)
        # a requested worker count beyond the local device pool clamps
        pi_big = ParallelInference.builder(net).workers(64).build()
        assert pi_big.workers == len(jax.local_devices())
        pi_big.shutdown()
    finally:
        pi.shutdown()


def test_parallel_inference_shutdown_does_not_hang_queued_callers():
    """Seed bug (ISSUE 1 satellite): queued-but-unbatched requests must be
    failed explicitly at shutdown, never left blocked forever."""
    import threading

    from deeplearning4j_tpu.serving import ServingShutdown

    net = MultiLayerNetwork(_conf()).init()
    pi = ParallelInference(net, max_batch_size=4, batch_timeout_ms=1.0)
    x, _ = _data(16)
    gate = threading.Event()
    orig = pi._batcher._forward
    pi._batcher._forward = lambda v: (gate.wait(5), orig(v))[1]
    done = []

    def client(i):
        try:
            pi.output(x[i:i + 1])
            done.append("ok")
        except ServingShutdown:
            done.append("shutdown")

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    import time
    time.sleep(0.3)  # stalled worker; requests pile up unbatched
    sd = threading.Thread(
        target=lambda: pi._batcher.shutdown(drain=False, timeout_s=10))
    sd.start()
    time.sleep(0.05)
    gate.set()
    sd.join(timeout=10)
    for t in threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in threads), "output() caller hung"
    assert len(done) == 8 and "shutdown" in done


def test_ring_attention_matches_full_softmax():
    mesh = create_mesh({SEQ_AXIS: 8})
    B, H, T, D = 2, 4, 64, 16
    rng = np.random.default_rng(3)
    q = rng.normal(0, 1, (B, H, T, D)).astype(np.float32)
    k = rng.normal(0, 1, (B, H, T, D)).astype(np.float32)
    v = rng.normal(0, 1, (B, H, T, D)).astype(np.float32)

    def reference(q, k, v, causal):
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((T, T), bool))
            s = np.where(mask, s, -1e30)
        w = np.exp(s - s.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", w, v)

    out = np.asarray(sequence_parallel_attention(q, k, v, mesh))
    np.testing.assert_allclose(out, reference(q, k, v, False), rtol=2e-4, atol=2e-5)

    out_c = np.asarray(sequence_parallel_attention(q, k, v, mesh, causal=True))
    np.testing.assert_allclose(out_c, reference(q, k, v, True), rtol=2e-4, atol=2e-5)


def test_parallel_wrapper_refuses_tbptt_and_solvers():
    """Modes the model's own fit() special-cases (tBPTT chunking, legacy
    solvers) must refuse loudly under ParallelWrapper instead of silently
    training with different gradients (round-5 review finding)."""
    from deeplearning4j_tpu.nn import GravesLSTM, RnnOutputLayer

    conf_t = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
              .list()
              .layer(GravesLSTM(n_out=8))
              .layer(RnnOutputLayer(n_out=4, activation="softmax",
                                    loss="mcxent"))
              .set_input_type(InputType.recurrent(6))
              .tbptt_fwd_length(4).tbptt_back_length(4)
              .build())
    net = MultiLayerNetwork(conf_t).init()
    pw = ParallelWrapper.builder(net).strategy("data_parallel").build()
    x = np.zeros((8, 6, 12), np.float32)
    y = np.zeros((8, 4, 12), np.float32)
    with pytest.raises(NotImplementedError, match="tBPTT"):
        pw.fit(NumpyDataSetIterator(x, y, batch_size=8), epochs=1)

    conf_s = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
              .optimization_algo("LBFGS")
              .list()
              .layer(DenseLayer(n_out=8, activation="tanh"))
              .layer(OutputLayer(n_out=4, activation="softmax"))
              .set_input_type(InputType.feed_forward(8))
              .build())
    net2 = MultiLayerNetwork(conf_s).init()
    pw2 = ParallelWrapper.builder(net2).strategy("data_parallel").build()
    xf, yf = _data(16)
    with pytest.raises(NotImplementedError, match="SGD only"):
        pw2.fit(NumpyDataSetIterator(xf, yf, batch_size=16), epochs=1)


def test_parallel_wrapper_tbptt_conf_with_nonsequence_data_trains():
    """A tbptt_fwd_length config trained on NON-sequence batches never
    engages tBPTT in the model's own fit — the wrapper must accept it too
    (round-5 review: the first guard refused on configuration alone)."""
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.feed_forward(8))
            .tbptt_fwd_length(4).tbptt_back_length(4)
            .build())
    net = MultiLayerNetwork(conf).init()
    pw = ParallelWrapper.builder(net).strategy("data_parallel").build()
    x, y = _data(16)
    pw.fit(NumpyDataSetIterator(x, y, batch_size=16), epochs=1)
    assert np.isfinite(net.score())
