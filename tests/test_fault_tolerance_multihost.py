"""Fault tolerance x multihost, integrated (VERDICT r2 item 6).

Two localhost processes train data-parallel through
``initialize_multihost`` with periodic checkpoints; the supervisor (this
test) watches per-worker heartbeat files through ``HeartbeatMonitor``.
Mid-training worker 1 is killed (simulated chip/host loss). The SPMD step
is all-or-nothing, so worker 0 stalls in the allreduce and its heartbeat
goes stale -> the monitor raises, the supervisor kills the survivor,
re-forms the mesh on a fresh coordinator port, and the restarted workers
restore the newest checkpoint and finish. The final weights must match an
uninterrupted single-process run exactly (deterministic per-epoch data).

This is the TPU-native analog of the reference's MeshOrganizer
heartbeat + node-remap + restart-round story (SURVEY.md §5.3): membership
change == restart round from checkpoint.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deeplearning4j_tpu.train.fault_tolerance import (HeartbeatMonitor,
                                                      TrainingFailure)

_WORKER = r"""
import json, os, sys, tempfile
import numpy as np

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.runtime.mesh import initialize_multihost

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
ckpt_dir = sys.argv[4]; total_epochs = int(sys.argv[5])
crash_at = int(sys.argv[6]); hb_file = sys.argv[7]

initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nproc, process_id=pid)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("dp",))

rng = np.random.default_rng(0)
W0 = rng.normal(0, 0.5, (8, 4)).astype(np.float32)

ckpt = os.path.join(ckpt_dir, "state.npz")
if os.path.exists(ckpt):
    blob = np.load(ckpt)
    W, start_epoch = blob["W"], int(blob["epoch"]) + 1
else:
    W, start_epoch = W0, 0
W = jnp.asarray(W)

def loss(w, x, y):
    p = jax.nn.log_softmax(x @ w)
    return -jnp.mean(jnp.sum(p * y, axis=-1))

step = jax.jit(lambda w, x, y: w - 0.1 * jax.grad(loss)(w, x, y))
xsh = NamedSharding(mesh, P("dp", None))
n_local = 16 // nproc
losses = []
for epoch in range(start_epoch, total_epochs):
    if pid == 1 and epoch == crash_at:
        os._exit(17)  # simulated worker death mid-round
    erng = np.random.default_rng(100 + epoch)  # deterministic per-epoch data
    X = erng.normal(0, 1, (16, 8)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[erng.integers(0, 4, 16)]
    lo = pid * n_local
    x_g = jax.make_array_from_process_local_data(xsh, X[lo:lo + n_local])
    y_g = jax.make_array_from_process_local_data(xsh, Y[lo:lo + n_local])
    W = step(W, x_g, y_g)
    losses.append(float(loss(W, x_g, y_g)))   # forces the step to finish
    with open(hb_file, "w") as f:              # heartbeat AFTER real progress
        f.write(str(epoch))
    if pid == 0:  # checkpoint each completed round, atomically
        Wh = np.asarray(jax.device_get(W))
        tmp = ckpt + ".tmp.npz"
        np.savez(tmp, W=Wh, epoch=epoch)
        os.replace(tmp, ckpt)
print("DONE" + json.dumps({"W": np.asarray(jax.device_get(W)).tolist(),
                           "losses": losses}))
"""


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


def _launch(wfile, env, port, ckpt_dir, epochs, crash_at, hb_files):
    return [subprocess.Popen(
        [sys.executable, str(wfile), str(pid), "2", port, str(ckpt_dir),
         str(epochs), str(crash_at), str(hb_files[pid])],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
        for pid in range(2)]


@pytest.mark.slow
def test_worker_death_detected_restored_and_completes(tmp_path):
    wfile = tmp_path / "worker.py"
    wfile.write_text(_WORKER)
    ckpt_dir = tmp_path / "ckpts"
    ckpt_dir.mkdir()
    hb_files = [tmp_path / f"hb{i}" for i in range(2)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith("PALLAS_AXON")}
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    EPOCHS, CRASH_AT = 6, 3

    # ---- round 1: worker 1 dies at epoch 3; monitor must notice ----
    procs = _launch(wfile, env, _free_port(), ckpt_dir, EPOCHS, CRASH_AT,
                    hb_files)
    monitor = HeartbeatMonitor(timeout_s=25.0)
    seen = {}
    failure = None
    deadline = time.time() + 240
    try:
        while time.time() < deadline:
            for i, hb in enumerate(hb_files):
                if hb.exists():
                    m = hb.stat().st_mtime
                    if seen.get(i) != m:
                        seen[i] = m
                        monitor.beat()  # any worker progressing = alive
            if any(p.poll() not in (None, 0) for p in procs):
                failure = TrainingFailure("worker process died")
                break
            try:
                monitor.check()
            except TrainingFailure as e:  # survivor stalled in allreduce
                failure = e
                break
            if all(p.poll() == 0 for p in procs):
                break
            time.sleep(0.5)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.communicate(timeout=60)
    assert failure is not None, \
        "the killed worker must be detected (exit or stale heartbeat)"
    # progress up to the crash round was checkpointed
    assert (ckpt_dir / "state.npz").exists()
    assert int(np.load(ckpt_dir / "state.npz")["epoch"]) == CRASH_AT - 1

    # ---- round 2: re-form the mesh, restore, finish ----
    procs = _launch(wfile, env, _free_port(), ckpt_dir, EPOCHS, -1, hb_files)
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"restarted worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("DONE")]
        assert line, out
        outs.append(json.loads(line[0][4:]))
    W_final = np.asarray(outs[0]["W"])
    np.testing.assert_array_equal(W_final, np.asarray(outs[1]["W"]))
    # restarted run resumed at the right epoch (3 remaining rounds)
    assert len(outs[0]["losses"]) == EPOCHS - CRASH_AT

    # ---- oracle: uninterrupted single-process run of the same schedule ----
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.normal(0, 0.5, (8, 4)).astype(np.float32))

    def loss(w, x, y):
        p = jax.nn.log_softmax(x @ w)
        return -jnp.mean(jnp.sum(p * y, axis=-1))

    step = jax.jit(lambda w, x, y: w - 0.1 * jax.grad(loss)(w, x, y))
    tail = []
    for epoch in range(EPOCHS):
        erng = np.random.default_rng(100 + epoch)
        X = erng.normal(0, 1, (16, 8)).astype(np.float32)
        Y = np.eye(4, dtype=np.float32)[erng.integers(0, 4, 16)]
        W = step(W, jnp.asarray(X), jnp.asarray(Y))
        tail.append(float(loss(W, jnp.asarray(X), jnp.asarray(Y))))
    np.testing.assert_allclose(W_final, np.asarray(W), rtol=1e-6, atol=1e-6)
    # the restarted run's loss tail matches the uninterrupted run's tail
    np.testing.assert_allclose(outs[0]["losses"][-2:], tail[-2:],
                               rtol=1e-5, atol=1e-6)
