"""GloVe + SameDiff control-flow tests."""

import numpy as np
import pytest

_CORPUS = [
    "the king rules the castle",
    "the queen rules the castle",
    "the king and the queen sit on thrones",
    "dogs chase cats around the garden",
    "cats chase mice around the garden",
    "the dog and the cat play in the garden",
] * 20


def test_glove_learns_cooccurrence():
    from deeplearning4j_tpu.nlp import Glove
    g = Glove(layer_size=24, window_size=4, min_word_frequency=2,
              epochs=40, learning_rate=0.05, seed=11)
    g.fit(_CORPUS)
    royal = g.similarity("king", "queen")
    cross = g.similarity("king", "mice")
    assert np.isfinite(royal) and np.isfinite(cross)
    assert royal > cross, f"king~queen {royal} vs king~mice {cross}"


def test_samediff_cond():
    from deeplearning4j_tpu.autodiff import SameDiff
    sd = SameDiff.create()
    x = sd.placeholder("x", (3,))
    pred = sd.placeholder("p", ())
    out = sd.cond(pred, lambda a: a * 2.0, lambda a: a - 1.0, x, name="branch")
    r_true = np.asarray(sd.output({"x": np.ones(3, np.float32), "p": True}, out.name))
    r_false = np.asarray(sd.output({"x": np.ones(3, np.float32), "p": False}, out.name))
    np.testing.assert_allclose(r_true, [2, 2, 2])
    np.testing.assert_allclose(r_false, [0, 0, 0])


def test_samediff_while_loop():
    from deeplearning4j_tpu.autodiff import SameDiff
    sd = SameDiff.create()
    i0 = sd.constant("i0", np.float32(0))
    acc0 = sd.constant("acc0", np.float32(0))
    i_out, acc_out = sd.while_loop(
        lambda i, acc: i < 5, lambda i, acc: (i + 1, acc + i), i0, acc0,
        name="loop")
    assert float(np.asarray(i_out.eval())) == 5.0
    assert float(np.asarray(acc_out.eval())) == 10.0  # 0+1+2+3+4


def test_control_flow_graphs_refuse_serialization(tmp_path):
    from deeplearning4j_tpu.autodiff import SameDiff
    sd = SameDiff.create()
    a = sd.constant("a", np.float32(1))
    sd.cond(a > 0.0, lambda: a, lambda: a)
    with pytest.raises(ValueError, match="not serializable"):
        sd.save(str(tmp_path / "x.sdz"))


def test_samediff_while_loop_max_iterations_differentiable():
    # bounded while lowers to scan -> reverse-mode AD works
    from deeplearning4j_tpu.autodiff import SameDiff
    sd = SameDiff.create()
    x = sd.var("x", array=np.float32(2.0))
    i0 = sd.constant("i0", np.float32(0))
    i_out, y, _ = sd.while_loop(
        lambda i, v, xv: i < 3, lambda i, v, xv: (i + 1, v * xv, xv), i0, x, x,
        name="loop", max_iterations=8)
    sd.set_loss_variables(y.name)
    g = sd.calculate_gradients({}, "x")
    # y = x * x^3 = x^4 -> dy/dx = 4x^3 = 32 at x=2
    np.testing.assert_allclose(float(np.asarray(g["x"])), 32.0, rtol=1e-5)
