"""Quantize/dequantize op hardening (ISSUE 8 satellite).

The ``quantize``/``dequantize`` pair in ``autodiff/ops_registry.py`` grew
from per-tensor scalar affine maps to serving-grade semantics: per-channel
1-D scale/zero-point arrays broadcast along an axis, symmetric AND
asymmetric schemes, narrow-range int8, and f64 inputs. These are the ops
``serving/quantize.py`` builds archives with, so the round-trip property —
``|dequantize(quantize(x)) - x| <= scale/2`` for in-range values — is the
foundation the whole quantized serving path's accuracy story rests on.

All tier-1 (pure numpy/jax on CPU, no model build).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.ops_registry import OPS

quant = OPS["quantize"]
dequant = OPS["dequantize"]


def _roundtrip(x, **kw):
    dq_kw = {k: kw[k] for k in ("scale", "zero_point", "axis") if k in kw}
    q = quant(x, **kw)
    return np.asarray(q), np.asarray(dequant(q, **dq_kw))


# ------------------------------------------------------------ round trip
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_roundtrip_error_bounded_per_tensor_symmetric(seed):
    """The headline property: symmetric per-tensor int8, in-range values,
    |roundtrip - x| <= scale/2 (+ f32 rounding slack)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(0.0, 1.0, (32, 16)).astype(np.float32)
    amax = float(np.abs(x).max())
    scale = amax / 127.0
    q, back = _roundtrip(x, scale=scale, zero_point=0, narrow_range=True)
    assert q.dtype == np.int8
    assert np.abs(back - x).max() <= scale / 2 + 1e-6


@pytest.mark.parametrize("seed", [0, 7])
def test_roundtrip_error_bounded_per_channel(seed):
    """Per-channel 1-D scale arrays along the last axis: each channel's
    round-trip error is bounded by ITS OWN scale/2 — the reason
    per-channel beats per-tensor for weight matrices with spread-out
    channel magnitudes."""
    rng = np.random.default_rng(seed)
    # channels with wildly different magnitudes (the per-channel win case)
    mags = np.array([0.01, 0.1, 1.0, 10.0], np.float32)
    x = (rng.normal(0, 1, (64, 4)).astype(np.float32) * mags)
    scale = np.abs(x).max(axis=0) / 127.0
    q, back = _roundtrip(x, scale=scale, zero_point=0, axis=-1,
                         narrow_range=True)
    assert q.dtype == np.int8
    err = np.abs(back - x)
    for c in range(4):
        assert err[:, c].max() <= scale[c] / 2 + 1e-5 * mags[c], \
            f"channel {c} error {err[:, c].max()} > scale/2 {scale[c] / 2}"
    # per-tensor at the same data would do far worse on the small channels
    pt_scale = float(np.abs(x).max()) / 127.0
    _, back_pt = _roundtrip(x, scale=pt_scale, zero_point=0)
    assert err[:, 0].max() < np.abs(back_pt - x)[:, 0].max()


def test_roundtrip_asymmetric_uint8():
    """Asymmetric scheme: nonzero zero_point, uint8 codes, shifted-range
    data (e.g. post-ReLU activations)."""
    rng = np.random.default_rng(3)
    x = rng.uniform(0.5, 4.5, (128, 8)).astype(np.float32)
    lo, hi = float(x.min()), float(x.max())
    scale = (hi - lo) / 255.0
    zp = int(round(-lo / scale))
    q, back = _roundtrip(x, scale=scale, zero_point=zp, dtype="uint8")
    assert q.dtype == np.uint8
    assert np.abs(back - x).max() <= scale / 2 + 1e-5


def test_per_channel_zero_point_array():
    """Both scale AND zero_point may be per-channel arrays (fully
    asymmetric per-channel affine)."""
    rng = np.random.default_rng(11)
    offs = np.array([0.0, 2.0, -3.0], np.float32)
    x = rng.uniform(-1, 1, (64, 3)).astype(np.float32) + offs
    # the affine range must cover 0 so the zero point is representable
    # (exactly what calibrate_inputs enforces for activation data)
    lo = np.minimum(x.min(axis=0), 0.0)
    hi = np.maximum(x.max(axis=0), 0.0)
    scale = ((hi - lo) / 255.0).astype(np.float32)
    zp = np.clip(np.round(-lo / scale), 0, 255).astype(np.int32)
    q, back = _roundtrip(x, scale=scale, zero_point=zp, axis=-1,
                         dtype="uint8")
    assert q.dtype == np.uint8
    assert np.abs(back - x).max() <= scale.max() / 2 + 1e-5


# ----------------------------------------------------------- edge cases
def test_f64_inputs_accepted():
    """f64 inputs quantize without raising (rounded in the input's own
    floating dtype under whatever precision jax canonicalizes to), and
    ``dequantize(dtype='float64')`` returns a floating result bit-close to
    the f32 path — the op must not crash on a JSON-parsed f64 request."""
    rng = np.random.default_rng(5)
    x64 = rng.normal(0, 1, (16, 4))
    assert x64.dtype == np.float64
    scale = float(np.abs(x64).max()) / 127.0
    q = np.asarray(quant(x64, scale=scale, narrow_range=True))
    assert q.dtype == np.int8
    back = np.asarray(dequant(q, scale=scale, dtype="float64"))
    assert np.issubdtype(back.dtype, np.floating)
    assert np.abs(back - x64.astype(np.float32)).max() <= scale / 2 + 1e-6


def test_narrow_range_never_emits_most_negative_code():
    """narrow_range symmetric int8 stays in [-127, 127] even for values
    far past the representable range — the most negative code -128 (which
    has no positive twin) never appears."""
    x = np.array([-1e9, -4.0, 0.0, 4.0, 1e9], np.float32)
    q = np.asarray(quant(x, scale=4.0 / 127.0, narrow_range=True))
    assert q.min() >= -127 and q.max() <= 127
    # without narrow_range the full [-128, 127] range is used
    q_full = np.asarray(quant(x, scale=4.0 / 127.0))
    assert q_full.min() == -128


def test_out_of_range_saturates():
    """Values past the representable range clip to the code range instead
    of wrapping — saturation, not integer overflow."""
    x = np.array([-100.0, 100.0], np.float32)
    q = np.asarray(quant(x, scale=1.0 / 127.0))
    assert q[0] == -128 and q[1] == 127
    qu = np.asarray(quant(x, scale=1.0 / 255.0, zero_point=128,
                          dtype="uint8"))
    assert qu[0] == 0 and qu[1] == 255


def test_integer_input_is_cast_not_rejected():
    """Integer inputs are accepted (cast to f32 before the affine map) —
    matches the reference op's permissive input contract."""
    q = np.asarray(quant(np.array([1, 2, 3], np.int32), scale=0.5))
    assert q.dtype == np.int8
    assert list(q) == [2, 4, 6]


def test_bad_per_channel_scale_rank_raises():
    """A 2-D scale array is a usage bug, not something to broadcast
    silently into the wrong shape."""
    x = np.zeros((4, 4), np.float32)
    with pytest.raises(ValueError, match="per-channel"):
        quant(x, scale=np.ones((2, 2), np.float32), axis=-1)


def test_axis_broadcast_on_leading_axis():
    """axis is any axis, not just the last: per-ROW scales on axis=0."""
    rng = np.random.default_rng(9)
    x = rng.normal(0, 1, (3, 32)).astype(np.float32) * \
        np.array([[0.1], [1.0], [10.0]], np.float32)
    scale = np.abs(x).max(axis=1) / 127.0
    q, back = _roundtrip(x, scale=scale, zero_point=0, axis=0,
                         narrow_range=True)
    err = np.abs(back - x)
    for r in range(3):
        assert err[r].max() <= scale[r] / 2 + 1e-5


# ------------------------------------------- serving weight-quant helper
def test_quantize_weight_roundtrip_bound():
    """The serving path's per-output-channel weight quantizer inherits the
    op property: per-channel round-trip error <= scale/2."""
    from deeplearning4j_tpu.serving.quantize import (dequantize_weight,
                                                     quantize_weight)
    rng = np.random.default_rng(21)
    w = (rng.normal(0, 1, (64, 16)).astype(np.float32)
         * rng.uniform(0.01, 5.0, 16).astype(np.float32))
    q, scale = quantize_weight(w, per_channel=True)
    assert q.dtype == np.int8 and scale.shape == (16,)
    assert np.abs(q).max() <= 127  # narrow range
    back = dequantize_weight(q, scale)
    err = np.abs(back - w)
    for c in range(16):
        assert err[:, c].max() <= scale[c] / 2 + 1e-6


def test_quantize_weight_per_tensor_mode():
    from deeplearning4j_tpu.serving.quantize import (dequantize_weight,
                                                     quantize_weight)
    rng = np.random.default_rng(22)
    w = rng.normal(0, 2, (8, 8)).astype(np.float32)
    q, scale = quantize_weight(w, per_channel=False)
    assert scale.ndim == 0
    back = dequantize_weight(q, scale)
    assert np.abs(back - w).max() <= float(scale) / 2 + 1e-6
