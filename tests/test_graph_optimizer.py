"""Graph-optimizer pattern fusion (reference: libnd4j graph optimization
passes before execution, SURVEY §3.2): imported layernorm/gelu subgraphs
collapse to the fused registry ops with identical outputs."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.autodiff.graph_optimizer import optimize
from deeplearning4j_tpu.imports import TFGraphMapper


def _frozen(fn, specs):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(conc)
    return (frozen.graph.as_graph_def(),
            [t.name.split(":")[0] for t in frozen.inputs],
            [t.name.split(":")[0] for t in frozen.outputs])


def test_layernorm_and_gelu_fusion_preserves_outputs():
    rng = np.random.default_rng(0)
    D = 16
    g = tf.constant(rng.normal(1, 0.1, (D,)).astype(np.float32))
    b = tf.constant(rng.normal(0, 0.1, (D,)).astype(np.float32))

    def model(x):
        mean = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mean), axis=-1,
                             keepdims=True)
        y = (x - mean) * tf.math.rsqrt(var + 1e-12) * g + b
        return 0.5 * y * (1.0 + tf.math.erf(y / np.float32(np.sqrt(2.0))))

    gd, inputs, outputs = _frozen(
        model, [tf.TensorSpec((4, D), tf.float32, name="x")])
    x = rng.normal(0, 2, (4, D)).astype(np.float32)

    sd = TFGraphMapper.import_graph(gd, optimize=False)
    before = np.asarray(sd.output({inputs[0]: x}, outputs[0]))
    n_before = len(sd.ops)
    stats = optimize(sd)
    after = np.asarray(sd.output({inputs[0]: x}, outputs[0]))

    assert stats["layer_norm"] == 1 and stats["gelu_erf"] == 1, stats
    assert len(sd.ops) < n_before - 8
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)
    ops = [n.op for n in sd.ops]
    assert "layer_norm" in ops and "gelu" in ops
    assert "squared_difference" not in ops and "erf" not in ops


def test_fusion_respects_extra_consumers():
    """A layernorm whose MEAN is also an observable output must NOT fuse."""
    def model(x):
        mean = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mean), axis=-1,
                             keepdims=True)
        y = (x - mean) * tf.math.rsqrt(var + 1e-12) * 2.0 + 0.5
        return y, mean

    gd, inputs, outputs = _frozen(
        model, [tf.TensorSpec((2, 8), tf.float32, name="x")])
    sd = TFGraphMapper.import_graph(gd, optimize=False)
    # mark the mean output as a loss variable = externally observed
    sd.set_loss_variables(outputs[1])
    stats = optimize(sd)
    assert stats["layer_norm"] == 0


def test_bert_block_fusion_count():
    """The full mini-BERT import fuses 2*layers+1 layernorms and `layers`
    gelus."""
    from deeplearning4j_tpu.imports.tf_oracles import build_bert_graphdef
    L = 2
    gd, inputs, _, _ = build_bert_graphdef(batch=2, seq_len=16, hidden=32,
                                           layers=L, heads=2, intermediate=64,
                                           vocab=50)
    sd = TFGraphMapper.import_graph(gd, optimize=False)
    from deeplearning4j_tpu.imports.tf_oracles import bert_synthetic_batch
    ids, types, m, _ = bert_synthetic_batch(2, 16, 50)
    feeds = dict(zip(inputs, [ids, types, m]))
    before = np.asarray(sd.output(feeds, "pooled_output"))
    stats = optimize(sd)
    after = np.asarray(sd.output(feeds, "pooled_output"))
    assert stats["layer_norm"] == 2 * L + 1, stats
    assert stats["gelu_erf"] == L, stats
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)


def test_attention_fusion_with_padding_mask():
    """The imported BERT attention chain (batch_matmul/scale/add-mask/
    softmax/batch_matmul) fuses to scaled_dot_product_attention with the
    padding bias PROVEN convertible to a boolean mask — outputs unchanged."""
    from deeplearning4j_tpu.imports.tf_oracles import (bert_synthetic_batch,
                                                       build_bert_graphdef)
    L = 2
    gd, inputs, _, _ = build_bert_graphdef(batch=2, seq_len=16, hidden=32,
                                           layers=L, heads=2, intermediate=64,
                                           vocab=50)
    sd = TFGraphMapper.import_graph(gd, optimize=False)
    ids, types, m, _ = bert_synthetic_batch(2, 16, 50)
    feeds = dict(zip(inputs, [ids, types, m]))
    before = np.asarray(sd.output(feeds, "pooled_output"))
    stats = optimize(sd)
    assert stats["attention"] == L, stats
    sdpa = [n for n in sd.ops if n.op == "scaled_dot_product_attention"]
    assert len(sdpa) == L and all(n.attrs["boolean_bias"] for n in sdpa)
    assert not any(n.op == "softmax" for n in sd.ops)
    after = np.asarray(sd.output(feeds, "pooled_output"))
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)


def test_attention_fusion_general_bias_stays_additive():
    """A NON-padding additive bias (e.g. relative-position scores) must fuse
    with boolean_bias=False and keep exact softmax(x+bias) numerics."""
    rng = np.random.default_rng(0)
    B, H, T, D = 2, 2, 8, 4
    bias_np = rng.normal(0, 1, (B, H, T, T)).astype(np.float32)
    bias_c = tf.constant(bias_np)

    def model(q, k, v):
        s = tf.matmul(q, k, transpose_b=True) / np.float32(np.sqrt(D))
        return tf.matmul(tf.nn.softmax(s + bias_c, axis=-1), v)

    spec = [tf.TensorSpec((B, H, T, D), tf.float32, name=n) for n in "qkv"]
    gd, inputs, outputs = _frozen(model, spec)
    sd = TFGraphMapper.import_graph(gd, optimize=False)
    q, k, v = (rng.normal(0, 1, (B, H, T, D)).astype(np.float32)
               for _ in range(3))
    feeds = dict(zip(inputs, [q, k, v]))
    before = np.asarray(sd.output(feeds, outputs[0]))
    stats = optimize(sd)
    assert stats["attention"] == 1, stats
    sdpa = [n for n in sd.ops if n.op == "scaled_dot_product_attention"]
    assert len(sdpa) == 1 and not sdpa[0].attrs["boolean_bias"]
    after = np.asarray(sd.output(feeds, outputs[0]))
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_attention_fusion_rank3_single_head():
    """A single-head (B, T, D) attention chain fuses and still computes
    correctly (rank-agnostic einsum path)."""
    rng = np.random.default_rng(1)
    B, T, D = 2, 8, 4

    def model(q, k, v):
        s = tf.matmul(q, k, transpose_b=True) / np.float32(np.sqrt(D))
        return tf.matmul(tf.nn.softmax(s, axis=-1), v)

    spec = [tf.TensorSpec((B, T, D), tf.float32, name=n) for n in "qkv"]
    gd, inputs, outputs = _frozen(model, spec)
    sd = TFGraphMapper.import_graph(gd, optimize=False)
    q, k, v = (rng.normal(0, 1, (B, T, D)).astype(np.float32)
               for _ in range(3))
    feeds = dict(zip(inputs, [q, k, v]))
    before = np.asarray(sd.output(feeds, outputs[0]))
    stats = optimize(sd)
    assert stats["attention"] == 1, stats
    after = np.asarray(sd.output(feeds, outputs[0]))
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_attention_fusion_fully_masked_row_matches_additive():
    """An ALL-padding sequence in the batch: softmax(x + const) == softmax(x),
    so the boolean conversion must reproduce that (not uniform/NaN rows)."""
    from deeplearning4j_tpu.imports.tf_oracles import build_bert_graphdef
    gd, inputs, _, _ = build_bert_graphdef(batch=2, seq_len=8, hidden=16,
                                           layers=1, heads=2, intermediate=32,
                                           vocab=30)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 30, (2, 8)).astype(np.int32)
    types = np.zeros((2, 8), np.int32)
    mask = np.stack([np.ones(8), np.zeros(8)]).astype(np.int32)  # row 2 ALL pad
    feeds = dict(zip(inputs, [ids, types, mask]))
    sd0 = TFGraphMapper.import_graph(gd, optimize=False)
    before = np.asarray(sd0.output(feeds, "pooled_output"))
    sd1 = TFGraphMapper.import_graph(gd)  # fused (boolean mask path)
    after = np.asarray(sd1.output(feeds, "pooled_output"))
    assert np.isfinite(after).all()
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)


def test_attention_fusion_mul_const_first():
    """mul(const, qk) scale spelling also fuses."""
    rng = np.random.default_rng(2)
    B, H, T, D = 1, 2, 8, 4

    def model(q, k, v):
        s = np.float32(1.0 / np.sqrt(D)) * tf.matmul(q, k, transpose_b=True)
        return tf.matmul(tf.nn.softmax(s, axis=-1), v)

    spec = [tf.TensorSpec((B, H, T, D), tf.float32, name=n) for n in "qkv"]
    gd, inputs, outputs = _frozen(model, spec)
    sd = TFGraphMapper.import_graph(gd, optimize=False)
    q, k, v = (rng.normal(0, 1, (B, H, T, D)).astype(np.float32)
               for _ in range(3))
    feeds = dict(zip(inputs, [q, k, v]))
    before = np.asarray(sd.output(feeds, outputs[0]))
    stats = optimize(sd)
    assert stats["attention"] == 1, stats
    after = np.asarray(sd.output(feeds, outputs[0]))
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_layout_passes_fold_2d_matmul_roundtrips():
    """The TF 2-D-matmul spelling (reshape -> matmul -> bias -> reshape)
    folds back to the batched 3-D form with identical outputs; the
    round-trip reshapes and their layout-conversion copies disappear
    (round-3 fix for the imported-BERT HBM gap, BASELINE.md)."""
    rng = np.random.default_rng(0)
    B, T, H, K = 2, 8, 16, 12
    W = rng.normal(0, 0.1, (H, K)).astype(np.float32)
    b = rng.normal(0, 0.1, (K,)).astype(np.float32)
    W2 = rng.normal(0, 0.1, (K, H)).astype(np.float32)

    def model(x):
        h = tf.matmul(tf.reshape(x, (B * T, H)), W) + b
        h = tf.nn.relu(h)
        h = tf.matmul(h, W2)
        return tf.reshape(h, (B, T, H)) + x

    gd, inputs, outputs = _frozen(
        model, [tf.TensorSpec((B, T, H), tf.float32, name="x")])
    x = rng.normal(0, 1, (B, T, H)).astype(np.float32)
    sd0 = TFGraphMapper.import_graph(gd, optimize=False)
    before = np.asarray(sd0.output({"x": x}, outputs[0]))

    sd = TFGraphMapper.import_graph(gd, optimize=False)
    from deeplearning4j_tpu.autodiff.graph_optimizer import optimize_layout
    stats = optimize_layout(sd)
    assert stats["layout_folds"] == 2, stats
    assert stats["reshape_sinks"] >= 2, stats
    after = np.asarray(sd.output({"x": x}, outputs[0]))
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_layout_passes_keep_multi_consumer_reshapes():
    """A reshape with two consumers is shared state — the sink pass must
    not duplicate or remove it."""
    rng = np.random.default_rng(1)
    B, T, H = 2, 4, 8
    W = rng.normal(0, 0.1, (H, H)).astype(np.float32)

    def model(x):
        flat = tf.reshape(x, (B * T, H))      # two consumers
        a = tf.matmul(flat, W)
        return a + flat

    gd, inputs, outputs = _frozen(
        model, [tf.TensorSpec((B, T, H), tf.float32, name="x")])
    x = rng.normal(0, 1, (B, T, H)).astype(np.float32)
    sd0 = TFGraphMapper.import_graph(gd, optimize=False)
    before = np.asarray(sd0.output({"x": x}, outputs[0]))
    sd = TFGraphMapper.import_graph(gd)  # full optimize incl. layout
    after = np.asarray(sd.output({"x": x}, outputs[0]))
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_layout_passes_attention_chain_golden():
    """Full imported attention block (proj reshapes/transposes + sdpa) stays
    golden through the layout passes."""
    rng = np.random.default_rng(2)
    B, T, H, heads = 2, 8, 16, 4
    dk = H // heads
    Wq, Wk, Wv = (rng.normal(0, 0.1, (H, H)).astype(np.float32)
                  for _ in range(3))

    def proj(x2, W):
        h = tf.matmul(x2, W)
        h = tf.reshape(h, (B, T, heads, dk))
        return tf.transpose(h, (0, 2, 1, 3))

    def model(x):
        x2 = tf.reshape(x, (B * T, H))
        q, k, v = proj(x2, Wq), proj(x2, Wk), proj(x2, Wv)
        s = tf.matmul(q, k, transpose_b=True) / np.float32(np.sqrt(dk))
        ctx = tf.matmul(tf.nn.softmax(s, axis=-1), v)
        return tf.reshape(tf.transpose(ctx, (0, 2, 1, 3)), (B, T, H))

    gd, inputs, outputs = _frozen(
        model, [tf.TensorSpec((B, T, H), tf.float32, name="x")])
    x = rng.normal(0, 1, (B, T, H)).astype(np.float32)
    sd0 = TFGraphMapper.import_graph(gd, optimize=False)
    before = np.asarray(sd0.output({"x": x}, outputs[0]))
    sd = TFGraphMapper.import_graph(gd)
    ops = [n.op for n in sd.ops]
    assert "scaled_dot_product_attention" in ops
    after = np.asarray(sd.output({"x": x}, outputs[0]))
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_layout_passes_dynamic_batch_stays_dynamic():
    """Graphs frozen with a None batch dim must still execute at ANY batch
    size after the layout passes — inferred (guessed) dims must never be
    baked into emitted reshape attrs."""
    rng = np.random.default_rng(3)
    T, H = 4, 8
    W = rng.normal(0, 0.1, (H, H)).astype(np.float32)
    b = rng.normal(0, 0.1, (H,)).astype(np.float32)

    def model(x):
        h = tf.matmul(tf.reshape(x, (-1, H)), W) + b
        return tf.reshape(h, (-1, T, H))

    gd, inputs, outputs = _frozen(
        model, [tf.TensorSpec((None, T, H), tf.float32, name="x")])
    sd = TFGraphMapper.import_graph(gd)
    sd0 = TFGraphMapper.import_graph(gd, optimize=False)
    for B in (2, 5):
        x = rng.normal(0, 1, (B, T, H)).astype(np.float32)
        before = np.asarray(sd0.output({"x": x}, outputs[0]))
        after = np.asarray(sd.output({"x": x}, outputs[0]))
        np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)
