"""Multihost smoke test (VERDICT r1 item 7): spawn two localhost processes
that call ``initialize_multihost`` (jax.distributed over a loopback
coordinator), build a global 2-process DP mesh, run ONE data-parallel step
each on its local shard, and assert the allreduced gradients match the
single-process run bit-for-bit.

This is the executable analog of the reference testing its whole Spark/Aeron
wire path on one box with ``local[N]`` (SURVEY.md §4): the same
``jax.distributed`` + GSPMD program later spans real hosts over ICI/DCN.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import json, os, sys
import numpy as np

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.runtime.mesh import initialize_multihost

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nproc, process_id=pid)

assert jax.process_count() == nproc, jax.process_count()
# 2 local CPU devices per process -> 4 global devices
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
devs = np.asarray(jax.devices()).reshape(-1)   # global device list
mesh = Mesh(devs, ("dp",))

rng = np.random.default_rng(0)
W = jnp.asarray(rng.normal(0, 0.5, (8, 4)), jnp.float32)     # replicated
X = rng.normal(0, 1, (16, 8)).astype(np.float32)             # global batch
Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]

def loss(w, x, y):
    p = jax.nn.log_softmax(x @ w)
    return -jnp.mean(jnp.sum(p * y, axis=-1))

xsh = NamedSharding(mesh, P("dp", None))
# each process hands jax only its LOCAL shard; make_array_from_process_local_data
# assembles the global array (the multi-host data-loading contract)
n_local = 16 // nproc
lo = pid * n_local
x_g = jax.make_array_from_process_local_data(xsh, X[lo:lo + n_local])
y_g = jax.make_array_from_process_local_data(xsh, Y[lo:lo + n_local])

g = jax.jit(jax.grad(loss))(W, x_g, y_g)
out = np.asarray(jax.device_get(g))
print("GRAD" + json.dumps(out.tolist()))
"""


@pytest.mark.slow
def test_two_process_dp_grads_match_single_process(tmp_path):
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])

    wfile = tmp_path / "worker.py"
    wfile.write_text(_WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # strip the TPU-plugin bootstrap (sitecustomize initialises the backend
    # at interpreter start, which must not happen before
    # jax.distributed.initialize) — workers are pure-CPU
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith("PALLAS_AXON")}
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(wfile), str(pid), "2", port],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
        for pid in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        grad_lines = [l for l in out.splitlines() if l.startswith("GRAD")]
        assert grad_lines, out
        outs.append(np.asarray(json.loads(grad_lines[0][4:])))

    # both processes see the same (allreduced) gradient
    np.testing.assert_array_equal(outs[0], outs[1])

    # single-process oracle
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.normal(0, 0.5, (8, 4)), jnp.float32)
    X = rng.normal(0, 1, (16, 8)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]

    def loss(w, x, y):
        p = jax.nn.log_softmax(x @ w)
        return -jnp.mean(jnp.sum(p * y, axis=-1))

    ref = np.asarray(jax.grad(loss)(W, jnp.asarray(X), jnp.asarray(Y)))
    np.testing.assert_allclose(outs[0], ref, rtol=1e-6, atol=1e-6)
