"""RL tests (reference RL4J patterns: toy-MDP convergence oracles)."""

import numpy as np

from deeplearning4j_tpu.rl import (A2CConfiguration, AdvantageActorCritic, BoltzmannPolicy,
                                   CartPole, EpsGreedy, ExpReplay, GridWorld,
                                   QLearningConfiguration, QLearningDiscreteDense,
                                   Transition)


def test_cartpole_env_dynamics():
    env = CartPole(seed=0)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0
    done = False
    while not done:
        obs, r, done, _ = env.step(1)
        total += r
    # pushing one way constantly falls quickly
    assert 5 <= total < 60


def test_replay_buffer_wraps():
    rep = ExpReplay(8, (3,), seed=0)
    for i in range(20):
        rep.store(Transition(np.full(3, i, np.float32), i % 2, float(i),
                             np.full(3, i + 1, np.float32), i % 5 == 0))
    assert len(rep) == 8
    s, a, r, s2, d = rep.sample(16)
    assert s.shape == (16, 3) and r.min() >= 12.0  # only newest 8 retained


def test_eps_greedy_anneals():
    pol = EpsGreedy(n_actions=4, min_epsilon=0.1, epsilon_nb_step=100)
    rng = np.random.default_rng(0)
    q = np.array([0.0, 1.0, 0.0, 0.0])
    assert pol.epsilon == 1.0
    for _ in range(200):
        pol.select(q, rng)
    assert pol.epsilon == 0.1
    # now mostly greedy
    picks = [pol.select(q, rng) for _ in range(50)]
    assert picks.count(1) > 40


def test_boltzmann_prefers_high_q():
    pol = BoltzmannPolicy(temperature=0.1)
    rng = np.random.default_rng(0)
    picks = [pol.select(np.array([0.0, 1.0]), rng) for _ in range(50)]
    assert picks.count(1) > 45


def test_dqn_solves_gridworld():
    env = GridWorld(n=5)
    conf = QLearningConfiguration(
        seed=7, max_step=1200, max_epoch_step=40, batch_size=32,
        exp_rep_max_size=2000, target_dqn_update_freq=100, update_start=32,
        min_epsilon=0.05, epsilon_nb_step=600, gamma=0.95, double_dqn=True)
    learner = QLearningDiscreteDense(env, conf, hidden=(32,))
    learner.train()
    # greedy policy should walk straight right: optimal return
    score = learner.play()
    assert score >= env.optimal_return() - 1e-6, (
        f"greedy return {score} < optimal {env.optimal_return()}")


def test_a2c_improves_on_gridworld():
    conf = A2CConfiguration(seed=3, max_step=6000, max_epoch_step=40,
                            num_envs=4, n_step=5, gamma=0.95,
                            entropy_coef=0.01)
    learner = AdvantageActorCritic(lambda i: GridWorld(n=5), conf, hidden=(32,))
    learner.train()
    env = learner.envs[0]
    score = learner.play()
    assert score >= env.optimal_return() - 0.2, (
        f"a2c return {score} too far below optimal {env.optimal_return()}")
