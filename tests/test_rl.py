"""RL tests (reference RL4J patterns: toy-MDP convergence oracles)."""

import numpy as np

from deeplearning4j_tpu.rl import (A2CConfiguration, AdvantageActorCritic, BoltzmannPolicy,
                                   CartPole, EpsGreedy, ExpReplay, GridWorld,
                                   QLearningConfiguration, QLearningDiscreteDense,
                                   Transition)


def test_cartpole_env_dynamics():
    env = CartPole(seed=0)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0
    done = False
    while not done:
        obs, r, done, _ = env.step(1)
        total += r
    # pushing one way constantly falls quickly
    assert 5 <= total < 60


def test_replay_buffer_wraps():
    rep = ExpReplay(8, (3,), seed=0)
    for i in range(20):
        rep.store(Transition(np.full(3, i, np.float32), i % 2, float(i),
                             np.full(3, i + 1, np.float32), i % 5 == 0))
    assert len(rep) == 8
    s, a, r, s2, d = rep.sample(16)
    assert s.shape == (16, 3) and r.min() >= 12.0  # only newest 8 retained


def test_eps_greedy_anneals():
    pol = EpsGreedy(n_actions=4, min_epsilon=0.1, epsilon_nb_step=100)
    rng = np.random.default_rng(0)
    q = np.array([0.0, 1.0, 0.0, 0.0])
    assert pol.epsilon == 1.0
    for _ in range(200):
        pol.select(q, rng)
    assert pol.epsilon == 0.1
    # now mostly greedy
    picks = [pol.select(q, rng) for _ in range(50)]
    assert picks.count(1) > 40


def test_boltzmann_prefers_high_q():
    pol = BoltzmannPolicy(temperature=0.1)
    rng = np.random.default_rng(0)
    picks = [pol.select(np.array([0.0, 1.0]), rng) for _ in range(50)]
    assert picks.count(1) > 45


def test_dqn_solves_gridworld():
    env = GridWorld(n=5)
    conf = QLearningConfiguration(
        seed=7, max_step=1200, max_epoch_step=40, batch_size=32,
        exp_rep_max_size=2000, target_dqn_update_freq=100, update_start=32,
        min_epsilon=0.05, epsilon_nb_step=600, gamma=0.95, double_dqn=True)
    learner = QLearningDiscreteDense(env, conf, hidden=(32,))
    learner.train()
    # greedy policy should walk straight right: optimal return
    score = learner.play()
    assert score >= env.optimal_return() - 1e-6, (
        f"greedy return {score} < optimal {env.optimal_return()}")


def test_a2c_improves_on_gridworld():
    conf = A2CConfiguration(seed=3, max_step=6000, max_epoch_step=40,
                            num_envs=4, n_step=5, gamma=0.95,
                            entropy_coef=0.01)
    learner = AdvantageActorCritic(lambda i: GridWorld(n=5), conf, hidden=(32,))
    learner.train()
    env = learner.envs[0]
    score = learner.play()
    assert score >= env.optimal_return() - 0.2, (
        f"a2c return {score} too far below optimal {env.optimal_return()}")


class _StubSpace:
    def __init__(self, shape=None, n=None, low=None, high=None):
        self.shape, self.n, self.low, self.high = shape, n, low, high


class _StubGymnasiumCorridor:
    """Gymnasium-API (5-tuple step, (obs, info) reset) corridor identical
    to GridWorld(n=5) — drives GymEnv without the offline-unavailable
    gymnasium package, the way rl4j tests stub gym-java-client."""

    def __init__(self, n=5):
        self.n = n
        self.observation_space = _StubSpace(shape=(n,),
                                            low=np.zeros(n), high=np.ones(n))
        self.action_space = _StubSpace(n=2)
        self._pos = 0
        self._steps = 0
        self.closed = False

    def _obs(self):
        v = np.zeros(self.n, np.float64)  # adapter must cast to f32
        v[self._pos] = 1.0
        return v

    def reset(self):
        self._pos, self._steps = 0, 0
        return self._obs(), {"info": True}

    def step(self, a):
        self._pos = min(self.n - 1, self._pos + 1) if a == 1 else max(0, self._pos - 1)
        self._steps += 1
        terminated = self._pos == self.n - 1
        truncated = self._steps >= 4 * self.n
        r = 1.0 if terminated else -0.01
        return self._obs(), r, terminated, truncated, {}

    def close(self):
        self.closed = True


class _StubClassicGymCorridor(_StubGymnasiumCorridor):
    """Classic-gym API: reset() -> obs, step() -> 4-tuple."""

    def reset(self):
        return super().reset()[0]

    def step(self, a):
        obs, r, terminated, truncated, info = super().step(a)
        return obs, r, terminated or truncated, info


def test_gym_adapter_both_apis():
    from deeplearning4j_tpu.rl import GymEnv
    for stub_cls in (_StubGymnasiumCorridor, _StubClassicGymCorridor):
        env = GymEnv(stub_cls())
        assert env.observation_space.shape == (5,)
        assert env.action_space.n == 2
        obs = env.reset()
        assert obs.dtype == np.float32 and obs.shape == (5,)
        total, done = 0.0, False
        while not done:
            obs, r, done, info = env.step(1)
            total += r
        assert abs(total - (1.0 - 0.01 * 3)) < 1e-6, total
        env.close()
        assert env.env.closed


def test_dqn_trains_through_gym_adapter():
    """The full rl4j-style loop (replay, target net, eps-greedy) runs over
    the gym-API adapter and solves the corridor."""
    from deeplearning4j_tpu.rl import GymEnv
    env = GymEnv(_StubGymnasiumCorridor(n=5))
    conf = QLearningConfiguration(
        seed=7, max_step=1200, max_epoch_step=40, batch_size=32,
        exp_rep_max_size=2000, target_dqn_update_freq=100, update_start=32,
        min_epsilon=0.05, epsilon_nb_step=600, gamma=0.95, double_dqn=True)
    learner = QLearningDiscreteDense(env, conf, hidden=(32,))
    learner.train()
    assert learner.play() >= (1.0 - 0.01 * 3) - 1e-6
