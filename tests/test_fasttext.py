"""FastText: subword embeddings (OOV composition) + supervised classifier."""

import numpy as np

from deeplearning4j_tpu.nlp import FastText
from deeplearning4j_tpu.nlp.fasttext import char_ngrams


def test_char_ngrams_boundaries():
    grams = char_ngrams("cat", 3, 4)
    assert "<ca" in grams and "at>" in grams and "cat>" in grams
    # whole-word gram "<cat>" excluded at n=5 (n >= len("<cat>"))
    assert "<cat>" not in grams


_CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick red fox runs over the sleepy cat",
    "a quick brown dog jumps over a lazy fox",
    "cats and dogs run quick over the brown field",
    "the lazy dog sleeps while the quick fox runs",
] * 6


def test_skipgram_subword_training_and_oov():
    ft = FastText(dim=16, epochs=3, bucket=2000, seed=0, min_word_frequency=1,
                  batch_size=256)
    ft.fit(_CORPUS)
    v = ft.get_word_vector("fox")
    assert v.shape == (16,) and np.isfinite(v).all()
    # OOV word gets a vector purely from n-gram buckets
    oov = ft.get_word_vector("foxes")
    assert oov.shape == (16,) and np.isfinite(oov).all()
    # shared subwords make morphological neighbors similar
    assert ft.similarity("fox", "foxes") > ft.similarity("fox", "sleeps")


def test_supervised_classification():
    texts = (["good great excellent wonderful amazing product"] * 10
             + ["bad terrible awful horrible poor product"] * 10)
    labels = ["pos"] * 10 + ["neg"] * 10
    clf = FastText(supervised=True, dim=12, epochs=40, bucket=1000, seed=1,
                   learning_rate=0.5)
    clf.fit(texts, labels)
    assert clf.predict("great wonderful amazing") == "pos"
    assert clf.predict("terrible awful poor") == "neg"
    probs = clf.predict_probability("good excellent product")
    assert set(probs) == {"pos", "neg"}
    assert abs(sum(probs.values()) - 1.0) < 1e-5
    assert probs["pos"] > 0.5
