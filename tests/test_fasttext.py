"""FastText: subword embeddings (OOV composition) + supervised classifier."""

import numpy as np

from deeplearning4j_tpu.nlp import FastText
from deeplearning4j_tpu.nlp.fasttext import char_ngrams


def test_char_ngrams_boundaries():
    grams = char_ngrams("cat", 3, 4)
    assert "<ca" in grams and "at>" in grams and "cat>" in grams
    # whole-word gram "<cat>" excluded at n=5 (n >= len("<cat>"))
    assert "<cat>" not in grams


_CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick red fox runs over the sleepy cat",
    "a quick brown dog jumps over a lazy fox",
    "cats and dogs run quick over the brown field",
    "the lazy dog sleeps while the quick fox runs",
] * 6


def test_skipgram_subword_training_and_oov():
    ft = FastText(dim=16, epochs=3, bucket=2000, seed=0, min_word_frequency=1,
                  batch_size=256)
    ft.fit(_CORPUS)
    v = ft.get_word_vector("fox")
    assert v.shape == (16,) and np.isfinite(v).all()
    # OOV word gets a vector purely from n-gram buckets
    oov = ft.get_word_vector("foxes")
    assert oov.shape == (16,) and np.isfinite(oov).all()
    # shared subwords make morphological neighbors similar
    assert ft.similarity("fox", "foxes") > ft.similarity("fox", "sleeps")


def test_supervised_classification():
    texts = (["good great excellent wonderful amazing product"] * 10
             + ["bad terrible awful horrible poor product"] * 10)
    labels = ["pos"] * 10 + ["neg"] * 10
    clf = FastText(supervised=True, dim=12, epochs=40, bucket=1000, seed=1,
                   learning_rate=0.5)
    clf.fit(texts, labels)
    assert clf.predict("great wonderful amazing") == "pos"
    assert clf.predict("terrible awful poor") == "neg"
    probs = clf.predict_probability("good excellent product")
    assert set(probs) == {"pos", "neg"}
    assert abs(sum(probs.values()) - 1.0) < 1e-5
    assert probs["pos"] > 0.5


def test_save_load_roundtrip(tmp_path):
    # min_word_frequency=2 exercises the direct vocab rebuild (a refit would
    # prune every word back to count 1 and crash)
    ft = FastText(dim=12, epochs=2, bucket=500, seed=0, min_word_frequency=2)
    ft.fit(_CORPUS)
    p = str(tmp_path / "ft_model")
    ft.save(p)
    back = FastText.load(p)
    v1, v2 = ft.get_word_vector("fox"), back.get_word_vector("fox")
    np.testing.assert_allclose(v1, v2, atol=1e-6)
    # OOV composition identical (same hashed buckets)
    np.testing.assert_allclose(ft.get_word_vector("foxish"),
                               back.get_word_vector("foxish"), atol=1e-6)

    clf = FastText(supervised=True, dim=8, epochs=20, bucket=300,
                   learning_rate=0.5, seed=1)
    clf.fit(["good great"] * 6 + ["bad awful"] * 6, ["pos"] * 6 + ["neg"] * 6)
    p2 = str(tmp_path / "ft_clf")
    clf.save(p2)
    back2 = FastText.load(p2)
    assert back2.predict("good great") == clf.predict("good great") == "pos"
    np.testing.assert_allclose(clf.predict_probability("bad")["neg"],
                               back2.predict_probability("bad")["neg"], atol=1e-6)
    # frequencies and sampling distribution survive the round trip
    assert back.vocab.counts["fox"] == ft.vocab.counts["fox"] > 1
    np.testing.assert_allclose(ft.vocab.negative_sampling_probs(),
                               back.vocab.negative_sampling_probs(), atol=1e-9)
