"""Declarative autodiff graph API (SameDiff equivalent).

Rebuild of upstream ``org.nd4j.autodiff.samediff``: symbolic variables
(VARIABLE / PLACEHOLDER / CONSTANT / ARRAY), op namespaces (``sd.math``,
``sd.nn``, ``sd.cnn``, ``sd.loss``), training via ``sd.fit()``, and
save/load. The execution design is the part the reference could only
approximate: where SameDiff topo-walks its op DAG dispatching one native call
per op (with a FlatBuffers whole-graph handoff as the fast path — SURVEY.md
§3.2), here the recorded graph IS a jax-traceable function, so every
``output()``/``fit()`` call executes one fused XLA program, and autodiff is
``jax.grad`` of the whole graph instead of per-op ``doDiff`` rules.
"""

from deeplearning4j_tpu.autodiff.samediff import SDVariable, SameDiff, TrainingConfig

__all__ = ["SameDiff", "SDVariable", "TrainingConfig"]
