"""Graph optimization passes over a SameDiff op graph.

The reference runs optimization passes over its graph IR before execution
(libnd4j's GraphExecutioner applies constant folding / fused-op rewrites;
SURVEY.md §3.2). Under XLA most classical fusion is the compiler's job, but
PATTERN fusion above the compiler still pays: imported TF graphs spell
layernorm/gelu out as 8-10 primitive nodes whose backward saves far more
intermediate HBM traffic than our fused registry ops (measured on the
imported BERT-base step: same FLOPs as the hand-built model, 1.8x the
bytes). These passes rewrite those subgraphs into the fused ops.

Passes are conservative: a match is rewritten only when every interior
value has no other consumer, so observable outputs never change.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import OpNode, SameDiff, VariableType


def _producers(sd: SameDiff) -> Dict[str, OpNode]:
    return {o: n for n in sd.ops for o in n.outputs}


def _use_counts(sd: SameDiff) -> Dict[str, int]:
    uses: Dict[str, int] = {}
    for n in sd.ops:
        for i in n.inputs:
            uses[i] = uses.get(i, 0) + 1
    for name in sd.loss_variables:
        uses[name] = uses.get(name, 0) + 1
    return uses


def _const_scalar(sd: SameDiff, name: str) -> Optional[float]:
    v = sd.vars.get(name)
    if v is None or v.vtype not in (VariableType.CONSTANT,):
        return None
    a = sd.arrays.get(name)
    if a is None or a.size != 1:
        return None
    return float(np.asarray(a).reshape(()))


def _is_last_axis(axis) -> bool:
    """True only for a last-axis reduction (layer_norm normalizes axis=-1;
    TF Mean(axis=[1,2]) spellings are group/instance norm — different op).
    The importer can't know the rank here, so only the unambiguous -1 form
    qualifies."""
    if axis is None:
        return False
    if isinstance(axis, (list, tuple)):
        return len(axis) == 1 and int(axis[0]) == -1
    return int(axis) == -1


def _binary(node: OpNode, op: str) -> Optional[Tuple[str, str]]:
    if node.op != op or len(node.inputs) != 2:
        return None
    return node.inputs[0], node.inputs[1]


def _replace(sd: SameDiff, dead: List[OpNode], new_node: OpNode) -> None:
    """Swap `dead` (whose last element produces new_node's output) for the
    fused node, preserving topological position."""
    idx = sd.ops.index(dead[-1])
    sd.ops[idx] = new_node
    for n in dead[:-1]:
        sd.ops.remove(n)
    sd._jit_cache.clear()
    sd._graph_version += 1


def fuse_layer_norm(sd: SameDiff) -> int:
    """(x - mean(x)) * rsqrt(var(x) + eps) * gamma + beta  ->  layer_norm.

    Matches the TF-emitted shape: Mean / SquaredDifference / Mean / Add(eps)
    / Rsqrt / Sub / Mul / Mul(gamma) / Add(beta), all reducing the LAST axis
    with keepdims."""
    fused = 0
    while True:
        prod = _producers(sd)
        uses = _use_counts(sd)

        def sole(name):  # interior value consumed exactly once, not a loss
            return uses.get(name, 0) == 1 and name not in sd.loss_variables

        match = None
        for out_node in sd.ops:
            b = _binary(out_node, "add")
            if not b:
                continue
            # out = add(scaled, beta) — beta is a leaf (const/variable)
            for scaled_name, beta in (b, b[::-1]):
                scaled = prod.get(scaled_name)
                # need: scaled produced by an op, beta a leaf (const/var)
                if scaled is None or prod.get(beta) is not None:
                    continue
                m2 = _binary(scaled, "mul")
                if not m2 or not sole(scaled_name):
                    continue
                for normed_name, gamma in (m2, m2[::-1]):
                    if prod.get(gamma) is not None:
                        continue
                    normed = prod.get(normed_name)
                    if normed is None or not sole(normed_name):
                        continue
                    m1 = _binary(normed, "mul")
                    if not m1:
                        continue
                    for centered_name, r_name in (m1, m1[::-1]):
                        centered = prod.get(centered_name)
                        r = prod.get(r_name)
                        if (centered is None or r is None
                                or centered.op != "sub" or r.op != "rsqrt"
                                or not sole(centered_name) or not sole(r_name)):
                            continue
                        x_name, mean_name = centered.inputs
                        mean_node = prod.get(mean_name)
                        if (mean_node is None or mean_node.op != "reduce_mean"
                                or mean_node.inputs[0] != x_name
                                or not mean_node.attrs.get("keepdims")
                                or not _is_last_axis(mean_node.attrs.get("axis"))):
                            continue
                        veps = prod.get(r.inputs[0])
                        if veps is None or veps.op != "add" or not sole(r.inputs[0]):
                            continue
                        vb = _binary(veps, "add")
                        for var_name, eps_name in (vb, vb[::-1]):
                            eps = _const_scalar(sd, eps_name)
                            var_node = prod.get(var_name)
                            if (eps is None or var_node is None
                                    or var_node.op != "reduce_mean"
                                    or not var_node.attrs.get("keepdims")
                                    or not _is_last_axis(var_node.attrs.get("axis"))
                                    or not sole(var_name)):
                                continue
                            sq = prod.get(var_node.inputs[0])
                            if (sq is None or sq.op != "squared_difference"
                                    or not sole(var_node.inputs[0])):
                                continue
                            sq_in = set(sq.inputs)
                            if sq_in != {x_name, mean_name}:
                                continue
                            # mean consumed by sub and squared_difference only
                            if uses.get(mean_name, 0) != 2:
                                continue
                            match = (out_node, scaled, normed, centered, r,
                                     veps, var_node, sq, mean_node,
                                     x_name, gamma, beta, eps)
                            break
                        if match:
                            break
                    if match:
                        break
                if match:
                    break
            if match:
                break
        if not match:
            return fused
        (out_node, scaled, normed, centered, r, veps, var_node, sq,
         mean_node, x_name, gamma, beta, eps) = match
        dead = [mean_node, sq, var_node, veps, r, centered, normed, scaled,
                out_node]
        _replace(sd, dead, OpNode(
            op="layer_norm", inputs=[x_name, gamma, beta],
            outputs=list(out_node.outputs), attrs={"axis": -1, "eps": eps}))
        fused += 1


def fuse_gelu_erf(sd: SameDiff) -> int:
    """0.5 * y * (1 + erf(y / sqrt(2)))  ->  gelu(y, approximate=False).

    Matches both association orders TF emits for the double product."""
    fused = 0
    while True:
        prod = _producers(sd)
        uses = _use_counts(sd)

        def sole(name):
            return uses.get(name, 0) == 1 and name not in sd.loss_variables

        def is_half(name):
            c = _const_scalar(sd, name)
            return c is not None and abs(c - 0.5) < 1e-12

        def one_plus_erf(name):
            """-> y_name if `name` is add(1, erf(y / sqrt2))."""
            n = prod.get(name)
            if n is None or n.op != "add" or not sole(name):
                return None
            for one_name, e_name in (n.inputs, n.inputs[::-1]):
                c = _const_scalar(sd, one_name)
                if c is None or abs(c - 1.0) > 1e-12:
                    continue
                e = prod.get(e_name)
                if e is None or e.op != "erf" or not sole(e_name):
                    continue
                d = prod.get(e.inputs[0])
                if d is None or not sole(e.inputs[0]):
                    continue
                if d.op == "div":
                    y, c2 = d.inputs
                    cv = _const_scalar(sd, c2)
                    if cv is not None and abs(cv - np.sqrt(2.0)) < 1e-4:
                        return y, [d, e, n]
                if d.op == "mul":
                    for y, c2 in (d.inputs, d.inputs[::-1]):
                        cv = _const_scalar(sd, c2)
                        if cv is not None and abs(cv - 1 / np.sqrt(2.0)) < 1e-4:
                            return y, [d, e, n]
            return None

        match = None
        for out_node in sd.ops:
            m = _binary(out_node, "mul")
            if not m:
                continue
            for a_name, b_name in (m, m[::-1]):
                # form A: mul(mul(0.5, y), 1+erf)   form B: mul(0.5, mul(y, 1+erf))
                res = one_plus_erf(b_name)
                if res is not None:
                    y, dead_tail = res
                    inner = prod.get(a_name)
                    if inner is not None and sole(a_name):
                        mi = _binary(inner, "mul")
                        if mi:
                            for h, yy in (mi, mi[::-1]):
                                if is_half(h) and yy == y:
                                    match = (y, dead_tail + [inner, out_node])
                                    break
                if match:
                    break
                if is_half(a_name):
                    inner = prod.get(b_name)
                    if inner is not None and sole(b_name):
                        mi = _binary(inner, "mul")
                        if mi:
                            for yy, oe_name in (mi, mi[::-1]):
                                res2 = one_plus_erf(oe_name)
                                if res2 is not None and res2[0] == yy:
                                    match = (yy, res2[1] + [inner, out_node])
                                    break
                if match:
                    break
            if match:
                break
        if not match:
            return fused
        y, dead = match
        # dead nodes may be discovered out of graph order; keep stable order
        dead = sorted(set(map(id, dead)), key=[id(n) for n in sd.ops].index)
        dead_nodes = [n for n in sd.ops if id(n) in dead]
        out_node = dead_nodes[-1]
        _replace(sd, dead_nodes, OpNode(
            op="gelu", inputs=[y], outputs=list(out_node.outputs),
            attrs={"approximate": False}))
        fused += 1


def optimize(sd: SameDiff) -> Dict[str, int]:
    """Run all passes to fixpoint; returns per-pass fusion counts."""
    stats = {"layer_norm": fuse_layer_norm(sd), "gelu_erf": fuse_gelu_erf(sd),
             "attention": fuse_attention(sd)}
    folded, shapes = _fold_shape_chains(sd)
    stats["shape_folds"] = folded
    stats.update(optimize_layout(sd, shapes=shapes))
    return stats


# --------------------------------------------------------- layout passes
#
# TF exporters spell batched matmuls as reshape-to-2D round trips
# (reshape(x,(B*T,H)) @ W, then reshape back), and thread bias-adds and
# activations through the 2-D form. XLA assigns the 2-D dot outputs
# column-major-style layouts that clash with the 3-D consumers', and the
# resulting layout-conversion copies measured 4.6 GB/step on the imported
# BERT-base (vs 0.45 GB in the hand-built model; see BASELINE.md round 3).
# These passes restore the 3-D form the hand-built layers use: fold the
# reshape into the matmul, sink the compensating reshape down through
# elementwise ops until it meets another reshape, and collapse the pair.

_SINK_UNARY = {"gelu", "tanh", "relu", "sigmoid", "identity", "erf", "neg",
               "rsqrt", "exp", "log", "softplus", "swish"}
_SINK_BINARY = {"add", "sub", "mul", "div", "bias_add", "maximum", "minimum",
                "squared_difference"}


def _infer(sd: SameDiff, lead: Optional[int] = None):
    """Incremental per-op shape + shape-VALUE propagation.

    Walks the (topologically ordered) op list once. For each op, inputs
    with statically-known VALUES (constants; shape_of of a known shape;
    arithmetic thereon) are passed concretely via closure — so shape
    chains evaluate to real integers — while the rest enter a per-op
    ``jax.eval_shape`` abstractly. An op that cannot be evaluated only
    blanks ITS outputs; downstream ops that don't depend on them still
    resolve (a whole-graph trace used to lose everything to one bad op).

    Every placeholder dim recorded as None is filled with ``lead`` (default:
    the most common known leading dim — the importer freezes real batch
    dims, so typically only grafted-loss label placeholders need filling).
    Such dims are GUESSES: rewrite passes must never bake inferred leading
    dims into emitted attrs (they use -1 / original attrs; see
    fold_shape_chains for the two-run taint check).

    Returns ``(shapes, values)`` dicts keyed by variable name."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.autodiff.ops_registry import get_op

    if lead is None:
        known_lead = [v.shape[0] for v in sd.vars.values()
                      if v.vtype == VariableType.PLACEHOLDER and v.shape
                      and v.shape[0] is not None]
        lead = max(set(known_lead), key=known_lead.count) if known_lead else 2

    shapes: Dict[str, Tuple[int, ...]] = {}
    dtypes: Dict[str, Any] = {}
    values: Dict[str, np.ndarray] = {}
    for name, a in sd.arrays.items():
        shapes[name] = tuple(a.shape)
        dtypes[name] = a.dtype
        arr = np.asarray(a)
        if sd.vars[name].vtype == VariableType.CONSTANT \
                and arr.dtype.kind in "iu" and arr.size <= 64:
            values[name] = arr
    for name, v in sd.vars.items():
        if name in shapes or v.vtype != VariableType.PLACEHOLDER \
                or v.shape is None:
            continue
        shapes[name] = tuple(lead if d is None else int(d) for d in v.shape)
        dtypes[name] = v.dtype or jnp.float32

    for n in sd.ops:
        if any(i not in shapes for i in n.inputs):
            continue
        if n.op == "shape_of":
            out = n.outputs[0]
            values[out] = np.asarray(shapes[n.inputs[0]], np.int64)
            shapes[out] = values[out].shape
            dtypes[out] = np.int32
            continue
        try:
            fn = n.attrs["fn"] if n.op == "__callable__" else get_op(n.op)
            attrs = {} if n.op == "__callable__" else n.attrs
            conc = {j: values[i] for j, i in enumerate(n.inputs)
                    if i in values}
            specs = [jax.ShapeDtypeStruct(shapes[i], dtypes[i])
                     for j, i in enumerate(n.inputs) if j not in conc]

            def f(*xs, _fn=fn, _attrs=attrs, _conc=conc, _n=len(n.inputs)):
                it = iter(xs)
                full = [_conc[j] if j in _conc else next(it)
                        for j in range(_n)]
                return _fn(*full, **_attrs)

            if conc and len(conc) == len(n.inputs):
                # fully concrete: evaluate for real — this is how shape
                # ARITHMETIC (slice/stack/mul of shape_of) stays a value
                res = f()
                res_t = res if isinstance(res, (tuple, list)) else (res,)
                for o, r in zip(n.outputs, res_t):
                    arr = np.asarray(r)
                    shapes[o] = arr.shape
                    dtypes[o] = arr.dtype
                    if arr.dtype.kind in "iu" and arr.size <= 64:
                        values[o] = arr
            else:
                res = jax.eval_shape(f, *specs)
                res_t = res if isinstance(res, (tuple, list)) else (res,)
                for o, r in zip(n.outputs, res_t):
                    shapes[o] = tuple(r.shape)
                    dtypes[o] = r.dtype
        except Exception:
            continue
    return shapes, values


def infer_shapes(sd: SameDiff, lead: Optional[int] = None
                 ) -> Optional[Dict[str, Tuple[int, ...]]]:
    """Shapes-only view of :func:`_infer`. Returns None — with a warning,
    since the layout passes then silently lose their measured win — when
    not a single op output could be resolved."""
    shapes, _ = _infer(sd, lead)
    if sd.ops and not any(o in shapes for n in sd.ops for o in n.outputs):
        import warnings
        warnings.warn(
            "graph_optimizer: shape inference resolved no op outputs; "
            "layout passes skipped — imported 2-D matmul round trips will "
            "keep their layout-conversion copies", stacklevel=2)
        return None
    return shapes or None


def fold_shape_chains(sd: SameDiff) -> int:
    """Public wrapper of :func:`_fold_shape_chains` (count only)."""
    return _fold_shape_chains(sd)[0]


def _fold_shape_chains(sd: SameDiff):
    """Rewrite ``reshape_dynamic`` (tensor shape operand, emitted by the TF
    importer for computed shapes) into static ``reshape`` attrs using the
    propagated shape VALUES from :func:`_infer`.

    Dims that depend on a dynamic (None) placeholder dim are detected by
    inferring twice with two different substituted leading dims: entries
    whose value CHANGES between the runs become -1 in the rewritten attr
    (jnp.reshape resolves one -1; chains needing more stay dynamic).

    Returns ``(folded_count, shapes_or_None)`` — the first run's shapes are
    handed back so optimize() can feed the layout passes without a third
    full graph walk (the rewrite preserves every output's shape)."""
    if not any(n.op == "reshape_dynamic" for n in sd.ops):
        return 0, None
    has_none = any(v.vtype == VariableType.PLACEHOLDER and v.shape
                   and any(d is None for d in v.shape)
                   for v in sd.vars.values())
    known_lead = [v.shape[0] for v in sd.vars.values()
                  if v.vtype == VariableType.PLACEHOLDER and v.shape
                  and v.shape[0] is not None]
    lead = max(set(known_lead), key=known_lead.count) if known_lead else 2
    s1, v1 = _infer(sd, lead=lead)
    # the second run MUST use a different substituted dim or batch-dependent
    # entries would match across runs and get baked as static ints
    v2 = _infer(sd, lead=lead + 1)[1] if has_none else v1
    folded = 0
    for n in sd.ops:
        if n.op != "reshape_dynamic":
            continue
        sname = n.inputs[1]
        a, b = v1.get(sname), v2.get(sname)
        if a is None or b is None or a.shape != b.shape or a.ndim != 1:
            continue
        target = [int(x) if int(x) == int(y) else -1 for x, y in zip(a, b)]
        if sum(1 for t in target if t == -1) > 1:
            continue
        n.op = "reshape"
        n.inputs = n.inputs[:1]
        n.attrs = {"shape": target}
        folded += 1
    if folded:
        sd._jit_cache.clear()
        sd._graph_version += 1
    return folded, s1


def _new_array_var(sd: SameDiff, base: str) -> str:
    from deeplearning4j_tpu.autodiff.samediff import SDVariable
    name = sd._unique(base)
    sd.vars[name] = SDVariable(sd, name, VariableType.ARRAY)
    return name


def fold_2d_matmuls(sd: SameDiff, shapes: Dict[str, Tuple[int, ...]]) -> int:
    """matmul(reshape(x, (M, K)), W) -> reshape(matmul(x, W), (M, N)) for
    rank>=3 x — the matmul runs batched in x's natural layout; the
    compensating reshape sinks/collapses in the companion passes."""
    changed = 0
    prod = _producers(sd)
    uses = _use_counts(sd)
    for mm in list(sd.ops):
        if mm.op != "matmul" or mm.attrs.get("transpose_a") \
                or mm.attrs.get("transpose_b"):
            continue
        a_name, w_name = mm.inputs
        r = prod.get(a_name)
        if r is None or r.op != "reshape":
            continue
        x = r.inputs[0]
        xs, ws, a2 = shapes.get(x), shapes.get(w_name), shapes.get(a_name)
        if xs is None or ws is None or a2 is None:
            continue
        if len(a2) != 2 or len(xs) < 3 or len(ws) != 2:
            continue
        src, src_shape = x, xs
        if xs[-1] != a2[-1]:
            # The flattening reshape also MERGES trailing dims — the
            # attention output projection's (B,T,H,dk) -> (B·T, H·dk).
            # A trailing-dim merge is contiguity-preserving (a bitcast on
            # TPU), so fold to: cheap pre-reshape (B,T,H·dk) + batched 3-D
            # matmul. Without this the projection ran 2-D and its
            # (B·T, d) output materialized in a layout the surrounding
            # 3-D ops then copy-converted (~1.4 ms/step on imported
            # BERT-base).
            k_dim = a2[-1]
            p, j = 1, len(xs)
            while j > 0 and p < k_dim:
                j -= 1
                p *= xs[j]
            if p != k_dim or j < 2:
                continue
            pre = _new_array_var(sd, a_name + "/merged")
            sd.ops.insert(sd.ops.index(mm), OpNode(
                op="reshape", inputs=[x], outputs=[pre],
                attrs={"shape": [-1] + [int(d) for d in xs[1:j]]
                       + [int(k_dim)]}))
            shapes[pre] = tuple(xs[:j]) + (k_dim,)
            src, src_shape = pre, shapes[pre]
        old_out = mm.outputs[0]
        mid = _new_array_var(sd, old_out + "/3d")
        mm.inputs = [src, w_name]
        mm.outputs = [mid]
        shapes[mid] = tuple(src_shape[:-1]) + (ws[-1],)
        # -1 leading dim: inferred dims may be guesses for dynamic-batch
        # placeholders, so never bake them into emitted attrs
        sd.ops.insert(sd.ops.index(mm) + 1, OpNode(
            op="reshape", inputs=[mid], outputs=[old_out],
            attrs={"shape": [-1, int(ws[-1])]}))
        if uses.get(a_name, 0) == 1 and a_name not in sd.loss_variables:
            sd.ops.remove(r)
        changed += 1
        prod = _producers(sd)
        uses = _use_counts(sd)
    return changed


def sink_reshapes(sd: SameDiff, shapes: Dict[str, Tuple[int, ...]]) -> int:
    """reshape-then-elementwise -> elementwise-then-reshape, when the other
    operand (if any) is rank<=1 and the reshape preserves the trailing axis
    (so broadcasting is unaffected). Run to fixpoint with collapse."""
    changed = 0
    while True:
        prod = _producers(sd)
        uses = _use_counts(sd)
        found = False
        for node in list(sd.ops):
            if node.op in _SINK_UNARY:
                r_idx = 0
            elif node.op in _SINK_BINARY and len(node.inputs) == 2:
                r_idx = None
                for i in (0, 1):
                    cand = prod.get(node.inputs[i])
                    other = shapes.get(node.inputs[1 - i])
                    if (cand is not None and cand.op == "reshape"
                            and other is not None and len(other) <= 1):
                        r_idx = i
                        break
                if r_idx is None:
                    continue
            else:
                continue
            r_name = node.inputs[r_idx]
            r = prod.get(r_name)
            if r is None or r.op != "reshape":
                continue
            if uses.get(r_name, 0) != 1 or r_name in sd.loss_variables:
                continue
            x = r.inputs[0]
            xs, tgt = shapes.get(x), shapes.get(r_name)
            if xs is None or tgt is None or not xs or not tgt \
                    or xs[-1] != tgt[-1]:
                continue
            # the inserted reshape reuses the ORIGINAL node's target attr
            # (elementwise with a rank<=1 operand preserves shape), keeping
            # any -1 dynamic dims; 0-dims (copy-dim) are positional w.r.t.
            # the input, which changes here — skip those
            orig_tgt = list(r.attrs.get("shape", ()))
            if not orig_tgt or any(int(d) == 0 for d in orig_tgt):
                continue
            old_out = node.outputs[0]
            mid = _new_array_var(sd, old_out + "/sunk")
            node.inputs[r_idx] = x
            node.outputs = [mid]
            shapes[mid] = xs
            sd.ops.insert(sd.ops.index(node) + 1, OpNode(
                op="reshape", inputs=[mid], outputs=[old_out],
                attrs={"shape": orig_tgt}))
            sd.ops.remove(r)
            changed += 1
            found = True
            break
        if not found:
            return changed


def collapse_reshapes(sd: SameDiff, shapes: Dict[str, Tuple[int, ...]]) -> int:
    """reshape(reshape(x)) -> reshape(x) (the inner one dies when sole)."""
    changed = 0
    while True:
        prod = _producers(sd)
        uses = _use_counts(sd)
        found = False
        for r2 in sd.ops:
            if r2.op != "reshape":
                continue
            # 0-dims (copy-dim) are positional w.r.t. the input, which this
            # rewrite changes — leave such reshapes alone
            if any(int(d) == 0 for d in r2.attrs.get("shape", ())):
                continue
            inner_name = r2.inputs[0]
            r1 = prod.get(inner_name)
            if r1 is None or r1.op != "reshape":
                continue
            r2.inputs[0] = r1.inputs[0]
            if uses.get(inner_name, 0) == 1 \
                    and inner_name not in sd.loss_variables:
                sd.ops.remove(r1)
            changed += 1
            found = True
            break
        if not found:
            return changed


def optimize_layout(sd: SameDiff,
                    shapes: Optional[Dict[str, Tuple[int, ...]]] = None
                    ) -> Dict[str, int]:
    """Run the 2-D-matmul folding + reshape sinking/collapsing to fixpoint.
    ``shapes`` may be handed in from an earlier _infer walk this round."""
    if shapes is None:
        shapes = infer_shapes(sd)
    if shapes is None:
        return {"layout_folds": 0}
    total = {"layout_folds": 0, "reshape_sinks": 0, "reshape_collapses": 0}
    for _ in range(50):
        a = fold_2d_matmuls(sd, shapes)
        b = sink_reshapes(sd, shapes)
        c = collapse_reshapes(sd, shapes)
        total["layout_folds"] += a
        total["reshape_sinks"] += b
        total["reshape_collapses"] += c
        if a + b + c == 0:
            break
    if sum(total.values()):
        sd._jit_cache.clear()
        sd._graph_version += 1
    return total


def _is_padding_bias(sd: SameDiff, prod, name: str) -> bool:
    """True when `name` provably computes the additive key-padding pattern
    ((1 - float(mask)) * -LARGE, possibly reshaped): values are exactly 0 or
    -LARGE, so converting to a boolean mask preserves softmax outputs."""
    node = prod.get(name)
    if node is None:
        return False
    if node.op in ("reshape", "expand_dims", "identity"):
        return _is_padding_bias(sd, prod, node.inputs[0])
    if node.op != "mul" or len(node.inputs) != 2:
        return False
    for a, b in (node.inputs, node.inputs[::-1]):
        c = _const_scalar(sd, b)
        if c is None or c > -1e3:  # the -10000-style masking constant
            continue
        sub = prod.get(a)
        if sub is None or sub.op != "sub":
            continue
        one = _const_scalar(sd, sub.inputs[0])
        if one is not None and abs(one - 1.0) < 1e-12:
            src = prod.get(sub.inputs[1])
            # (1 - cast(mask)) where mask is a graph INPUT (placeholder):
            # the importer's key-padding contract is a 0/1-valued mask
            # feed. A cast of a COMPUTED tensor (e.g. a relative-position
            # score) is not provably {0,1} and must stay additive.
            if src is not None and src.op == "cast":
                cast_in = src.inputs[0]
                through = prod.get(cast_in)
                while through is not None and through.op in (
                        "reshape", "expand_dims", "identity"):
                    cast_in = through.inputs[0]
                    through = prod.get(cast_in)
                v = sd.vars.get(cast_in)
                if v is not None and v.vtype == VariableType.PLACEHOLDER:
                    return True
    return False


def fuse_attention(sd: SameDiff) -> int:
    """batch_matmul(q, k, T) * scale [+ bias] -> softmax -> batch_matmul(v)
    collapses to scaled_dot_product_attention. When the bias is the proven
    key-padding pattern, the fused op routes through dot_product_attention
    (Pallas flash kernel for eligible shapes)."""
    fused = 0
    while True:
        prod = _producers(sd)
        uses = _use_counts(sd)

        def sole(name):
            return uses.get(name, 0) == 1 and name not in sd.loss_variables

        match = None
        for bm2 in sd.ops:
            if bm2.op != "batch_matmul" or bm2.attrs.get("transpose_a") \
                    or bm2.attrs.get("transpose_b"):
                continue
            p_name, v_name = bm2.inputs
            sm = prod.get(p_name)
            if sm is None or sm.op != "softmax" or not sole(p_name):
                continue
            if sm.attrs.get("axis", -1) != -1:
                continue  # fused op normalizes the LAST axis only
            scores_name = sm.inputs[0]
            scores = prod.get(scores_name)
            if scores is None or not sole(scores_name):
                continue
            def resolve_scaled(node):
                """-> (qk_name, scale, bm1) for div/mul-by-const of a
                transpose_b batch_matmul, else None. Checks BOTH operand
                orders for mul (exporters emit mul(const, qk) too; div's
                constant is always the divisor)."""
                orders = [(node.inputs[0], node.inputs[1])]
                if node.op == "mul":
                    orders.append((node.inputs[1], node.inputs[0]))
                for qk_name, c_name in orders:
                    c = _const_scalar(sd, c_name)
                    if c is None:
                        continue
                    bm1 = prod.get(qk_name)
                    if (bm1 is None or bm1.op != "batch_matmul"
                            or not bm1.attrs.get("transpose_b")
                            or bm1.attrs.get("transpose_a")
                            or not sole(qk_name)):
                        continue
                    return qk_name, (1.0 / c) if node.op == "div" else c, bm1
                return None

            bias_name = None
            resolved = None
            scale_node = None
            if scores.op == "add":
                sa, sb = scores.inputs
                # one side is the scaled qk product, the other the bias;
                # try BOTH pairings fully (the bias itself may be a mul)
                for cand, other in ((sa, sb), (sb, sa)):
                    cn = prod.get(cand)
                    if cn is None or cn.op not in ("div", "mul") \
                            or not sole(cand):
                        continue
                    resolved = resolve_scaled(cn)
                    if resolved is not None:
                        bias_name = other
                        scale_node = cn
                        break
            elif scores.op in ("div", "mul"):
                resolved = resolve_scaled(scores)
                scale_node = scores
            if resolved is None:
                continue
            qk_name, scale, bm1 = resolved
            q_name, k_name = bm1.inputs
            boolean_bias = (bias_name is not None
                            and _is_padding_bias(sd, prod, bias_name))
            dead = [bm1, scale_node] \
                + ([scores] if scores is not scale_node else []) + [sm, bm2]
            inputs = [q_name, k_name, v_name] + (
                [bias_name] if bias_name is not None else [])
            match = (dead, inputs, scale, boolean_bias, bm2)
            break
        if not match:
            return fused
        dead, inputs, scale, boolean_bias, bm2 = match
        _replace(sd, dead, OpNode(
            op="scaled_dot_product_attention", inputs=inputs,
            outputs=list(bm2.outputs),
            attrs={"scale": scale, "boolean_bias": boolean_bias}))
        fused += 1
