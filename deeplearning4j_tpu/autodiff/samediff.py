"""SameDiff-equivalent declarative graph.

Rebuild of upstream ``org.nd4j.autodiff.samediff.SameDiff`` (the reference's
~10k-line core class) with a compiler at the other end: the op graph records
named registry ops (data, serializable), execution traces the whole graph
into ONE jitted XLA program, and gradients come from ``jax.grad`` of that
program (replacing per-op ``doDiff`` and the topo-walking
``InferenceSession``/``TrainingSession``).

API parity sketch::

    sd = SameDiff.create()
    x = sd.placeholder("x", (None, 784))
    w = sd.var("w", (784, 10))
    b = sd.var("b", (10,))
    logits = x @ w + b                      # operator sugar
    probs = sd.nn.softmax(logits, name="probs")
    labels = sd.placeholder("labels", (None, 10))
    loss = sd.loss.softmax_cross_entropy("loss", labels, logits)
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(updater=Adam(1e-3),
                                          data_set_feature_mapping=["x"],
                                          data_set_label_mapping=["labels"]))
    sd.fit(iterator, epochs=2)
    out = sd.output({"x": arr}, "probs")
    sd.save(path); SameDiff.load(path)
"""

from __future__ import annotations

import dataclasses
import enum
import io
import json
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.autodiff.ops_registry import OPS, RNG_OPS, get_op
from deeplearning4j_tpu.ops.initializers import WeightInit, init_weights
from deeplearning4j_tpu.train.updaters import Adam, Updater


class VariableType(str, enum.Enum):
    VARIABLE = "variable"      # trainable
    PLACEHOLDER = "placeholder"
    CONSTANT = "constant"
    ARRAY = "array"            # op output


@dataclasses.dataclass
class OpNode:
    op: str                      # registry name
    inputs: List[str]            # input variable names
    outputs: List[str]
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    out_index: Optional[int] = None  # for multi-output ops: which output


class SDVariable:
    def __init__(self, sd: "SameDiff", name: str, vtype: VariableType,
                 shape: Optional[Tuple] = None, dtype=None):
        self.sd = sd
        self.name = name
        self.vtype = vtype
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    # ---- operator sugar (reference SDVariable methods) ----
    def _bin(self, op, other, reverse=False):
        other = self.sd._lift(other)
        a, b = (other, self) if reverse else (self, other)
        return self.sd._apply(op, [a, b])

    def __add__(self, o):
        return self._bin("add", o)
    __radd__ = __add__

    def __sub__(self, o):
        return self._bin("sub", o)

    def __rsub__(self, o):
        return self._bin("sub", o, reverse=True)

    def __mul__(self, o):
        return self._bin("mul", o)
    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin("div", o)

    def __rtruediv__(self, o):
        return self._bin("div", o, reverse=True)

    def __pow__(self, o):
        return self._bin("pow", o)

    def __matmul__(self, o):
        return self._bin("matmul", o)

    def __neg__(self):
        return self.sd._apply("neg", [self])

    def __gt__(self, o):
        return self._bin("gt", o)

    def __lt__(self, o):
        return self._bin("lt", o)

    # common instance methods, reference-style
    def add(self, o, name=None):
        return self.sd._apply("add", [self, self.sd._lift(o)], name=name)

    def mmul(self, o, name=None):
        return self.sd._apply("matmul", [self, self.sd._lift(o)], name=name)

    def reshape(self, *shape, name=None):
        return self.sd._apply("reshape", [self], attrs={"shape": shape}, name=name)

    def transpose(self, *perm, name=None):
        return self.sd._apply("transpose", [self],
                              attrs={"perm": perm or None}, name=name)

    def sum(self, axis=None, keepdims=False, name=None):
        return self.sd._apply("reduce_sum", [self],
                              attrs={"axis": axis, "keepdims": keepdims}, name=name)

    def mean(self, axis=None, keepdims=False, name=None):
        return self.sd._apply("reduce_mean", [self],
                              attrs={"axis": axis, "keepdims": keepdims}, name=name)

    def std(self, axis=None, keepdims=False, name=None):
        return self.sd._apply("reduce_std", [self],
                              attrs={"axis": axis, "keepdims": keepdims}, name=name)

    def eval(self, placeholders: Optional[Dict[str, Any]] = None):
        """Evaluate this variable (reference ``SDVariable.eval()``)."""
        return self.sd.output(placeholders or {}, self.name)

    def get_arr(self):
        return self.sd.arrays.get(self.name)

    def set_arr(self, value):
        self.sd.arrays[self.name] = jnp.asarray(value)
        # only a CONSTANT's value is baked into traced train steps —
        # invalidate and EVICT (stale executables pin the old device
        # buffers); VARIABLE/ARRAY values are passed as step arguments
        if self.vtype is VariableType.CONSTANT:
            self.sd._graph_version += 1
            self.sd._jit_cache.clear()

    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self.name, new_name)
        return self

    def __repr__(self):
        return f"SDVariable(name={self.name!r}, type={self.vtype.value}, shape={self.shape})"


class _Namespace:
    """Op namespace (sd.math / sd.nn / sd.cnn / sd.loss / sd.random)."""

    def __init__(self, sd: "SameDiff", ops: Sequence[str], loss_style: bool = False):
        self._sd = sd
        self._ops = set(ops)
        self._loss_style = loss_style

    def __getattr__(self, op):
        if op.startswith("_") or op not in self._ops:
            raise AttributeError(op)

        def call(*args, name=None, **attrs):
            if self._loss_style and args and isinstance(args[0], str) and name is None:
                name, args = args[0], args[1:]
            vars_ = [self._sd._lift(a) for a in args]
            n_out = _MULTI_OUTPUT_OPS.get(op, 1)
            if op == "svd" and attrs.get("compute_uv") is False:
                n_out = 1  # singular values only
            return self._sd._apply(op, vars_, attrs=attrs, name=name,
                                   n_outputs=n_out)

        return call


_MATH_OPS = [n for n in OPS if n not in ("conv2d", "max_pool2d", "avg_pool2d")]
_NN_OPS = ["relu", "relu6", "leaky_relu", "elu", "selu", "gelu", "sigmoid", "tanh",
           "softmax", "log_softmax", "softplus", "softsign", "swish", "mish",
           "hard_sigmoid", "layer_norm", "batch_norm", "bias_add", "linear",
           "dropout", "multi_head_dot_product_attention", "pad", "one_hot"]
_CNN_OPS = ["conv2d", "max_pool2d", "avg_pool2d", "batch_norm",
            "conv1d", "conv3d", "depthwise_conv2d", "max_pool1d",
            "avg_pool1d", "max_pool3d", "avg_pool3d",
            "local_response_normalization", "im2col", "space_to_depth",
            "depth_to_space", "space_to_batch", "batch_to_space",
            "dilation2d"]
_RNN_OPS = ["lstm_layer", "gru", "lstm_cell", "gru_cell"]
# ops whose registry callable returns a tuple (namespace calls unpack them)
_MULTI_OUTPUT_OPS = {"lstm_layer": 3, "gru": 2, "lstm_cell": 2,
                     "svd": 3, "qr": 2, "eigh": 2, "eig": 2,
                     "top_k": 2, "unique": 2, "non_max_suppression": 2,
                     "meshgrid": 2, "moments": 2, "normalize_moments": 2,
                     "lu": 2}
_LOSS_OPS = ["softmax_cross_entropy", "sparse_softmax_cross_entropy",
             "sigmoid_cross_entropy", "mean_squared_error", "mean_absolute_error",
             "l2_loss", "log_loss", "cosine_distance", "hinge_loss", "huber_loss",
             "kl_divergence", "poisson_loss", "mean_pairwise_squared_error",
             "mean_squared_log_error", "mean_absolute_percentage_error",
             "ctc_loss"]
_LINALG_OPS = ["cholesky", "solve", "triangular_solve", "lstsq",
               "matrix_inverse", "matrix_determinant", "logdet", "svd", "qr",
               "eigh", "eig", "matrix_band_part", "cross", "diag", "diag_part",
               "trace", "matmul"]
_BITWISE_OPS = ["bitwise_and", "bitwise_or", "bitwise_xor", "bit_shift",
                "bit_shift_right", "bit_rotl", "bit_rotr"]
_RANDOM_OPS = ["random_uniform", "random_normal", "random_bernoulli",
               "random_exponential", "random_shuffle", "random_gamma",
               "random_poisson", "random_gumbel", "random_laplace",
               "truncated_normal", "random_categorical", "multinomial"]
_IMAGE_OPS = ["resize_bilinear", "resize_nearest", "crop_to_box",
              "flip_left_right", "flip_up_down", "adjust_brightness",
              "adjust_contrast", "adjust_saturation", "rgb_to_grayscale",
              "hsv_to_rgb", "rgb_to_hsv", "crop_and_resize",
              "non_max_suppression"]


@dataclasses.dataclass
class TrainingConfig:
    """Reference ``org.nd4j.autodiff.samediff.TrainingConfig``."""

    updater: Updater = dataclasses.field(default_factory=lambda: Adam(1e-3))
    data_set_feature_mapping: List[str] = dataclasses.field(default_factory=list)
    data_set_label_mapping: List[str] = dataclasses.field(default_factory=list)
    l1: float = 0.0
    l2: float = 0.0

    def to_dict(self):
        return {"updater": self.updater.to_dict(),
                "data_set_feature_mapping": self.data_set_feature_mapping,
                "data_set_label_mapping": self.data_set_label_mapping,
                "l1": self.l1, "l2": self.l2}

    @staticmethod
    def from_dict(d):
        return TrainingConfig(
            updater=Updater.from_dict(d["updater"]),
            data_set_feature_mapping=list(d.get("data_set_feature_mapping", [])),
            data_set_label_mapping=list(d.get("data_set_label_mapping", [])),
            l1=d.get("l1", 0.0), l2=d.get("l2", 0.0))


class History(list):
    """``sd.fit`` return value (reference
    ``org.nd4j.autodiff.listeners.records.History``): behaves as the list of
    per-iteration losses (backward compatible) and exposes the reference's
    curve accessors."""

    def __init__(self, losses, epoch_bounds):
        super().__init__(losses)
        self._bounds = list(epoch_bounds)  # iteration count at each epoch end

    def loss_curve(self):
        return list(self)

    def epoch_losses(self):
        out, start = [], 0
        for end in self._bounds:
            if end > start:
                out.append(sum(self[start:end]) / (end - start))
            start = end
        return out

    def final_loss(self):
        return self[-1] if self else None


class SameDiff:
    def __init__(self):
        self.vars: Dict[str, SDVariable] = {}
        self.ops: List[OpNode] = []
        self.arrays: Dict[str, jax.Array] = {}  # VARIABLE + CONSTANT values
        self.loss_variables: List[str] = []
        self.training_config: Optional[TrainingConfig] = None
        self._name_counter = 0
        self._graph_version = 0  # bumped on any change a traced step closed over
        self._opt_state = None
        self._tx = None
        self._jit_cache: Dict[Any, Any] = {}
        self._rng_key = jax.random.PRNGKey(0)
        self._train_iter = 0  # global step count (rng stream position)
        self._listeners: List[Any] = []
        self.math = _Namespace(self, _MATH_OPS)
        self.nn = _Namespace(self, _NN_OPS)
        self.cnn = _Namespace(self, _CNN_OPS)
        self.rnn = _Namespace(self, _RNN_OPS)
        self.loss = _Namespace(self, _LOSS_OPS, loss_style=True)
        self.linalg = _Namespace(self, _LINALG_OPS)
        self.bitwise = _Namespace(self, _BITWISE_OPS)
        self.random = _Namespace(self, _RANDOM_OPS)
        self.image = _Namespace(self, _IMAGE_OPS)

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # ------------------------------------------------------------- variables
    def _unique(self, base: str) -> str:
        if base not in self.vars:
            return base
        while True:
            self._name_counter += 1
            cand = f"{base}_{self._name_counter}"
            if cand not in self.vars:
                return cand

    def placeholder(self, name: str, shape=None, dtype=jnp.float32) -> SDVariable:
        v = SDVariable(self, self._unique(name), VariableType.PLACEHOLDER, shape, dtype)
        self.vars[v.name] = v
        return v

    # reference alias
    place_holder = placeholder

    def var(self, name: str, shape=None, weight_init: Union[str, WeightInit] = WeightInit.XAVIER,
            array=None, dtype=jnp.float32) -> SDVariable:
        """Trainable variable; initialised from ``array`` or ``weight_init``."""
        v = SDVariable(self, self._unique(name), VariableType.VARIABLE, shape, dtype)
        self.vars[v.name] = v
        if array is not None:
            self.arrays[v.name] = jnp.asarray(array, dtype)
        else:
            if shape is None:
                raise ValueError("var() needs shape or array")
            self._rng_key, sub = jax.random.split(self._rng_key)
            self.arrays[v.name] = init_weights(sub, shape, WeightInit(weight_init), dtype=dtype)
        return v

    def constant(self, name_or_value, value=None) -> SDVariable:
        if value is None:
            name, value = None, name_or_value
        else:
            name = name_or_value
        value = jnp.asarray(value)
        v = SDVariable(self, self._unique(name or "const"), VariableType.CONSTANT,
                       value.shape, value.dtype)
        self.vars[v.name] = v
        self.arrays[v.name] = value
        return v

    def _lift(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            return x
        return self.constant(None, x)

    def convert_to_variable(self, *names) -> None:
        """Make CONSTANT variables trainable (reference
        ``sd.convertToVariable``) — the fine-tune-an-imported-graph path:
        ``TFGraphMapper.import_graph`` materialises weights as constants;
        converting them lets ``fit()`` train them."""
        for n in names:
            n = n.name if isinstance(n, SDVariable) else n
            v = self.vars[n]
            if v.vtype == VariableType.VARIABLE:
                continue
            if v.vtype != VariableType.CONSTANT:
                raise ValueError(f"{n!r} is {v.vtype.value}, not a constant")
            v.vtype = VariableType.VARIABLE
        self._jit_cache.clear()

    def convert_to_constant(self, *names) -> None:
        """Freeze VARIABLEs (reference ``sd.convertToConstant``) — e.g. to
        fine-tune only a grafted head on an imported backbone."""
        for n in names:
            n = n.name if isinstance(n, SDVariable) else n
            v = self.vars[n]
            if v.vtype == VariableType.VARIABLE:
                v.vtype = VariableType.CONSTANT
        self._jit_cache.clear()

    def trainable_float_constants(self, min_size: int = 2) -> List[str]:
        """Names of float CONSTANTs big enough to plausibly be weights
        (imported-model helper: everything except scalar/axis-style consts)."""
        out = []
        for n, a in self.arrays.items():
            if (self.vars[n].vtype == VariableType.CONSTANT
                    and jnp.issubdtype(a.dtype, jnp.floating)
                    and a.size >= min_size):
                out.append(n)
        return out

    def _rename(self, old: str, new: str) -> None:
        if new in self.vars:
            raise ValueError(f"Variable {new!r} already exists")
        v = self.vars.pop(old)
        v.name = new
        self.vars[new] = v
        if old in self.arrays:
            self.arrays[new] = self.arrays.pop(old)
        for node in self.ops:
            node.inputs = [new if i == old else i for i in node.inputs]
            node.outputs = [new if o == old else o for o in node.outputs]
        self.loss_variables = [new if n == old else n for n in self.loss_variables]
        self._jit_cache.clear()
        self._graph_version += 1

    # ------------------------------------------------------------------- ops
    def _apply(self, op: str, inputs: List[SDVariable], attrs=None, name=None,
               n_outputs: int = 1) -> Union[SDVariable, Tuple[SDVariable, ...]]:
        get_op(op)  # validate
        attrs = {k: v for k, v in (attrs or {}).items() if v is not None}
        outs = []
        for j in range(n_outputs):
            base = name if (name and n_outputs == 1) else f"{name or op}_{j}" if name else op
            out = SDVariable(self, self._unique(base), VariableType.ARRAY)
            self.vars[out.name] = out
            outs.append(out)
        self.ops.append(OpNode(op=op, inputs=[v.name for v in inputs],
                               outputs=[o.name for o in outs], attrs=attrs))
        self._jit_cache.clear()
        self._graph_version += 1
        return outs[0] if n_outputs == 1 else tuple(outs)

    def invoke(self, op: str, *args, name=None, n_outputs: int = 1, **attrs):
        """Apply any registry op by name (escape hatch / importer path)."""
        return self._apply(op, [self._lift(a) for a in args], attrs=attrs,
                           name=name, n_outputs=n_outputs)

    # ---- control flow (reference: TF-style Switch/Merge/Enter/Exit frames;
    # here structured lax primitives, which is what XLA wants) ----
    def _apply_callable(self, fn, inputs: List[SDVariable], name: str,
                        n_outputs: int = 1):
        outs = []
        for j in range(n_outputs):
            base = name if n_outputs == 1 else f"{name}_{j}"
            out = SDVariable(self, self._unique(base), VariableType.ARRAY)
            self.vars[out.name] = out
            outs.append(out)
        self.ops.append(OpNode(op="__callable__", inputs=[v.name for v in inputs],
                               outputs=[o.name for o in outs], attrs={"fn": fn}))
        self._jit_cache.clear()
        self._graph_version += 1
        return outs[0] if n_outputs == 1 else tuple(outs)

    def cond(self, pred, true_fn, false_fn, *operands, name: str = "cond",
             n_outputs: int = 1):
        """``lax.cond`` over graph values: ``true_fn``/``false_fn`` take the
        operand arrays and return ``n_outputs`` arrays (reference:
        If/Switch-Merge)."""
        def fn(p, *xs, key=None):
            tf_ = ((lambda *a: true_fn(*a, key=key))
                   if getattr(true_fn, "_accepts_rng", False) else true_fn)
            ff_ = ((lambda *a: false_fn(*a, key=key))
                   if getattr(false_fn, "_accepts_rng", False) else false_fn)
            return jax.lax.cond(jnp.reshape(p, ()).astype(bool), tf_, ff_, *xs)

        if any(getattr(f, "_accepts_rng", False) for f in (true_fn, false_fn)):
            fn._accepts_rng = True
        return self._apply_callable(
            fn, [self._lift(pred)] + [self._lift(o) for o in operands], name,
            n_outputs=n_outputs)

    def while_loop(self, cond_fn, body_fn, *init, name: str = "while",
                   max_iterations: Optional[int] = None):
        """``lax.while_loop`` with an N-array carry (reference: While/Enter-
        Exit frames). ``cond_fn(*carry) -> bool``, ``body_fn(*carry) -> carry``.

        Without ``max_iterations`` this lowers to ``lax.while_loop``, which
        supports forward execution only — reverse-mode AD
        (``calculate_gradients`` through the loop) raises, as in JAX. Pass
        ``max_iterations`` (TF's ``maximum_iterations``) to lower to a
        fixed-length ``lax.scan`` with predicate masking, which is fully
        differentiable."""
        n = len(init)

        def fn(*xs, key=None):
            # stochastic bodies: the key is fixed per TRAINING STEP (fresh
            # masks every sd.fit iteration) but constant across loop
            # iterations within the step — per-loop-iteration freshness
            # would need the counter folded in by the body itself
            bf = ((lambda *a: body_fn(*a, key=key))
                  if getattr(body_fn, "_accepts_rng", False) else body_fn)
            cf = ((lambda *a: cond_fn(*a, key=key))
                  if getattr(cond_fn, "_accepts_rng", False) else cond_fn)
            if max_iterations is None:
                out = jax.lax.while_loop(
                    lambda c: jnp.reshape(cf(*c), ()).astype(bool),
                    lambda c: tuple(bf(*c)), tuple(xs))
            else:
                def step(c, _):
                    pred = jnp.reshape(cf(*c), ()).astype(bool)
                    new = tuple(bf(*c))
                    c2 = tuple(jnp.where(pred, b, a) for a, b in zip(c, new))
                    return c2, None

                out, _ = jax.lax.scan(step, tuple(xs), None,
                                      length=max_iterations)
            return out if n > 1 else out[0]

        if any(getattr(f, "_accepts_rng", False) for f in (cond_fn, body_fn)):
            fn._accepts_rng = True
        return self._apply_callable(fn, [self._lift(i) for i in init], name,
                                    n_outputs=n)

    # --------------------------------------------------------------- execute
    def _needed_ops(self, outputs: Sequence[str]) -> List[OpNode]:
        """Ancestor subgraph of ``outputs`` (so executing 'probs' never
        touches the loss op and its label placeholder)."""
        producer = {}
        for node in self.ops:
            for o in node.outputs:
                producer[o] = node
        needed: List[OpNode] = []
        seen = set()
        stack = list(outputs)
        marked = set()
        while stack:
            name = stack.pop()
            if name in marked:
                continue
            marked.add(name)
            node = producer.get(name)
            if node is not None and id(node) not in seen:
                seen.add(id(node))
                needed.append(node)
                stack.extend(node.inputs)
        order = {id(n): i for i, n in enumerate(self.ops)}
        needed.sort(key=lambda n: order[id(n)])
        return needed

    def _exec_graph(self, env: Dict[str, Any], outputs: Sequence[str]):
        # "__rng__" is a RESERVED env entry (never a variable name): when the
        # caller provides it (sd.fit's train step passes a per-iteration
        # key), every stochastic op gets a distinct subkey — fold_in by the
        # node's stable position in self.ops, so two dropout nodes never
        # share a mask and re-traces are deterministic. Without it
        # (output()/eval), RNG ops fall back to their static `seed` attr and
        # dropout is the identity — the reference's inference semantics.
        rng = env.get("__rng__")
        pos = None
        for node in self._needed_ops(outputs):
            if all(o in env for o in node.outputs):
                continue
            fn = node.attrs["fn"] if node.op == "__callable__" else get_op(node.op)
            args = [env[i] for i in node.inputs]
            attrs = {} if node.op == "__callable__" else node.attrs
            if rng is not None and (
                    node.op in RNG_OPS
                    # control-flow callables that declare rng support
                    # (cond/while bodies containing stochastic ops — the
                    # sub-executor re-injects per-node subkeys from this key)
                    or (node.op == "__callable__"
                        and getattr(fn, "_accepts_rng", False))):
                if pos is None:
                    pos = {id(n): i for i, n in enumerate(self.ops)}
                attrs = dict(attrs)
                attrs["key"] = jax.random.fold_in(rng, pos[id(node)])
            res = fn(*args, **attrs)
            if len(node.outputs) == 1:
                env[node.outputs[0]] = res
            else:
                for o, r in zip(node.outputs, res):
                    env[o] = r
        return [env[o] for o in outputs]

    def _build_forward(self, output_names: Tuple[str, ...], ph_names: Tuple[str, ...]):
        # SMALL INTEGER constants are closed over (static): shape chains
        # that mix shape_of results with graph constants (e.g. a Const -1
        # in a computed reshape target) then stay trace-time concrete,
        # which reshape_dynamic requires. Big float constants (imported
        # frozen weights) stay ARGUMENTS — baking them would duplicate the
        # weight set into every cached executable as HLO literals.
        # Consistency: set_arr on a CONSTANT clears the whole jit cache,
        # so baked values never go stale.
        consts = {n: a for n, a in self.arrays.items()
                  if self._baked_const(n)}

        def fn(variables, placeholders):
            env = dict(consts)
            env.update(variables)
            env.update(placeholders)
            return self._exec_graph(env, output_names)

        return jax.jit(fn)

    def _baked_const(self, name: str) -> bool:
        if self.vars[name].vtype != VariableType.CONSTANT:
            return False
        a = self.arrays[name]
        return a.size <= 64 and jnp.issubdtype(a.dtype, jnp.integer)

    def _non_constant_arrays(self) -> Dict[str, Any]:
        """Arrays passed as executable arguments (everything not baked)."""
        return {n: a for n, a in self.arrays.items()
                if not self._baked_const(n)}

    def output(self, placeholders: Dict[str, Any], *outputs: str):
        """Execute and return the requested outputs (reference
        ``sd.output(Map, String...)``). Single name -> single array; a LIST
        of names (reference ``output(Map, List<String>)``) -> name->array
        dict."""
        as_map = len(outputs) == 1 and isinstance(outputs[0], (list, tuple))
        names = tuple(outputs[0]) if as_map else tuple(outputs)
        names = tuple(n.name if isinstance(n, SDVariable) else n for n in names)
        ph = {k: jnp.asarray(v) for k, v in placeholders.items()}
        key = (names, tuple(sorted(ph.keys())))
        if key not in self._jit_cache:
            self._jit_cache[key] = self._build_forward(names, tuple(sorted(ph.keys())))
        res = self._jit_cache[key](self._non_constant_arrays(), ph)
        if as_map:
            return {n: np.asarray(r) for n, r in zip(names, res)}
        return res[0] if len(names) == 1 else res

    def batch_output(self, placeholders, outputs):
        return self.output(placeholders, *outputs)

    # -------------------------------------------------------------- training
    def set_loss_variables(self, *names: str) -> None:
        self.loss_variables = [n.name if isinstance(n, SDVariable) else n for n in names]

    def set_training_config(self, cfg: TrainingConfig) -> None:
        self.training_config = cfg
        self._graph_version += 1
        # a new config means a new updater: rebuild optimizer state lazily
        self._tx = None
        self._opt_state = None

    def set_listeners(self, *listeners) -> None:
        """Training listeners (reference ``sd.setListeners``): objects with
        ``iteration_done(sd, iteration, epoch, loss)`` called per batch.
        Note: reading ``loss`` forces a device sync; listeners receive the
        on-device scalar and may keep it lazy."""
        self._listeners = list(listeners)

    def _trainable(self) -> Dict[str, jax.Array]:
        return {n: a for n, a in self.arrays.items()
                if self.vars[n].vtype == VariableType.VARIABLE}

    def _make_train_step(self, ph_names: Tuple[str, ...], packer=None,
                         unroll: int = 1):
        cfg = self.training_config
        consts = {n: a for n, a in self.arrays.items()
                  if self.vars[n].vtype == VariableType.CONSTANT}
        # Mixed precision (TPU policy): master weights stay f32; the traced
        # program computes in env.compute_dtype (bf16 when enabled via
        # Environment.allow_bfloat16). Grads flow back through the cast, so
        # updates land on the f32 masters.
        from deeplearning4j_tpu.runtime.environment import get_environment
        cdt = get_environment().compute_dtype

        def _c(a):
            if jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != cdt:
                return a.astype(cdt)
            return a

        def loss_fn(trainable, placeholders, rng):
            env = {n: _c(a) for n, a in consts.items()}
            env.update({n: _c(a) for n, a in trainable.items()})
            env.update({n: _c(a) for n, a in placeholders.items()})
            env["__rng__"] = rng
            losses = self._exec_graph(env, self.loss_variables)
            total = sum(jnp.sum(l.astype(jnp.float32)) for l in losses)
            return total

        from deeplearning4j_tpu.runtime.environment import get_environment
        if get_environment().remat_segments:
            # Imported graphs have no layer boundaries to cut at, so use the
            # dots-saveable policy: keep matmul outputs, recompute the
            # elementwise chains in backward. Measured on the imported
            # BERT-base step: bytes-accessed is the limiter (63 GB vs the
            # hand-built model's 35 GB at identical FLOPs), and this trades
            # a few re-FLOPs for most of that traffic.
            loss_fn = jax.checkpoint(
                loss_fn, policy=jax.checkpoint_policies.dots_saveable)

        def loss_with_reg(trainable, placeholders, rng):
            total = loss_fn(trainable, placeholders, rng)
            if cfg.l2:
                total = total + 0.5 * cfg.l2 * sum(
                    jnp.sum(w * w) for w in trainable.values())
            if cfg.l1:
                total = total + cfg.l1 * sum(
                    jnp.sum(jnp.abs(w)) for w in trainable.values())
            return total

        # Per-step randomness: the step takes the GLOBAL iteration index
        # (a 4-byte scalar upload, async, negligible next to the batch) and
        # folds it into a base key on-device. Fresh dropout masks / random
        # draws every iteration; bit-reproducible given the SameDiff seed.
        base_key = self._rng_key

        def step(trainable, opt_state, placeholders, step_idx):
            rng = jax.random.fold_in(base_key, step_idx)
            loss, grads = jax.value_and_grad(loss_with_reg)(
                trainable, placeholders, rng)
            updates, opt_state = self._tx.update(grads, opt_state, trainable)
            return optax.apply_updates(trainable, updates), opt_state, loss

        if packer is None:
            return jax.jit(step, donate_argnums=(0, 1))

        # Packed variant (runtime/state_packing.py): an imported BERT-base
        # carries ~600 (variable + Adam-moment) leaves, mostly small bias/
        # layernorm vectors — one buffer-handle marshal each per dispatch.
        if unroll <= 1:
            def packed_step(packed, placeholders, step_idx):
                trainable, opt_state = packer.unpack(packed)
                new_t, new_o, loss = step(trainable, opt_state, placeholders,
                                          step_idx)
                return packer.pack((new_t, new_o)), loss

            return jax.jit(packed_step, donate_argnums=(0,))

        # Grouped dispatch (env.dispatch_unroll, same mechanism as
        # MultiLayerNetwork.fit): K same-shape batches as ONE unrolled
        # program. The batches arrive as a LIST of placeholder dicts — a
        # plain pytree argument — rather than pre-stacked arrays: stacking
        # on-device would cost ~4 tiny dispatches per placeholder per
        # group, which is the very overhead grouping exists to remove.
        def packed_step_unrolled(packed, ph_list, step_idxs):
            trainable, opt_state = packer.unpack(packed)
            losses = []
            for i in range(unroll):
                trainable, opt_state, loss = step(trainable, opt_state,
                                                  ph_list[i], step_idxs[i])
                losses.append(loss)
            return packer.pack((trainable, opt_state)), jnp.stack(losses)

        return jax.jit(packed_step_unrolled, donate_argnums=(0,))

    def fit(self, data, labels=None, epochs: int = 1, batch_size: Optional[int] = None):
        """Train (reference ``sd.fit(DataSetIterator)``). Accepts a
        DataSetIterator or (features, labels) arrays."""
        if self.training_config is None:
            raise ValueError("Call set_training_config first")
        if not self.loss_variables:
            raise ValueError("Call set_loss_variables first")
        cfg = self.training_config
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
        if isinstance(data, MultiDataSet):
            from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
            iterator = ExistingDataSetIterator([data])
        elif labels is not None:
            from deeplearning4j_tpu.data.iterators import ListDataSetIterator
            iterator = ListDataSetIterator(
                [DataSet(np.asarray(data), np.asarray(labels))],
                batch_size=batch_size or len(data))
        else:
            iterator = data
        trainable = self._trainable()
        if self._tx is None:
            self._tx = cfg.updater.make()
            self._opt_state = self._tx.init(trainable)
        ph_names = tuple(cfg.data_set_feature_mapping + cfg.data_set_label_mapping)
        from deeplearning4j_tpu.runtime.environment import get_environment
        # _graph_version covers everything the traced step closes over that
        # the structural key can't see: constant VALUES (set_arr), the
        # training config (l1/l2), graph edits
        # Packing keeps self.arrays stale until fit returns, so it is only
        # safe when no attached listener reads model state mid-fit (same
        # rule as MultiLayerNetwork.fit).
        from deeplearning4j_tpu.train.prefetch import stateless_listeners
        use_packing = (get_environment().packed_state
                       and stateless_listeners(self))
        unroll = max(1, int(get_environment().dispatch_unroll)) \
            if use_packing else 1
        key = ("train_step", ph_names, str(get_environment().compute_dtype),
               get_environment().remat_segments,
               tuple(sorted(trainable)), self._graph_version, use_packing)
        if key not in self._jit_cache:
            if use_packing:
                from deeplearning4j_tpu.runtime.state_packing import LeafPacker
                packer = LeafPacker((trainable, self._opt_state))
                self._jit_cache[key] = (self._make_train_step(ph_names, packer),
                                        packer)
            else:
                self._jit_cache[key] = (self._make_train_step(ph_names), None)
        step, packer = self._jit_cache[key]
        group_step = None
        if unroll > 1:
            gkey = key + ("unroll", unroll)
            if gkey not in self._jit_cache:
                self._jit_cache[gkey] = (
                    self._make_train_step(ph_names, packer, unroll=unroll),
                    packer)
            group_step, _ = self._jit_cache[gkey]
        history = []
        bounds = []
        it_count = 0
        # Host->device transfer cache for this fit call: iterators commonly
        # hand back the SAME numpy arrays every epoch, and re-uploading them
        # costs a full round trip per batch on remote-device tunnels. The
        # weakref guards against id() reuse after an array dies; the content
        # hash catches iterators that refill one buffer in place (a host
        # memcpy+hash is orders of magnitude cheaper than a tunnel upload);
        # the size cap bounds HBM held for fresh-array-per-batch iterators.
        import hashlib
        import weakref
        h2d: Dict[int, Any] = {}

        def _fp(a):
            return hashlib.blake2b(np.ascontiguousarray(a).tobytes(),
                                   digest_size=16).digest()

        def dev(a):
            if isinstance(a, jax.Array):
                return a
            fp = _fp(a)
            ent = h2d.get(id(a))
            if ent is not None and ent[0]() is a and ent[2] == fp:
                return ent[1]
            buf = jnp.asarray(a)
            if len(h2d) > 64:
                h2d.clear()
            try:
                h2d[id(a)] = (weakref.ref(a), buf, fp)
            except TypeError:
                pass
            return buf

        packed = (packer.pack_device((trainable, self._opt_state))
                  if packer is not None else None)
        cur_ep = 0
        # AOT dispatch fast path (env.aot_dispatch): per placeholder-shape
        # signature, the hot loop calls a cached lower().compile()
        # executable with the donated packed buffers instead of re-entering
        # jit dispatch every step — bit-identical (same trace, same
        # executable). The cache lives in _jit_cache, so graph edits /
        # set_arr on constants (which clear it) invalidate executables too.
        from deeplearning4j_tpu.runtime.compile_cache import AotCache
        from deeplearning4j_tpu.runtime.state_packing import (
            step_args_signature)
        aot = self._jit_cache.setdefault("__aot__", AotCache("sd-fit"))

        def run_single(a):
            nonlocal packed
            packed, loss = aot.call(
                ("single", key, step_args_signature((a[0],))),
                step, packed, a[0], np.uint32(a[1]))
            return loss

        def run_group(todo):
            nonlocal packed
            idxs = np.asarray([t[1] for t in todo], np.uint32)
            packed, losses = aot.call(
                ("group", gkey, step_args_signature((todo[0][0],))),
                group_step, packed, [t[0] for t in todo], idxs)
            return [losses[i] for i in range(len(todo))]

        def deliver(args, loss):
            nonlocal it_count
            # keep losses on-device: a float() here would stall the
            # pipeline on every step (one full host round-trip per batch
            # through a remote-device tunnel)
            history.append(loss)
            it_count += 1
            for lst in self._listeners:
                lst.iteration_done(self, it_count, cur_ep, loss)

        from deeplearning4j_tpu.runtime.state_packing import GroupedDispatch
        gd = GroupedDispatch(
            unroll=unroll,
            compatible=lambda a, b: ({n: v.shape for n, v in a[0].items()}
                                     == {n: v.shape for n, v in b[0].items()}),
            run_single=run_single, run_group=run_group, deliver=deliver)

        try:
            for ep in range(int(epochs)):
                cur_ep = ep
                iterator.reset()
                for batch in iterator:
                    feats = [batch.features] if not isinstance(batch.features, list) else batch.features
                    labs = [batch.labels] if not isinstance(batch.labels, list) else batch.labels
                    ph = {n: dev(a) for n, a in
                          zip(cfg.data_set_feature_mapping, feats)}
                    ph.update({n: dev(a) for n, a in
                               zip(cfg.data_set_label_mapping, labs)})
                    if packer is None:
                        trainable, self._opt_state, loss = step(
                            trainable, self._opt_state, ph,
                            np.uint32(self._train_iter))
                        self._train_iter += 1
                        history.append(loss)
                        it_count += 1
                        for lst in self._listeners:
                            lst.iteration_done(self, it_count, ep, loss)
                        continue
                    gd.submit((ph, self._train_iter))
                    self._train_iter += 1
                gd.flush()
                bounds.append(it_count)
        finally:
            gd.drain_on_error()  # deliver batches buffered before an error
            from deeplearning4j_tpu.runtime.state_packing import LeafPacker
            if packed is not None and not LeafPacker.is_dead(packed):
                # (a raising donated step leaves no newer state to recover)
                trainable, self._opt_state = packer.unpack_device(
                    packed, donate=True)
                self.arrays.update(trainable)  # even on exceptional exit
        if packer is None:
            self.arrays.update(trainable)
        if history:
            # ONE device->host transfer for all losses: converting scalars
            # one by one costs a full round trip each on remote tunnels.
            # Padded to a power of two so the stack's concatenate compiles
            # once per size CLASS, not once per distinct step count — a
            # fresh 30-operand concatenate was measured at 3 s of compile
            # through the tunnel, dwarfing the steps themselves.
            n = len(history)
            size = 1 << max(0, n - 1).bit_length()
            padded = history + [history[-1]] * (size - n)
            history = np.asarray(jnp.stack(padded))[:n].astype(float).tolist()
        return History(history, bounds)

    def evaluate(self, iterator, output_name: str, evaluation=None,
                 label_index: int = 0):
        """Evaluate a graph output against the iterator's labels (reference
        ``sd.evaluate(iterator, outputName, evaluation)``). Feature arrays
        feed ``training_config.data_set_feature_mapping``; labels go to the
        evaluation object, not the graph."""
        if evaluation is None:
            from deeplearning4j_tpu.evaluation import Evaluation
            evaluation = Evaluation()
        cfg = self.training_config
        if cfg is None or not cfg.data_set_feature_mapping:
            raise ValueError("evaluate() needs a TrainingConfig with "
                             "data_set_feature_mapping")
        iterator.reset()
        for batch in iterator:
            feats = [batch.features] if not isinstance(batch.features, list) \
                else batch.features
            labs = [batch.labels] if not isinstance(batch.labels, list) \
                else batch.labels
            ph = {n: jnp.asarray(a) for n, a in
                  zip(cfg.data_set_feature_mapping, feats)}
            pred = self.output(ph, output_name)
            evaluation.eval(np.asarray(labs[label_index]), np.asarray(pred))
        return evaluation

    def calculate_gradients(self, placeholders: Dict[str, Any],
                            *wrt: str) -> Dict[str, jax.Array]:
        """Gradients of the (summed) loss wrt named variables (reference
        ``sd.calculateGradients``)."""
        if not self.loss_variables:
            raise ValueError("Call set_loss_variables first")
        consts = {n: a for n, a in self.arrays.items()
                  if self.vars[n].vtype != VariableType.ARRAY}
        ph = {k: jnp.asarray(v) for k, v in placeholders.items()}
        wrt = tuple(wrt) or tuple(self._trainable().keys())

        def loss_fn(sub):
            env = dict(consts)
            env.update(sub)
            env.update(ph)
            return sum(jnp.sum(l) for l in self._exec_graph(env, self.loss_variables))

        sub = {n: consts[n] for n in wrt}
        grads = jax.grad(loss_fn)(sub)
        return grads

    # ----------------------------------------------------------------- serde
    def to_dict(self) -> dict:
        if any(n.op == "__callable__" for n in self.ops):
            raise ValueError(
                "Graphs containing python control-flow callables (cond/"
                "while_loop) are not serializable; export StableHLO instead")
        return {
            "vars": [{"name": v.name, "type": v.vtype.value,
                      "shape": list(v.shape) if v.shape else None}
                     for v in self.vars.values()],
            "ops": [{"op": n.op, "inputs": n.inputs, "outputs": n.outputs,
                     "attrs": _json_attrs(n.attrs)} for n in self.ops],
            "loss_variables": self.loss_variables,
            "training_config": self.training_config.to_dict() if self.training_config else None,
        }

    def save(self, path: str, save_updater_state: bool = False) -> None:
        """Zip: graph.json + arrays.npz (the ``.fb`` single-artifact analog —
        reference ``sd.save(file, saveUpdaterState)``).

        The RNG stream position (``_train_iter`` + base key) is always
        persisted: now that train-time stochasticity is real, a mid-training
        save/restore must NOT replay dropout masks from step 0. With
        ``save_updater_state=True`` the optimizer state (Adam moments etc.)
        is saved too, giving bit-exact resume — the reference's
        ``sd.save(file, true)`` contract."""
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", json.dumps(self.to_dict(), indent=2))
            buf = io.BytesIO()
            np.savez(buf, **{k: np.asarray(v) for k, v in self.arrays.items()})
            zf.writestr("arrays.npz", buf.getvalue())
            buf = io.BytesIO()
            np.savez(buf, train_iter=np.asarray(self._train_iter, np.int64),
                     rng_key=np.asarray(self._rng_key))
            zf.writestr("training_state.npz", buf.getvalue())
            if save_updater_state and self._opt_state is not None:
                from deeplearning4j_tpu.models.serializer import _save_pytree_npz
                zf.writestr("updaterState.npz",
                            _save_pytree_npz(self._opt_state))

    @staticmethod
    def load(path: str) -> "SameDiff":
        sd = SameDiff()
        with zipfile.ZipFile(path) as zf:
            d = json.loads(zf.read("graph.json").decode())
            z = np.load(io.BytesIO(zf.read("arrays.npz")))
            for vd in d["vars"]:
                v = SDVariable(sd, vd["name"], VariableType(vd["type"]),
                               tuple(vd["shape"]) if vd["shape"] else None)
                sd.vars[v.name] = v
            for od in d["ops"]:
                sd.ops.append(OpNode(op=od["op"], inputs=od["inputs"],
                                     outputs=od["outputs"], attrs=od.get("attrs", {})))
            for k in z.files:
                sd.arrays[k] = jnp.asarray(z[k])
            sd.loss_variables = d.get("loss_variables", [])
            if d.get("training_config"):
                sd.training_config = TrainingConfig.from_dict(d["training_config"])
            if "training_state.npz" in zf.namelist():
                ts = np.load(io.BytesIO(zf.read("training_state.npz")))
                sd._train_iter = int(ts["train_iter"])
                sd._rng_key = jnp.asarray(ts["rng_key"])
            if ("updaterState.npz" in zf.namelist()
                    and sd.training_config is not None):
                # Rebuild the optimizer pytree structure from the config
                # (eval_shape: structure only, no device allocation — a
                # BERT-scale moment set is hundreds of MB), then graft the
                # saved leaves into it via the shared leaf-order protocol.
                from deeplearning4j_tpu.models.serializer import _load_pytree_npz
                sd._tx = sd.training_config.updater.make()
                template = jax.eval_shape(sd._tx.init, sd._trainable())
                sd._opt_state = _load_pytree_npz(
                    zf.read("updaterState.npz"), template)
        return sd

    def export_stablehlo(self, placeholders: Dict[str, Any], *outputs: str) -> str:
        """Lower the graph to StableHLO text via jax.export — the analog of
        the reference's FlatBuffers graph handoff to libnd4j's
        GraphExecutioner (SURVEY.md §3.2), with XLA as the executor."""
        names = tuple(outputs)
        ph = {k: jnp.asarray(v) for k, v in placeholders.items()}
        fn = self._build_forward(names, tuple(sorted(ph.keys())))
        lowered = fn.lower(self._non_constant_arrays(), ph)
        return lowered.as_text()

    # convenience summaries (reference sd.summary())
    def summary(self) -> str:
        lines = [f"SameDiff: {len(self.vars)} variables, {len(self.ops)} ops"]
        for v in self.vars.values():
            if v.vtype != VariableType.ARRAY:
                lines.append(f"  {v.vtype.value:12s} {v.name:24s} {v.shape}")
        for n in self.ops:
            lines.append(f"  op {n.op:24s} {n.inputs} -> {n.outputs}")
        return "\n".join(lines)


def _json_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (np.ndarray, jax.Array)):
            v = np.asarray(v).tolist()
        elif isinstance(v, tuple):
            v = list(v)
        out[k] = v
    return out
