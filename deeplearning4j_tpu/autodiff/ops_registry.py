"""Named op registry for the declarative graph.

Every graph op is registered by name so graphs serialize as data (the
FlatBuffers-schema analog of the reference: op nodes store op NAME + attrs,
never code). The callables take jnp arrays (+ static attrs) and are traceable
under jit. Covers the reference's op namespaces used by SameDiff programs and
the TF importer's op set (upstream ``org.nd4j.autodiff.samediff.ops.*``).
"""

from __future__ import annotations

from typing import Callable, Dict

import math

import jax
import jax.numpy as jnp
from jax import lax

OPS: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        OPS[name] = fn
        return fn
    return deco


def get_op(name: str) -> Callable:
    if name not in OPS:
        raise KeyError(f"Unknown op {name!r}; registered: {sorted(OPS)[:40]}...")
    return OPS[name]


# ---- elementwise binary ----
register("add")(lambda a, b: a + b)
register("sub")(lambda a, b: a - b)
register("mul")(lambda a, b: a * b)
register("div")(lambda a, b: a / b)
register("pow")(lambda a, b: a ** b)
register("mod")(lambda a, b: jnp.mod(a, b))
register("maximum")(jnp.maximum)
register("minimum")(jnp.minimum)
register("squared_difference")(lambda a, b: (a - b) ** 2)
register("floordiv")(lambda a, b: jnp.floor_divide(a, b))

# comparisons (float outputs, like the reference)
register("gt")(lambda a, b: (a > b))
register("gte")(lambda a, b: (a >= b))
register("lt")(lambda a, b: (a < b))
register("lte")(lambda a, b: (a <= b))
register("eq")(lambda a, b: (a == b))
register("neq")(lambda a, b: (a != b))
register("logical_and")(jnp.logical_and)
register("logical_or")(jnp.logical_or)
register("logical_not")(jnp.logical_not)
register("where")(jnp.where)

# ---- elementwise unary ----
register("neg")(lambda a: -a)
register("abs")(jnp.abs)
register("exp")(jnp.exp)
register("log")(jnp.log)
register("log1p")(jnp.log1p)
register("sqrt")(jnp.sqrt)
register("rsqrt")(lax.rsqrt)
register("square")(jnp.square)
register("sign")(jnp.sign)
register("floor")(jnp.floor)
register("ceil")(jnp.ceil)
register("round")(jnp.round)
register("sin")(jnp.sin)
register("cos")(jnp.cos)
register("tan")(jnp.tan)
register("asin")(jnp.arcsin)
register("acos")(jnp.arccos)
register("atan")(jnp.arctan)
register("sinh")(jnp.sinh)
register("cosh")(jnp.cosh)
register("tanh")(jnp.tanh)
register("erf")(jax.scipy.special.erf)
register("sigmoid")(jax.nn.sigmoid)
register("relu")(jax.nn.relu)
register("relu6")(jax.nn.relu6)
register("leaky_relu")(lambda a, alpha=0.01: jax.nn.leaky_relu(a, alpha))
register("elu")(jax.nn.elu)
register("selu")(jax.nn.selu)
# NOTE: wrapping the erf form in jax.checkpoint to skip its saved
# intermediate was measured BOTH ways on the imported BERT-base: -1.2 GB
# before the layout passes, +1.8 GB after them (the checkpoint barrier
# blocks the post-layout fusions). The recompute-in-backward custom_vjps
# (ops.activations) take the third route: save ONLY the input, recompute
# erf/tanh in the backward — no checkpoint barrier, no saved intermediate.
from deeplearning4j_tpu.ops.activations import (gelu_exact_recompute,
                                                gelu_tanh_recompute)


@register("gelu")
def _gelu(a, approximate=True):
    if approximate:
        return gelu_tanh_recompute(a)
    return gelu_exact_recompute(a)
register("softplus")(jax.nn.softplus)
register("softsign")(jax.nn.soft_sign)
register("swish")(jax.nn.swish)
register("mish")(jax.nn.mish)
# DL4J/Keras hardSigmoid is clip(0.2x + 0.5), NOT jax.nn.hard_sigmoid's
# relu6(x+3)/6 — keep the registry, layer activations, and imports on the
# same formula
register("hard_sigmoid")(lambda a: jnp.clip(0.2 * a + 0.5, 0.0, 1.0))
register("reciprocal")(lambda a: 1.0 / a)
register("clip_by_value")(lambda a, lo=0.0, hi=1.0: jnp.clip(a, lo, hi))
register("cast")(lambda a, dtype="float32": a.astype(jnp.dtype(dtype)))
register("identity")(lambda a: a)
register("stop_gradient")(lax.stop_gradient)
@register("dropout")
def _dropout(a, key=None, rate=0.5):
    """Inverted dropout (reference ``sd.nn.dropout`` / TrainingSession).

    With no ``key`` (inference: ``sd.output`` / ``eval``) this is the
    identity, matching the reference's inference behavior. During
    ``sd.fit`` the executor injects a per-step, per-node ``key``
    (``SameDiff._exec_graph``), making the mask fresh every iteration.
    The mask draw rides the rbg generator (``nn.base.dropout_mask``) —
    threefry counter math measured ~15 ms/step on BERT-base (v5e)."""
    if key is None:
        return a
    from deeplearning4j_tpu.nn.base import dropout_mask
    keep = 1.0 - rate
    mask = dropout_mask(key, keep, a.shape)
    return jnp.where(mask, a / keep, jnp.zeros_like(a))


# ---- matmul / linalg ----
@register("matmul")
def _matmul(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return a @ b


register("batch_matmul")(lambda a, b, transpose_a=False, transpose_b=False:
                         _matmul(a, b, transpose_a, transpose_b))
register("tensordot")(lambda a, b, axes=2: jnp.tensordot(a, b, axes))
register("outer")(jnp.outer)
register("dot")(jnp.dot)
register("norm2")(lambda a, axis=None: jnp.sqrt(jnp.sum(a * a, axis=axis)))
register("l2_normalize")(lambda a, axis=-1, eps=1e-12:
                         a / jnp.sqrt(jnp.maximum(jnp.sum(a * a, axis=axis, keepdims=True), eps)))

# ---- reductions ----
register("reduce_sum")(lambda a, axis=None, keepdims=False: jnp.sum(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_mean")(lambda a, axis=None, keepdims=False: jnp.mean(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_max")(lambda a, axis=None, keepdims=False: jnp.max(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_min")(lambda a, axis=None, keepdims=False: jnp.min(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_prod")(lambda a, axis=None, keepdims=False: jnp.prod(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_var")(lambda a, axis=None, keepdims=False: jnp.var(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_std")(lambda a, axis=None, keepdims=False: jnp.std(a, axis=_ax(axis), keepdims=keepdims))
register("argmax")(lambda a, axis=-1: jnp.argmax(a, axis=axis))
register("argmin")(lambda a, axis=-1: jnp.argmin(a, axis=axis))
register("cumsum")(lambda a, axis=0: jnp.cumsum(a, axis=axis))
register("logsumexp")(lambda a, axis=None, keepdims=False:
                      jax.scipy.special.logsumexp(a, axis=_ax(axis), keepdims=keepdims))


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---- shape ----
register("reshape")(lambda a, shape=(): jnp.reshape(
    a, tuple(a.shape[i] if int(s) == 0 else int(s)  # 0 = copy dim (ONNX/TF)
             for i, s in enumerate(shape))))
register("transpose")(lambda a, perm=None: jnp.transpose(a, perm))
register("expand_dims")(lambda a, axis=0: jnp.expand_dims(a, axis))
register("squeeze")(lambda a, axis=None: jnp.squeeze(a, axis))
register("concat")(lambda *arrays, axis=0: jnp.concatenate(arrays, axis=axis))
register("stack")(lambda *arrays, axis=0: jnp.stack(arrays, axis=axis))


@register("unstack")
def _unstack(a, axis=0, num=None):
    n = num if num is not None else a.shape[axis]
    return tuple(jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis))


@register("split")
def _split(a, num_splits=2, axis=0):
    return tuple(jnp.split(a, num_splits, axis=axis))


register("tile")(lambda a, multiples=(): jnp.tile(a, tuple(int(m) for m in multiples)))
register("slice")(lambda a, begin=(), size=():
                  lax.slice(a, tuple(int(b) for b in begin),
                            tuple(int(b) + int(s) for b, s in zip(begin, size))))


@register("strided_slice")
def _strided_slice(a, begin=(), end=(), strides=None, begin_mask=0, end_mask=0,
                   shrink_axis_mask=0, new_axis_mask=0, ellipsis_mask=0):
    # numpy-style basic indexing reconstruction (TF StridedSlice semantics)
    strides = strides or [1] * len(begin)
    idx = []
    in_dim = 0
    for i in range(len(begin)):
        if ellipsis_mask & (1 << i):
            idx.append(Ellipsis)
            in_dim = a.ndim - (len(begin) - i - 1)
            continue
        if new_axis_mask & (1 << i):
            idx.append(None)
            continue
        b = None if (begin_mask & (1 << i)) else int(begin[i])
        e = None if (end_mask & (1 << i)) else int(end[i])
        s = int(strides[i])
        if shrink_axis_mask & (1 << i):
            idx.append(int(begin[i]))
        else:
            idx.append(slice(b, e, s))
        in_dim += 1
    return a[tuple(idx)]


@register("gather")
def _gather(a, indices, axis=0):
    idx = indices.astype(jnp.int32)
    if (axis == 0 and a.ndim == 2 and a.shape[0] <= 16
            and jnp.issubdtype(a.dtype, jnp.floating)):
        # Tiny-table gather as a one-hot matmul (bit-exact for in-range
        # ids: each output row is 1.0*row + 0.0*rest at HIGHEST
        # precision). The generic form's BACKWARD is a scatter with
        # massively colliding indices for these tables (a BERT token-type
        # lookup is 8192 updates onto 2 rows), which XLA:TPU lowers
        # through a ~0.6 ms sort pipeline; the one-hot form's backward is
        # a small dense matmul instead. Deviation for INVALID ids only:
        # this path yields an all-zero row, where jit-compiled take()
        # wraps negative ids pythonically and fill-NaNs ids >= V — both
        # out-of-contract for embedding lookups.
        oh = jax.nn.one_hot(idx, a.shape[0], dtype=a.dtype)
        # HIGHEST precision: the default TPU matmul precision would
        # bf16-round f32 table rows, breaking the bit-exactness claim
        return jnp.einsum("...v,vd->...d", oh, a,
                          precision=jax.lax.Precision.HIGHEST)
    return jnp.take(a, idx, axis=axis)


@register("gather_nd")
def _gather_nd(a, indices):
    idx = tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))
    return a[idx]


@register("scatter_update")
def _scatter_update(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].set(updates)


register("one_hot")(lambda a, depth=2, on_value=1.0, off_value=0.0, axis=-1:
                    jax.nn.one_hot(a.astype(jnp.int32), depth, axis=axis) * (on_value - off_value) + off_value)
def _pad(a, paddings=(), constant_value=0.0, mode="constant"):
    pads = tuple(tuple(int(x) for x in p) for p in paddings)
    if mode == "constant":
        return jnp.pad(a, pads, constant_values=constant_value)
    return jnp.pad(a, pads, mode=mode)  # 'reflect' / 'edge' / 'wrap'


register("pad")(_pad)


register("flatten2d")(lambda a, axis=1: jnp.reshape(
    a, (math.prod(a.shape[:axis]) if axis else 1, -1)))
register("reverse")(lambda a, axis=0: jnp.flip(a, axis))
register("shape_of")(lambda a: jnp.asarray(a.shape, jnp.int32))
register("size")(lambda a: jnp.asarray(a.size, jnp.int32))
register("rank")(lambda a: jnp.asarray(a.ndim, jnp.int32))
register("fill")(lambda shape, value=0.0: jnp.full(tuple(int(s) for s in shape), value))
register("zeros_like")(jnp.zeros_like)
register("ones_like")(jnp.ones_like)
register("linspace")(lambda start=0.0, stop=1.0, num=10: jnp.linspace(start, stop, int(num)))
register("range")(lambda start=0, limit=10, delta=1: jnp.arange(start, limit, delta))

# ---- nn ----
register("softmax")(lambda a, axis=-1: jax.nn.softmax(a, axis=axis))
register("log_softmax")(lambda a, axis=-1: jax.nn.log_softmax(a, axis=axis))


@register("layer_norm")
def _layer_norm(x, gain, bias=None, axis=-1, eps=1e-5):
    if isinstance(axis, (tuple, list)):  # multi-axis: generic two-pass form
        mean = jnp.mean(x, axis=tuple(axis), keepdims=True)
        var = jnp.var(x, axis=tuple(axis), keepdims=True)
        out = (x - mean) * lax.rsqrt(var + eps) * gain
        return out + bias if bias is not None else out
    # Single-axis: shifted single-pass f32 stats (ops.activations.
    # single_pass_norm_stats — jnp.var's (x-mean)^2 needs a second full
    # read of x and doubles the backward saves; measured 2.7 ms/step of
    # extra convert+reduce fusions on the imported BERT-base fine-tune).
    from deeplearning4j_tpu.ops.activations import single_pass_norm_stats
    mean, var = single_pass_norm_stats(x, axis)
    out = ((x.astype(jnp.float32) - mean)
           * lax.rsqrt(var + eps)).astype(x.dtype) * gain
    return out + bias if bias is not None else out


@register("batch_norm")
def _batch_norm(x, mean, variance, gamma=None, beta=None, eps=1e-5):
    out = (x - mean) * lax.rsqrt(variance + eps)
    if gamma is not None:
        out = out * gamma
    if beta is not None:
        out = out + beta
    return out


@register("bias_add")
def _bias_add(x, bias):
    return x + bias


@register("linear")
def _linear(x, w, b=None):
    y = x @ w
    return y + b if b is not None else y


@register("conv2d")
def _conv2d(x, w, b=None, stride=(1, 1), padding="SAME", dilation=(1, 1),
            groups=1):
    y = lax.conv_general_dilated(x, w, window_strides=tuple(stride), padding=padding,
                                 rhs_dilation=tuple(dilation),
                                 dimension_numbers=("NHWC", "HWIO", "NHWC"),
                                 feature_group_count=groups)
    return y + b if b is not None else y


@register("max_pool2d")
def _max_pool2d(x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, *kernel, 1), (1, *stride, 1), padding)


@register("avg_pool2d")
def _avg_pool2d(x, kernel=(2, 2), stride=(2, 2), padding="VALID",
                count_include_pad=False):
    s = lax.reduce_window(x, 0.0, lax.add, (1, *kernel, 1), (1, *stride, 1), padding)
    if count_include_pad:  # ONNX AveragePool count_include_pad=1
        return s / (kernel[0] * kernel[1])
    c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, (1, *kernel, 1), (1, *stride, 1), padding)
    return s / c


@register("multi_head_dot_product_attention")
def _mhdpa(q, k, v, mask=None, scaled=True):
    """(batch, heads, time, d) attention — the reference's
    ``multiHeadDotProductAttention`` op."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    if scaled:
        s = s / jnp.sqrt(jnp.asarray(d, s.dtype))
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, -1e9)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


# ---- losses (fused stable forms) ----
@register("softmax_cross_entropy")
def _sce(labels, logits, axis=-1):
    return jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(logits, axis=axis), axis=axis))


@register("sparse_softmax_cross_entropy")
def _ssce(labels, logits):
    lp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(lp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return -jnp.mean(ll)


@register("sigmoid_cross_entropy")
def _sigce(labels, logits):
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.mean(jnp.sum(per, axis=-1))


register("mean_squared_error")(lambda labels, pred: jnp.mean(jnp.sum((pred - labels) ** 2, axis=-1)))
register("mean_absolute_error")(lambda labels, pred: jnp.mean(jnp.sum(jnp.abs(pred - labels), axis=-1)))
register("l2_loss")(lambda a: 0.5 * jnp.sum(a * a))
register("log_loss")(lambda labels, pred, eps=1e-7:
                     -jnp.mean(jnp.sum(labels * jnp.log(pred + eps)
                                       + (1 - labels) * jnp.log(1 - pred + eps), axis=-1)))
register("cosine_distance")(lambda labels, pred, axis=-1:
                            jnp.mean(1.0 - jnp.sum(labels * pred, axis=axis)
                                     / jnp.maximum(jnp.linalg.norm(labels, axis=axis)
                                                   * jnp.linalg.norm(pred, axis=axis), 1e-12)))
register("hinge_loss")(lambda labels, pred:
                       jnp.mean(jnp.sum(jnp.maximum(0.0, 1.0 - jnp.where(labels > 0, 1.0, -1.0) * pred), axis=-1)))
register("huber_loss")(lambda labels, pred, delta=1.0:
                       jnp.mean(jnp.sum(jnp.where(jnp.abs(pred - labels) <= delta,
                                                  0.5 * (pred - labels) ** 2,
                                                  delta * (jnp.abs(pred - labels) - 0.5 * delta)), axis=-1)))


# ---- fused recurrent ops (reference sd.rnn() namespace: lstmLayer, gru) ----
# Thin wrappers over the nn layer implementations — ONE copy of the gate math
# (deliberate: a recurrence fix in nn/recurrent_layers.py reaches sd.rnn too).
def _rnn_layer(kind, n_out):
    from deeplearning4j_tpu.nn import recurrent_layers as rl
    from deeplearning4j_tpu.nn.base import GlobalConfig
    layer = {"lstm": rl.LSTM, "gru": rl.GRU}[kind](n_out=n_out)
    layer._g = GlobalConfig()
    return layer


@register("lstm_layer")
def _lstm_layer(x, W, W_rec, b, h0=None, c0=None):
    """Whole-sequence LSTM (reference ``sd.rnn().lstmLayer`` / libnd4j
    ``lstmLayer``). x: (B, T, F); W: (F, 4H) packed [i,f,g,o]; W_rec:
    (H, 4H); b: (4H,). Returns (ys, h_T, c_T)."""
    H = W_rec.shape[0]
    layer = _rnn_layer("lstm", H)
    B = x.shape[0]
    h = jnp.zeros((B, H), x.dtype) if h0 is None else h0
    c = jnp.zeros((B, H), x.dtype) if c0 is None else c0
    ys, (h, c) = layer.forward_with_carry(
        {"W": W, "W_rec": W_rec, "b": b}, (h, c), x)
    return ys, h, c


@register("gru")
def _gru_op(x, W, W_rec, b, h0=None):
    """Whole-sequence GRU (reference ``sd.rnn().gru``), packed gates
    [r, u, n]. Returns (ys, h_T)."""
    H = W_rec.shape[0]
    layer = _rnn_layer("gru", H)
    B = x.shape[0]
    h = jnp.zeros((B, H), x.dtype) if h0 is None else h0
    ys, (h,) = layer.forward_with_carry(
        {"W": W, "W_rec": W_rec, "b": b}, (h,), x)
    return ys, h


@register("lstm_cell")
def _lstm_cell(x_t, h, c, W, W_rec, b):
    """Single LSTM step (reference ``sd.rnn().lstmCell``): returns (h', c')."""
    layer = _rnn_layer("lstm", W_rec.shape[0])
    return layer._step({"W_rec": W_rec}, h, c, x_t @ W + b)


@register("gru_cell")
def _gru_cell(x_t, h, W, W_rec, b):
    """Single GRU step (reference ``sd.rnn().gruCell``)."""
    _, h_n = _gru_op(x_t[:, None, :], W, W_rec, b, h0=h)
    return h_n


# ---------------------------------------------------------------- linalg
# (reference sd.linalg() / org.nd4j.linalg.api.ops.impl.* matrix ops)


@register("cholesky")
def _cholesky(a):
    return jnp.linalg.cholesky(a)


@register("solve")
def _solve(a, b, adjoint=False):
    if adjoint:
        a = jnp.swapaxes(jnp.conj(a), -1, -2)
    return jnp.linalg.solve(a, b)


@register("triangular_solve")
def _triangular_solve(a, b, lower=True, adjoint=False):
    import jax.scipy.linalg as jsl
    return jsl.solve_triangular(a, b, lower=lower,
                                trans="C" if adjoint else "N")


@register("lstsq")
def _lstsq(a, b, fast=True):
    # `fast` is the reference's performance hint (Cholesky-vs-QR path);
    # jnp.linalg.lstsq picks the backend-appropriate algorithm, result
    # semantics are identical.
    return jnp.linalg.lstsq(a, b)[0]


@register("matrix_inverse")
def _matrix_inverse(a):
    return jnp.linalg.inv(a)


@register("matrix_determinant")
def _matrix_determinant(a):
    return jnp.linalg.det(a)


@register("logdet")
def _logdet(a):
    sign, logabs = jnp.linalg.slogdet(a)
    return logabs


@register("svd")
def _svd(a, full_matrices=False, compute_uv=True):
    if not compute_uv:
        return jnp.linalg.svd(a, full_matrices=full_matrices, compute_uv=False)
    u, s, vt = jnp.linalg.svd(a, full_matrices=full_matrices)
    return s, u, vt  # reference Svd returns s first


@register("qr")
def _qr(a, full_matrices=False):
    return jnp.linalg.qr(a, mode="complete" if full_matrices else "reduced")


@register("eigh")
def _eigh(a):
    """Self-adjoint (symmetric/Hermitian) eigendecomposition."""
    w, v = jnp.linalg.eigh(a)
    return w, v


@register("eig")
def _eig(a):
    """General (non-symmetric) eigendecomposition -> (values, vectors),
    complex64/128. XLA has no TPU lowering for general eig, so this runs
    as a host callback to LAPACK via numpy — the same CPU-execution
    fallback the reference uses for its ``eig`` custom op (upstream
    ``libnd4j`` linalg family runs eig on host too). Forward-only: no
    gradient is defined (matching the reference, which registers no
    ``doDiff`` for it)."""
    import numpy as _np
    a = jnp.asarray(a)
    cdt = jnp.complex128 if a.dtype == jnp.float64 else jnp.complex64
    out_shape = (jax.ShapeDtypeStruct(a.shape[:-1], cdt),
                 jax.ShapeDtypeStruct(a.shape, cdt))

    def _cb(x):
        w, v = _np.linalg.eig(_np.asarray(x))
        return (w.astype(_np.dtype(cdt)), v.astype(_np.dtype(cdt)))

    return tuple(jax.pure_callback(_cb, out_shape, a))


@register("matrix_band_part")
def _matrix_band_part(a, num_lower=-1, num_upper=-1):
    m, n = a.shape[-2], a.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if num_lower >= 0:
        keep &= (i - j) <= num_lower
    if num_upper >= 0:
        keep &= (j - i) <= num_upper
    return jnp.where(keep, a, jnp.zeros((), a.dtype))


@register("cross")
def _cross(a, b):
    return jnp.cross(a, b)


@register("diag")
def _diag(a):
    return jnp.diagflat(a) if a.ndim == 1 else jnp.diagonal(a, axis1=-2, axis2=-1)


@register("diag_part")
def _diag_part(a):
    return jnp.diagonal(a, axis1=-2, axis2=-1)


@register("trace")
def _trace(a):
    return jnp.trace(a, axis1=-2, axis2=-1)


# ---------------------------------------------------------------- bitwise
# (reference sd.bitwise(): and/or/xor, shifts, cyclic shifts)


@register("bitwise_and")
def _bitwise_and(a, b):
    return jnp.bitwise_and(a, b)


@register("bitwise_or")
def _bitwise_or(a, b):
    return jnp.bitwise_or(a, b)


@register("bitwise_xor")
def _bitwise_xor(a, b):
    return jnp.bitwise_xor(a, b)


@register("bit_shift")
def _bit_shift(a, shift):
    return jnp.left_shift(a, shift)


@register("bit_shift_right")
def _bit_shift_right(a, shift):
    return jnp.right_shift(a, shift)


@register("bit_rotl")
def _bit_rotl(a, shift):
    bits = a.dtype.itemsize * 8
    shift = jnp.asarray(shift) % bits
    # logical rotate: force unsigned for the right shift; the complementary
    # shift is taken mod bits because shifting by the full width is
    # implementation-defined in StableHLO
    ua = a.astype(jnp.dtype(f"uint{bits}"))
    out = jnp.left_shift(ua, shift) | jnp.right_shift(ua, (bits - shift) % bits)
    return out.astype(a.dtype)


@register("bit_rotr")
def _bit_rotr(a, shift):
    bits = a.dtype.itemsize * 8
    shift = jnp.asarray(shift) % bits
    ua = a.astype(jnp.dtype(f"uint{bits}"))
    out = jnp.right_shift(ua, shift) | jnp.left_shift(ua, (bits - shift) % bits)
    return out.astype(a.dtype)


# ---------------------------------------------------------------- random
# (reference sd.random(): draws take an explicit integer `seed` attr backed
# by a stateful NativeRandom, so training redraws every iteration. Here the
# static `seed` names the STREAM; when the executor threads a per-step key
# (SameDiff._exec_graph injects `key=` during sd.fit), the draw is
# key-folded-with-seed and therefore fresh each step. With no key (inference
# / standalone eval) the draw is the deterministic PRNGKey(seed) result.)


def _key(seed, key=None):
    import jax
    if key is None:
        return jax.random.PRNGKey(int(seed))
    return jax.random.fold_in(key, int(seed) & 0x7FFFFFFF)


# Ops that accept an executor-injected `key=` kwarg for per-step randomness
# (SameDiff._exec_graph folds a per-node subkey off the train step's key for
# each of these; everything else is deterministic given the graph).
RNG_OPS = frozenset({
    "dropout", "alpha_dropout", "random_uniform", "random_normal",
    "random_bernoulli", "random_exponential", "random_shuffle",
    "random_gamma", "random_poisson", "random_gumbel", "random_laplace",
    "truncated_normal", "random_categorical", "multinomial",
    "random_binomial", "random_lognormal", "random_crop",
    "random_flip_left_right", "random_brightness", "random_contrast",
})


@register("random_uniform")
def _random_uniform(shape=None, minval=0.0, maxval=1.0, seed=0, key=None):
    import jax
    return jax.random.uniform(_key(seed, key), tuple(shape),
                              minval=minval, maxval=maxval)


@register("random_normal")
def _random_normal(shape=None, mean=0.0, stddev=1.0, seed=0, key=None):
    import jax
    return mean + stddev * jax.random.normal(_key(seed, key), tuple(shape))


@register("random_bernoulli")
def _random_bernoulli(shape=None, p=0.5, seed=0, key=None):
    import jax
    return jax.random.bernoulli(
        _key(seed, key), p, tuple(shape)).astype(jnp.float32)


@register("random_exponential")
def _random_exponential(shape=None, lam=1.0, seed=0, key=None):
    import jax
    return jax.random.exponential(_key(seed, key), tuple(shape)) / lam


@register("random_shuffle")
def _random_shuffle(a, seed=0, key=None):
    import jax
    return jax.random.permutation(_key(seed, key), a, axis=0)


# ---------------------------------------------------------------- image
# (reference sd.image(): resize, crop, flip, adjust ops used by the CNN
# import paths)


@register("resize_bilinear")
def _resize_bilinear(images, height=None, width=None, align_corners=False):
    if align_corners:
        raise NotImplementedError(
            "resize_bilinear(align_corners=True) is not supported; "
            "jax.image.resize uses half-pixel alignment")
    n, h, w, c = images.shape
    return jax.image.resize(images, (n, int(height), int(width), c),
                            method="bilinear")


@register("resize_nearest")
def _resize_nearest(images, height=None, width=None, half_pixel_centers=True):
    n, h, w, c = images.shape
    if half_pixel_centers:
        return jax.image.resize(images, (n, int(height), int(width), c),
                                method="nearest")
    # legacy TF1 sampling (ResizeNearestNeighbor half_pixel_centers=False):
    # src index = min(floor(dst * in/out), in-1)
    hi = jnp.minimum((jnp.arange(int(height)) * (h / int(height)))
                     .astype(jnp.int32), h - 1)
    wi = jnp.minimum((jnp.arange(int(width)) * (w / int(width)))
                     .astype(jnp.int32), w - 1)
    return images[:, hi][:, :, wi]


@register("crop_to_box")
def _crop_to_box(images, top=0, left=0, height=None, width=None):
    return jax.lax.dynamic_slice(
        images, (0, int(top), int(left), 0),
        (images.shape[0], int(height), int(width), images.shape[3]))


@register("flip_left_right")
def _flip_left_right(images):
    return jnp.flip(images, axis=2)


@register("flip_up_down")
def _flip_up_down(images):
    return jnp.flip(images, axis=1)


@register("adjust_brightness")
def _adjust_brightness(images, delta=0.0):
    return images + jnp.asarray(delta, images.dtype)


@register("adjust_contrast")
def _adjust_contrast(images, factor=1.0):
    mean = jnp.mean(images, axis=(1, 2), keepdims=True)
    return (images - mean) * factor + mean


@register("adjust_saturation")
def _adjust_saturation(images, factor=1.0):
    gray = jnp.mean(images, axis=-1, keepdims=True)
    return gray + (images - gray) * factor


@register("rgb_to_grayscale")
def _rgb_to_grayscale(images):
    w = jnp.asarray([0.2989, 0.587, 0.114], images.dtype)
    return jnp.sum(images * w, axis=-1, keepdims=True)


@register("hsv_to_rgb")
def _hsv_to_rgb(images):
    h, s, v = images[..., 0], images[..., 1], images[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


@register("rgb_to_hsv")
def _rgb_to_hsv(images):
    r, g, b = images[..., 0], images[..., 1], images[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe_d = jnp.where(d > 0, d, 1.0)
    h = jnp.where(
        d == 0, 0.0,
        jnp.where(mx == r, ((g - b) / safe_d) % 6.0,
                  jnp.where(mx == g, (b - r) / safe_d + 2.0,
                            (r - g) / safe_d + 4.0))) / 6.0
    s = jnp.where(mx > 0, d / jnp.where(mx > 0, mx, 1.0), 0.0)
    return jnp.stack([h, s, mx], axis=-1)


# -------------------------------------------------- scatter / segment ops
# (reference libnd4j scatter_* and segment_* declarable families — the
# sparse-update path the embedding and graph-NN workloads use)


@register("scatter_add")
def _scatter_add(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].add(updates)


@register("scatter_sub")
def _scatter_sub(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].add(-updates)


@register("scatter_mul")
def _scatter_mul(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].multiply(updates)


@register("scatter_div")
def _scatter_div(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].divide(updates)


@register("scatter_max")
def _scatter_max(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].max(updates)


@register("scatter_min")
def _scatter_min(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].min(updates)


@register("scatter_nd")
def _scatter_nd(indices, updates, shape):
    out = jnp.zeros(tuple(int(s) for s in shape), updates.dtype)
    return out.at[tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))].add(updates)


@register("scatter_nd_add")
def _scatter_nd_add(a, indices, updates):
    return a.at[tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))].add(updates)


@register("scatter_nd_update")
def _scatter_nd_update(a, indices, updates):
    return a.at[tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))].set(updates)


@register("segment_sum")
def _segment_sum(data, segment_ids, num_segments=None):
    n = int(num_segments) if num_segments is not None else None
    return jax.ops.segment_sum(data, segment_ids.astype(jnp.int32), n)


@register("segment_mean")
def _segment_mean(data, segment_ids, num_segments=None):
    ids = segment_ids.astype(jnp.int32)
    n = int(num_segments) if num_segments is not None else None
    tot = jax.ops.segment_sum(data, ids, n)
    cnt = jax.ops.segment_sum(jnp.ones_like(data, jnp.float32), ids, n)
    return tot / jnp.maximum(cnt, 1.0)


@register("segment_max")
def _segment_max(data, segment_ids, num_segments=None):
    n = int(num_segments) if num_segments is not None else None
    return jax.ops.segment_max(data, segment_ids.astype(jnp.int32), n)


@register("segment_min")
def _segment_min(data, segment_ids, num_segments=None):
    n = int(num_segments) if num_segments is not None else None
    return jax.ops.segment_min(data, segment_ids.astype(jnp.int32), n)


@register("segment_prod")
def _segment_prod(data, segment_ids, num_segments=None):
    n = int(num_segments) if num_segments is not None else None
    return jax.ops.segment_prod(data, segment_ids.astype(jnp.int32), n)


@register("unsorted_segment_sum")
def _unsorted_segment_sum(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, segment_ids.astype(jnp.int32),
                               int(num_segments), indices_are_sorted=False)


@register("embedding_lookup")
def _embedding_lookup(table, ids):
    """Dense gather over the vocab axis (reference embedding_lookup — XLA
    lowers this to a dynamic-gather the TPU executes natively)."""
    return jnp.take(table, ids.astype(jnp.int32), axis=0)


@register("embedding_bag")
def _embedding_bag(table, ids, offsets=None, mode="sum"):
    """Pooled embedding gather (reference/torch EmbeddingBag): ``ids``
    (B, L) with -1 padding; pooled over L."""
    ids = ids.astype(jnp.int32)
    valid = (ids >= 0).astype(table.dtype)[..., None]
    emb = jnp.take(table, jnp.maximum(ids, 0), axis=0) * valid
    if mode == "sum":
        return jnp.sum(emb, axis=-2)
    if mode == "mean":
        return jnp.sum(emb, axis=-2) / jnp.maximum(
            jnp.sum(valid, axis=-2), 1.0)
    if mode == "max":
        neg = jnp.where(valid > 0, emb, jnp.full_like(emb, -jnp.inf))
        return jnp.max(neg, axis=-2)
    raise ValueError(f"embedding_bag mode {mode!r}")


# ------------------------------------------------------ spatial transforms
# (reference space_to_batch/depth family + dilation2d)


@register("space_to_batch")
def _space_to_batch(x, block_size=2, paddings=((0, 0), (0, 0))):
    p = [[0, 0]] + [list(q) for q in paddings] + [[0, 0]]
    x = jnp.pad(x, p)
    n, h, w, c = x.shape
    bs = int(block_size)
    x = x.reshape(n, h // bs, bs, w // bs, bs, c)
    x = x.transpose(2, 4, 0, 1, 3, 5)
    return x.reshape(n * bs * bs, h // bs, w // bs, c)


@register("batch_to_space")
def _batch_to_space(x, block_size=2, crops=((0, 0), (0, 0))):
    nb, h, w, c = x.shape
    bs = int(block_size)
    n = nb // (bs * bs)
    x = x.reshape(bs, bs, n, h, w, c)
    x = x.transpose(2, 3, 0, 4, 1, 5)
    x = x.reshape(n, h * bs, w * bs, c)
    (ct, cb), (cl, cr) = crops
    return x[:, int(ct):h * bs - int(cb), int(cl):w * bs - int(cr), :]


@register("space_to_depth")
def _space_to_depth(x, block_size=2):
    n, h, w, c = x.shape
    bs = int(block_size)
    x = x.reshape(n, h // bs, bs, w // bs, bs, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // bs, w // bs, bs * bs * c)


@register("depth_to_space")
def _depth_to_space(x, block_size=2):
    n, h, w, c = x.shape
    bs = int(block_size)
    x = x.reshape(n, h, w, bs, bs, c // (bs * bs))
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h * bs, w * bs, c // (bs * bs))


@register("dilation2d")
def _dilation2d(x, kernel, stride=(1, 1), rates=(1, 1), padding="SAME"):
    """Grayscale morphological dilation (reference Dilation2D)."""
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        window_dimensions=(1, kernel.shape[0], kernel.shape[1], 1),
        window_strides=(1, int(stride[0]), int(stride[1]), 1),
        window_dilation=(1, int(rates[0]), int(rates[1]), 1),
        padding=padding) if kernel.ndim == 2 else _dilation2d_full(
            x, kernel, stride, rates, padding)


def _dilation2d_full(x, kernel, stride, rates, padding):
    # kernel (kh, kw, C): shifted adds then max — small kernels only
    kh, kw, c = kernel.shape
    pads = jax.lax.padtype_to_pads(
        x.shape, (1, kh, kw, 1),
        (1, int(stride[0]), int(stride[1]), 1), padding) if isinstance(
            padding, str) else padding
    patches = []
    xp = jnp.pad(x, [(0, 0), tuple(pads[1]), tuple(pads[2]), (0, 0)],
                 constant_values=-jnp.inf)
    oh = (xp.shape[1] - ((kh - 1) * int(rates[0]) + 1)) // int(stride[0]) + 1
    ow = (xp.shape[2] - ((kw - 1) * int(rates[1]) + 1)) // int(stride[1]) + 1
    for i in range(kh):
        for j in range(kw):
            sl = xp[:, i * int(rates[0]):, j * int(rates[1]):, :]
            sl = sl[:, :oh * int(stride[0]):int(stride[0]),
                    :ow * int(stride[1]):int(stride[1]), :]
            patches.append(sl + kernel[i, j])
    return jnp.max(jnp.stack(patches), axis=0)


# ------------------------------------------------------------ image extras
# (reference crop_and_resize + non_max_suppression — the detection path)


@register("crop_and_resize")
def _crop_and_resize(images, boxes, box_indices, crop_size, method="bilinear"):
    """Per-box crop + resize (reference CropAndResize; TF semantics:
    boxes are normalised [y1, x1, y2, x2])."""
    n, h, w, c = images.shape
    ch, cw = (int(s) for s in crop_size)

    def one(box, bi):
        y1, x1, y2, x2 = box
        ys = y1 * (h - 1) + jnp.arange(ch) * (y2 - y1) * (h - 1) / max(ch - 1, 1)
        xs = x1 * (w - 1) + jnp.arange(cw) * (x2 - x1) * (w - 1) / max(cw - 1, 1)
        img = images[bi]
        if method == "nearest":
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, w - 1)
            return img[yi][:, xi]
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        a = img[y0][:, x0]
        b = img[y0][:, x1i]
        cc = img[y1i][:, x0]
        d = img[y1i][:, x1i]
        return (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
                + cc * wy * (1 - wx) + d * wy * wx)

    return jax.vmap(one)(boxes, box_indices.astype(jnp.int32))


@register("non_max_suppression")
def _non_max_suppression(boxes, scores, max_output_size=10,
                         iou_threshold=0.5, score_threshold=-jnp.inf):
    """Greedy NMS (reference NonMaxSuppression) as a fixed-trip lax.scan —
    static output size (TPU-friendly): returns (indices, valid_mask)."""
    k = int(max_output_size)

    y1, x1, y2, x2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)

    def iou_with(i):
        yy1 = jnp.maximum(y1, y1[i])
        xx1 = jnp.maximum(x1, x1[i])
        yy2 = jnp.minimum(y2, y2[i])
        xx2 = jnp.minimum(x2, x2[i])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area + area[i] - inter, 1e-9)

    def step(state, _):
        avail, = state
        masked = jnp.where(avail, scores, -jnp.inf)
        i = jnp.argmax(masked)
        ok = masked[i] > score_threshold
        suppress = iou_with(i) >= iou_threshold
        avail = avail & ~suppress & (jnp.arange(len(scores)) != i)
        return (avail,), (jnp.where(ok, i, -1), ok)

    (_,), (idx, valid) = jax.lax.scan(
        step, (jnp.ones(len(scores), bool),), None, length=k)
    return idx.astype(jnp.int32), valid


# --------------------------------------------------------- random (extras)


@register("random_gamma")
def _random_gamma(shape=None, alpha=1.0, beta=1.0, seed=0, key=None):
    import jax
    return jax.random.gamma(_key(seed, key), alpha, tuple(shape)) / beta


@register("random_poisson")
def _random_poisson(shape=None, lam=1.0, seed=0, key=None):
    import jax
    return jax.random.poisson(_key(seed, key), lam, tuple(shape)).astype(jnp.float32)


@register("random_gumbel")
def _random_gumbel(shape=None, seed=0, key=None):
    import jax
    return jax.random.gumbel(_key(seed, key), tuple(shape))


@register("random_laplace")
def _random_laplace(shape=None, seed=0, key=None):
    import jax
    return jax.random.laplace(_key(seed, key), tuple(shape))


@register("truncated_normal")
def _truncated_normal(shape=None, mean=0.0, stddev=1.0, seed=0, key=None):
    import jax
    return mean + stddev * jax.random.truncated_normal(
        _key(seed, key), -2.0, 2.0, tuple(shape))


@register("random_categorical")
def _random_categorical(logits, num_samples=1, seed=0, key=None):
    import jax
    return jnp.moveaxis(jax.random.categorical(
        _key(seed, key), logits, axis=-1,
        shape=(int(num_samples),) + logits.shape[:-1]), 0, -1)


@register("multinomial")
def _multinomial(probs, num_samples=1, seed=0, key=None):
    import jax
    return jnp.moveaxis(jax.random.categorical(
        _key(seed, key), jnp.log(jnp.maximum(probs, 1e-30)), axis=-1,
        shape=(int(num_samples),) + probs.shape[:-1]), 0, -1)


# ----------------------------------------------------- misc math / sorting


@register("top_k")
def _top_k(a, k=1):
    v, i = jax.lax.top_k(a, int(k))
    return v, i.astype(jnp.int32)


@register("in_top_k")
def _in_top_k(predictions, targets, k=1):
    _, idx = jax.lax.top_k(predictions, int(k))
    return jnp.any(idx == targets.astype(jnp.int32)[:, None], axis=-1)


@register("sort")
def _sort(a, axis=-1, descending=False):
    out = jnp.sort(a, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


@register("argsort")
def _argsort(a, axis=-1, descending=False):
    out = jnp.argsort(a, axis=axis).astype(jnp.int32)
    return jnp.flip(out, axis=axis) if descending else out


@register("unique")
def _unique(a, size=None):
    """Static-size unique (XLA needs static shapes): returns (values,
    counts) padded to ``size`` (defaults to a.size) with the fill value."""
    n = int(size) if size is not None else a.size
    vals, counts = jnp.unique(a, return_counts=True, size=n, fill_value=0)
    return vals, counts.astype(jnp.int32)


@register("bincount")
def _bincount(a, minlength=0, maxlength=None, weights=None):
    """TF ``tf.math.bincount`` semantics. Under jit the output length must
    be static: pass ``maxlength`` (values >= maxlength are dropped, as in
    TF). Without ``maxlength`` the length is computed from the concrete
    data (numpy semantics) — eager only."""
    flat = a.astype(jnp.int32).ravel()
    if maxlength is not None:
        return jnp.bincount(flat, weights=weights, minlength=int(minlength),
                            length=int(maxlength))
    # eager path: concrete max. Inside jit this raises a tracer error with
    # a clear remedy rather than silently truncating counts.
    try:
        needed = int(jnp.max(flat)) + 1 if flat.size else 0
    except Exception as e:
        raise ValueError(
            "bincount without maxlength needs concrete data; pass "
            "maxlength= for a static output length under jit") from e
    return jnp.bincount(flat, weights=weights,
                        length=max(int(minlength), needed, 1))


@register("searchsorted")
def _searchsorted(sorted_seq, values, side="left"):
    return jnp.searchsorted(sorted_seq, values, side=side).astype(jnp.int32)


@register("isnan")
def _isnan(a):
    return jnp.isnan(a)


@register("isinf")
def _isinf(a):
    return jnp.isinf(a)


@register("isfinite")
def _isfinite(a):
    return jnp.isfinite(a)


@register("nan_to_num")
def _nan_to_num(a, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf)


@register("atan2")
def _atan2(a, b):
    return jnp.arctan2(a, b)


@register("asinh")
def _asinh(a):
    return jnp.arcsinh(a)


@register("acosh")
def _acosh(a):
    return jnp.arccosh(a)


@register("atanh")
def _atanh(a):
    return jnp.arctanh(a)


@register("expm1")
def _expm1(a):
    return jnp.expm1(a)


@register("rint")
def _rint(a):
    return jnp.rint(a)


@register("erfc")
def _erfc(a):
    return jax.scipy.special.erfc(a)


@register("lgamma")
def _lgamma(a):
    return jax.scipy.special.gammaln(a)


@register("digamma")
def _digamma(a):
    return jax.scipy.special.digamma(a)


@register("betainc")
def _betainc(a, b, x):
    return jax.scipy.special.betainc(a, b, x)


@register("igamma")
def _igamma(a, x):
    return jax.scipy.special.gammainc(a, x)


@register("igammac")
def _igammac(a, x):
    return jax.scipy.special.gammaincc(a, x)


@register("zeta")
def _zeta(x, q):
    return jax.scipy.special.zeta(x, q)


@register("polygamma")
def _polygamma(n, x):
    return jax.scipy.special.polygamma(n.astype(jnp.int32) if hasattr(n, "astype") else int(n), x)


@register("xlogy")
def _xlogy(x, y):
    return jax.scipy.special.xlogy(x, y)


@register("cumprod")
def _cumprod(a, axis=-1):
    return jnp.cumprod(a, axis=axis)


@register("logcumsumexp")
def _logcumsumexp(a, axis=-1):
    return jax.lax.cumlogsumexp(a, axis=axis)


@register("clip_by_norm")
def _clip_by_norm(a, clip_norm, axes=None):
    n = jnp.sqrt(jnp.sum(a * a, axis=axes, keepdims=axes is not None))
    scale = jnp.where(n > clip_norm, clip_norm / jnp.maximum(n, 1e-12), 1.0)
    return a * scale


@register("clip_by_global_norm")
def _clip_by_global_norm(a, clip_norm):
    n = jnp.sqrt(jnp.sum(a * a))
    return a * jnp.where(n > clip_norm, clip_norm / jnp.maximum(n, 1e-12), 1.0)


@register("swap_axes")
def _swap_axes(a, axis1=0, axis2=1):
    return jnp.swapaxes(a, int(axis1), int(axis2))


@register("meshgrid")
def _meshgrid(a, b, indexing="xy"):
    return tuple(jnp.meshgrid(a, b, indexing=indexing))


@register("broadcast_to")
def _broadcast_to(a, shape):
    return jnp.broadcast_to(a, tuple(int(s) for s in shape))


@register("squared_norm")
def _squared_norm(a, axis=None, keepdims=False):
    return jnp.sum(a * a, axis=axis, keepdims=keepdims)


# ------------------------------------------------------- registry wave 3
# (more of the reference declarable-op surface: boolean reductions,
# structure ops, conv/pool variants, statistical moments, extra losses)


@register("reduce_any")
def _reduce_any(a, axis=None, keepdims=False):
    return jnp.any(a.astype(bool), axis=_ax(axis), keepdims=keepdims)


@register("reduce_all")
def _reduce_all(a, axis=None, keepdims=False):
    return jnp.all(a.astype(bool), axis=_ax(axis), keepdims=keepdims)


def _ax(axis):
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


@register("count_nonzero")
def _count_nonzero(a, axis=None, keepdims=False):
    return jnp.count_nonzero(a, axis=_ax(axis), keepdims=keepdims).astype(jnp.int32)


@register("reduce_median")
def _reduce_median(a, axis=None, keepdims=False):
    return jnp.median(a, axis=_ax(axis), keepdims=keepdims)


@register("quantile")
def _quantile(a, q, axis=None, keepdims=False):
    return jnp.quantile(a, q, axis=_ax(axis), keepdims=keepdims)


@register("moments")
def _moments(a, axis=None, keepdims=False):
    """(mean, variance) pair (reference/TF nn.moments)."""
    mean = jnp.mean(a, axis=_ax(axis), keepdims=keepdims)
    var = jnp.var(a, axis=_ax(axis), keepdims=keepdims)
    return mean, var


@register("normalize_moments")
def _normalize_moments(counts, mean_ss, variance_ss, shift=0.0):
    mean = mean_ss / counts + shift
    variance = variance_ss / counts - (mean - shift) ** 2
    return mean, variance


@register("roll")
def _roll(a, shift, axis=None):
    return jnp.roll(a, shift, axis=axis)


@register("eye")
def _eye(n, m=None, dtype="float32"):
    return jnp.eye(int(n), int(m) if m is not None else None,
                   dtype=jnp.dtype(dtype))


@register("tril")
def _tril(a, k=0):
    return jnp.tril(a, int(k))


@register("triu")
def _triu(a, k=0):
    return jnp.triu(a, int(k))


@register("kron")
def _kron(a, b):
    return jnp.kron(a, b)


@register("matrix_diag")
def _matrix_diag(a):
    """Batched vector -> diagonal matrices (reference MatrixDiag)."""
    return a[..., :, None] * jnp.eye(a.shape[-1], dtype=a.dtype)


@register("matrix_set_diag")
def _matrix_set_diag(a, diag):
    k = min(a.shape[-2], a.shape[-1])
    idx = jnp.arange(k)
    return a.at[..., idx, idx].set(diag[..., :k])


@register("repeat_elements")
def _repeat_elements(a, repeats, axis=0):
    return jnp.repeat(a, int(repeats), axis=int(axis))


@register("flip")
def _flip(a, axis=None):
    return jnp.flip(a, axis=axis)


@register("approx_equal")
def _approx_equal(a, b, tolerance=1e-5):
    return jnp.abs(a - b) <= tolerance


# activations (remaining reference set)
@register("log_sigmoid")
def _log_sigmoid(a):
    return jax.nn.log_sigmoid(a)


@register("hard_swish")
def _hard_swish(a):
    return a * jnp.clip(a / 6.0 + 0.5, 0.0, 1.0)


@register("celu")
def _celu(a, alpha=1.0):
    return jax.nn.celu(a, alpha)


@register("glu")
def _glu(a, axis=-1):
    return jax.nn.glu(a, axis)


@register("prelu")
def _prelu(a, alpha):
    return jnp.where(a >= 0, a, alpha * a)


@register("thresholded_relu")
def _thresholded_relu(a, theta=1.0):
    return jnp.where(a > theta, a, 0.0)


@register("rational_tanh")
def _rational_tanh(a):
    """Reference RationalTanh: fast tanh approximation
    1.7159 * tanh_approx(2/3 x)."""
    x = 2.0 * a / 3.0
    ax = jnp.abs(x)
    approx = jnp.sign(x) * (1.0 - 1.0 / (1.0 + ax + x * x
                                         + 1.41645 * ax * ax * ax * ax))
    return 1.7159 * approx


@register("rectified_tanh")
def _rectified_tanh(a):
    return jnp.maximum(0.0, jnp.tanh(a))


# conv / pool variants
@register("conv1d")
def _conv1d(x, w, stride=1, padding="SAME", dilation=1):
    """(B, T, C) 1-D conv, kernel (K, C, F)."""
    return jax.lax.conv_general_dilated(
        x[:, :, None, :], w[:, None, :, :], (int(stride), 1), padding,
        rhs_dilation=(int(dilation), 1),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))[:, :, 0, :]


@register("conv3d")
def _conv3d(x, w, stride=(1, 1, 1), padding="SAME"):
    """(B, D, H, W, C) 3-D conv, kernel (KD, KH, KW, C, F)."""
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    return jax.lax.conv_general_dilated(
        x, w, s, padding, dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))


@register("depthwise_conv2d")
def _depthwise_conv2d(x, w, stride=(1, 1), padding="SAME"):
    """Kernel (KH, KW, C, M) TF-style -> grouped conv with C groups."""
    kh, kw, c, m = w.shape
    s = (stride,) * 2 if isinstance(stride, int) else tuple(stride)
    return jax.lax.conv_general_dilated(
        x, w.reshape(kh, kw, 1, c * m), s, padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)


def _pool(x, kind, kernel, stride, padding, nd):
    k = (kernel,) * nd if isinstance(kernel, int) else tuple(kernel)
    s = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    dims = (1,) + k + (1,)
    strides = (1,) + s + (1,)
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strides,
                                     padding)
    total = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, padding)
    cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add, dims,
                                strides, padding)
    return total / cnt


@register("max_pool1d")
def _max_pool1d(x, kernel=2, stride=2, padding="VALID"):
    return _pool(x, "max", kernel, stride, padding, 1)


@register("avg_pool1d")
def _avg_pool1d(x, kernel=2, stride=2, padding="VALID"):
    return _pool(x, "avg", kernel, stride, padding, 1)


@register("max_pool3d")
def _max_pool3d(x, kernel=2, stride=2, padding="VALID"):
    return _pool(x, "max", kernel, stride, padding, 3)


@register("avg_pool3d")
def _avg_pool3d(x, kernel=2, stride=2, padding="VALID"):
    return _pool(x, "avg", kernel, stride, padding, 3)


@register("local_response_normalization")
def _lrn(x, depth_radius=5, bias=1.0, alpha=1.0, beta=0.5):
    """TF-style LRN over the channel axis of NHWC."""
    r = int(depth_radius)
    sq = x * x
    pad = jnp.pad(sq, ((0, 0),) * (x.ndim - 1) + ((r, r),))
    win = sum(pad[..., i:i + x.shape[-1]] for i in range(2 * r + 1))
    return x / jnp.power(bias + alpha * win, beta)


@register("im2col")
def _im2col(x, kernel=(3, 3), stride=(1, 1), padding="VALID"):
    """Patch extraction (reference im2col): (B, H, W, C) ->
    (B, OH, OW, KH*KW*C)."""
    kh, kw = kernel
    out = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out


# extra losses (reference loss-function set)
@register("kl_divergence")
def _kl_divergence(labels, predictions, eps=1e-7):
    p = jnp.clip(labels, eps, 1.0)
    q = jnp.clip(predictions, eps, 1.0)
    return jnp.mean(jnp.sum(p * jnp.log(p / q), axis=-1))


@register("poisson_loss")
def _poisson_loss(labels, log_predictions):
    return jnp.mean(jnp.exp(log_predictions) - labels * log_predictions)


@register("mean_pairwise_squared_error")
def _mpse(labels, predictions):
    d = (predictions - labels)
    n = d.shape[-1]
    sum_d = jnp.sum(d, axis=-1, keepdims=True)
    return jnp.mean((n * jnp.sum(d * d, axis=-1)
                     - jnp.sum(d, axis=-1) ** 2) / max(n * (n - 1), 1))


@register("mean_squared_log_error")
def _msle(labels, predictions):
    return jnp.mean((jnp.log1p(jnp.maximum(labels, 0))
                     - jnp.log1p(jnp.maximum(predictions, 0))) ** 2)


@register("mean_absolute_percentage_error")
def _mape(labels, predictions):
    return 100.0 * jnp.mean(jnp.abs((labels - predictions)
                                    / jnp.maximum(jnp.abs(labels), 1e-7)))


@register("ctc_loss")
def _ctc_loss(log_probs, label_seqs, input_lengths, label_lengths, blank=0):
    """Connectionist Temporal Classification (reference/TF ctc_loss), as a
    fixed-shape lax.scan over the extended-label forward recursion —
    TPU-friendly (static shapes, no host sync). ``log_probs`` (B, T, C)
    log-softmaxed; ``label_seqs`` (B, S) padded with any value past
    ``label_lengths``."""
    B, T, C = log_probs.shape
    S = label_seqs.shape[1]
    L = 2 * S + 1
    labels = label_seqs.astype(jnp.int32)
    # extended sequence: blank, l1, blank, l2, ... blank
    ext = jnp.full((B, L), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    pos = jnp.arange(L)[None, :]
    valid = pos < (2 * label_lengths[:, None] + 1)
    # transitions: from s, s-1 always; s-2 only when ext[s] != blank and
    # ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :L]
    allow_skip = (ext != blank) & (ext != ext_m2)
    neg = jnp.asarray(-1e30, log_probs.dtype)

    def emit(t):
        return jnp.take_along_axis(log_probs[:, t], ext, axis=1)

    alpha0 = jnp.full((B, L), neg)
    alpha0 = alpha0.at[:, 0].set(log_probs[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_lengths > 0,
                  jnp.take_along_axis(log_probs[:, 0], labels[:, :1],
                                      axis=1)[:, 0], neg))

    def step(alpha, t):
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=neg)[:, :L]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=neg)[:, :L]
        a2 = jnp.where(allow_skip, a2, neg)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        new = merged + emit(t)
        new = jnp.where(valid, new, neg)
        # frozen past the input length (final alpha read at T-1 uses the
        # mask below)
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    endA = 2 * label_lengths - 1
    endB = 2 * label_lengths
    pA = jnp.take_along_axis(alpha, jnp.maximum(endA, 0)[:, None], axis=1)[:, 0]
    pA = jnp.where(label_lengths > 0, pA, neg)
    pB = jnp.take_along_axis(alpha, endB[:, None], axis=1)[:, 0]
    return -jnp.mean(jnp.logaddexp(pA, pB))


@register("scaled_dot_product_attention")
def _sdpa(q, k, v, bias=None, scale=None, boolean_bias=False):
    """softmax(q @ k^T * scale + bias) @ v over (B, H, T, D) operands —
    the graph-optimizer's fusion target for imported attention subgraphs.

    ``boolean_bias=True`` is set by the fuser only when it PROVED the bias
    subgraph is the additive key-padding pattern ((1 - mask) * -LARGE), in
    which case it is converted to a boolean mask and the computation routes
    through :func:`nn.attention_layers.dot_product_attention` (and from
    there to the Pallas flash kernel when shapes allow). A general additive
    bias keeps the exact XLA softmax form."""
    from deeplearning4j_tpu.nn.attention_layers import dot_product_attention
    d = q.shape[-1]
    nat = 1.0 / math.sqrt(d)
    s = nat if scale is None else float(scale)
    if q.ndim == 4 and (bias is None or boolean_bias):
        if not math.isclose(s, nat, rel_tol=1e-6):
            q = q * jnp.asarray(s / nat, q.dtype)
        mask = None
        if bias is not None:
            mask = bias > jnp.asarray(-1.0, bias.dtype)
            # a FULLY-masked row's additive form is softmax(x + const) ==
            # softmax(x); reproduce that exactly by unmasking such rows
            # (a hard mask would instead give uniform/NaN weights)
            row_any = jnp.any(mask, axis=-1, keepdims=True)
            mask = mask | ~row_any
        return dot_product_attention(q, k, v, mask=mask)
    # rank-agnostic exact form (leading dims are batch; also the general
    # additive-bias path)
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * jnp.asarray(s, q.dtype)
    if bias is not None:
        scores = scores + (jnp.where(bias > -1.0, 0.0, -1e9).astype(scores.dtype)
                           if boolean_bias else bias)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


# ------------------------------------------------------- registry wave 4
# (reduce3 distance ops, index accumulations, summary statistics, sequence
# ops, remaining comparison/loss/matrix families of the declarable set)


@register("logical_xor")
def _logical_xor(a, b):
    return jnp.logical_xor(a, b)


@register("isclose")
def _isclose(a, b, rtol=1e-5, atol=1e-8):
    return jnp.isclose(a, b, rtol=rtol, atol=atol)


@register("remainder")
def _remainder(a, b):
    return jnp.remainder(a, b)


@register("trunc")
def _trunc(a):
    return jnp.trunc(a)


@register("cube")
def _cube(a):
    return a * a * a


@register("step")
def _step(a, cutoff=0.0):
    return (a > cutoff).astype(jnp.float32)


@register("hard_tanh")
def _hard_tanh(a):
    return jnp.clip(a, -1.0, 1.0)


@register("logspace")
def _logspace(start, stop, num, base=10.0):
    return jnp.logspace(start, stop, int(num), base=base)


# summary statistics (reference SummaryStats ops)
@register("skewness")
def _skewness(a, axis=None, keepdims=False):
    ax = _ax(axis)
    m = jnp.mean(a, axis=ax, keepdims=True)
    s = jnp.std(a, axis=ax, keepdims=True)
    z = (a - m) / jnp.maximum(s, 1e-12)
    return jnp.mean(z ** 3, axis=ax, keepdims=keepdims)


@register("kurtosis")
def _kurtosis(a, axis=None, keepdims=False):
    ax = _ax(axis)
    m = jnp.mean(a, axis=ax, keepdims=True)
    s = jnp.std(a, axis=ax, keepdims=True)
    z = (a - m) / jnp.maximum(s, 1e-12)
    return jnp.mean(z ** 4, axis=ax, keepdims=keepdims) - 3.0


# index accumulations (reference IAMax/IAMin/FirstIndex/LastIndex)
@register("argamax")
def _argamax(a, axis=-1):
    return jnp.argmax(jnp.abs(a), axis=axis)


@register("argamin")
def _argamin(a, axis=-1):
    return jnp.argmin(jnp.abs(a), axis=axis)


@register("first_index")
def _first_index(a, condition, axis=-1):
    """Index of the first element matching ``condition`` along axis; -1 if
    none (reference FirstIndex)."""
    m = condition(a)
    idx = jnp.argmax(m, axis=axis)
    any_ = jnp.any(m, axis=axis)
    return jnp.where(any_, idx, -1).astype(jnp.int32)


@register("last_index")
def _last_index(a, condition, axis=-1):
    m = condition(a)
    n = a.shape[axis]
    idx = n - 1 - jnp.argmax(jnp.flip(m, axis), axis=axis)
    any_ = jnp.any(m, axis=axis)
    return jnp.where(any_, idx, -1).astype(jnp.int32)


@register("size_at")
def _size_at(a, dim=0):
    return jnp.asarray(a.shape[int(dim)], jnp.int32)


# reduce3 pairwise distances (reference org.nd4j...ops.impl.reduce3)
@register("cosine_similarity")
def _cosine_similarity(a, b, axis=-1, eps=1e-12):
    num = jnp.sum(a * b, axis=_ax(axis))
    den = (jnp.sqrt(jnp.sum(a * a, axis=_ax(axis)))
           * jnp.sqrt(jnp.sum(b * b, axis=_ax(axis))))
    return num / jnp.maximum(den, eps)


@register("euclidean_distance")
def _euclidean_distance(a, b, axis=-1):
    d = a - b
    return jnp.sqrt(jnp.sum(d * d, axis=_ax(axis)))


@register("manhattan_distance")
def _manhattan_distance(a, b, axis=-1):
    return jnp.sum(jnp.abs(a - b), axis=_ax(axis))


@register("hamming_distance")
def _hamming_distance(a, b, axis=-1):
    return jnp.sum((a != b).astype(jnp.float32), axis=_ax(axis))


@register("jaccard_distance")
def _jaccard_distance(a, b, axis=-1, eps=1e-12):
    inter = jnp.sum(jnp.minimum(a, b), axis=_ax(axis))
    union = jnp.sum(jnp.maximum(a, b), axis=_ax(axis))
    return 1.0 - inter / jnp.maximum(union, eps)


# sequence / matrix utilities
@register("reverse_sequence")
def _reverse_sequence(a, seq_lengths, seq_axis=1, batch_axis=0):
    """Reverse each sequence's first ``seq_lengths[i]`` steps (reference/TF
    ReverseSequence)."""
    t = a.shape[seq_axis]
    idx = jnp.arange(t)
    lens = seq_lengths.astype(jnp.int32)
    # per-batch gather indices: reversed inside the length, identity after
    def gather_one(x, l):
        g = jnp.where(idx < l, l - 1 - idx, idx)
        return jnp.take(x, g, axis=seq_axis - 1 if seq_axis > batch_axis else seq_axis)
    return jax.vmap(gather_one, in_axes=(batch_axis, 0), out_axes=batch_axis)(a, lens)


@register("confusion_matrix")
def _confusion_matrix(labels, predictions, num_classes, weights=None):
    l = labels.astype(jnp.int32).ravel()
    p = predictions.astype(jnp.int32).ravel()
    n = int(num_classes)
    flat = l * n + p
    w = jnp.ones_like(flat, jnp.float32) if weights is None \
        else weights.astype(jnp.float32).ravel()
    out = jnp.zeros((n * n,), jnp.float32).at[flat].add(w)
    return out.reshape(n, n)


@register("nth_element")
def _nth_element(a, n, reverse=False):
    s = jnp.sort(a, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., int(n)]


@register("standardize")
def _standardize(a, axis=-1, eps=1e-12):
    m = jnp.mean(a, axis=_ax(axis), keepdims=True)
    s = jnp.std(a, axis=_ax(axis), keepdims=True)
    return (a - m) / jnp.maximum(s, eps)


@register("matrix_norm")
def _matrix_norm(a, ord="fro", axis=None):
    return jnp.linalg.norm(a, ord=ord, axis=axis)


@register("lu")
def _lu(a):
    """LU with partial pivoting; returns (lu_packed, pivots) like
    jax.scipy.linalg.lu_factor (reference Lu op)."""
    import jax.scipy.linalg as jsl
    lu_, piv = jsl.lu_factor(a)
    return lu_, piv.astype(jnp.int32)


# remaining losses / stochastic ops
@register("weighted_cross_entropy_with_logits")
def _wce(labels, logits, pos_weight=1.0):
    log_w = (1.0 + (pos_weight - 1.0) * labels)
    return jnp.mean(
        (1.0 - labels) * logits
        + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logits)))
                   + jnp.maximum(-logits, 0.0)))


@register("log_poisson_loss")
def _log_poisson_loss(targets, log_input, compute_full_loss=False):
    loss = jnp.exp(log_input) - log_input * targets
    if compute_full_loss:
        stirling = (targets * jnp.log(jnp.maximum(targets, 1e-12)) - targets
                    + 0.5 * jnp.log(2.0 * jnp.pi * jnp.maximum(targets, 1.0)))
        loss = loss + jnp.where(targets > 1, stirling, 0.0)
    return jnp.mean(loss)


@register("random_binomial")
def _random_binomial(shape=None, n=1, p=0.5, seed=0, key=None):
    import jax
    return jax.random.binomial(_key(seed, key), n, p, shape=tuple(shape)
                               ).astype(jnp.float32)


@register("random_lognormal")
def _random_lognormal(shape=None, mean=0.0, stddev=1.0, seed=0, key=None):
    import jax
    return jnp.exp(mean + stddev * jax.random.normal(_key(seed, key), tuple(shape)))


@register("alpha_dropout")
def _alpha_dropout(a, key=None, rate=0.5):
    """SELU-preserving dropout (reference AlphaDropOut); inference no-op
    without a key."""
    if key is None or rate <= 0.0:
        return a
    alpha_p = -1.7580993408473766
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, a.shape)
    x = jnp.where(mask, a, alpha_p)
    q = keep + alpha_p ** 2 * keep * (1 - keep)
    scale = q ** -0.5
    bias = -scale * alpha_p * (1 - keep)
    return scale * x + bias


# boolean structure checks
@register("is_non_decreasing")
def _is_non_decreasing(a):
    f = a.ravel()
    return jnp.all(f[1:] >= f[:-1]) if f.size > 1 else jnp.asarray(True)


@register("is_strictly_increasing")
def _is_strictly_increasing(a):
    f = a.ravel()
    return jnp.all(f[1:] > f[:-1]) if f.size > 1 else jnp.asarray(True)


@register("is_numeric_tensor")
def _is_numeric_tensor(a):
    return jnp.asarray(jnp.issubdtype(a.dtype, jnp.number))


@register("compare_and_set")
def _compare_and_set(a, compare, set_value, eps=1e-12):
    """Where |a - compare| <= eps, replace with set_value (reference
    CompareAndSet)."""
    return jnp.where(jnp.abs(a - compare) <= eps, set_value, a)


@register("replace_nans")
def _replace_nans(a, value=0.0):
    return jnp.where(jnp.isnan(a), value, a)


# ------------------------------------------------------- registry wave 5
# (round 3: importer-generality ops — einsum, deconv, dynamic reshape,
# AddN — plus the remaining declarable families: FFT, dynamic
# partition/stitch, sequence mask, matrix band, histograms)


@register("einsum")
def _einsum(*operands, equation=""):
    """General einsum (TF Einsum / reference Einsum declarable op)."""
    return jnp.einsum(equation, *operands)


@register("conv2d_transpose")
def _conv2d_transpose(y, w, stride=(1, 1), padding="SAME", output_shape=None):
    """Gradient-of-conv2d w.r.t. its input (TF ``Conv2DBackpropInput``; the
    reference's ``deconv2d`` declarable op / DL4J ``Deconvolution2D``).
    ``w`` is HWIO like the forward conv; ``output_shape`` (when given, e.g.
    by the TF importer) is validated against the result — TF's deconv
    output size is ambiguous for some stride/pad combos and we only
    implement the standard one ``lax.conv_transpose`` produces."""
    out = lax.conv_transpose(y, w, tuple(stride), padding,
                             dimension_numbers=("NHWC", "HWIO", "NHWC"),
                             transpose_kernel=True)
    if output_shape is not None and tuple(int(s) for s in output_shape) != tuple(out.shape):
        raise NotImplementedError(
            f"conv2d_transpose: requested output shape {tuple(output_shape)} "
            f"!= computed {tuple(out.shape)} (non-standard TF deconv sizing)")
    return out


@register("reshape_dynamic")
def _reshape_dynamic(a, shape):
    """Reshape with a TENSOR shape operand — the importer's fallback when a
    TF Reshape's shape input is computed rather than Const. The graph
    optimizer's ``fold_shape_chains`` statically evaluates such chains at
    import time and rewrites this op to a plain ``reshape``; executing it
    directly under jit only works when ``shape`` is concrete (it is not,
    once any primitive has touched it inside a trace)."""
    import numpy as np
    try:
        vals = np.asarray(shape)
    except Exception as e:
        raise NotImplementedError(
            "reshape_dynamic with a traced shape operand: computed reshape "
            "shapes must be folded statically first — run "
            "graph_optimizer.fold_shape_chains (TFGraphMapper does this "
            "when optimize=True) or make the shape a constant") from e
    return jnp.reshape(a, tuple(int(s) for s in vals))


@register("add_n")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register("fft")
def _fft(a):
    return jnp.fft.fft(a)


@register("ifft")
def _ifft(a):
    return jnp.fft.ifft(a)


@register("rfft")
def _rfft(a, fft_length=None):
    return jnp.fft.rfft(a, n=int(fft_length) if fft_length else None)


@register("irfft")
def _irfft(a, fft_length=None):
    return jnp.fft.irfft(a, n=int(fft_length) if fft_length else None)


@register("fft2d")
def _fft2d(a):
    return jnp.fft.fft2(a)


@register("ifft2d")
def _ifft2d(a):
    return jnp.fft.ifft2(a)


@register("dynamic_partition")
def _dynamic_partition(data, partitions, num_partitions=2):
    """TF dynamic_partition with static sizes: returns ``num_partitions``
    arrays of data.shape size padded with zeros plus a per-partition count
    (XLA needs static shapes, so the TPU-native contract is the padded
    form; the counts let callers mask). Rows of ``data`` whose partition
    index equals p are packed (stably) at the front of output p."""
    n = data.shape[0]
    parts = []
    counts = []
    for p in range(int(num_partitions)):
        sel = partitions == p
        # stable pack-to-front permutation: order by (not selected, index)
        order = jnp.argsort(jnp.where(sel, 0, 1) * n + jnp.arange(n))
        packed = data[order]
        cnt = jnp.sum(sel)
        mask_shape = (n,) + (1,) * (data.ndim - 1)
        keep = (jnp.arange(n) < cnt).reshape(mask_shape)
        parts.append(jnp.where(keep, packed, jnp.zeros_like(packed)))
        counts.append(cnt)
    return tuple(parts) + (jnp.stack(counts),)


@register("dynamic_stitch")
def _dynamic_stitch(indices, *data, total=None):
    """TF dynamic_stitch: scatter rows of each data piece to positions given
    by the matching indices piece; later pieces win on overlap. XLA needs a
    static output size: pass ``total`` explicitly, else it defaults to the
    summed index-piece sizes (exact for the canonical partition/stitch
    round trip, where indices cover 0..N-1)."""
    idx_list = indices if isinstance(indices, (list, tuple)) else [indices]
    n_pieces = len(idx_list)
    vals = data[:n_pieces]
    if total is not None:
        total = int(total)
    else:
        try:  # TF sizing: max index + 1 — needs concrete indices
            import numpy as _np
            total = max(int(_np.asarray(i).max())
                        for i in idx_list if i.size) + 1
        except Exception as e:
            raise ValueError(
                "dynamic_stitch under jit needs a static output size: pass "
                "total= explicitly (TF sizes by max(indices)+1, which is "
                "data-dependent)") from e
    out_shape = (total,) + tuple(vals[0].shape[idx_list[0].ndim:])
    out = jnp.zeros(out_shape, vals[0].dtype)
    for i, v in zip(idx_list, vals):
        out = out.at[i.reshape(-1)].set(v.reshape((-1,) + out_shape[1:]))
    return out


@register("sequence_mask")
def _sequence_mask(lengths, maxlen=None, dtype="bool"):
    if maxlen is None:
        raise ValueError(
            "sequence_mask needs a static maxlen under XLA (TF computes "
            "max(lengths) dynamically; pass maxlen explicitly)")
    mask = jnp.arange(int(maxlen)) < jnp.asarray(lengths)[..., None]
    return mask if dtype == "bool" else mask.astype(dtype)


@register("histogram_fixed_width")
def _histogram_fixed_width(values, value_range, nbins=100):
    lo, hi = value_range[0], value_range[1]
    scaled = (values - lo) / jnp.maximum(hi - lo, 1e-30) * nbins
    idx = jnp.clip(scaled.astype(jnp.int32), 0, nbins - 1)
    return jnp.zeros((int(nbins),), jnp.int32).at[idx.reshape(-1)].add(1)


@register("bincount")
def _bincount(arr, size=None, weights=None):
    if size is None:
        raise ValueError(
            "bincount needs a static size under XLA (TF sizes the output "
            "by max(arr) dynamically; pass size explicitly)")
    n = int(size)
    if weights is None:
        return jnp.zeros((n,), jnp.int32).at[arr.reshape(-1)].add(1)
    return jnp.zeros((n,), jnp.asarray(weights).dtype).at[arr.reshape(-1)].add(
        jnp.asarray(weights).reshape(-1))


# ------------------------------------------------------- registry wave 6
# (round 3: declarable-set long tail — image adjusts, matrix family,
# segments, nan-reductions, signal/window family, quantization, misc math;
# reference [U] libnd4j/include/ops/declarable/ families)

register("xdivy")(lambda a, b: jnp.where(a == 0, 0.0, a / jnp.where(a == 0, 1.0, b)))
register("multiply_no_nan")(lambda a, b: jnp.where(b == 0, 0.0, a * b))
register("div_no_nan")(lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)))
register("truncate_div")(lambda a, b: jnp.trunc(a / b).astype(a.dtype))
register("truncate_mod")(lambda a, b: a - jnp.trunc(a / b).astype(a.dtype) * b)
register("unravel_index")(lambda idx, shape=(): jnp.stack(
    jnp.unravel_index(idx, tuple(int(s) for s in shape))))
register("rot90")(lambda a, k=1: jnp.rot90(a, int(k)))
register("diff")(lambda a, n=1, axis=-1: jnp.diff(a, int(n), axis=axis))
register("ediff1d")(lambda a: jnp.diff(a.ravel()))
register("percentile")(lambda a, q=50.0, axis=None, keepdims=False:
                       jnp.percentile(a, q, axis=axis, keepdims=keepdims))
register("median")(lambda a, axis=None, keepdims=False:
                   jnp.median(a, axis=axis, keepdims=keepdims))
register("nanmean")(lambda a, axis=None, keepdims=False: jnp.nanmean(a, axis, keepdims=keepdims))
register("nansum")(lambda a, axis=None, keepdims=False: jnp.nansum(a, axis, keepdims=keepdims))
register("nanmax")(lambda a, axis=None, keepdims=False: jnp.nanmax(a, axis, keepdims=keepdims))
register("nanmin")(lambda a, axis=None, keepdims=False: jnp.nanmin(a, axis, keepdims=keepdims))
register("nanvar")(lambda a, axis=None, keepdims=False: jnp.nanvar(a, axis, keepdims=keepdims))
register("nanstd")(lambda a, axis=None, keepdims=False: jnp.nanstd(a, axis, keepdims=keepdims))
register("allclose")(lambda a, b, rtol=1e-5, atol=1e-8: jnp.allclose(a, b, rtol, atol))
register("array_equal")(lambda a, b: jnp.array_equal(a, b))
register("isin")(lambda a, test: jnp.isin(a, test))
register("take_along_axis")(lambda a, idx, axis=-1: jnp.take_along_axis(a, idx, axis))
register("repeat")(lambda a, repeats=1, axis=None: jnp.repeat(a, int(repeats), axis=axis))
register("swapaxes")(lambda a, axis1=0, axis2=1: jnp.swapaxes(a, int(axis1), int(axis2)))
register("moveaxis")(lambda a, source=0, destination=-1:
                     jnp.moveaxis(a, int(source), int(destination)))
register("hstack")(lambda *xs: jnp.hstack(xs))
register("vstack")(lambda *xs: jnp.vstack(xs))
register("dstack")(lambda *xs: jnp.dstack(xs))
register("tri")(lambda n, m=None, k=0: jnp.tri(int(n), int(m) if m else None, int(k)))
register("vander")(lambda a, n=None: jnp.vander(a, int(n) if n else None))
register("inner")(jnp.inner)
register("vdot")(jnp.vdot)
register("matrix_transpose")(lambda a: jnp.swapaxes(a, -1, -2))
register("sinc")(jnp.sinc)
register("log1mexp")(lambda a: jnp.log1p(-jnp.exp(-jnp.abs(a))))
register("erfinv")(lambda a: jax.scipy.special.erfinv(a))
register("nextafter")(jnp.nextafter)
register("hardswish")(jax.nn.hard_swish)
register("reduce_logsumexp")(lambda a, axis=None, keepdims=False:
                             jax.scipy.special.logsumexp(a, axis=axis, keepdims=keepdims))
register("reduce_euclidean_norm")(lambda a, axis=None, keepdims=False:
                                  jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdims)))
register("cummax")(lambda a, axis=0: jax.lax.cummax(a, axis=int(axis)))
register("cummin")(lambda a, axis=0: jax.lax.cummin(a, axis=int(axis)))
register("hard_shrink")(lambda a, lambd=0.5: jnp.where(jnp.abs(a) > lambd, a, 0.0))
register("soft_shrink")(lambda a, lambd=0.5:
                        jnp.sign(a) * jnp.maximum(jnp.abs(a) - lambd, 0.0))
register("kthvalue")(lambda a, k=1, axis=-1: jnp.sort(a, axis=axis).take(int(k) - 1, axis=axis))
register("batch_gather")(lambda a, idx: jnp.take_along_axis(
    a, idx, axis=1) if a.ndim > idx.ndim else jnp.take_along_axis(a, idx, axis=-1))
register("adjoint")(lambda a: jnp.conj(jnp.swapaxes(a, -1, -2)))
register("norm")(lambda a, ord=None, axis=None, keepdims=False:
                 jnp.linalg.norm(a, ord=ord, axis=axis, keepdims=keepdims))
register("pinv")(jnp.linalg.pinv)
register("matrix_power")(lambda a, n=1: jnp.linalg.matrix_power(a, int(n)))
register("slogdet")(lambda a: tuple(jnp.linalg.slogdet(a)))
register("expm")(lambda a: jax.scipy.linalg.expm(a))
register("matrix_diag_part")(lambda a: jnp.diagonal(a, axis1=-2, axis2=-1))
register("matrix_solve")(lambda a, b: jnp.linalg.solve(a, b))
register("cholesky_solve")(lambda chol, b: jax.scipy.linalg.cho_solve((chol, True), b))
register("lu_solve")(lambda a, b: jnp.linalg.solve(a, b))  # factor+solve fused
register("tridiagonal_solve")(lambda dl, d, du, b: jax.lax.linalg.tridiagonal_solve(
    dl, d, du, b))
register("invert_permutation")(lambda p: jnp.argsort(p))


@register("setdiff1d")
def _setdiff1d(a, b):
    """Values in a not in b, padded with zeros to a's size plus count (XLA
    static-shape contract, same style as dynamic_partition)."""
    a = a.ravel()
    keep = ~jnp.isin(a, b)
    order = jnp.argsort(jnp.where(keep, 0, 1) * a.size + jnp.arange(a.size))
    packed = a[order]
    cnt = jnp.sum(keep)
    return jnp.where(jnp.arange(a.size) < cnt, packed, 0), cnt


@register("boolean_mask")
def _boolean_mask(a, mask):
    """Rows of a where mask, packed to the front and zero-padded, plus the
    count (static-shape contract)."""
    m = mask.ravel().astype(bool)
    n = m.shape[0]
    order = jnp.argsort(jnp.where(m, 0, 1) * n + jnp.arange(n))
    packed = a[order]
    cnt = jnp.sum(m)
    keep = (jnp.arange(n) < cnt).reshape((n,) + (1,) * (a.ndim - 1))
    return jnp.where(keep, packed, jnp.zeros_like(packed)), cnt


def _unsorted_segment(op_name, kind):
    def f(data, segment_ids, num_segments=None):
        n = int(num_segments)
        if kind == "one":
            init = jnp.ones((), data.dtype)
        else:
            if jnp.issubdtype(data.dtype, jnp.floating):
                ext = jnp.finfo(data.dtype)
            else:
                ext = jnp.iinfo(data.dtype)
            init = ext.min if kind == "max" else ext.max
        out = jnp.full((n,) + data.shape[segment_ids.ndim:], init, data.dtype)
        return getattr(out.at[segment_ids.reshape(-1)],
                       op_name)(data.reshape((-1,) + data.shape[segment_ids.ndim:]))
    return f


register("unsorted_segment_max")(_unsorted_segment("max", "max"))
register("unsorted_segment_min")(_unsorted_segment("min", "min"))
register("unsorted_segment_prod")(_unsorted_segment("mul", "one"))


@register("unsorted_segment_mean")
def _unsorted_segment_mean(data, segment_ids, num_segments=None):
    n = int(num_segments)
    flat = data.reshape((-1,) + data.shape[segment_ids.ndim:])
    ids = segment_ids.reshape(-1)
    tot = jnp.zeros((n,) + flat.shape[1:], data.dtype).at[ids].add(flat)
    cnt = jnp.zeros((n,), data.dtype).at[ids].add(1.0)
    return tot / jnp.maximum(cnt, 1.0).reshape((n,) + (1,) * (flat.ndim - 1))


@register("bucketize")
def _bucketize(a, boundaries=()):
    return jnp.searchsorted(jnp.asarray(list(boundaries)), a, side="right")


@register("tensor_scatter_update")
def _tensor_scatter_update(a, indices, updates):
    return a.at[tuple(jnp.moveaxis(indices, -1, 0))].set(updates)


@register("batch_to_space_nd")
def _batch_to_space_nd(a, block_shape=(2, 2), crops=((0, 0), (0, 0))):
    bh, bw = int(block_shape[0]), int(block_shape[1])
    n, h, w, c = a.shape
    nb = n // (bh * bw)
    x = a.reshape(bh, bw, nb, h, w, c).transpose(2, 3, 0, 4, 1, 5)
    x = x.reshape(nb, h * bh, w * bw, c)
    (ct, cb), (cl, cr) = crops
    return x[:, int(ct):h * bh - int(cb), int(cl):w * bw - int(cr), :]


@register("space_to_batch_nd")
def _space_to_batch_nd(a, block_shape=(2, 2), paddings=((0, 0), (0, 0))):
    bh, bw = int(block_shape[0]), int(block_shape[1])
    (pt, pb), (pl, pr) = paddings
    a = jnp.pad(a, ((0, 0), (int(pt), int(pb)), (int(pl), int(pr)), (0, 0)))
    n, h, w, c = a.shape
    x = a.reshape(n, h // bh, bh, w // bw, bw, c).transpose(2, 4, 0, 1, 3, 5)
    return x.reshape(n * bh * bw, h // bh, w // bw, c)


@register("fake_quant_with_min_max_vars")
def _fake_quant(a, vmin=-6.0, vmax=6.0, num_bits=8):
    levels = float(2 ** int(num_bits) - 1)
    scale = (vmax - vmin) / levels
    q = jnp.round((jnp.clip(a, vmin, vmax) - vmin) / scale)
    return q * scale + vmin


def _quant_broadcast(v, ndim: int, axis):
    """Reshape a per-channel scale/zero-point array so it broadcasts along
    ``axis`` of a rank-``ndim`` tensor (scalars pass through untouched)."""
    v = jnp.asarray(v)
    if v.ndim == 0 or axis is None:
        return v
    if v.ndim != 1:
        raise ValueError(f"per-channel quantization expects a 1-D "
                         f"scale/zero-point array, got shape {v.shape}")
    ax = axis % ndim
    shape = [1] * ndim
    shape[ax] = v.shape[0]
    return v.reshape(shape)


@register("quantize")
def _quantize(a, scale=1.0, zero_point=0, dtype="int8", axis=None,
              narrow_range=False):
    """Affine quantization ``q = clip(round(a / scale) + zero_point)``.

    Serving-grade semantics (ISSUE 8): ``scale``/``zero_point`` may be
    per-channel 1-D arrays broadcast along ``axis`` (e.g. per-output-channel
    int8 weights with ``axis=-1``); ``zero_point=0`` everywhere is the
    symmetric scheme, a nonzero/array ``zero_point`` the asymmetric one;
    ``narrow_range`` drops the most negative code (``[-127, 127]`` for
    int8) so symmetric int8 stays sign-symmetric. f64 inputs are accepted
    (rounding happens in the input's own floating dtype before the integer
    cast, under whatever precision jax canonicalizes to)."""
    a = jnp.asarray(a)
    if not jnp.issubdtype(a.dtype, jnp.floating):
        a = a.astype(jnp.float32)
    scale = _quant_broadcast(jnp.asarray(scale, a.dtype), a.ndim, axis)
    zp = _quant_broadcast(jnp.asarray(zero_point), a.ndim, axis)
    info = jnp.iinfo(jnp.dtype(dtype))
    lo = info.min + 1 if narrow_range else info.min
    return jnp.clip(jnp.round(a / scale) + zp, lo, info.max).astype(dtype)


@register("dequantize")
def _dequantize(q, scale=1.0, zero_point=0, axis=None, dtype="float32"):
    """Inverse affine map ``(q - zero_point) * scale`` in ``dtype``
    (float32 default; pass ``float64`` to reconstruct f64 inputs).
    ``scale``/``zero_point`` accept the same per-channel 1-D arrays as
    :func:`_quantize` (broadcast along ``axis``)."""
    q = jnp.asarray(q)
    out_dt = jnp.dtype(dtype)
    scale = _quant_broadcast(jnp.asarray(scale, out_dt), q.ndim, axis)
    zp = _quant_broadcast(jnp.asarray(zero_point), q.ndim, axis)
    return (q.astype(out_dt) - zp.astype(out_dt)) * scale


@register("adjust_hue")
def _adjust_hue(img, delta=0.0):
    from deeplearning4j_tpu.autodiff.ops_registry import OPS as _O
    hsv = _O["rgb_to_hsv"](img)
    h = jnp.mod(hsv[..., 0:1] + delta, 1.0)
    return _O["hsv_to_rgb"](jnp.concatenate([h, hsv[..., 1:]], axis=-1))


@register("adjust_gamma")
def _adjust_gamma(img, gamma=1.0, gain=1.0):
    return gain * img ** gamma


@register("grayscale_to_rgb")
def _grayscale_to_rgb(img):
    return jnp.repeat(img, 3, axis=-1) if img.shape[-1] == 1 \
        else jnp.stack([img] * 3, axis=-1)


@register("per_image_standardization")
def _per_image_standardization(img):
    axes = tuple(range(1, img.ndim))
    n = 1
    for a in axes:
        n *= img.shape[a]
    mean = jnp.mean(img, axis=axes, keepdims=True)
    std = jnp.maximum(jnp.std(img, axis=axes, keepdims=True),
                      1.0 / math.sqrt(n))
    return (img - mean) / std


@register("total_variation")
def _total_variation(img):
    dh = jnp.abs(img[:, 1:, :, :] - img[:, :-1, :, :])
    dw = jnp.abs(img[:, :, 1:, :] - img[:, :, :-1, :])
    axes = tuple(range(1, img.ndim))
    return jnp.sum(dh, axis=axes) + jnp.sum(dw, axis=axes)


@register("extract_image_patches")
def _extract_image_patches(img, ksizes=(1, 3, 3, 1), strides=(1, 1, 1, 1),
                           rates=(1, 1, 1, 1), padding="VALID"):
    if any(int(r) != 1 for r in rates):
        raise NotImplementedError(
            f"extract_image_patches with rates={tuple(rates)} (dilated "
            "patches) is not implemented")
    kh, kw = int(ksizes[1]), int(ksizes[2])
    sh, sw = int(strides[1]), int(strides[2])
    n, h, w, c = img.shape
    patches = jax.lax.conv_general_dilated_patches(
        img, (kh, kw), (sh, sw), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # conv_patches emits C-major (c, kh, kw); TF wants (kh, kw, c)
    nh, nw = patches.shape[1], patches.shape[2]
    return patches.reshape(n, nh, nw, c, kh, kw).transpose(
        0, 1, 2, 4, 5, 3).reshape(n, nh, nw, kh * kw * c)


@register("col2im")
def _col2im(cols, out_h=None, out_w=None, kernel=(3, 3), stride=(1, 1)):
    """Inverse of im2col (overlap-add): cols (N, nh, nw, kh*kw*C) back to
    (N, H, W, C). The reference's col2im declarable op."""
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    n, nh, nw, _ = cols.shape
    c = cols.shape[-1] // (kh * kw)
    H, W = int(out_h), int(out_w)
    out = jnp.zeros((n, H, W, c), cols.dtype)
    cols = cols.reshape(n, nh, nw, kh, kw, c)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, i:i + nh * sh:sh, j:j + nw * sw:sw, :].add(
                cols[:, :, :, i, j, :])
    return out


# -- signal/window family (reference [U] declarable ops + tf.signal) --
register("hann_window")(lambda n, periodic=True: jnp.hanning(int(n) + 1)[:-1]
                        if periodic else jnp.hanning(int(n)))
register("hamming_window")(lambda n, periodic=True: jnp.hamming(int(n) + 1)[:-1]
                           if periodic else jnp.hamming(int(n)))
register("blackman_window")(lambda n, periodic=True: jnp.blackman(int(n) + 1)[:-1]
                            if periodic else jnp.blackman(int(n)))


@register("frame")
def _frame(a, frame_length=256, frame_step=128, axis=-1):
    fl, fs = int(frame_length), int(frame_step)
    ax = int(axis) % a.ndim
    n = a.shape[ax]
    num = max(0, (n - fl) // fs + 1)
    a = jnp.moveaxis(a, ax, -1)
    idx = jnp.arange(num)[:, None] * fs + jnp.arange(fl)[None, :]
    out = a[..., idx]  # (..., num, fl)
    return out if ax == a.ndim - 1 else jnp.moveaxis(out, (-2, -1), (ax, ax + 1))


@register("overlap_and_add")
def _overlap_and_add(frames, frame_step=128):
    fs = int(frame_step)
    num, fl = frames.shape[-2], frames.shape[-1]
    out_len = (num - 1) * fs + fl
    out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
    for i in range(num):
        out = out.at[..., i * fs:i * fs + fl].add(frames[..., i, :])
    return out


@register("stft")
def _stft(a, frame_length=256, frame_step=128, fft_length=None):
    fl = int(frame_length)
    frames = _frame(a, fl, frame_step)
    win = jnp.hanning(fl + 1)[:-1].astype(a.dtype)
    return jnp.fft.rfft(frames * win,
                        n=int(fft_length) if fft_length else fl)


@register("istft")
def _istft(spec, frame_length=256, frame_step=128):
    fl, fs = int(frame_length), int(frame_step)
    frames = jnp.fft.irfft(spec, n=fl)
    win = jnp.hanning(fl + 1)[:-1]
    acc = _overlap_and_add(frames * win, fs)
    norm = _overlap_and_add(jnp.broadcast_to(win * win, frames.shape), fs)
    return acc / jnp.maximum(norm, 1e-12)


# ------------------------------------------------------- registry wave 7
# (round 3 cont.: math/complex/loss tails + the reference's native updater
# ops — upstream org.nd4j.linalg.learning applied as single fused ops)

register("cbrt")(jnp.cbrt)
register("log2")(jnp.log2)
register("log10")(jnp.log10)
register("logaddexp")(jnp.logaddexp)
register("logaddexp2")(jnp.logaddexp2)
register("hypot")(jnp.hypot)
register("copysign")(jnp.copysign)
register("deg2rad")(jnp.deg2rad)
register("rad2deg")(jnp.rad2deg)
register("heaviside")(jnp.heaviside)
register("signbit")(jnp.signbit)
register("float_power")(jnp.float_power)
register("gammaln")(lambda a: jax.scipy.special.gammaln(a))
register("betaln")(lambda a, b: jax.scipy.special.betaln(a, b))
register("factorial")(lambda n: jnp.exp(jax.scipy.special.gammaln(n + 1.0)))
register("i0")(lambda a: jax.scipy.special.i0(a))
register("i0e")(lambda a: jax.scipy.special.i0e(a))
register("i1")(lambda a: jax.scipy.special.i1(a))
register("i1e")(lambda a: jax.scipy.special.i1e(a))
register("exprel")(lambda a: jnp.where(jnp.abs(a) < 1e-6, 1.0 + a / 2,
                                       jnp.expm1(a) / jnp.where(
                                           jnp.abs(a) < 1e-6, 1.0, a)))
register("squareplus")(lambda a, b=4.0: 0.5 * (a + jnp.sqrt(a * a + b)))
register("angle")(jnp.angle)
register("real")(jnp.real)
register("imag")(jnp.imag)
register("conj")(jnp.conj)
register("complex")(lambda re, im: jax.lax.complex(re, im))
register("polar")(lambda mag, ang: jax.lax.complex(mag * jnp.cos(ang),
                                                   mag * jnp.sin(ang)))
register("clamp")(lambda a, lo=0.0, hi=1.0: jnp.clip(a, lo, hi))
register("fix")(jnp.trunc)
register("fliplr")(jnp.fliplr)
register("flipud")(jnp.flipud)
register("lerp")(lambda a, b, t=0.5: a + (b - a) * t)
register("addcmul")(lambda a, b, c, value=1.0: a + value * b * c)
register("addcdiv")(lambda a, b, c, value=1.0: a + value * b / c)
register("round_half_to_even")(jnp.round)  # jnp.round IS banker's rounding
register("isneginf")(jnp.isneginf)
register("isposinf")(jnp.isposinf)
register("population_count")(lambda a: lax.population_count(
    a.astype(jnp.uint32)).astype(jnp.int32))
register("bitwise_not")(jnp.bitwise_not)
@register("eye_like")
def _eye_like(a):
    if a.ndim < 2:
        raise ValueError(f"eye_like needs rank>=2, got shape {a.shape}")
    e = jnp.eye(a.shape[-2], a.shape[-1], dtype=a.dtype)
    return jnp.broadcast_to(e, a.shape)
register("tril_indices")(lambda n, k=0: jnp.stack(jnp.tril_indices(int(n), int(k))))
register("triu_indices")(lambda n, k=0: jnp.stack(jnp.triu_indices(int(n), int(k))))
register("in1d")(lambda a, b: jnp.isin(a, b))
register("list_diff")(lambda a, b: OPS["setdiff1d"](a, b))


@register("unique_counts")
def _unique_counts(a, size=None):
    """unique values + counts, zero-padded to ``size`` (default a.size) —
    the XLA static-shape contract (jnp.unique with size=)."""
    n = int(size) if size is not None else int(a.size)
    vals, counts = jnp.unique(a.reshape(-1), size=n, fill_value=0,
                              return_counts=True)
    return vals, counts


@register("global_norm")
def _global_norm(*tensors):
    return jnp.sqrt(sum(jnp.sum(jnp.square(t.astype(jnp.float32)))
                        for t in tensors))


@register("renorm")
def _renorm(a, p=2.0, axis=0, maxnorm=1.0):
    """Clip the p-norm of each slice along ``axis`` to maxnorm (torch-style
    renorm; the reference's per-row constraint op)."""
    axes = tuple(i for i in range(a.ndim) if i != (axis % a.ndim))
    norms = jnp.sum(jnp.abs(a) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > maxnorm, maxnorm / jnp.maximum(norms, 1e-12), 1.0)
    return a * scale


@register("clip_by_average_norm")
def _clip_by_average_norm(a, clip_norm=1.0):
    # TF semantics: scale so the AVERAGE (per-element) L2 norm is at most
    # clip_norm; unchanged when avg <= clip_norm
    avg = jnp.sqrt(jnp.sum(jnp.square(a))) / a.size
    return a * (clip_norm / jnp.maximum(avg, clip_norm))


# -- loss tail --
@register("binary_cross_entropy")
def _binary_cross_entropy(labels, probs, eps=1e-7):
    p = jnp.clip(probs, eps, 1.0 - eps)
    return -jnp.mean(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))


register("cross_entropy_with_logits")(
    lambda labels, logits: -jnp.mean(jnp.sum(
        labels * jax.nn.log_softmax(logits, axis=-1), axis=-1)))


@register("focal_loss")
def _focal_loss(labels, logits, gamma=2.0, alpha=0.25):
    p = jax.nn.sigmoid(logits)
    ce = -(labels * jnp.log(jnp.clip(p, 1e-7, 1.0))
           + (1 - labels) * jnp.log(jnp.clip(1 - p, 1e-7, 1.0)))
    pt = labels * p + (1 - labels) * (1 - p)
    w = (labels * alpha + (1 - labels) * (1 - alpha)) * (1 - pt) ** gamma
    return jnp.mean(w * ce)


@register("dice_loss")
def _dice_loss(labels, probs, eps=1.0):
    num = 2.0 * jnp.sum(labels * probs) + eps
    den = jnp.sum(labels) + jnp.sum(probs) + eps
    return 1.0 - num / den


@register("smooth_l1_loss")
def _smooth_l1_loss(labels, preds, beta=1.0):
    d = jnp.abs(preds - labels)
    return jnp.mean(jnp.where(d < beta, 0.5 * d * d / beta, d - 0.5 * beta))


@register("margin_ranking_loss")
def _margin_ranking_loss(x1, x2, y, margin=0.0):
    return jnp.mean(jnp.maximum(0.0, -y * (x1 - x2) + margin))


@register("cosine_embedding_loss")
def _cosine_embedding_loss(x1, x2, y, margin=0.0):
    cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    return jnp.mean(jnp.where(y > 0, 1.0 - cos,
                              jnp.maximum(0.0, cos - margin)))


# -- native updater ops (reference org.nd4j.linalg.learning.*Updater as
# fused ops: take (param, grad, state...) -> (new_param, new_state...)) --
@register("sgd_update")
def _sgd_update(param, grad, lr=0.01):
    return param - lr * grad


@register("momentum_update")
def _momentum_update(param, grad, v, lr=0.01, momentum=0.9, nesterov=False):
    v_new = momentum * v + grad
    step = (momentum * v_new + grad) if nesterov else v_new
    return param - lr * step, v_new


@register("adam_update")
def _adam_update(param, grad, m, v, t, lr=1e-3, beta1=0.9, beta2=0.999,
                 eps=1e-8):
    t = t + 1
    m_new = beta1 * m + (1 - beta1) * grad
    v_new = beta2 * v + (1 - beta2) * grad * grad
    mhat = m_new / (1 - beta1 ** t)
    vhat = v_new / (1 - beta2 ** t)
    return param - lr * mhat / (jnp.sqrt(vhat) + eps), m_new, v_new, t


@register("adagrad_update")
def _adagrad_update(param, grad, accum, lr=0.01, eps=1e-8):
    # eps INSIDE the sqrt — the reference AdaGradUpdater's form; outside
    # it, a near-zero state gives first steps ~1/eps larger
    accum_new = accum + grad * grad
    return param - lr * grad / jnp.sqrt(accum_new + eps), accum_new


@register("rmsprop_update")
def _rmsprop_update(param, grad, ms, lr=0.001, decay=0.9, eps=1e-8):
    # eps INSIDE the sqrt (reference RmsPropUpdater)
    ms_new = decay * ms + (1 - decay) * grad * grad
    return param - lr * grad / jnp.sqrt(ms_new + eps), ms_new


@register("lars_update")
def _lars_update(param, grad, lr=0.01, trust=0.001, weight_decay=0.0):
    g = grad + weight_decay * param
    pn = jnp.linalg.norm(param.reshape(-1))
    gn = jnp.linalg.norm(g.reshape(-1))
    local_lr = jnp.where(gn > 0, trust * pn / jnp.maximum(gn, 1e-12), 1.0)
    return param - lr * local_lr * g


# ------------------------------------------------------- registry wave 8
# (round 3 final: image colorspace/crop/augment family, statistics, polynomial/
# signal math, scatter variants — crossing the reference's ~500-op scale)

import numpy as _np

# numpy (host) constants: module import must not allocate device buffers
_YIQ = _np.array([[0.299, 0.587, 0.114],
                  [0.59590059, -0.27455667, -0.32134392],
                  [0.21153661, -0.52273617, 0.31119955]], _np.float32)
_YUV = _np.array([[0.299, 0.587, 0.114],
                  [-0.14714119, -0.28886916, 0.43601035],
                  [0.61497538, -0.51496512, -0.10001026]], _np.float32)

_YIQ_INV = _np.linalg.inv(_YIQ)
_YUV_INV = _np.linalg.inv(_YUV)

register("rgb_to_yiq")(lambda img: img @ _YIQ.T.astype(img.dtype))
register("yiq_to_rgb")(lambda img: img @ _YIQ_INV.T.astype(img.dtype))
register("rgb_to_yuv")(lambda img: img @ _YUV.T.astype(img.dtype))
register("yuv_to_rgb")(lambda img: img @ _YUV_INV.T.astype(img.dtype))


@register("central_crop")
def _central_crop(img, fraction=1.0):
    h, w = img.shape[-3], img.shape[-2]
    ch, cw = int(round(h * fraction)), int(round(w * fraction))
    top, left = (h - ch) // 2, (w - cw) // 2
    return img[..., top:top + ch, left:left + cw, :]


@register("pad_to_bounding_box")
def _pad_to_bounding_box(img, offset_height=0, offset_width=0,
                         target_height=None, target_width=None):
    h, w = img.shape[-3], img.shape[-2]
    th, tw = int(target_height), int(target_width)
    pads = [(0, 0)] * (img.ndim - 3) + [
        (int(offset_height), th - h - int(offset_height)),
        (int(offset_width), tw - w - int(offset_width)), (0, 0)]
    return jnp.pad(img, pads)


@register("resize_with_crop_or_pad")
def _resize_with_crop_or_pad(img, target_height=None, target_width=None):
    h, w = img.shape[-3], img.shape[-2]
    th, tw = int(target_height), int(target_width)
    if h > th:
        top = (h - th) // 2
        img = img[..., top:top + th, :, :]
    if w > tw:
        left = (w - tw) // 2
        img = img[..., :, left:left + tw, :]
    h, w = img.shape[-3], img.shape[-2]
    if h < th or w < tw:
        img = _pad_to_bounding_box(img, (th - h) // 2, (tw - w) // 2, th, tw)
    return img


@register("random_crop")
def _random_crop(img, size=(), seed=0, key=None):
    size = tuple(int(s) for s in size)
    key = _key(seed, key)
    starts = []
    for dim, s in zip(img.shape, size):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, dim - s + 1))
    return jax.lax.dynamic_slice(img, starts, size)


@register("random_flip_left_right")
def _random_flip_left_right(img, seed=0, key=None):
    flip = jax.random.bernoulli(_key(seed, key), 0.5)
    return jnp.where(flip, img[..., :, ::-1, :], img)


@register("random_brightness")
def _random_brightness(img, max_delta=0.1, seed=0, key=None):
    delta = jax.random.uniform(_key(seed, key), (), minval=-max_delta,
                               maxval=max_delta)
    return img + delta.astype(img.dtype)


@register("random_contrast")
def _random_contrast(img, lower=0.8, upper=1.2, seed=0, key=None):
    f = jax.random.uniform(_key(seed, key), (), minval=lower, maxval=upper)
    mean = jnp.mean(img, axis=(-3, -2), keepdims=True)
    return (img - mean) * f.astype(img.dtype) + mean


@register("sobel_edges")
def _sobel_edges(img):
    """(B, H, W, C) -> (B, H, W, C, 2) [dy, dx] (tf.image.sobel_edges)."""
    ky = jnp.array([[-1, -2, -1], [0, 0, 0], [1, 2, 1]], img.dtype)
    kx = ky.T
    c = img.shape[-1]
    k = jnp.stack([ky, kx], -1)                      # (3,3,2)
    k = jnp.tile(k[:, :, None, :], (1, 1, c, 1))     # (3,3,C,2)
    pad = jnp.pad(img, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="reflect")
    out = jax.lax.conv_general_dilated(
        pad, k.reshape(3, 3, 1, c * 2), (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)
    return out.reshape(img.shape + (2,))


@register("image_gradients")
def _image_gradients(img):
    dy = jnp.concatenate([img[:, 1:] - img[:, :-1],
                          jnp.zeros_like(img[:, :1])], axis=1)
    dx = jnp.concatenate([img[:, :, 1:] - img[:, :, :-1],
                          jnp.zeros_like(img[:, :, :1])], axis=2)
    return dy, dx


@register("draw_bounding_boxes")
def _draw_bounding_boxes(img, boxes, color=1.0):
    """Burn box OUTLINES into images; boxes (B, N, 4) normalized
    [ymin, xmin, ymax, xmax] (tf.image.draw_bounding_boxes semantics)."""
    b, h, w, c = img.shape
    ys = jnp.arange(h)[None, :, None]  # (1,H,1)
    xs = jnp.arange(w)[None, None, :]  # (1,1,W)
    out = img
    for i in range(boxes.shape[1]):
        y0 = jnp.round(boxes[:, i, 0] * (h - 1))[:, None, None]
        x0 = jnp.round(boxes[:, i, 1] * (w - 1))[:, None, None]
        y1 = jnp.round(boxes[:, i, 2] * (h - 1))[:, None, None]
        x1 = jnp.round(boxes[:, i, 3] * (w - 1))[:, None, None]
        in_y = (ys >= y0) & (ys <= y1)
        in_x = (xs >= x0) & (xs <= x1)
        edge = (in_y & in_x) & ((ys == y0) | (ys == y1)
                                | (xs == x0) | (xs == x1))
        out = jnp.where(edge[..., None], color, out)
    return out


@register("psnr")
def _psnr(a, b, max_val=1.0):
    mse = jnp.mean(jnp.square(a.astype(jnp.float32) - b.astype(jnp.float32)),
                   axis=(-3, -2, -1))
    return 10.0 * jnp.log10(max_val * max_val / jnp.maximum(mse, 1e-12))


@register("ssim")
def _ssim(a, b, max_val=1.0, filter_size=11, k1=0.01, k2=0.03):
    """Mean SSIM with a uniform window (TF uses Gaussian; uniform keeps the
    kernel fully in-registry — documented approximation)."""
    c1, c2 = (k1 * max_val) ** 2, (k2 * max_val) ** 2
    f = int(filter_size)
    win = (1, f, f, 1)

    def mean_pool(x):
        return lax.reduce_window(x, 0.0, lax.add, win, (1, 1, 1, 1),
                                 "VALID") / (f * f)

    af, bf = a.astype(jnp.float32), b.astype(jnp.float32)
    mu_a, mu_b = mean_pool(af), mean_pool(bf)
    var_a = mean_pool(af * af) - mu_a * mu_a
    var_b = mean_pool(bf * bf) - mu_b * mu_b
    cov = mean_pool(af * bf) - mu_a * mu_b
    s = ((2 * mu_a * mu_b + c1) * (2 * cov + c2)) / (
        (mu_a * mu_a + mu_b * mu_b + c1) * (var_a + var_b + c2))
    return jnp.mean(s, axis=(-3, -2, -1))


# -- statistics --
@register("mode")
def _mode(a):
    vals, counts = jnp.unique(a.reshape(-1), size=int(a.size),
                              fill_value=0, return_counts=True)
    return vals[jnp.argmax(counts)]


@register("skewness")
def _skewness(a, axis=None):
    m = jnp.mean(a, axis=axis, keepdims=True)
    s = jnp.std(a, axis=axis, keepdims=True)
    return jnp.mean(((a - m) / jnp.maximum(s, 1e-12)) ** 3, axis=axis)


@register("kurtosis")
def _kurtosis(a, axis=None, fisher=True):
    m = jnp.mean(a, axis=axis, keepdims=True)
    s = jnp.std(a, axis=axis, keepdims=True)
    k = jnp.mean(((a - m) / jnp.maximum(s, 1e-12)) ** 4, axis=axis)
    return k - 3.0 if fisher else k


@register("weighted_mean")
def _weighted_mean(a, weights, axis=None):
    return jnp.sum(a * weights, axis=axis) / jnp.sum(weights, axis=axis)


@register("pearson_correlation")
def _pearson_correlation(a, b):
    af, bf = a.reshape(-1), b.reshape(-1)
    am, bm = af - jnp.mean(af), bf - jnp.mean(bf)
    return jnp.sum(am * bm) / jnp.maximum(
        jnp.linalg.norm(am) * jnp.linalg.norm(bm), 1e-12)


@register("covariance_matrix")
def _covariance_matrix(a, rowvar=False, ddof=1):
    """Columns (rowvar=False) are variables, rows observations."""
    x = a if rowvar else a.T
    x = x - jnp.mean(x, axis=1, keepdims=True)
    n = x.shape[1]
    return (x @ x.T) / max(n - int(ddof), 1)


@register("correlation_matrix")
def _correlation_matrix(a, rowvar=False):
    c = _covariance_matrix(a, rowvar=rowvar)
    d = jnp.sqrt(jnp.diagonal(c))
    return c / jnp.maximum(jnp.outer(d, d), 1e-12)


# -- polynomial / signal math --
register("polyval")(lambda coeffs, x: jnp.polyval(coeffs, x))
register("interp")(lambda x, xp, fp: jnp.interp(x, xp, fp))
register("gradient")(lambda a, axis=None: (jnp.gradient(a) if axis is None
                                           else jnp.gradient(a, axis=axis)))
register("trapz")(lambda y, dx=1.0: jnp.trapezoid(y, dx=dx))
register("convolve")(lambda a, v, mode="full": jnp.convolve(a, v, mode=mode))
register("correlate")(lambda a, v, mode="full": jnp.correlate(a, v, mode=mode))
register("toeplitz")(lambda c, r=None: jax.scipy.linalg.toeplitz(
    c, r if r is not None else c))
register("block_diag")(lambda *ms: jax.scipy.linalg.block_diag(*ms))
register("cond")(lambda a, p=None: jnp.linalg.cond(a, p))
register("matrix_rank")(lambda a: jnp.linalg.matrix_rank(a))
register("multi_dot")(lambda *ms: jnp.linalg.multi_dot(ms))
register("log_matrix_determinant")(OPS["slogdet"])  # TF alias
register("softmax_cross_entropy_with_logits_v2")(
    lambda labels, logits: -jnp.sum(
        labels * jax.nn.log_softmax(logits, axis=-1), axis=-1))


@register("pad_sequences")
def _pad_sequences(seqs, maxlen=None, value=0.0):
    """List of 1-D arrays -> (N, maxlen) right-padded matrix (keras util /
    reference sequence-batching helper)."""
    seqs = [jnp.asarray(s).reshape(-1) for s in seqs]
    m = int(maxlen) if maxlen is not None else max(int(s.shape[0]) for s in seqs)
    out = jnp.full((len(seqs), m), value, seqs[0].dtype)  # keep int token ids
    for i, s in enumerate(seqs):
        k = min(int(s.shape[0]), m)
        out = out.at[i, :k].set(s[:k].astype(out.dtype))
    return out


@register("ctc_greedy_decoder")
def _ctc_greedy_decoder(log_probs, blank=0):
    """(T, B, V) log-probs -> (B, T) best-path labels with repeats+blanks
    collapsed, padded with -1, plus (B,) lengths (static-shape contract)."""
    path = jnp.argmax(log_probs, axis=-1).T      # (B, T)
    prev = jnp.concatenate([jnp.full_like(path[:, :1], -1), path[:, :-1]], 1)
    keep = (path != blank) & (path != prev)
    b, t = path.shape
    order = jnp.argsort(jnp.where(keep, 0, 1) * t + jnp.arange(t)[None, :],
                        axis=1)
    packed = jnp.take_along_axis(path, order, axis=1)
    lens = jnp.sum(keep, axis=1)
    out = jnp.where(jnp.arange(t)[None, :] < lens[:, None], packed, -1)
    return out, lens


# -- scatter variants --
@register("tensor_scatter_add")
def _tensor_scatter_add(a, indices, updates):
    return a.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


@register("tensor_scatter_min")
def _tensor_scatter_min(a, indices, updates):
    return a.at[tuple(jnp.moveaxis(indices, -1, 0))].min(updates)


@register("tensor_scatter_max")
def _tensor_scatter_max(a, indices, updates):
    return a.at[tuple(jnp.moveaxis(indices, -1, 0))].max(updates)


@register("sparse_to_dense")
def _sparse_to_dense(indices, output_shape, values, default_value=0.0):
    out = jnp.full(tuple(int(s) for s in output_shape), default_value,
                   jnp.asarray(values).dtype)  # TF: dtype follows values
    if indices.ndim == 1:
        return out.at[indices].set(values)
    return out.at[tuple(jnp.moveaxis(indices, -1, 0))].set(values)
