"""Named op registry for the declarative graph.

Every graph op is registered by name so graphs serialize as data (the
FlatBuffers-schema analog of the reference: op nodes store op NAME + attrs,
never code). The callables take jnp arrays (+ static attrs) and are traceable
under jit. Covers the reference's op namespaces used by SameDiff programs and
the TF importer's op set (upstream ``org.nd4j.autodiff.samediff.ops.*``).
"""

from __future__ import annotations

from typing import Callable, Dict

import math

import jax
import jax.numpy as jnp
from jax import lax

OPS: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        OPS[name] = fn
        return fn
    return deco


def get_op(name: str) -> Callable:
    if name not in OPS:
        raise KeyError(f"Unknown op {name!r}; registered: {sorted(OPS)[:40]}...")
    return OPS[name]


# ---- elementwise binary ----
register("add")(lambda a, b: a + b)
register("sub")(lambda a, b: a - b)
register("mul")(lambda a, b: a * b)
register("div")(lambda a, b: a / b)
register("pow")(lambda a, b: a ** b)
register("mod")(lambda a, b: jnp.mod(a, b))
register("maximum")(jnp.maximum)
register("minimum")(jnp.minimum)
register("squared_difference")(lambda a, b: (a - b) ** 2)
register("floordiv")(lambda a, b: jnp.floor_divide(a, b))

# comparisons (float outputs, like the reference)
register("gt")(lambda a, b: (a > b))
register("gte")(lambda a, b: (a >= b))
register("lt")(lambda a, b: (a < b))
register("lte")(lambda a, b: (a <= b))
register("eq")(lambda a, b: (a == b))
register("neq")(lambda a, b: (a != b))
register("logical_and")(jnp.logical_and)
register("logical_or")(jnp.logical_or)
register("logical_not")(jnp.logical_not)
register("where")(jnp.where)

# ---- elementwise unary ----
register("neg")(lambda a: -a)
register("abs")(jnp.abs)
register("exp")(jnp.exp)
register("log")(jnp.log)
register("log1p")(jnp.log1p)
register("sqrt")(jnp.sqrt)
register("rsqrt")(lax.rsqrt)
register("square")(jnp.square)
register("sign")(jnp.sign)
register("floor")(jnp.floor)
register("ceil")(jnp.ceil)
register("round")(jnp.round)
register("sin")(jnp.sin)
register("cos")(jnp.cos)
register("tan")(jnp.tan)
register("asin")(jnp.arcsin)
register("acos")(jnp.arccos)
register("atan")(jnp.arctan)
register("sinh")(jnp.sinh)
register("cosh")(jnp.cosh)
register("tanh")(jnp.tanh)
register("erf")(jax.scipy.special.erf)
register("sigmoid")(jax.nn.sigmoid)
register("relu")(jax.nn.relu)
register("relu6")(jax.nn.relu6)
register("leaky_relu")(lambda a, alpha=0.01: jax.nn.leaky_relu(a, alpha))
register("elu")(jax.nn.elu)
register("selu")(jax.nn.selu)
register("gelu")(jax.nn.gelu)
register("softplus")(jax.nn.softplus)
register("softsign")(jax.nn.soft_sign)
register("swish")(jax.nn.swish)
register("mish")(jax.nn.mish)
register("hard_sigmoid")(jax.nn.hard_sigmoid)
register("reciprocal")(lambda a: 1.0 / a)
register("clip_by_value")(lambda a, lo=0.0, hi=1.0: jnp.clip(a, lo, hi))
register("cast")(lambda a, dtype="float32": a.astype(jnp.dtype(dtype)))
register("identity")(lambda a: a)
register("stop_gradient")(lax.stop_gradient)
register("dropout")(lambda a, key=None, rate=0.5: a)  # inference no-op; fit wires rng


# ---- matmul / linalg ----
@register("matmul")
def _matmul(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return a @ b


register("batch_matmul")(lambda a, b, transpose_a=False, transpose_b=False:
                         _matmul(a, b, transpose_a, transpose_b))
register("tensordot")(lambda a, b, axes=2: jnp.tensordot(a, b, axes))
register("outer")(jnp.outer)
register("dot")(jnp.dot)
register("norm2")(lambda a, axis=None: jnp.sqrt(jnp.sum(a * a, axis=axis)))
register("l2_normalize")(lambda a, axis=-1, eps=1e-12:
                         a / jnp.sqrt(jnp.maximum(jnp.sum(a * a, axis=axis, keepdims=True), eps)))

# ---- reductions ----
register("reduce_sum")(lambda a, axis=None, keepdims=False: jnp.sum(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_mean")(lambda a, axis=None, keepdims=False: jnp.mean(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_max")(lambda a, axis=None, keepdims=False: jnp.max(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_min")(lambda a, axis=None, keepdims=False: jnp.min(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_prod")(lambda a, axis=None, keepdims=False: jnp.prod(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_var")(lambda a, axis=None, keepdims=False: jnp.var(a, axis=_ax(axis), keepdims=keepdims))
register("reduce_std")(lambda a, axis=None, keepdims=False: jnp.std(a, axis=_ax(axis), keepdims=keepdims))
register("argmax")(lambda a, axis=-1: jnp.argmax(a, axis=axis))
register("argmin")(lambda a, axis=-1: jnp.argmin(a, axis=axis))
register("cumsum")(lambda a, axis=0: jnp.cumsum(a, axis=axis))
register("logsumexp")(lambda a, axis=None, keepdims=False:
                      jax.scipy.special.logsumexp(a, axis=_ax(axis), keepdims=keepdims))


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---- shape ----
register("reshape")(lambda a, shape=(): jnp.reshape(
    a, tuple(a.shape[i] if int(s) == 0 else int(s)  # 0 = copy dim (ONNX/TF)
             for i, s in enumerate(shape))))
register("transpose")(lambda a, perm=None: jnp.transpose(a, perm))
register("expand_dims")(lambda a, axis=0: jnp.expand_dims(a, axis))
register("squeeze")(lambda a, axis=None: jnp.squeeze(a, axis))
register("concat")(lambda *arrays, axis=0: jnp.concatenate(arrays, axis=axis))
register("stack")(lambda *arrays, axis=0: jnp.stack(arrays, axis=axis))


@register("unstack")
def _unstack(a, axis=0, num=None):
    n = num if num is not None else a.shape[axis]
    return tuple(jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis))


@register("split")
def _split(a, num_splits=2, axis=0):
    return tuple(jnp.split(a, num_splits, axis=axis))


register("tile")(lambda a, multiples=(): jnp.tile(a, tuple(int(m) for m in multiples)))
register("slice")(lambda a, begin=(), size=():
                  lax.slice(a, tuple(int(b) for b in begin),
                            tuple(int(b) + int(s) for b, s in zip(begin, size))))


@register("strided_slice")
def _strided_slice(a, begin=(), end=(), strides=None, begin_mask=0, end_mask=0,
                   shrink_axis_mask=0, new_axis_mask=0, ellipsis_mask=0):
    # numpy-style basic indexing reconstruction (TF StridedSlice semantics)
    strides = strides or [1] * len(begin)
    idx = []
    in_dim = 0
    for i in range(len(begin)):
        if ellipsis_mask & (1 << i):
            idx.append(Ellipsis)
            in_dim = a.ndim - (len(begin) - i - 1)
            continue
        if new_axis_mask & (1 << i):
            idx.append(None)
            continue
        b = None if (begin_mask & (1 << i)) else int(begin[i])
        e = None if (end_mask & (1 << i)) else int(end[i])
        s = int(strides[i])
        if shrink_axis_mask & (1 << i):
            idx.append(int(begin[i]))
        else:
            idx.append(slice(b, e, s))
        in_dim += 1
    return a[tuple(idx)]


register("gather")(lambda a, indices, axis=0: jnp.take(a, indices.astype(jnp.int32), axis=axis))


@register("gather_nd")
def _gather_nd(a, indices):
    idx = tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))
    return a[idx]


@register("scatter_update")
def _scatter_update(a, indices, updates):
    return a.at[indices.astype(jnp.int32)].set(updates)


register("one_hot")(lambda a, depth=2, on_value=1.0, off_value=0.0, axis=-1:
                    jax.nn.one_hot(a.astype(jnp.int32), depth, axis=axis) * (on_value - off_value) + off_value)
def _pad(a, paddings=(), constant_value=0.0, mode="constant"):
    pads = tuple(tuple(int(x) for x in p) for p in paddings)
    if mode == "constant":
        return jnp.pad(a, pads, constant_values=constant_value)
    return jnp.pad(a, pads, mode=mode)  # 'reflect' / 'edge' / 'wrap'


register("pad")(_pad)


register("flatten2d")(lambda a, axis=1: jnp.reshape(
    a, (math.prod(a.shape[:axis]) if axis else 1, -1)))
register("reverse")(lambda a, axis=0: jnp.flip(a, axis))
register("shape_of")(lambda a: jnp.asarray(a.shape, jnp.int32))
register("size")(lambda a: jnp.asarray(a.size, jnp.int32))
register("rank")(lambda a: jnp.asarray(a.ndim, jnp.int32))
register("fill")(lambda shape, value=0.0: jnp.full(tuple(int(s) for s in shape), value))
register("zeros_like")(jnp.zeros_like)
register("ones_like")(jnp.ones_like)
register("linspace")(lambda start=0.0, stop=1.0, num=10: jnp.linspace(start, stop, int(num)))
register("range")(lambda start=0, limit=10, delta=1: jnp.arange(start, limit, delta))

# ---- nn ----
register("softmax")(lambda a, axis=-1: jax.nn.softmax(a, axis=axis))
register("log_softmax")(lambda a, axis=-1: jax.nn.log_softmax(a, axis=axis))


@register("layer_norm")
def _layer_norm(x, gain, bias=None, axis=-1, eps=1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps) * gain
    return out + bias if bias is not None else out


@register("batch_norm")
def _batch_norm(x, mean, variance, gamma=None, beta=None, eps=1e-5):
    out = (x - mean) * lax.rsqrt(variance + eps)
    if gamma is not None:
        out = out * gamma
    if beta is not None:
        out = out + beta
    return out


@register("bias_add")
def _bias_add(x, bias):
    return x + bias


@register("linear")
def _linear(x, w, b=None):
    y = x @ w
    return y + b if b is not None else y


@register("conv2d")
def _conv2d(x, w, b=None, stride=(1, 1), padding="SAME", dilation=(1, 1),
            groups=1):
    y = lax.conv_general_dilated(x, w, window_strides=tuple(stride), padding=padding,
                                 rhs_dilation=tuple(dilation),
                                 dimension_numbers=("NHWC", "HWIO", "NHWC"),
                                 feature_group_count=groups)
    return y + b if b is not None else y


@register("max_pool2d")
def _max_pool2d(x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, *kernel, 1), (1, *stride, 1), padding)


@register("avg_pool2d")
def _avg_pool2d(x, kernel=(2, 2), stride=(2, 2), padding="VALID",
                count_include_pad=False):
    s = lax.reduce_window(x, 0.0, lax.add, (1, *kernel, 1), (1, *stride, 1), padding)
    if count_include_pad:  # ONNX AveragePool count_include_pad=1
        return s / (kernel[0] * kernel[1])
    c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, (1, *kernel, 1), (1, *stride, 1), padding)
    return s / c


@register("multi_head_dot_product_attention")
def _mhdpa(q, k, v, mask=None, scaled=True):
    """(batch, heads, time, d) attention — the reference's
    ``multiHeadDotProductAttention`` op."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    if scaled:
        s = s / jnp.sqrt(jnp.asarray(d, s.dtype))
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, -1e9)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


# ---- losses (fused stable forms) ----
@register("softmax_cross_entropy")
def _sce(labels, logits, axis=-1):
    return jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(logits, axis=axis), axis=axis))


@register("sparse_softmax_cross_entropy")
def _ssce(labels, logits):
    lp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(lp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return -jnp.mean(ll)


@register("sigmoid_cross_entropy")
def _sigce(labels, logits):
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.mean(jnp.sum(per, axis=-1))


register("mean_squared_error")(lambda labels, pred: jnp.mean(jnp.sum((pred - labels) ** 2, axis=-1)))
register("mean_absolute_error")(lambda labels, pred: jnp.mean(jnp.sum(jnp.abs(pred - labels), axis=-1)))
register("l2_loss")(lambda a: 0.5 * jnp.sum(a * a))
register("log_loss")(lambda labels, pred, eps=1e-7:
                     -jnp.mean(jnp.sum(labels * jnp.log(pred + eps)
                                       + (1 - labels) * jnp.log(1 - pred + eps), axis=-1)))
register("cosine_distance")(lambda labels, pred, axis=-1:
                            jnp.mean(1.0 - jnp.sum(labels * pred, axis=axis)
                                     / jnp.maximum(jnp.linalg.norm(labels, axis=axis)
                                                   * jnp.linalg.norm(pred, axis=axis), 1e-12)))
register("hinge_loss")(lambda labels, pred:
                       jnp.mean(jnp.sum(jnp.maximum(0.0, 1.0 - jnp.where(labels > 0, 1.0, -1.0) * pred), axis=-1)))
register("huber_loss")(lambda labels, pred, delta=1.0:
                       jnp.mean(jnp.sum(jnp.where(jnp.abs(pred - labels) <= delta,
                                                  0.5 * (pred - labels) ** 2,
                                                  delta * (jnp.abs(pred - labels) - 0.5 * delta)), axis=-1)))


# ---- fused recurrent ops (reference sd.rnn() namespace: lstmLayer, gru) ----
# Thin wrappers over the nn layer implementations — ONE copy of the gate math
# (deliberate: a recurrence fix in nn/recurrent_layers.py reaches sd.rnn too).
def _rnn_layer(kind, n_out):
    from deeplearning4j_tpu.nn import recurrent_layers as rl
    from deeplearning4j_tpu.nn.base import GlobalConfig
    layer = {"lstm": rl.LSTM, "gru": rl.GRU}[kind](n_out=n_out)
    layer._g = GlobalConfig()
    return layer


@register("lstm_layer")
def _lstm_layer(x, W, W_rec, b, h0=None, c0=None):
    """Whole-sequence LSTM (reference ``sd.rnn().lstmLayer`` / libnd4j
    ``lstmLayer``). x: (B, T, F); W: (F, 4H) packed [i,f,g,o]; W_rec:
    (H, 4H); b: (4H,). Returns (ys, h_T, c_T)."""
    H = W_rec.shape[0]
    layer = _rnn_layer("lstm", H)
    B = x.shape[0]
    h = jnp.zeros((B, H), x.dtype) if h0 is None else h0
    c = jnp.zeros((B, H), x.dtype) if c0 is None else c0
    ys, (h, c) = layer.forward_with_carry(
        {"W": W, "W_rec": W_rec, "b": b}, (h, c), x)
    return ys, h, c


@register("gru")
def _gru_op(x, W, W_rec, b, h0=None):
    """Whole-sequence GRU (reference ``sd.rnn().gru``), packed gates
    [r, u, n]. Returns (ys, h_T)."""
    H = W_rec.shape[0]
    layer = _rnn_layer("gru", H)
    B = x.shape[0]
    h = jnp.zeros((B, H), x.dtype) if h0 is None else h0
    ys, (h,) = layer.forward_with_carry(
        {"W": W, "W_rec": W_rec, "b": b}, (h,), x)
    return ys, h


@register("lstm_cell")
def _lstm_cell(x_t, h, c, W, W_rec, b):
    """Single LSTM step (reference ``sd.rnn().lstmCell``): returns (h', c')."""
    layer = _rnn_layer("lstm", W_rec.shape[0])
    return layer._step({"W_rec": W_rec}, h, c, x_t @ W + b)


@register("gru_cell")
def _gru_cell(x_t, h, W, W_rec, b):
    """Single GRU step (reference ``sd.rnn().gruCell``)."""
    _, h_n = _gru_op(x_t[:, None, :], W, W_rec, b, h0=h)
    return h_n


# ---------------------------------------------------------------- linalg
# (reference sd.linalg() / org.nd4j.linalg.api.ops.impl.* matrix ops)


@register("cholesky")
def _cholesky(a):
    return jnp.linalg.cholesky(a)


@register("solve")
def _solve(a, b, adjoint=False):
    if adjoint:
        a = jnp.swapaxes(jnp.conj(a), -1, -2)
    return jnp.linalg.solve(a, b)


@register("triangular_solve")
def _triangular_solve(a, b, lower=True, adjoint=False):
    import jax.scipy.linalg as jsl
    return jsl.solve_triangular(a, b, lower=lower,
                                trans="C" if adjoint else "N")


@register("lstsq")
def _lstsq(a, b, fast=True):
    # `fast` is the reference's performance hint (Cholesky-vs-QR path);
    # jnp.linalg.lstsq picks the backend-appropriate algorithm, result
    # semantics are identical.
    return jnp.linalg.lstsq(a, b)[0]


@register("matrix_inverse")
def _matrix_inverse(a):
    return jnp.linalg.inv(a)


@register("matrix_determinant")
def _matrix_determinant(a):
    return jnp.linalg.det(a)


@register("logdet")
def _logdet(a):
    sign, logabs = jnp.linalg.slogdet(a)
    return logabs


@register("svd")
def _svd(a, full_matrices=False, compute_uv=True):
    if not compute_uv:
        return jnp.linalg.svd(a, full_matrices=full_matrices, compute_uv=False)
    u, s, vt = jnp.linalg.svd(a, full_matrices=full_matrices)
    return s, u, vt  # reference Svd returns s first


@register("qr")
def _qr(a, full_matrices=False):
    return jnp.linalg.qr(a, mode="complete" if full_matrices else "reduced")


@register("eigh")
def _eigh(a):
    """Self-adjoint (symmetric/Hermitian) eigendecomposition. A general
    non-symmetric ``eig`` is CPU-only in XLA and deliberately not registered
    — silently wrong answers on symmetric-only backends are worse than an
    unknown-op error."""
    w, v = jnp.linalg.eigh(a)
    return w, v


@register("matrix_band_part")
def _matrix_band_part(a, num_lower=-1, num_upper=-1):
    m, n = a.shape[-2], a.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if num_lower >= 0:
        keep &= (i - j) <= num_lower
    if num_upper >= 0:
        keep &= (j - i) <= num_upper
    return jnp.where(keep, a, jnp.zeros((), a.dtype))


@register("cross")
def _cross(a, b):
    return jnp.cross(a, b)


@register("diag")
def _diag(a):
    return jnp.diagflat(a) if a.ndim == 1 else jnp.diagonal(a, axis1=-2, axis2=-1)


@register("diag_part")
def _diag_part(a):
    return jnp.diagonal(a, axis1=-2, axis2=-1)


@register("trace")
def _trace(a):
    return jnp.trace(a, axis1=-2, axis2=-1)


# ---------------------------------------------------------------- bitwise
# (reference sd.bitwise(): and/or/xor, shifts, cyclic shifts)


@register("bitwise_and")
def _bitwise_and(a, b):
    return jnp.bitwise_and(a, b)


@register("bitwise_or")
def _bitwise_or(a, b):
    return jnp.bitwise_or(a, b)


@register("bitwise_xor")
def _bitwise_xor(a, b):
    return jnp.bitwise_xor(a, b)


@register("bit_shift")
def _bit_shift(a, shift):
    return jnp.left_shift(a, shift)


@register("bit_shift_right")
def _bit_shift_right(a, shift):
    return jnp.right_shift(a, shift)


@register("bit_rotl")
def _bit_rotl(a, shift):
    bits = a.dtype.itemsize * 8
    shift = jnp.asarray(shift) % bits
    # logical rotate: force unsigned for the right shift; the complementary
    # shift is taken mod bits because shifting by the full width is
    # implementation-defined in StableHLO
    ua = a.astype(jnp.dtype(f"uint{bits}"))
    out = jnp.left_shift(ua, shift) | jnp.right_shift(ua, (bits - shift) % bits)
    return out.astype(a.dtype)


@register("bit_rotr")
def _bit_rotr(a, shift):
    bits = a.dtype.itemsize * 8
    shift = jnp.asarray(shift) % bits
    ua = a.astype(jnp.dtype(f"uint{bits}"))
    out = jnp.right_shift(ua, shift) | jnp.left_shift(ua, (bits - shift) % bits)
    return out.astype(a.dtype)


# ---------------------------------------------------------------- random
# (reference sd.random(): draws take an explicit integer `seed` attr —
# jax.random threaded explicitly, no global RNG)


def _key(seed):
    import jax
    return jax.random.PRNGKey(int(seed))


@register("random_uniform")
def _random_uniform(shape=None, minval=0.0, maxval=1.0, seed=0):
    import jax
    return jax.random.uniform(_key(seed), tuple(shape),
                              minval=minval, maxval=maxval)


@register("random_normal")
def _random_normal(shape=None, mean=0.0, stddev=1.0, seed=0):
    import jax
    return mean + stddev * jax.random.normal(_key(seed), tuple(shape))


@register("random_bernoulli")
def _random_bernoulli(shape=None, p=0.5, seed=0):
    import jax
    return jax.random.bernoulli(_key(seed), p, tuple(shape)).astype(jnp.float32)


@register("random_exponential")
def _random_exponential(shape=None, lam=1.0, seed=0):
    import jax
    return jax.random.exponential(_key(seed), tuple(shape)) / lam


@register("random_shuffle")
def _random_shuffle(a, seed=0):
    import jax
    return jax.random.permutation(_key(seed), a, axis=0)


# ---------------------------------------------------------------- image
# (reference sd.image(): resize, crop, flip, adjust ops used by the CNN
# import paths)


@register("resize_bilinear")
def _resize_bilinear(images, height=None, width=None, align_corners=False):
    if align_corners:
        raise NotImplementedError(
            "resize_bilinear(align_corners=True) is not supported; "
            "jax.image.resize uses half-pixel alignment")
    n, h, w, c = images.shape
    return jax.image.resize(images, (n, int(height), int(width), c),
                            method="bilinear")


@register("resize_nearest")
def _resize_nearest(images, height=None, width=None):
    n, h, w, c = images.shape
    return jax.image.resize(images, (n, int(height), int(width), c),
                            method="nearest")


@register("crop_to_box")
def _crop_to_box(images, top=0, left=0, height=None, width=None):
    return jax.lax.dynamic_slice(
        images, (0, int(top), int(left), 0),
        (images.shape[0], int(height), int(width), images.shape[3]))


@register("flip_left_right")
def _flip_left_right(images):
    return jnp.flip(images, axis=2)


@register("flip_up_down")
def _flip_up_down(images):
    return jnp.flip(images, axis=1)


@register("adjust_brightness")
def _adjust_brightness(images, delta=0.0):
    return images + jnp.asarray(delta, images.dtype)


@register("adjust_contrast")
def _adjust_contrast(images, factor=1.0):
    mean = jnp.mean(images, axis=(1, 2), keepdims=True)
    return (images - mean) * factor + mean


@register("adjust_saturation")
def _adjust_saturation(images, factor=1.0):
    gray = jnp.mean(images, axis=-1, keepdims=True)
    return gray + (images - gray) * factor


@register("rgb_to_grayscale")
def _rgb_to_grayscale(images):
    w = jnp.asarray([0.2989, 0.587, 0.114], images.dtype)
    return jnp.sum(images * w, axis=-1, keepdims=True)


@register("hsv_to_rgb")
def _hsv_to_rgb(images):
    h, s, v = images[..., 0], images[..., 1], images[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


@register("rgb_to_hsv")
def _rgb_to_hsv(images):
    r, g, b = images[..., 0], images[..., 1], images[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe_d = jnp.where(d > 0, d, 1.0)
    h = jnp.where(
        d == 0, 0.0,
        jnp.where(mx == r, ((g - b) / safe_d) % 6.0,
                  jnp.where(mx == g, (b - r) / safe_d + 2.0,
                            (r - g) / safe_d + 4.0))) / 6.0
    s = jnp.where(mx > 0, d / jnp.where(mx > 0, mx, 1.0), 0.0)
    return jnp.stack([h, s, mx], axis=-1)
