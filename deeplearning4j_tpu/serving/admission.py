"""Admission control: deadlines, queue limits, load shedding.

The seed's ``ParallelInference`` queued without bound and had no notion of a
deadline — under overload every caller just waited longer. Production
serving needs the opposite: reject *early* with an explicit error the client
can act on (retry elsewhere, degrade, shed). Two error types:

- :class:`Overloaded` — raised synchronously at submit time when the queue
  is full (the request never entered the system).
- :class:`DeadlineExceeded` — the request was admitted but its deadline
  passed before the model ran it (the batcher fails it instead of wasting
  compute on an answer nobody is waiting for).
"""

from __future__ import annotations

import time
from typing import Optional


class ServingError(RuntimeError):
    """Base class for explicit serving rejections."""


class Overloaded(ServingError):
    """Queue full — request rejected at admission, never enqueued.

    ``retry_after_ms`` (when set) is the shedding worker's own estimate of
    when its queue will have drained — the hint the HTTP layer surfaces as
    a ``Retry-After`` header so a router fails over to a *different*
    worker instead of hammering the one that just shed (ISSUE 7)."""

    def __init__(self, *args, retry_after_ms: Optional[float] = None):
        super().__init__(*args)
        self.retry_after_ms = retry_after_ms


class PagingInProgress(Overloaded):
    """The requested model is COLD and its page-in could not complete
    within the caller's deadline (ISSUE 11, HBM-budgeted paging).

    A cold-model request normally just WAITS in the page-in queue and
    succeeds; this is raised only when the deadline provably cannot cover
    the wait. ``retry_after_ms`` is the *honest* remaining estimate —
    the model's measured page-in cost minus the time the in-flight load
    has already spent (:func:`page_in_retry_after_ms`) — rather than the
    generic drain-rate hint an overload rejection carries."""


class HBMBudgetExceeded(ServingError):
    """No room under the HBM budget and no evictable victim: every other
    resident model is pinned by in-flight requests or is not
    archive-backed. A transient condition — pins are request-scoped —
    surfaced explicitly instead of silently overshooting the budget."""


class DeadlineExceeded(ServingError):
    """Request admitted but its deadline expired before execution."""


class ServingShutdown(ServingError):
    """The batcher was shut down while this request was still queued."""


def page_in_retry_after_ms(est_page_in_ms: float, elapsed_ms: float = 0.0,
                           floor_ms: float = 25.0) -> float:
    """Honest ``Retry-After`` for a request that cannot wait out a cold
    model's page-in: the measured page-in cost minus what the in-flight
    load has already spent, floored like the overload drain hint so an
    unmeasured first page-in never advertises an instant retry."""
    return max(float(floor_ms), float(est_page_in_ms) - float(elapsed_ms))


class AdmissionController:
    """Policy object consulted by the batcher at submit time.

    ``queue_limit`` bounds how many *requests* may wait (load shedding);
    ``default_timeout_ms`` gives every request a deadline even when the
    caller does not pass one (None = wait forever, the seed behaviour).
    """

    def __init__(self, queue_limit: int = 256,
                 default_timeout_ms: Optional[float] = None,
                 retry_after_floor_ms: float = 25.0):
        self.queue_limit = int(queue_limit)
        self.default_timeout_ms = default_timeout_ms
        self.retry_after_floor_ms = float(retry_after_floor_ms)

    def retry_after_ms(self, queue_depth: int,
                       drain_ms_per_request: Optional[float] = None) -> float:
        """How long a shed caller should wait before retrying THIS worker:
        the queued work divided by the measured drain rate (the batcher
        passes its recent per-request service estimate), floored so an
        empty measurement window never advertises an instant retry."""
        per = float(drain_ms_per_request or 0.0)
        return max(self.retry_after_floor_ms, queue_depth * per)

    def admit(self, queue_depth: int,
              drain_ms_per_request: Optional[float] = None) -> None:
        """Raise :class:`Overloaded` if the queue cannot take this request.
        The rejection carries a queue-depth-derived ``retry_after_ms``
        hint (see :meth:`retry_after_ms`)."""
        if queue_depth >= self.queue_limit:
            raise Overloaded(
                f"serving queue full ({queue_depth}/{self.queue_limit} "
                f"requests waiting); retry later or raise queue_limit",
                retry_after_ms=self.retry_after_ms(queue_depth,
                                                   drain_ms_per_request))

    def page_in_retry_after_ms(self, est_page_in_ms: float,
                               elapsed_ms: float = 0.0) -> float:
        """The page-in twin of :meth:`retry_after_ms` (ISSUE 11): the
        honest cold-model hint, floored by this controller's own
        ``retry_after_floor_ms``."""
        return page_in_retry_after_ms(est_page_in_ms, elapsed_ms,
                                      floor_ms=self.retry_after_floor_ms)

    def deadline_for(self, timeout_ms: Optional[float]) -> Optional[float]:
        """Absolute monotonic deadline for a request, or None."""
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        if timeout_ms is None:
            return None
        return time.monotonic() + float(timeout_ms) / 1000.0
