"""SLO metrics for the serving subsystem.

The reference stack exposes serving health through the konduit model-server's
Prometheus endpoint; here the same signals — request latency percentiles,
QPS, queue depth, batch occupancy, rejection counts, and XLA compile counts —
are collected in-process and rendered on ``/metrics`` in Prometheus text
format. The pipelined executor (ISSUE 3) adds its own observability: an
in-flight depth gauge (dispatched batches awaiting readback), per-replica
batch counts, and a dispatch-to-completion latency histogram. :class:`LatencyHistogram` is deliberately stdlib-only so
``runtime.profiler`` can reuse it for section-latency percentiles without
pulling the serving stack into the training import graph.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class LatencyHistogram:
    """Fixed log-spaced latency histogram with percentile queries.

    Buckets are geometric (factor 2) from ``lo`` seconds to ``hi`` seconds
    plus an overflow bucket, so a p99 over millions of observations costs
    O(#buckets) memory and the percentile error is bounded by one bucket
    width (the standard Prometheus-histogram trade).
    """

    def __init__(self, lo: float = 1e-4, hi: float = 64.0):
        self._bounds: List[float] = []
        b = lo
        while b <= hi:
            self._bounds.append(b)
            b *= 2.0
        self._counts = [0] * (len(self._bounds) + 1)  # +1 overflow
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        seconds = float(seconds)
        i = 0
        for i, b in enumerate(self._bounds):
            if seconds <= b:
                break
        else:
            i = len(self._bounds)
        self._counts[i] += 1
        self.count += 1
        self.sum += seconds
        if seconds > self.max:
            self.max = seconds

    def percentile(self, p: float) -> float:
        """p in [0, 100]; returns the upper bound of the bucket holding the
        p-th observation (0.0 when empty) — a conservative (>=) estimate."""
        if self.count == 0:
            return 0.0
        rank = max(1, int(round(p / 100.0 * self.count)))
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                return self._bounds[i] if i < len(self._bounds) else self.max
        return self.max

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    # ------------------------------------------------------ merge / wire
    def to_wire(self) -> Dict[str, object]:
        """JSON-able form carrying the raw bucket state, so a remote
        reader (the fleet router's ``/metrics`` aggregation, ISSUE 9) can
        :meth:`merge` histograms instead of averaging percentiles —
        percentiles of a merged histogram are exact (to bucket width),
        percentiles averaged across workers are meaningless."""
        return {"bounds": list(self._bounds), "counts": list(self._counts),
                "count": self.count, "sum": self.sum, "max": self.max}

    @classmethod
    def from_wire(cls, wire: Dict[str, object]) -> "LatencyHistogram":
        h = cls.__new__(cls)
        h._bounds = [float(b) for b in wire["bounds"]]
        h._counts = [int(c) for c in wire["counts"]]
        if len(h._counts) != len(h._bounds) + 1:
            raise ValueError("histogram wire form has mismatched "
                             f"{len(h._bounds)} bounds / "
                             f"{len(h._counts)} counts")
        h.count = int(wire["count"])
        h.sum = float(wire["sum"])
        h.max = float(wire["max"])
        return h

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Accumulate ``other``'s buckets into this histogram (in place;
        returns self). Both must share the same bucket bounds — every
        histogram built with the default ``lo``/``hi`` does."""
        if other._bounds != self._bounds:
            raise ValueError("cannot merge histograms with different "
                             "bucket bounds")
        for i, c in enumerate(other._counts):
            self._counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.max > self.max:
            self.max = other.max
        return self


class ServingMetrics:
    """Per-model serving counters, gauges and histograms (thread-safe)."""

    def __init__(self, queue_depth_fn: Optional[Callable[[], int]] = None,
                 compile_count_fn: Optional[Callable[[], int]] = None,
                 inflight_fn: Optional[Callable[[], int]] = None):
        # guards: requests_total, responses_total, rejected_overload, rejected_deadline, rejected_circuit, retries_total, errors_total, batches_total, rows_real_total, rows_padded_total, zero_copy_rows_total, request_latency, batch_latency, dispatch_latency, quant_latency, float_latency, quantized_requests_total, dtype_policy_label, replica_batches, warmup_seconds, _qps_slots, _qps_times, _window_started_at
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self._window_started_at = self.started_at  # reset_window restarts it
        self.requests_total = 0          # admitted into the queue
        self.responses_total = 0         # completed successfully
        self.rejected_overload = 0
        self.rejected_deadline = 0
        self.rejected_circuit = 0        # shed by an open circuit breaker
        self.retries_total = 0           # resubmits after transient failures
        self.errors_total = 0            # model/runtime failures
        self.batches_total = 0
        self.rows_real_total = 0         # pre-padding rows executed
        self.rows_padded_total = 0       # post-padding rows executed
        # zero-copy ingest observability (ISSUE 18): rows that arrived as
        # read-only views over a binary wire frame (or shared-memory
        # segment) and were copied exactly once — into the pad buffer
        self.zero_copy_rows_total = 0
        self.request_latency = LatencyHistogram()
        self.batch_latency = LatencyHistogram()
        # quantized-serving observability (ISSUE 8): how much traffic rides
        # the reduced-precision path, and its latency split vs float
        # traffic (also surfaced by runtime.profiler.quant_split_stats)
        self.quantized_requests_total = 0
        self.dtype_policy_label: Optional[str] = None
        self.quant_latency = LatencyHistogram()
        self.float_latency = LatencyHistogram()
        # pipeline observability (ISSUE 3): time from async dispatch to
        # readback completion, and which replica served each batch
        self.dispatch_latency = LatencyHistogram()
        self.replica_batches: Dict[int, int] = {}
        # cold-start observability (ISSUE 5): build+warmup wall time of the
        # served model, stamped by the registry at register/load/hot-swap
        self.warmup_seconds = 0.0
        self._queue_depth_fn = queue_depth_fn or (lambda: 0)
        self._compile_count_fn = compile_count_fn or (lambda: 0)
        self._inflight_fn = inflight_fn or (lambda: 0)
        self._breaker = None             # CircuitBreaker, attached post-init
        # 60-slot per-second ring for windowed QPS
        self._qps_slots = [0] * 60
        self._qps_times = [0] * 60

    # ------------------------------------------------------------ recording
    def record_admitted(self, quantized: bool = False) -> None:
        with self._lock:
            self.requests_total += 1
            if quantized:
                self.quantized_requests_total += 1

    def record_zero_copy(self, rows: int) -> None:
        """Count rows ingested as zero-copy wire views (ISSUE 18)."""
        with self._lock:
            self.zero_copy_rows_total += int(rows)

    def set_dtype_policy(self, label: str) -> None:
        """Attach the served model's dtype-policy label (rendered as the
        ``serving_dtype_policy`` info gauge)."""
        with self._lock:
            self.dtype_policy_label = str(label)

    def record_response(self, latency_s: float,
                        quantized: bool = False) -> None:
        with self._lock:
            self.responses_total += 1
            self.request_latency.observe(latency_s)
            (self.quant_latency if quantized
             else self.float_latency).observe(latency_s)
            now = int(time.monotonic())
            slot = now % 60
            if self._qps_times[slot] != now:
                self._qps_times[slot] = now
                self._qps_slots[slot] = 0
            self._qps_slots[slot] += 1

    def record_rejection(self, reason: str) -> None:
        with self._lock:
            if reason == "overload":
                self.rejected_overload += 1
            elif reason == "deadline":
                self.rejected_deadline += 1
            elif reason == "circuit":
                self.rejected_circuit += 1
            else:
                self.errors_total += 1

    def record_retry(self) -> None:
        with self._lock:
            self.retries_total += 1

    def set_warmup_seconds(self, seconds: float) -> None:
        """Time-to-first-ready for this served model (build + AOT warmup,
        manifest replay included) — the number ``bench.py --coldstart``
        A/Bs cold vs warm."""
        with self._lock:
            self.warmup_seconds = float(seconds)

    def attach_breaker(self, breaker) -> None:
        """Attach the model's CircuitBreaker so snapshots and the
        Prometheus rendering expose its state (gauge: 0 closed,
        1 half-open, 2 open) and open count."""
        self._breaker = breaker

    def record_batch(self, real_rows: int, padded_rows: int,
                     latency_s: float, replica: Optional[int] = None) -> None:
        with self._lock:
            self.batches_total += 1
            self.rows_real_total += int(real_rows)
            self.rows_padded_total += int(padded_rows)
            self.batch_latency.observe(latency_s)
            if replica is not None:
                self.replica_batches[int(replica)] = \
                    self.replica_batches.get(int(replica), 0) + 1

    def record_dispatch(self, latency_s: float) -> None:
        """Dispatch-to-completion: async dispatch returned -> readback done
        (device queue wait + execution + readback for one batch)."""
        with self._lock:
            self.dispatch_latency.observe(latency_s)

    def reset_window(self) -> None:
        """Start a fresh measurement window: zero the latency histograms,
        batch counters and per-replica counts. Cumulative service totals
        (requests/responses/rejections) keep counting. Benchmarks call
        this between rounds so percentiles describe ONE load window, not
        warmup plus every discarded round."""
        with self._lock:
            self.request_latency = LatencyHistogram()
            self.batch_latency = LatencyHistogram()
            self.dispatch_latency = LatencyHistogram()
            self.quant_latency = LatencyHistogram()
            self.float_latency = LatencyHistogram()
            self.replica_batches = {}
            self.batches_total = 0
            self.rows_real_total = 0
            self.rows_padded_total = 0
            self._window_started_at = time.monotonic()

    # -------------------------------------------------------------- reading
    @property                                           # holds: _lock
    def batch_occupancy(self) -> float:
        """Fraction of executed rows that were real requests (1.0 = no
        padding waste)."""
        return (self.rows_real_total / self.rows_padded_total
                if self.rows_padded_total else 0.0)

    def qps(self, window_s: int = 10) -> float:
        now = int(time.monotonic())
        with self._lock:
            n = sum(c for c, t in zip(self._qps_slots, self._qps_times)
                    if now - t < window_s)
        return n / float(window_s)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            req_lat, bat_lat = self.request_latency, self.batch_latency
            snap = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "rejected_overload": self.rejected_overload,
                "rejected_deadline": self.rejected_deadline,
                "rejected_circuit": self.rejected_circuit,
                "retries_total": self.retries_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "rows_real_total": self.rows_real_total,
                "rows_padded_total": self.rows_padded_total,
                "zero_copy_rows_total": self.zero_copy_rows_total,
                "batch_occupancy": round(self.batch_occupancy, 4),
                "latency_p50_s": req_lat.percentile(50),
                "latency_p99_s": req_lat.percentile(99),
                "latency_mean_s": req_lat.mean,
                "batch_latency_p50_s": bat_lat.percentile(50),
                "dispatch_p50_s": self.dispatch_latency.percentile(50),
                "dispatch_p99_s": self.dispatch_latency.percentile(99),
                "replica_batches": dict(self.replica_batches),
                "warmup_seconds": round(self.warmup_seconds, 4),
                "uptime_s": round(time.monotonic() - self.started_at, 3),
                "quantized_requests_total": self.quantized_requests_total,
                "dtype_policy": self.dtype_policy_label,
                "latency_quant_p50_s": self.quant_latency.percentile(50),
                "latency_quant_p99_s": self.quant_latency.percentile(99),
                "latency_float_p50_s": self.float_latency.percentile(50),
                "latency_float_p99_s": self.float_latency.percentile(99),
                "quant_responses": self.quant_latency.count,
                "float_responses": self.float_latency.count,
            }
        snap["qps_10s"] = self.qps(10)
        snap["queue_depth"] = int(self._queue_depth_fn())
        snap["compile_count"] = int(self._compile_count_fn())
        snap["inflight_depth"] = int(self._inflight_fn())
        if self._breaker is not None:
            b = self._breaker.snapshot()
            snap["breaker_state"] = b["state"]
            snap["breaker_opens_total"] = b["opens_total"]
            snap["breaker_failures_in_window"] = b["failures_in_window"]
        return snap

    def utilization_snapshot(self) -> Dict[str, object]:
        """The raw pieces ``serving/capacity.py`` derives replica
        busy-fractions from, captured in ONE lock acquisition so the
        parts are mutually consistent: the dispatch-to-completion
        histogram's *sum* is the pipeline's measured busy-seconds (a
        depth>1 pipeline can legitimately exceed the window — overlap
        reads as utilization > 1, i.e. queue pressure), apportioned per
        replica by batch share; ``window_s`` is the metrics window
        (since construction, or the last :meth:`reset_window`)."""
        with self._lock:
            return {
                "window_s": time.monotonic() - self._window_started_at,
                "busy_s": self.dispatch_latency.sum,
                "batches_total": self.batches_total,
                "replica_batches": dict(self.replica_batches),
                "dispatch_wire": self.dispatch_latency.to_wire(),
            }

    def wire_snapshot(self) -> Dict[str, object]:
        """Machine-readable snapshot for the fleet router's ``/metrics``
        aggregation (ISSUE 9): summable counters plus raw-bucket
        histograms (:meth:`LatencyHistogram.to_wire`) so one scrape of the
        router sees fleet-wide counts and MERGED latency percentiles.
        Ships the model's own breaker verdict too (ISSUE 12): what a
        freshly (re)started router warm-starts its passive per-worker
        breaker from, so it never re-routes traffic into a worker its
        peers already isolated."""
        breaker = (self._breaker.snapshot()
                   if self._breaker is not None else None)
        with self._lock:
            return {
                "breaker": breaker,
                "counters": {
                    "requests_total": self.requests_total,
                    "responses_total": self.responses_total,
                    "rejected_overload": self.rejected_overload,
                    "rejected_deadline": self.rejected_deadline,
                    "rejected_circuit": self.rejected_circuit,
                    "retries_total": self.retries_total,
                    "errors_total": self.errors_total,
                    "batches_total": self.batches_total,
                    "rows_real_total": self.rows_real_total,
                    "rows_padded_total": self.rows_padded_total,
                    "zero_copy_rows_total": self.zero_copy_rows_total,
                    "quantized_requests_total": self.quantized_requests_total,
                },
                "histograms": {
                    # request_latency only: it is what the router's
                    # aggregation merges; shipping the batch/dispatch
                    # histograms too would inflate every scrape for no
                    # consumer (add them here WHEN something merges them)
                    "request_latency": self.request_latency.to_wire(),
                },
            }

    def render_prometheus(self, model: str) -> str:
        s = self.snapshot()
        lbl = f'{{model="{model}"}}'
        lines = [
            f"serving_requests_total{lbl} {s['requests_total']}",
            f"serving_responses_total{lbl} {s['responses_total']}",
            f'serving_rejected_total{{model="{model}",reason="overload"}} '
            f"{s['rejected_overload']}",
            f'serving_rejected_total{{model="{model}",reason="deadline"}} '
            f"{s['rejected_deadline']}",
            f'serving_rejected_total{{model="{model}",reason="circuit_open"}} '
            f"{s['rejected_circuit']}",
            f"serving_retries_total{lbl} {s['retries_total']}",
            f"serving_errors_total{lbl} {s['errors_total']}",
            f"serving_batches_total{lbl} {s['batches_total']}",
            f"serving_batch_occupancy{lbl} {s['batch_occupancy']}",
            f"serving_zero_copy_rows_total{lbl} {s['zero_copy_rows_total']}",
            f'serving_latency_seconds{{model="{model}",quantile="0.5"}} '
            f"{s['latency_p50_s']}",
            f'serving_latency_seconds{{model="{model}",quantile="0.99"}} '
            f"{s['latency_p99_s']}",
            f"serving_qps{lbl} {s['qps_10s']}",
            f"serving_queue_depth{lbl} {s['queue_depth']}",
            f"serving_xla_compile_count{lbl} {s['compile_count']}",
            f"serving_inflight_depth{lbl} {s['inflight_depth']}",
            f'serving_dispatch_to_completion_seconds'
            f'{{model="{model}",quantile="0.5"}} {s["dispatch_p50_s"]}',
            f'serving_dispatch_to_completion_seconds'
            f'{{model="{model}",quantile="0.99"}} {s["dispatch_p99_s"]}',
            f"serving_warmup_seconds{lbl} {s['warmup_seconds']}",
        ]
        lines.append(f"serving_quantized_requests_total{lbl} "
                     f"{s['quantized_requests_total']}")
        if s["dtype_policy"] is not None:
            # info gauge: the label IS the payload, the value is always 1
            lines.append(f'serving_dtype_policy{{model="{model}",'
                         f'policy="{s["dtype_policy"]}"}} 1')
            for cls, p50, p99 in (
                    ("quantized", s["latency_quant_p50_s"],
                     s["latency_quant_p99_s"]),
                    ("float", s["latency_float_p50_s"],
                     s["latency_float_p99_s"])):
                lines.append(f'serving_dtype_latency_seconds'
                             f'{{model="{model}",class="{cls}",'
                             f'quantile="0.5"}} {p50}')
                lines.append(f'serving_dtype_latency_seconds'
                             f'{{model="{model}",class="{cls}",'
                             f'quantile="0.99"}} {p99}')
        for idx in sorted(s["replica_batches"]):
            lines.append(
                f'serving_replica_batches_total'
                f'{{model="{model}",replica="{idx}"}} '
                f"{s['replica_batches'][idx]}")
        if "breaker_state" in s:
            state_gauge = {"CLOSED": 0, "HALF_OPEN": 1, "OPEN": 2}.get(
                s["breaker_state"], -1)
            lines.append(f"serving_breaker_state{lbl} {state_gauge}")
            lines.append(f"serving_breaker_opens_total{lbl} "
                         f"{s['breaker_opens_total']}")
        return "\n".join(lines) + "\n"
