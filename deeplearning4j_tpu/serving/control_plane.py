"""Replicated serving control plane (ISSUE 12 tentpole; ROADMAP item 4 —
"a control plane with no single point of failure").

PRs 7–11 built failover, hedging, paging and autoscaling — all of it
behind ONE ``FleetRouter`` process and ONE in-process ``SLOAutoscaler``:
kill that process and the fleet goes dark, so every robustness guarantee
was conditional on a single point of failure. The reference's production
story has no such point: its multi-JVM serving tier and the Spark
``SharedTrainingMaster`` control tier both survive individual process
loss. This module replicates ours the same way, with three pieces that
deliberately share NOTHING but files and scrapes:

- :class:`FleetConfig` — the versioned shared fleet-config file (worker
  roster, router roster, model catalogue, deploy state, applied-action
  ledger) written with the checkpoint-atomics discipline (tmp file +
  ``os.replace`` in the target directory, the ``train/checkpoint.py`` /
  ``serving/manifest.py`` idiom) under a cross-process lock file. Readers
  degrade, never crash: a corrupt or version-regressed snapshot keeps the
  last-valid config and bumps a loud counter (chaos point
  ``serving.router.config_load``). The config IS a fleet for
  :class:`~deeplearning4j_tpu.serving.router.FleetRouter` (it has
  ``endpoints()``), so N router processes front one worker roster with no
  coordinator on the serving path — per-model SLO/capacity state is
  scrape-derived and breakers/hedging p99s rebuild from traffic, so
  routers stay shared-nothing by construction.
- :class:`LeaseElection` — file-lock leader election for the autoscaler
  tier: atomic-create acquisition (``os.link``), heartbeat = lease-file
  mtime, takeover once the mtime goes stale past the lease window, a
  monotonic ``seq`` fencing token bumped on every takeover. Exactly one
  router's ``SLOAutoscaler`` acts; the others shadow-compute and log
  ``follower`` decisions; a SIGKILL'd (or hung — chaos point
  ``serving.autoscale.lease``) leader loses the lease within one window
  and the next scaling decision comes from the new leader.
- :class:`RouterSupervisor` + :func:`router_main` — the
  :class:`~deeplearning4j_tpu.serving.fleet.FleetSupervisor` pattern one
  level up: N ``FleetRouter`` *processes* (``python -m
  deeplearning4j_tpu.serving.control_plane <spec.json>``) with port-file
  readiness (written only after the router has probed its workers and
  registered itself in the shared config), heartbeat + exit-code
  watchdog, and budgeted restarts. Router pids register in this module's
  own leak-guard tables, polled by the conftest guard exactly like fleet
  worker pids.
- :class:`MultiRouterClient` — the caller's side of the story:
  round-robin across the live router roster with connect-fail/5xx
  failover, so a SIGKILL'd router is invisible to callers (the drill of
  record: ``bench.py --control-plane`` kills a router mid-load and
  asserts zero client-visible errors). Used by ``bench.py`` and
  ``examples/fleet_serving.py``.

This module imports no jax — like the router, it is pure host code.
"""

from __future__ import annotations

import copy
import dataclasses
import http.client
import itertools
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.runtime import chaos, journal
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.serving.fleet import FleetSupervisor, PidRegistry
from deeplearning4j_tpu.serving.manifest import atomic_replace

logger = logging.getLogger(__name__)

__all__ = ["FleetConfig", "LeaseElection", "MultiRouterClient",
           "RouterSpec", "RouterSupervisor", "router_main",
           "live_router_pids", "kill_stray_routers",
           "orphaned_router_pids", "kill_orphaned_routers"]

CONFIG_FORMAT = "dl4j-fleet-config-v1"
LEASE_FORMAT = "dl4j-lease-v1"


# -------------------------------------------------------------------------
# router-pid registry: same contract (and implementation —
# fleet.PidRegistry) as serving.fleet's worker registry, but a SEPARATE
# population so the conftest leak guard names router leaks as router
# leaks and killing strays in one tier never touches the other
_registry = PidRegistry()


def _track_router(proc: subprocess.Popen) -> None:
    _registry.track(proc)


def live_router_pids() -> List[int]:
    """PIDs of router subprocesses launched through this module that are
    still alive — polled by the conftest leak guard after every test."""
    return _registry.live_pids()


def kill_stray_routers() -> List[int]:
    """Kill any still-live tracked routers (leak-guard teardown)."""
    return _registry.kill_stray()


def orphaned_router_pids() -> List[int]:
    """Live tracked router pids NOT owned by any active supervisor — a
    supervised fixture router tier is managed, not leaked."""
    return _registry.orphaned_pids()


def kill_orphaned_routers() -> List[int]:
    """Kill only the ORPHANED tracked routers (leak-guard teardown)."""
    return _registry.kill_orphaned()


# =========================================================== fleet config
def _empty_config() -> Dict[str, Any]:
    return {"format": CONFIG_FORMAT, "version": 0,
            "workers": {},            # worker_id -> "host:port"
            "routers": {},            # router_id -> "host:port"
            "models": {},             # model catalogue (name -> metadata)
            "deploy": {},             # deploy state (archive, version, ...)
            "applied_actions": {},    # action_id -> record (exactly-once)
            "schedules": [],          # pre-scaling windows (autoscaler)
            "updated_at": 0.0}


class FleetConfig:
    """The versioned shared fleet-config file N routers front a fleet
    through.

    Reads are mtime-cached and DEGRADE on failure: a corrupt, truncated,
    missing or version-regressed file keeps the last-valid in-memory
    snapshot and bumps ``load_failures_total`` — a bad config write can
    slow convergence, never take a router down (chaos point
    ``serving.router.config_load``; drill in ``tests/test_chaos.py`` /
    ``tests/test_control_plane.py``).

    Writes go through :meth:`mutate`: a cross-process lock file
    serializes read-modify-write cycles, the version bumps by exactly one
    per committed mutation, and the write itself is the checkpoint-atomic
    tmp-file + ``os.replace``. :meth:`try_claim` builds exactly-once
    action application on top (rolling deploys, autoscaler levers): the
    first claimant records the action id in the ledger, every later
    claimant sees it and skips — two live routers can never double-apply.

    A ``FleetConfig`` is also a *fleet* (``endpoints()``), so
    ``FleetRouter(FleetConfig(path))`` just works.
    """

    def __init__(self, path: str, create: bool = True,
                 lock_timeout_s: float = 10.0,
                 stale_lock_s: float = 30.0,
                 max_applied_actions: int = 256):
        self.path = str(path)
        self.lock_timeout_s = float(lock_timeout_s)
        self.stale_lock_s = float(stale_lock_s)
        self.max_applied_actions = int(max_applied_actions)
        # reload/mutate critical section; readers take the _last_valid
        # reference lock-free by design (degrade-never-crash)
        # guards: (reload/mutate critical section)
        self._lock = threading.Lock()
        self._last_valid = _empty_config()
        self._last_stat: Optional[Tuple[int, int]] = None
        self.loads_total = 0
        self.load_failures_total = 0
        if create and not os.path.exists(self.path):
            try:
                self._seed_empty()
            except OSError:
                logger.exception("could not seed fleet config %s", self.path)
        with self._lock:
            self._refresh_locked()

    def _seed_empty(self) -> None:
        """Create-if-absent of the v0 config, atomically: the file is
        linked into place only if nothing exists there — a racing
        creator that already wrote (and possibly populated) the config
        must never be stomped back to an empty v0."""
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".fleet-config-seed-", dir=d)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._last_valid, f, indent=2, sort_keys=True)
            try:
                os.link(tmp, self.path)  # atomic create: loses to anyone
            except FileExistsError:
                pass  # someone else seeded (or populated) it first
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # --------------------------------------------------------------- reads
    def _read_disk(self) -> Dict[str, Any]:
        """Parse the on-disk config; raises on anything malformed. The
        bytes pass through the ``serving.router.config_load`` byte point
        so chaos drills can corrupt exactly what a torn write would."""
        with open(self.path, "rb") as f:
            data = f.read()
        data = chaos.transform_bytes("serving.router.config_load", data)
        cfg = json.loads(data.decode())
        fmt = cfg.get("format") if isinstance(cfg, dict) else None
        if fmt != CONFIG_FORMAT:
            raise ValueError(f"not a fleet config (format={fmt!r})")
        cfg["version"] = int(cfg["version"])
        base = _empty_config()
        base.update(cfg)
        return base

    def _refresh_locked(self) -> None:
        """Reload when the file changed; on ANY failure keep the
        last-valid snapshot (degrade + count, never crash)."""
        try:
            st = os.stat(self.path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            if self._last_stat is not None:
                # the file vanished under us: a failure mode, not a reset
                self.load_failures_total += 1
                self._last_stat = None
            return
        if sig == self._last_stat:
            return
        try:
            chaos.inject("serving.router.config_load")
            cfg = self._read_disk()
            if cfg["version"] < self._last_valid["version"]:
                raise ValueError(
                    f"stale config: version {cfg['version']} regressed "
                    f"below last-valid {self._last_valid['version']}")
        except Exception as e:
            self.load_failures_total += 1
            self._last_stat = sig  # don't re-pay the parse until it changes
            logger.warning(
                "fleet config load failed (%s: %s); keeping last-valid "
                "v%d", type(e).__name__, e, self._last_valid["version"])
            return
        self._last_valid = cfg
        self._last_stat = sig
        self.loads_total += 1

    def snapshot(self, refresh: bool = True) -> Dict[str, Any]:
        """The latest VALID config (a deep copy — mutate via
        :meth:`mutate`, never in place)."""
        with self._lock:
            if refresh:
                self._refresh_locked()
            return copy.deepcopy(self._last_valid)

    @property
    def version(self) -> int:
        return self.snapshot()["version"]

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {"version": self._last_valid["version"],
                    "loads_total": self.loads_total,
                    "load_failures_total": self.load_failures_total}

    # the fleet duck-type: what FleetRouter calls every probe cycle
    def endpoints(self) -> Dict[str, str]:
        return dict(self.snapshot()["workers"])

    def routers(self) -> Dict[str, str]:
        return dict(self.snapshot()["routers"])

    def deploy_state(self) -> Optional[Dict[str, Any]]:
        """The last completed deploy's published record (archive,
        version, strategy, router, action_id) — what a restarted router
        reads to learn which artifact the fleet is supposed to run."""
        return self.snapshot().get("deploy")

    # -------------------------------------------------------------- writes
    @contextmanager
    def _flock(self):
        """Cross-process mutation lock: O_EXCL lock-file create with
        stale-lock breaking (a crashed holder's lock older than
        ``stale_lock_s`` is reclaimed)."""
        lock = self.path + ".lock"
        deadline = time.monotonic() + self.lock_timeout_s
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, f"{os.getpid()}\n".encode())
                os.close(fd)
                break
            except FileExistsError:
                try:
                    st1 = os.stat(lock)
                    if time.time() - st1.st_mtime > self.stale_lock_s:
                        # break only the SAME lock instance we judged
                        # stale (inode + mtime re-checked right before
                        # the unlink): a holder releasing and a fresh
                        # waiter re-creating in the window must not have
                        # its brand-new lock stolen out from under it
                        st2 = os.stat(lock)
                        if (st2.st_ino, st2.st_mtime_ns) == \
                                (st1.st_ino, st1.st_mtime_ns):
                            os.unlink(lock)
                        continue
                except OSError:
                    continue  # holder released between stat and unlink
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"fleet-config lock {lock} held past "
                        f"{self.lock_timeout_s:.0f}s")
                time.sleep(0.005)
        try:
            yield
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    def _write_locked(self, cfg: Dict[str, Any]) -> None:
        def write(tmp):
            with open(tmp, "w") as f:
                json.dump(cfg, f, indent=2, sort_keys=True)
        atomic_replace(self.path, write, prefix=".fleet-config-")

    def mutate(self, fn) -> Dict[str, Any]:
        """Cross-process read-modify-write: under the lock file, re-read
        the LATEST config, apply ``fn(cfg)`` in place (return ``False``
        to abort without writing), bump the version by one, write
        atomically. Returns the (new or unchanged) config."""
        with self._flock():
            with self._lock:
                # FORCE a re-parse: a reader that cached a failed load
                # must not mutate from (and then re-publish) a stale
                # snapshot when the on-disk config has since healed —
                # only if the disk is truly unreadable is rewriting from
                # last-valid the right repair
                self._last_stat = None
                self._refresh_locked()
                cfg = copy.deepcopy(self._last_valid)
            if fn(cfg) is False:
                return cfg
            cfg["version"] = int(cfg["version"]) + 1
            cfg["updated_at"] = time.time()
            self._write_locked(cfg)
            with self._lock:
                self._last_valid = cfg
                try:
                    st = os.stat(self.path)
                    self._last_stat = (st.st_mtime_ns, st.st_size)
                except OSError:
                    self._last_stat = None
                self.loads_total += 1
            # every committed mutation is a journal event (ISSUE 15): the
            # black box shows WHICH config version a deploy/roster change
            # produced, next to the stages that consumed it
            journal.emit("control.config_apply", version=cfg["version"],
                         workers=len(cfg.get("workers") or {}),
                         routers=len(cfg.get("routers") or {}))
            return copy.deepcopy(cfg)

    def set_workers(self, endpoints: Dict[str, str]) -> None:
        """Publish the worker roster (the supervisor's seam)."""
        endpoints = {str(k): str(v) for k, v in endpoints.items()}

        def fn(cfg):
            if cfg["workers"] == endpoints:
                return False
            cfg["workers"] = endpoints
        self.mutate(fn)

    def set_router(self, router_id: str, address: str) -> None:
        def fn(cfg):
            if cfg["routers"].get(router_id) == address:
                return False
            cfg["routers"][str(router_id)] = str(address)
        self.mutate(fn)

    def remove_router(self, router_id: str) -> None:
        def fn(cfg):
            if router_id not in cfg["routers"]:
                return False
            del cfg["routers"][router_id]
        self.mutate(fn)

    def try_claim(self, action_id: str,
                  payload: Optional[Dict[str, Any]] = None) -> bool:
        """Exactly-once action claim: ``True`` for the FIRST caller
        (across every process sharing this config), ``False`` for every
        later one. The ledger is bounded (oldest claims age out), so an
        action id must be unique within the ledger's horizon — deploys
        and autoscaler levers key on content (archive/version,
        model/level), not on wall time."""
        out = {"claimed": True}

        def fn(cfg):
            ledger = cfg["applied_actions"]
            if action_id in ledger:
                out["claimed"] = False
                out["by"] = ledger[action_id]
                return False
            ledger[str(action_id)] = {"ts": time.time(),
                                      "pid": os.getpid(),
                                      **(payload or {})}
            if len(ledger) > self.max_applied_actions:
                for k in sorted(ledger,
                                key=lambda k: ledger[k].get("ts", 0.0))[
                        :len(ledger) - self.max_applied_actions]:
                    del ledger[k]
        self.mutate(fn)
        return out["claimed"]

    def release_claim(self, action_id: str) -> None:
        """Roll a claim back (the claimant's action FAILED partway): the
        action id leaves the ledger so a retry — from this router or any
        peer — can claim it again instead of being skipped forever as
        'already applied'."""
        def fn(cfg):
            if action_id not in cfg["applied_actions"]:
                return False
            del cfg["applied_actions"][action_id]
        self.mutate(fn)

    def applied(self, action_id: str) -> Optional[Dict[str, Any]]:
        return self.snapshot()["applied_actions"].get(action_id)


# ========================================================= lease election
class LeaseElection:
    """File-lock lease election (ISSUE 12: exactly one autoscaler acts).

    The lease is one JSON file: ``{"format", "holder", "seq",
    "acquired_at"}``. Acquisition of a FREE lease is atomic
    (``os.link`` of a prepared tmp file — creation fails if the path
    exists); while held, the holder heartbeats by touching the file's
    mtime (chaos point ``serving.autoscale.lease`` fires before each
    beat, so a drill can hang or fail exactly the heartbeat); a lease
    whose mtime is older than ``lease_s`` is STALE and any follower may
    take it over (``os.replace`` with ``seq + 1`` — the fencing token —
    then a re-read to confirm the takeover actually stuck; a lost race
    resolves into ``follower`` at the next :meth:`ensure`).

    The holder re-reads the lease BEFORE every beat: a leader whose
    heartbeat hung long enough to lose the lease observes the new holder
    and steps down instead of stomping the new leader's heartbeat.
    Every transition is recorded in :attr:`elections` (bounded) and
    reported through ``on_transition`` — the autoscaler folds them into
    its ``/v1/autoscaler`` decision log.

    :meth:`is_leader` is a lock-free read of the last settled role, so
    the autoscaler's fencing check never blocks behind a hung heartbeat.
    """

    def __init__(self, path: str, holder_id: str, lease_s: float = 2.0,
                 heartbeat_s: Optional[float] = None,
                 on_transition=None):
        self.path = str(path)
        self.holder_id = str(holder_id)
        self.lease_s = float(lease_s)
        self.heartbeat_s = (float(heartbeat_s) if heartbeat_s is not None
                            else self.lease_s / 4.0)
        self.on_transition = on_transition
        self.role = "follower"
        self.seq = 0                      # fencing token of OUR last lease
        self.elections: deque = deque(maxlen=64)
        self._lock = threading.Lock()  # guards: (ensure()/heartbeat step serialization)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --------------------------------------------------------------- state
    def _read(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path) as f:
                rec = json.load(f)
            if rec.get("format") != LEASE_FORMAT:
                return None
            return rec
        except (OSError, ValueError):
            return None  # absent or torn: treated as up for grabs

    def _mtime(self) -> Optional[float]:
        try:
            return os.stat(self.path).st_mtime
        except OSError:
            return None

    def holder(self) -> Optional[str]:
        rec = self._read()
        return rec.get("holder") if rec else None

    def is_leader(self) -> bool:
        return self.role == "leader"

    def verify(self) -> bool:
        """Fencing check: does the lease FILE, read right now, still name
        us? Lock-free and state-free by design — it must stay truthful
        even while the heartbeat thread is hung inside an election step
        holding ``_lock`` (the one scenario where the cached role lies).
        Used by the autoscaler immediately before firing a lever."""
        if self.role != "leader":
            return False
        rec = self._read()
        return rec is not None and rec.get("holder") == self.holder_id

    def _set_role(self, role: str, rec: Optional[Dict[str, Any]],
                  reason: str) -> None:
        if role == self.role:
            return
        self.role = role
        event = {"ts": time.time(), "role": role,
                 "holder": (rec or {}).get("holder"),
                 "seq": int((rec or {}).get("seq", 0)),
                 "reason": reason, "id": self.holder_id}
        self.elections.append(event)
        logger.info("lease %s: %s -> %s (%s)", self.path, self.holder_id,
                    role, reason)
        if self.on_transition is not None:
            try:
                self.on_transition(event)
            except Exception:
                logger.exception("lease transition callback failed")

    # ------------------------------------------------------------ election
    def ensure(self) -> str:
        """One election step: beat if held, acquire if free/stale,
        observe otherwise. Non-blocking when another step (e.g. a hung
        heartbeat) is already in flight — the caller gets the last
        settled role, and a hung beat simply stops refreshing the mtime,
        which is exactly what lets a follower take over."""
        if not self._lock.acquire(blocking=False):
            return self.role
        try:
            return self._ensure_locked()
        finally:
            self._lock.release()

    def _ensure_locked(self) -> str:
        rec = self._read()
        mtime = self._mtime()
        if rec is not None and rec.get("holder") == self.holder_id:
            # we hold it: heartbeat. The chaos point sits BEFORE the
            # beat — a hang here leaves the mtime stale (takeover feed),
            # a fault skips the beat entirely.
            beat_fault = None
            try:
                chaos.inject("serving.autoscale.lease")
            except Exception as e:
                beat_fault = e
            fresh = self._read()  # post-hang/fault re-check: still ours?
            if fresh is None or fresh.get("holder") != self.holder_id:
                self._set_role("follower", fresh, "lease_lost")
                return self.role
            if beat_fault is not None:
                # a faulted beat skips the mtime touch: repeated faults
                # age the lease out and a follower takes over
                logger.warning("lease heartbeat chaos fault: %r", beat_fault)
                self._set_role("leader", fresh, "heartbeat_faulted")
                return self.role
            try:
                os.utime(self.path)
            except OSError:
                pass
            self.seq = int(rec.get("seq", 0))
            self._set_role("leader", rec, "heartbeat")
            return self.role
        stale = (rec is None or mtime is None
                 or time.time() - mtime > self.lease_s)
        if stale:
            self._try_take(rec)
        else:
            self._set_role("follower", rec, "observed_holder")
        return self.role

    def _try_take(self, prev: Optional[Dict[str, Any]]) -> None:
        # Acquisition/takeover runs under a brief O_EXCL take-lock:
        # without it two followers can BOTH os.replace a stale lease and
        # both confirm (the second replace landing between the first's
        # replace and its re-read), minting dual leaders with the SAME
        # seq token. Losing the lock just means another election is in
        # progress — stay follower and re-observe next heartbeat.
        lock = self.path + ".takelock"
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
        except FileExistsError:
            try:
                if time.time() - os.stat(lock).st_mtime > \
                        max(self.lease_s, 5.0):
                    os.unlink(lock)  # a crashed elector's leftover
            except OSError:
                pass
            self._set_role("follower", self._read(),
                           "election_in_progress")
            return
        except OSError:
            self._set_role("follower", self._read(),
                           "election_in_progress")
            return
        try:
            # re-validate UNDER the lock: another elector may have just
            # won and heart-beaten before we got here
            cur = self._read()
            mtime = self._mtime()
            if (cur is not None and mtime is not None
                    and time.time() - mtime <= self.lease_s
                    and cur.get("holder") != self.holder_id):
                self._set_role("follower", cur, "lost_race")
                return
            rec = {"format": LEASE_FORMAT, "holder": self.holder_id,
                   "seq": int((cur or prev or {}).get("seq", 0)) + 1,
                   "acquired_at": time.time()}
            d = os.path.dirname(os.path.abspath(self.path)) or "."
            fd, tmp = tempfile.mkstemp(prefix=".lease-", dir=d)
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(rec, f)
                if cur is None and not os.path.exists(self.path):
                    try:
                        os.link(tmp, self.path)  # atomic: fails if raced
                    except (FileExistsError, OSError):
                        self._set_role("follower", self._read(),
                                       "lost_race")
                        return
                else:
                    os.replace(tmp, self.path)  # takeover, serialized
                    tmp = None
            finally:
                if tmp is not None:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
            confirm = self._read()
            if confirm is not None and \
                    confirm.get("holder") == self.holder_id:
                self.seq = int(confirm.get("seq", rec["seq"]))
                self._set_role("leader", confirm,
                               "acquired" if prev is None else "takeover")
            else:
                self._set_role("follower", confirm, "lost_race")
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    def release(self) -> None:
        """Give the lease up voluntarily (graceful shutdown): unlink only
        when WE hold it, so a follower's shutdown never revokes the live
        leader."""
        with self._lock:
            rec = self._read()
            if rec is not None and rec.get("holder") == self.holder_id:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
                self._set_role("follower", None, "released")

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "LeaseElection":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"lease-election-{self.holder_id}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.ensure()
            except Exception:
                logger.exception("lease election step failed")

    def stop(self, release: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, self.lease_s * 2))
            self._thread = None
        if release:
            self.release()

    def snapshot(self) -> Dict[str, Any]:
        rec = self._read()
        return {"path": self.path, "id": self.holder_id,
                "role": self.role, "lease_s": self.lease_s,
                "holder": (rec or {}).get("holder"),
                "seq": int((rec or {}).get("seq", 0)),
                "age_s": (None if self._mtime() is None
                          else round(time.time() - self._mtime(), 3)),
                "elections": list(self.elections)}

    def __enter__(self) -> "LeaseElection":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


# ====================================================== multi-router client
class MultiRouterClient:
    """Client-side failover across N shared-nothing routers.

    ``endpoints`` is a static ``["host:port", ...]`` list, or pass
    ``config`` (a :class:`FleetConfig`) to follow the live router roster.
    Requests ROUND-ROBIN across routers (each router's SLO monitor and
    hedging p99s learn from the share it serves) and FAIL OVER to the
    next router on: connection faults (the SIGKILL drill), router 5xx
    (500/502), and ``503 no_healthy_workers`` (a router whose probe view
    is momentarily empty — a peer with a warmer view can still serve).
    A shed 503 (``Retry-After``: every worker overloaded) and 504
    (deadline spent) are TERMINAL — every router fronts the same
    workers, so retrying elsewhere would only hammer them harder or
    double-spend an expired deadline.
    """

    def __init__(self, endpoints: Optional[List[str]] = None,
                 config: Optional[FleetConfig] = None,
                 timeout_s: float = 60.0, keepalive: bool = True,
                 protocol: str = "binary"):
        if not endpoints and config is None:
            raise ValueError("need endpoints or a FleetConfig")
        if protocol not in ("binary", "json"):
            raise ValueError(f"unknown protocol {protocol!r}")
        self._static = list(endpoints or [])
        self._config = config
        self.timeout_s = float(timeout_s)
        self._rr = itertools.count()
        #: reuse HTTP/1.1 connections across requests; ``False`` restores
        #: the one-connection-per-request behaviour (the bench's baseline
        #: arm measures exactly that TCP-setup tax — ISSUE 18)
        self.keepalive = bool(keepalive)
        #: preferred predict encoding; a 415 from a wire-disabled fleet
        #: downgrades ONCE and is cached (all routers front the same
        #: workers, so one verdict covers the client)
        self.protocol = protocol
        self._wire_ok: Optional[bool] = None
        self.pool = wire.ConnectionPool()
        # guards: requests_total, failovers_total, router_requests, wire_downgrades_total
        self._lock = threading.Lock()
        self.requests_total = 0
        self.failovers_total = 0
        self.router_requests: Dict[str, int] = {}
        self.wire_downgrades_total = 0

    def endpoints(self) -> List[str]:
        if self._config is not None:
            routers = self._config.routers()
            eps = [routers[k] for k in sorted(routers)]
            if eps:
                return eps
        return list(self._static)

    def _http(self, address: str, method: str, path: str, body, headers,
              timeout: float) -> Tuple[int, Dict[str, str], bytes]:
        if self.keepalive:
            return self.pool.request(address, method, path, body=body,
                                     headers=headers or {}, timeout=timeout)
        host, port = address.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, dict(resp.getheaders()), resp.read()
        finally:
            conn.close()

    @staticmethod
    def _retryable(status: int, data: bytes) -> bool:
        if status in (500, 502):
            return True
        if status == 503:
            try:
                reason = json.loads(data.decode()).get("reason")
            except Exception:
                reason = None
            return reason == "no_healthy_workers"
        return False

    def request(self, method: str, path: str, body: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                timeout_s: Optional[float] = None
                ) -> Tuple[int, Dict[str, str], bytes]:
        """One request with router failover; raises only when EVERY
        router is unreachable (the last connection error propagates)."""
        eps = self.endpoints()
        if not eps:
            raise RuntimeError("no router endpoints known")
        with self._lock:
            self.requests_total += 1
            start = next(self._rr) % len(eps)
        order = eps[start:] + eps[:start]
        timeout = self.timeout_s if timeout_s is None else timeout_s
        last_err: Optional[BaseException] = None
        last_5xx = None
        for i, ep in enumerate(order):
            if i:
                with self._lock:
                    self.failovers_total += 1
            try:
                status, hdrs, data = self._http(ep, method, path, body,
                                                headers, timeout)
            except Exception as e:
                # a dead router (the SIGKILL drill) poisons every pooled
                # connection to it — drop them so failback reconnects
                self.pool.invalidate(ep)
                last_err = e
                continue
            with self._lock:
                self.router_requests[ep] = self.router_requests.get(ep, 0) + 1
            if self._retryable(status, data):
                last_5xx = (status, hdrs, data)
                continue
            return status, hdrs, data
        if last_5xx is not None:
            return last_5xx  # every router answered; surface the response
        raise last_err  # every router unreachable

    def predict(self, model: str, inputs, timeout_ms: Optional[float] = None,
                timeout_s: Optional[float] = None,
                protocol: Optional[str] = None
                ) -> Tuple[int, Dict[str, Any]]:
        """Predict convenience: returns ``(status, payload)``.

        ``protocol`` overrides the client default ("binary"/"json"). The
        binary path ships inputs as a CRC-framed ndarray frame and gets
        the response tensor back without JSON marshalling (``outputs`` is
        an ndarray); a 415 from a wire-disabled fleet falls back to JSON
        for this request and caches the verdict. Error responses are JSON
        on both protocols, so the payload shape is identical."""
        proto = self.protocol if protocol is None else protocol
        if proto not in ("binary", "json"):
            raise ValueError(f"unknown protocol {proto!r}")
        if proto == "binary" and self._wire_ok is not False:
            frame = wire.encode_predict_request(inputs, timeout_ms=timeout_ms)
            status, hdrs, data = self.request(
                "POST", f"/v1/models/{model}/predict", body=frame,
                headers={"Content-Type": wire.CONTENT_TYPE},
                timeout_s=timeout_s)
            if status != 415:
                if status == 200:
                    self._wire_ok = True
                    ctype = next((v for k, v in hdrs.items()
                                  if k.lower() == "content-type"), "")
                    if ctype.split(";")[0].strip() == wire.CONTENT_TYPE:
                        name, version, out, fr = \
                            wire.decode_predict_response(data)
                        try:
                            payload = {"model": name, "version": version,
                                       "outputs": np.array(out)}
                        finally:
                            out = None
                            fr.close()
                        return status, payload
                    # a JSON-only worker behind a wire-capable router:
                    # the router transcoded — parse as JSON below
                return status, self._json_payload(data)
            # 415: the fleet speaks JSON only — cache and fall through
            with self._lock:
                if self._wire_ok is not False:
                    self.wire_downgrades_total += 1
                self._wire_ok = False
        req: Dict[str, Any] = {"inputs": inputs}
        if isinstance(inputs, np.ndarray):
            req["inputs"] = inputs.tolist()
            req["dtype"] = str(inputs.dtype)
        if timeout_ms is not None:
            req["timeout_ms"] = float(timeout_ms)
        status, _, data = self.request(
            "POST", f"/v1/models/{model}/predict",
            body=json.dumps(req).encode(),
            headers={"Content-Type": "application/json"},
            timeout_s=timeout_s)
        return status, self._json_payload(data)

    @staticmethod
    def _json_payload(data: bytes) -> Dict[str, Any]:
        try:
            return json.loads(data.decode())
        except Exception:
            return {"raw": data.decode(errors="replace")[:200]}

    def close(self) -> None:
        """Drop every pooled connection (idempotent)."""
        self.pool.close()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"requests_total": self.requests_total,
                    "failovers_total": self.failovers_total,
                    "router_requests": dict(self.router_requests),
                    "wire_downgrades_total": self.wire_downgrades_total,
                    "pool": self.pool.snapshot()}


# ========================================================= router processes
@dataclasses.dataclass
class RouterSpec:
    """One router process's configuration (JSON-serializable; the spec
    file IS the router's argv). Field names mirror
    :class:`~deeplearning4j_tpu.serving.fleet.WorkerSpec` where the
    supervisor machinery reads them (``worker_id`` is aliased)."""

    router_id: str
    config_path: str
    #: lease file for autoscaler leader election (default: next to the
    #: config). Only consulted when ``autoscaler`` is set.
    lease_path: Optional[str] = None
    lease_s: float = 2.0
    #: FleetRouter constructor kwargs (hedge knobs, probe intervals, ...)
    router_kw: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: SLOMonitor windows + target for THIS router's fleet-wide monitor
    slo_windows_s: Optional[List[int]] = None
    slo_target: Optional[Dict[str, float]] = None
    #: AutoscalerConfig kwargs; ``None`` runs the router with no
    #: autoscaler at all (pure data plane)
    autoscaler: Optional[Dict[str, Any]] = None
    host: str = "local"
    jax_platforms: str = "cpu"
    host_device_count: int = 1
    heartbeat_interval_s: float = 0.5

    @property
    def worker_id(self) -> str:  # the supervisor's handle/file naming key
        return self.router_id

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class RouterSupervisor(FleetSupervisor):
    """Launch + watch + restart N router processes: the
    :class:`FleetSupervisor` pattern one level up. Port-file readiness
    (written only after the router probed its workers, registered in the
    shared config, and is serving), heartbeat + exit-code watchdog,
    budgeted restarts — all inherited; only the subprocess module and
    the leak-guard registries differ. ``kill_router`` is the chaos
    drill's SIGKILL (the watchdog relaunches within budget)."""

    _worker_module = "deeplearning4j_tpu.serving.control_plane"

    @staticmethod
    def _track(proc: subprocess.Popen) -> None:
        _track_router(proc)

    @staticmethod
    def _active_list() -> List["RouterSupervisor"]:
        return _registry.active

    def router_ids(self) -> List[str]:
        return self.worker_ids()

    def kill_router(self, router_id: str) -> int:
        return self.kill_worker(router_id)

    def restart_router(self, router_id: str) -> int:
        return self.restart_worker(router_id)


def router_main(spec_path: str) -> int:
    """Router process entry point (``python -m
    deeplearning4j_tpu.serving.control_plane <spec.json>``): build the
    config-backed :class:`FleetRouter`, optionally a lease-elected
    :class:`SLOAutoscaler`, register in the shared router roster, write
    the readiness port file, heartbeat until SIGTERM, then deregister
    and release the lease on the way out."""
    import signal

    with open(spec_path) as f:
        spec = json.load(f)

    from deeplearning4j_tpu.serving.autoscale import (AutoscalerConfig,
                                                      SLOAutoscaler)
    from deeplearning4j_tpu.serving.router import FleetRouter
    from deeplearning4j_tpu.serving.slo import SLOMonitor, SLOTarget

    stop = threading.Event()

    def _graceful(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    rid = spec["router_id"]
    config = FleetConfig(spec["config_path"], create=True)
    slo_kw: Dict[str, Any] = {}
    if spec.get("slo_windows_s"):
        slo_kw["windows_s"] = tuple(int(w) for w in spec["slo_windows_s"])
    target = (SLOTarget(**spec["slo_target"])
              if spec.get("slo_target") else None)
    router = FleetRouter(config, slo=SLOMonitor(target=target, **slo_kw),
                         **(spec.get("router_kw") or {}))
    router.router_id = rid
    router.attach_config(config)
    election = auto = None
    if spec.get("autoscaler") is not None:
        lease_path = spec.get("lease_path") or (spec["config_path"]
                                                + ".autoscaler.lease")
        # lease identity is per PROCESS INCARNATION, not per router id: a
        # relaunched router finding its predecessor's holder id in the
        # lease file must NOT silently resume a dead incarnation's lease
        # (skipping the election and the fencing-seq bump) — it re-enters
        # as a follower and wins the lease properly or not at all
        election = LeaseElection(lease_path,
                                 holder_id=f"{rid}@{os.getpid()}",
                                 lease_s=float(spec.get("lease_s", 2.0)))
        auto = SLOAutoscaler(router,
                             config=AutoscalerConfig(**spec["autoscaler"]),
                             election=election)
    port = router.start(0)
    if election is not None:
        election.start()
    if auto is not None:
        auto.start()
    config.set_router(rid, f"127.0.0.1:{port}")
    # the port file is the readiness signal: written only after the
    # router has probed its workers (FleetRouter.start's first probe
    # cycle), registered itself, and is serving — atomic, like the
    # fleet workers'
    info = {"port": port, "pid": os.getpid(), "router_id": rid}
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(spec["port_file"]))
    with os.fdopen(fd, "w") as f:
        json.dump(info, f)
    os.replace(tmp, spec["port_file"])

    hb = spec["heartbeat_file"]
    interval = float(spec.get("heartbeat_interval_s", 0.5))
    while not stop.wait(interval):
        with open(hb, "a"):
            os.utime(hb)
    # graceful exit: leave the roster, stop acting, release the lease so
    # a follower can take over without waiting out the window
    try:
        config.remove_router(rid)
    except Exception:
        logger.exception("router %s deregistration failed", rid)
    if auto is not None:
        auto.stop()
    if election is not None:
        election.stop(release=True)
    router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(router_main(sys.argv[1]))
