"""Warmup manifests: the record that makes cold start replayable.

A warmed :class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher`
knows exactly which XLA programs its steady state needs — one per
(bucket, replica, dtype). That knowledge dies with the process, so every
restart (and every registry hot-swap) used to rediscover it by compiling on
live traffic. A :class:`WarmupManifest` persists it as JSON next to the
model archive (``<archive>.warmup.json``):

- ``ModelRegistry.load`` finds the manifest and replays it — the batcher is
  constructed with the RECORDED bucket set (including buckets minted for
  oversized requests under the previous process's traffic) and warmed from
  the recorded input signature, so the model reaches READY having compiled
  exactly the manifest's pairs and *nothing compiles on live traffic*.
- With the persistent executable cache enabled
  (:mod:`deeplearning4j_tpu.runtime.compile_cache`), each replayed warmup
  compile is a cache *hit* — deserialization instead of XLA compilation —
  so time-to-first-ready collapses (measured by ``bench.py --coldstart``;
  ``serving_warmup_seconds`` on ``/metrics``).
- A registry hot-swap inherits the OLD entry's manifest automatically, so
  the replacement pre-warms the full live bucket set before taking
  traffic.

A missing, corrupt, or stale manifest is never fatal: the registry falls
back to the ordinary cold path (default buckets, warm-on-example or
compile-on-traffic) and writes a fresh manifest after warmup.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

ArrayOrDict = Union[np.ndarray, Dict[str, np.ndarray]]

logger = logging.getLogger(__name__)

MANIFEST_SUFFIX = ".warmup.json"
_FORMAT = "dl4j-tpu-warmup-v1"

#: Key used for the single-array (MultiLayerNetwork-style) input signature.
_SINGLE = "__single__"


def manifest_path(archive_path: str) -> str:
    """Where a model archive's warmup manifest lives (next to it)."""
    return archive_path + MANIFEST_SUFFIX


def atomic_replace(path: str, writer, prefix: str = ".tmp-",
                   suffix: str = "") -> None:
    """Crash-safe file write shared by the serving sidecars (warmup
    manifests, dtype-policy sidecars, quantized archives): ``writer(tmp)``
    fills a temp file in the target's own directory (same filesystem, so
    the final ``os.replace`` is atomic — the discipline of
    ``train/checkpoint.py``), then the rename lands it; any failure
    unlinks the temp so a crash leaves either the old file or none,
    never a torn one."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=prefix, suffix=suffix, dir=d)
    os.close(fd)
    try:
        writer(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


@dataclasses.dataclass
class WarmupManifest:
    """Everything needed to rebuild a batcher's warm state offline.

    ``inputs`` maps input name (or ``__single__``) to
    ``{"shape_tail": [...], "dtype": "float32"}`` — the per-row feature
    signature warmup examples are built from. ``pairs`` is the audit
    record: every (bucket, replica, dtype) the recording batcher actually
    compiled, the bound "compiles on replay <= recorded pairs" is checked
    against.
    """

    inputs: Dict[str, Dict[str, object]]
    buckets: List[int]
    replicas: int
    pairs: List[Tuple[int, int, str]]
    max_batch_size: int = 0  # 0 = unrecorded (fall back to max bucket)
    model: str = ""
    created_at: float = 0.0
    #: serving dtype policy of the recording batcher (ISSUE 8) — recorded
    #: so a restart's audit trail shows WHY int8 pairs appear in ``pairs``
    #: (the replayed warmup itself re-derives quantized variants from the
    #: model's own embedded policy, which stays authoritative)
    policy: Optional[dict] = None
    #: measured device bytes of the recording served model (ISSUE 11):
    #: lets a registry COLD-register this archive with an accurate HBM
    #: cost estimate without restoring it first (0 = unrecorded)
    device_bytes: int = 0
    #: measured page-in wall seconds (ISSUE 11): seeds the honest
    #: ``Retry-After`` estimate before this process has paged it in once
    page_in_s: float = 0.0
    #: ParallelPlan of the recording batcher (ISSUE 20,
    #: ``ParallelPlan.describe()``): a plan-sliced warmup replayed under a
    #: DIFFERENT plan would mint different executables, so the replayer
    #: rebuilds the same slicing (or treats the manifest as cold)
    plan: Optional[dict] = None

    # ------------------------------------------------------------ construct
    @staticmethod
    def from_example(example: ArrayOrDict, buckets: List[int], replicas: int,
                     pairs: List[Tuple[int, int, str]],
                     max_batch_size: int = 0,
                     model: str = "",
                     policy: Optional[dict] = None,
                     plan: Optional[dict] = None) -> "WarmupManifest":
        if isinstance(example, dict):
            inputs = {str(k): {"shape_tail": list(v.shape[1:]),
                               "dtype": str(np.asarray(v).dtype)}
                      for k, v in example.items()}
        else:
            a = np.asarray(example)
            inputs = {_SINGLE: {"shape_tail": list(a.shape[1:]),
                                "dtype": str(a.dtype)}}
        return WarmupManifest(inputs=inputs,
                              buckets=sorted(int(b) for b in buckets),
                              replicas=int(replicas),
                              pairs=[(int(b), int(r), str(d))
                                     for b, r, d in pairs],
                              max_batch_size=int(max_batch_size),
                              model=model, created_at=time.time(),
                              policy=policy, plan=plan)

    def example(self, rows: int = 1) -> ArrayOrDict:
        """A ``rows``-row zeros warmup example matching the recorded input
        signature (zeros are what warmup uses anyway — only shape/dtype
        reach the compiler)."""
        def zeros(spec):
            return np.zeros((rows,) + tuple(int(d) for d in
                                            spec["shape_tail"]),
                            np.dtype(str(spec["dtype"])))
        if set(self.inputs) == {_SINGLE}:
            return zeros(self.inputs[_SINGLE])
        return {name: zeros(spec) for name, spec in self.inputs.items()}

    # ----------------------------------------------------------------- serde
    def to_dict(self) -> dict:
        d = {"format": _FORMAT, "model": self.model,
             "created_at": self.created_at, "inputs": self.inputs,
             "buckets": list(self.buckets), "replicas": self.replicas,
             "max_batch_size": self.max_batch_size,
             "pairs": [list(p) for p in self.pairs]}
        if self.policy is not None:
            d["policy"] = self.policy
        if self.plan is not None:
            d["plan"] = self.plan
        if self.device_bytes:
            d["device_bytes"] = int(self.device_bytes)
        if self.page_in_s:
            d["page_in_s"] = float(self.page_in_s)
        return d

    @staticmethod
    def from_dict(d: dict) -> "WarmupManifest":
        if d.get("format") != _FORMAT:
            raise ValueError(f"not a warmup manifest (format="
                             f"{d.get('format')!r}, expected {_FORMAT!r})")
        return WarmupManifest(
            inputs={str(k): dict(v) for k, v in d["inputs"].items()},
            buckets=[int(b) for b in d["buckets"]],
            replicas=int(d["replicas"]),
            pairs=[(int(b), int(r), str(dt)) for b, r, dt in
                   d.get("pairs", [])],
            max_batch_size=int(d.get("max_batch_size", 0)),
            model=str(d.get("model", "")),
            created_at=float(d.get("created_at", 0.0)),
            policy=d.get("policy"),
            device_bytes=int(d.get("device_bytes", 0)),
            page_in_s=float(d.get("page_in_s", 0.0)),
            plan=d.get("plan"))

    def save(self, path: str) -> None:
        """Atomic write (tmp + rename) — a crash mid-save must leave either
        the old manifest or none, never a torn one (same discipline as
        ``train/checkpoint.py``)."""
        def write(tmp):
            with open(tmp, "w") as f:
                json.dump(self.to_dict(), f, indent=2)
        atomic_replace(path, write, prefix=".warmup-")

    @staticmethod
    def load(path: str) -> "WarmupManifest":
        with open(path) as f:
            return WarmupManifest.from_dict(json.load(f))

    @staticmethod
    def load_for_archive(archive_path: str) -> Optional["WarmupManifest"]:
        """The manifest recorded next to ``archive_path``, or ``None`` when
        absent or unreadable (a corrupt manifest only costs the cold path,
        it never fails a load)."""
        path = manifest_path(archive_path)
        if not os.path.exists(path):
            return None
        try:
            return WarmupManifest.load(path)
        except Exception as e:
            logger.warning("ignoring unreadable warmup manifest %s (%s: %s); "
                           "falling back to cold warmup", path,
                           type(e).__name__, e)
            return None
