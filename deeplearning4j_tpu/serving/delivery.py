"""Gated continuous delivery (ISSUE 17, ``docs/fleet_serving.md``).

The fleet can hot-swap, page, scale, and stream — but before this module
a bad model version could take 100% of traffic the moment
``rolling_deploy`` readmitted a worker. Here every deploy earns traffic
through staged promotion, and every verdict lands in the event journal:

- :class:`GoldenGate` — THE gate implementation (``deploy_quantized``'s
  :class:`~deeplearning4j_tpu.serving.quantize.AccuracyGate` is now a
  subclass): candidate and golden are evaluated on a declared golden
  set, and the candidate may trail by at most ``max_delta``. Failure
  raises :class:`GateFailed` — the candidate never serves.
- :class:`GoldenSet` — the declared evaluation set, per-archive (a
  CRC-framed ``<archive>.golden`` sidecar) or per-request. A corrupted
  sidecar is :class:`GateRefused` — the deploy is refused loudly, never
  passed silently (chaos point ``serving.delivery.gate``).
- :class:`ShadowComparator` — the shadow stage's ledger: mirrored
  responses compared for top-1 disagreement and latency delta; the
  mirror is NEVER returned to clients and never feeds worker breakers
  (chaos point ``serving.delivery.shadow`` corrupts exactly what wire
  rot would — a comparison that fails its CRC refuses promotion).
- :class:`DeliveryController` — the per-deploy state machine
  (``gate -> shadow -> canary (ramped) -> promoted | rolled_back``)
  the router consults on every request; its per-version
  :class:`~deeplearning4j_tpu.serving.slo.SLOMonitor` window is the
  auto-rollback trigger.
- :class:`FeedbackLog` — the flywheel's data feed (``POST
  /v1/feedback``): client labels joined against the structured access
  log by trace id into an append-only labeled-example file.

Driven fleet-wide by ``FleetRouter.rolling_deploy(strategy="gated")``
(``serving/router.py``), which claims the deploy in the
:class:`~deeplearning4j_tpu.serving.control_plane.FleetConfig`
applied-action ledger so the whole drill is one idempotent, crash-safe
lever. Journal event types: ``delivery.gate``, ``delivery.stage``,
``delivery.shadow_stats``, ``delivery.rollback``, ``delivery.promote``.
"""

from __future__ import annotations

import json
import os
import random
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.runtime import chaos, journal
from deeplearning4j_tpu.serving.slo import SLOMonitor, SLOTarget

__all__ = [
    "DeliveryConfig", "DeliveryController", "FeedbackLog", "GateFailed",
    "GateRefused", "GoldenGate", "GoldenSet", "ShadowComparator",
    "feedback_counters", "iter_feedback_examples",
]

#: the golden-set gate's chaos point (call at every gate evaluation;
#: byte point over the CRC-framed golden-set sidecar)
GATE_POINT = "serving.delivery.gate"
#: the shadow mirror's chaos point (call at every mirror launch; byte
#: point over the mirrored response body)
SHADOW_POINT = "serving.delivery.shadow"


class GateFailed(RuntimeError):
    """The candidate failed its golden-set gate; the incumbent keeps
    serving. ``report`` carries the measured deltas."""

    def __init__(self, msg: str, report: Optional[Dict[str, Any]] = None):
        super().__init__(msg)
        self.report = report or {}


class GateRefused(GateFailed):
    """The gate could not be TRUSTED (corrupt or truncated golden set,
    unreadable sidecar) — the deploy is refused exactly like a failed
    gate; a damaged bar can degrade the answer to "no", never to a
    silently-passed candidate."""


# ============================================================ golden set
class GoldenSet:
    """The declared evaluation set a candidate must clear before it may
    serve: inputs, optional labels (default: the golden model's own
    top-1 — the **top-1 agreement** metric), and an optional declared
    ``max_delta``/``metric`` overriding the gate's default bar.

    Persisted per-archive as a CRC-framed sidecar
    (``<archive>.golden``): 4-byte LE CRC32 header + JSON payload. The
    read path passes the payload through the ``serving.delivery.gate``
    byte point BEFORE the CRC check, so injected corruption/truncation
    is exactly what torn storage would do — and is caught
    deterministically as :class:`GateRefused`."""

    def __init__(self, inputs, labels=None, max_delta: Optional[float] = None,
                 metric: Optional[str] = None):
        self.inputs = np.asarray(inputs)
        self.labels = None if labels is None else np.asarray(labels)
        self.max_delta = None if max_delta is None else float(max_delta)
        self.metric = metric

    def gate(self, default: Optional["GoldenGate"] = None) -> "GoldenGate":
        """The gate this set declares: the sidecar's ``max_delta`` /
        ``metric`` when present, else ``default`` (or the stock bar)."""
        base = default or GoldenGate()
        return GoldenGate(
            max_delta=(self.max_delta if self.max_delta is not None
                       else base.max_delta),
            metric=(self.metric if self.metric is not None else base.metric))

    @staticmethod
    def sidecar(archive_path: str) -> str:
        return archive_path + ".golden"

    def save(self, path: str) -> str:
        payload = json.dumps({
            "inputs": self.inputs.tolist(),
            "labels": None if self.labels is None else self.labels.tolist(),
            "max_delta": self.max_delta,
            "metric": self.metric,
        }).encode()
        framed = struct.pack("<I", zlib.crc32(payload)) + payload
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(framed)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "GoldenSet":
        try:
            with open(path, "rb") as f:
                framed = f.read()
        except OSError as e:
            raise GateRefused(
                f"golden set {path!r} unreadable ({e}) — deploy refused")
        if len(framed) < 4:
            raise GateRefused(
                f"golden set {path!r} truncated below its CRC header — "
                f"deploy refused")
        payload = chaos.transform_bytes("serving.delivery.gate", framed[4:])
        (crc,) = struct.unpack("<I", framed[:4])
        if zlib.crc32(payload) != crc:
            raise GateRefused(
                f"golden set {path!r} failed its CRC check (corrupt or "
                f"truncated golden set) — deploy refused, candidate never "
                f"serves")
        try:
            obj = json.loads(payload.decode())
            return cls(obj["inputs"], labels=obj.get("labels"),
                       max_delta=obj.get("max_delta"),
                       metric=obj.get("metric"))
        except Exception as e:
            raise GateRefused(
                f"golden set {path!r} unparsable after a clean CRC "
                f"({e!r}) — deploy refused")

    @classmethod
    def for_archive(cls, archive_path: str) -> Optional["GoldenSet"]:
        """The archive's declared golden set, or ``None`` when no
        sidecar exists. A sidecar that exists but cannot be trusted is
        :class:`GateRefused`, never ``None`` — a deploy must not fall
        back to ungated because its bar rotted."""
        path = cls.sidecar(archive_path)
        if not os.path.exists(path):
            return None
        return cls.load(path)


# ================================================================= gate
class GoldenGate:
    """THE deploy bar (exactly one implementation — ISSUE 17): the
    candidate's accuracy on the golden set may trail the golden model's
    by at most ``max_delta``. With explicit labels the metric is plain
    accuracy delta; without, labels default to the golden's own top-1
    predictions, making the metric **top-1 agreement** (golden accuracy
    1.0 by construction, delta = disagreement rate).

    A candidate carrying a ``dtype_policy``
    (:class:`~deeplearning4j_tpu.serving.quantize.QuantizedModel`) is
    evaluated **through its real request-quantization path** — the gate
    measures what serving would do, not a flattering f32 shortcut.
    ``golden_fn`` / ``candidate_fn`` override how each side produces
    probabilities (the fleet pipeline routes the golden side through the
    live serving path and the candidate through a real cold-loaded
    batcher)."""

    #: subclasses re-point this at their own registered chaos point
    #: (``AccuracyGate`` fires ``serving.quantize.gate``)
    chaos_point = GATE_POINT
    #: the exception class a failed bar raises (subclasses narrow it)
    failure_exc = GateFailed

    def __init__(self, max_delta: float = 0.02,
                 metric: str = "top1_agreement"):
        self.max_delta = float(max_delta)
        self.metric = metric

    @classmethod
    def from_policy(cls, policy) -> "GoldenGate":
        g = getattr(policy, "gate", None) or {}
        return cls(max_delta=float(g.get("max_delta", 0.02)),
                   metric=str(g.get("metric", "top1_agreement")))

    @staticmethod
    def _run(model, x):
        """One side's probabilities through ``model.output`` (graph
        models fed by input name)."""
        graph_inputs = list(getattr(getattr(model, "conf", None),
                                    "inputs", []) or [])
        if graph_inputs:
            if not isinstance(x, dict):
                x = {graph_inputs[0]: x}
            out = model.output(*[x[n] for n in graph_inputs])
            return np.asarray(out[0] if isinstance(out, list) else out)
        return np.asarray(model.output(x))

    def check(self, golden, candidate, inputs, labels=None,
              golden_fn: Optional[Callable[[Any], Any]] = None,
              candidate_fn: Optional[Callable[[Any], Any]] = None
              ) -> Dict[str, Any]:
        """Evaluate both sides and enforce the bar. Raises
        :attr:`failure_exc` with the report attached on failure; returns
        the report on success."""
        from deeplearning4j_tpu.evaluation import Evaluation
        chaos.inject(self.chaos_point)
        golden_probs = np.asarray(
            golden_fn(inputs) if golden_fn is not None
            else self._run(golden, inputs))
        if labels is None:
            labels = golden_probs.argmax(-1)
        labels = np.asarray(labels)
        policy = getattr(candidate, "dtype_policy", None)
        c_inputs = inputs
        if policy is not None and candidate_fn is None:
            from deeplearning4j_tpu.serving.quantize import quantize_requests
            c_inputs = quantize_requests(inputs, policy)
        cand_probs = np.asarray(
            candidate_fn(c_inputs) if candidate_fn is not None
            else self._run(candidate, c_inputs))
        ev_g, ev_c = Evaluation(), Evaluation()
        ev_g.eval(labels, golden_probs)
        ev_c.eval(labels, cand_probs)
        delta = ev_g.accuracy() - ev_c.accuracy()
        report = {"metric": self.metric,
                  "golden_accuracy": round(ev_g.accuracy(), 6),
                  "candidate_accuracy": round(ev_c.accuracy(), 6),
                  # legacy key (ISSUE 8 report shape) kept so recorded
                  # quantized-deploy reports keep their schema
                  "quantized_accuracy": round(ev_c.accuracy(), 6),
                  "accuracy_delta": round(float(delta), 6),
                  "max_delta": self.max_delta,
                  "n_examples": int(ev_g.total),
                  "passed": bool(delta <= self.max_delta)}
        if not report["passed"]:
            raise self.failure_exc(
                f"candidate failed its golden-set gate: delta "
                f"{delta:.4f} > max_delta {self.max_delta} "
                f"(golden {report['golden_accuracy']}, candidate "
                f"{report['candidate_accuracy']} over "
                f"{report['n_examples']} examples)", report)
        return report


# ======================================================== shadow stage
def _top1(obj) -> Optional[np.ndarray]:
    """Top-1 predictions out of a decoded ``outputs`` payload, or
    ``None`` when the payload has no argmax-able shape."""
    try:
        arr = np.asarray(obj, dtype=np.float64)
    except Exception:
        return None
    if arr.ndim < 1 or arr.size == 0:
        return None
    return arr.argmax(-1)


class ShadowComparator:
    """The shadow stage's ledger: every mirrored response is compared to
    the incumbent's for top-1 disagreement and latency delta. Mirrors
    are observational only — a candidate error or disagreement here
    refuses promotion; it can never touch a client response or a worker
    breaker."""

    def __init__(self, max_disagreement: float = 0.0,
                 min_samples: int = 16):
        self.max_disagreement = float(max_disagreement)
        self.min_samples = int(min_samples)
        # guards: mirrored_total, compared_total, disagreed_total, candidate_errors_total, corrupt_total, incumbent_latency_s, candidate_latency_s
        self._lock = threading.Lock()
        self.mirrored_total = 0
        self.compared_total = 0
        self.disagreed_total = 0
        self.candidate_errors_total = 0
        self.corrupt_total = 0
        self.incumbent_latency_s = 0.0
        self.candidate_latency_s = 0.0

    def observe(self, incumbent_body: bytes, candidate_status: int,
                candidate_body: bytes, incumbent_latency_s: float,
                candidate_latency_s: float, corrupt: bool = False) -> bool:
        """Fold one mirror's outcome in; returns True when the pair
        DISAGREED (or could not be compared)."""
        disagreed = False
        if corrupt:
            pass  # counted below; a corrupt comparison refuses promotion
        elif candidate_status != 200:
            pass
        else:
            try:
                inc = json.loads(incumbent_body.decode())["outputs"]
                cand = json.loads(candidate_body.decode())["outputs"]
            except Exception:
                corrupt = True
            else:
                t_inc, t_cand = _top1(inc), _top1(cand)
                disagreed = (t_inc is None or t_cand is None
                             or t_inc.shape != t_cand.shape
                             or not np.array_equal(t_inc, t_cand))
        with self._lock:
            self.mirrored_total += 1
            if corrupt:
                self.corrupt_total += 1
            elif candidate_status != 200:
                self.candidate_errors_total += 1
            else:
                self.compared_total += 1
                self.incumbent_latency_s += float(incumbent_latency_s)
                self.candidate_latency_s += float(candidate_latency_s)
                if disagreed:
                    self.disagreed_total += 1
        return disagreed or corrupt

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            compared = self.compared_total
            return {
                "mirrored_total": self.mirrored_total,
                "compared_total": compared,
                "disagreed_total": self.disagreed_total,
                "candidate_errors_total": self.candidate_errors_total,
                "corrupt_total": self.corrupt_total,
                "disagreement_rate": round(
                    self.disagreed_total / compared, 6) if compared else 0.0,
                "latency_delta_ms": round(
                    (self.candidate_latency_s - self.incumbent_latency_s)
                    / compared * 1e3, 3) if compared else 0.0,
            }

    def verdict(self) -> Optional[str]:
        """``None`` while evidence is still accruing, ``"pass"`` once
        ``min_samples`` clean comparisons agree, else the refusal
        cause. Corruption and candidate errors refuse IMMEDIATELY — a
        comparison that cannot be trusted must never be averaged away."""
        s = self.snapshot()
        if s["corrupt_total"] > 0:
            return "shadow_corrupt"
        if s["candidate_errors_total"] > 0:
            return "shadow_candidate_errors"
        if s["compared_total"] < self.min_samples:
            return None
        if s["disagreement_rate"] > self.max_disagreement:
            return "shadow_divergence"
        return "pass"


# ===================================================== delivery control
class DeliveryConfig:
    """Knobs for one gated delivery. ``canary_fractions`` is the ramp
    schedule — each step must see ``canary_min_requests`` candidate
    responses with both burn rates under the limits before the next
    step (the last step's pass is the promotion verdict). ``now_fn``
    and ``seed`` are injectable so drills replay deterministically."""

    def __init__(self, shadow_fraction: float = 0.5,
                 shadow_min_samples: int = 16,
                 shadow_max_disagreement: float = 0.0,
                 canary_fractions: Sequence[float] = (0.1, 0.3),
                 canary_min_requests: int = 16,
                 canary_target: Optional[SLOTarget] = None,
                 max_availability_burn: float = 1.0,
                 max_latency_burn: float = 1.0,
                 canary_window_s: int = 60,
                 stage_timeout_s: float = 120.0,
                 seed: int = 0,
                 now_fn: Callable[[], float] = time.monotonic):
        if not 0.0 <= shadow_fraction <= 1.0:
            raise ValueError(f"bad shadow_fraction {shadow_fraction!r}")
        fractions = tuple(float(f) for f in canary_fractions)
        if not fractions or any(not 0.0 < f <= 1.0 for f in fractions):
            raise ValueError(f"bad canary_fractions {canary_fractions!r}")
        self.shadow_fraction = float(shadow_fraction)
        self.shadow_min_samples = int(shadow_min_samples)
        self.shadow_max_disagreement = float(shadow_max_disagreement)
        self.canary_fractions = fractions
        self.canary_min_requests = int(canary_min_requests)
        self.canary_target = canary_target or SLOTarget(
            availability=0.99, latency_ms=250.0, latency_target=0.9)
        self.max_availability_burn = float(max_availability_burn)
        self.max_latency_burn = float(max_latency_burn)
        self.canary_window_s = int(canary_window_s)
        self.stage_timeout_s = float(stage_timeout_s)
        self.seed = int(seed)
        self.now_fn = now_fn


#: stages a controller moves through (terminal: promoted / rolled_back /
#: gate_failed)
STAGES = ("gate", "shadow", "canary", "promote_ready", "rollback_pending",
          "promoted", "rolled_back", "gate_failed")


class DeliveryController:
    """One gated deploy's state machine. The router consults
    :meth:`take_shadow` / :meth:`take_canary` per request, feeds
    :meth:`observe_shadow` / :meth:`observe_canary` per outcome, and the
    deploy driver calls :meth:`tick` until a terminal verdict. Every
    transition is a typed ``delivery.stage`` journal event, so the full
    gate -> shadow -> canary -> verdict history reconstructs from one
    ``/v1/debug/bundle``."""

    def __init__(self, model: str, archive: str, version,
                 candidate_worker: str, config: Optional[DeliveryConfig]
                 = None, gate_report: Optional[Dict[str, Any]] = None):
        self.model = str(model)
        self.archive = archive
        self.version = version
        self.candidate_worker = str(candidate_worker)
        self.config = config or DeliveryConfig()
        self.gate_report = gate_report or {}
        self.shadow = ShadowComparator(
            max_disagreement=self.config.shadow_max_disagreement,
            min_samples=self.config.shadow_min_samples)
        # the candidate's own per-version SLO window — the rollback
        # trigger, fed ONLY by canary outcomes (never by shadow mirrors)
        self.canary_slo = SLOMonitor(
            target=self.config.canary_target,
            windows_s=(self.config.canary_window_s,),
            now_fn=self.config.now_fn)
        self._rng = random.Random(self.config.seed)
        # guards: stage, ramp_index, canary_requests, canary_failures, client_errors, rollback_cause, history
        self._lock = threading.Lock()
        self.stage = "gate"
        self.ramp_index = 0
        self.canary_requests = 0     # candidate responses at current step
        self.canary_failures = 0     # candidate failures (client-invisible)
        self.client_errors = 0       # must stay 0 across the whole drill
        self.rollback_cause: Optional[str] = None
        self.history: List[Dict[str, Any]] = []
        self._stage_started = self.config.now_fn()
        self._record("gate")

    # ----------------------------------------------------------- stages
    # holds: _lock
    def _record(self, stage: str, **attrs) -> None:
        entry = {"stage": stage, "at": round(self.config.now_fn(), 3),
                 **attrs}
        self.history.append(entry)
        journal.emit("delivery.stage", model=self.model,
                     archive=self.archive, version=self.version,
                     candidate=self.candidate_worker, stage=stage, **attrs)

    def transition(self, stage: str, **attrs) -> None:
        with self._lock:
            if stage == self.stage:
                return
            attrs.setdefault("from_stage", self.stage)
            self.stage = stage
            self._stage_started = self.config.now_fn()
            self._record(stage, **attrs)

    @property
    def decided(self) -> bool:
        return self.stage in ("promote_ready",  # unguarded-ok: racy read
                              "rollback_pending", "promoted",
                              "rolled_back", "gate_failed")

    def canary_fraction(self) -> float:
        idx = min(self.ramp_index,  # unguarded-ok: racy read, bounds-safe
                  len(self.config.canary_fractions) - 1)
        return self.config.canary_fractions[idx]

    # ---------------------------------------------------- request hooks
    def matches(self, model: str) -> bool:
        return str(model) == self.model

    def take_shadow(self) -> bool:
        if self.stage != "shadow":  # unguarded-ok: stale read self-heals
            return False
        with self._lock:
            return self._rng.random() < self.config.shadow_fraction

    def take_canary(self) -> bool:
        if self.stage != "canary":  # unguarded-ok: stale read self-heals
            return False
        with self._lock:
            return self._rng.random() < self.canary_fraction()

    def observe_shadow(self, incumbent_body: bytes, candidate_status: int,
                       candidate_body: bytes, incumbent_latency_s: float,
                       candidate_latency_s: float,
                       corrupt: bool = False) -> bool:
        return self.shadow.observe(incumbent_body, candidate_status,
                                   candidate_body, incumbent_latency_s,
                                   candidate_latency_s, corrupt=corrupt)

    def observe_canary(self, ok: bool, latency_s: float) -> None:
        self.canary_slo.record(self.model, ok=ok, latency_s=latency_s)
        with self._lock:
            self.canary_requests += 1
            if not ok:
                self.canary_failures += 1

    def client_error(self) -> None:
        """A client-visible non-2xx attributable to the delivery drill —
        the zero-error contract's counter (must stay 0)."""
        with self._lock:
            self.client_errors += 1

    # ------------------------------------------------------- evaluation
    def _canary_burns(self) -> Tuple[int, float, float]:
        rep = self.canary_slo.report(models=[self.model]).get(self.model)
        if rep is None:
            return 0, 0.0, 0.0
        w = rep["windows"][f"{self.config.canary_window_s}s"]
        return (int(w["requests"]), float(w["availability_burn_rate"]),
                float(w["latency_burn_rate"]))

    def tick(self) -> Optional[str]:
        """Advance the state machine from accrued evidence. Returns the
        new stage when a transition fired, else ``None``. Safe to call
        from the deploy driver's wait loop at any cadence."""
        stage = self.stage  # unguarded-ok: the driver is the only ticker
        if stage not in ("shadow", "canary"):
            return None
        timed_out = (self.config.now_fn() - self._stage_started
                     > self.config.stage_timeout_s)
        if stage == "shadow":
            v = self.shadow.verdict()
            if v == "pass":
                journal.emit("delivery.shadow_stats", model=self.model,
                             archive=self.archive, verdict="pass",
                             **self.shadow.snapshot())
                self.transition("canary",
                                fraction=self.canary_fraction())
                return "canary"
            if v is not None or timed_out:
                cause = v or "shadow_timeout"
                journal.emit("delivery.shadow_stats", model=self.model,
                             archive=self.archive, verdict=cause,
                             **self.shadow.snapshot())
                return self._decide_rollback(cause)
            return None
        # canary: any breach rolls back; a full healthy step ramps
        n, avail_burn, lat_burn = self._canary_burns()
        min_evidence = max(4, self.config.canary_min_requests // 4)
        if n >= min_evidence:
            if avail_burn > self.config.max_availability_burn:
                return self._decide_rollback(
                    "slo_availability_burn",
                    availability_burn=avail_burn, requests=n)
            if lat_burn > self.config.max_latency_burn:
                return self._decide_rollback(
                    "slo_latency_burn", latency_burn=lat_burn, requests=n)
        with self._lock:
            step_done = self.canary_requests >= self.config.canary_min_requests
        if step_done:
            with self._lock:
                last = (self.ramp_index
                        >= len(self.config.canary_fractions) - 1)
                if not last:
                    self.ramp_index += 1
                    self.canary_requests = 0
                    fraction = self.canary_fraction()
            if last:
                self.transition("promote_ready",
                                availability_burn=avail_burn,
                                latency_burn=lat_burn)
                return "promote_ready"
            self._record("canary_ramp", fraction=fraction)
            return None
        if timed_out:
            return self._decide_rollback("canary_timeout", requests=n)
        return None

    def _decide_rollback(self, cause: str, **attrs) -> str:
        with self._lock:
            self.rollback_cause = cause
        self.transition("rollback_pending", cause=cause, **attrs)
        return "rollback_pending"

    # ---------------------------------------------------------- verdicts
    def finish_promoted(self) -> None:
        self.transition("promoted")
        journal.emit("delivery.promote", model=self.model,
                     archive=self.archive, version=self.version,
                     candidate=self.candidate_worker,
                     shadow=self.shadow.snapshot(),
                     client_errors=self.client_errors)  # unguarded-ok

    def finish_rolled_back(self, cause: Optional[str] = None) -> None:
        cause = (cause or self.rollback_cause  # unguarded-ok: settled
                 or "unknown")
        self.transition("rolled_back", cause=cause)
        journal.emit("delivery.rollback", model=self.model,
                     archive=self.archive, version=self.version,
                     candidate=self.candidate_worker, cause=cause,
                     shadow=self.shadow.snapshot(),
                     client_errors=self.client_errors)  # unguarded-ok

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "model": self.model,
                "archive": self.archive,
                "version": self.version,
                "candidate_worker": self.candidate_worker,
                "stage": self.stage,
                "ramp_index": self.ramp_index,
                "canary_fraction": self.canary_fraction(),
                "canary_requests": self.canary_requests,
                "canary_failures": self.canary_failures,
                "client_errors": self.client_errors,
                "rollback_cause": self.rollback_cause,
                "gate_report": dict(self.gate_report),
                "shadow": self.shadow.snapshot(),
                "history": [dict(h) for h in self.history],
            }


# ======================================================= feedback (flywheel)
#: process-wide feedback counters (rendered as
#: ``serving_feedback_joined_total`` / ``serving_feedback_orphaned_total``)
_FEEDBACK_LOCK = threading.Lock()  # guards: (feedback counters + appends)
_FEEDBACK_COUNTS = {"joined_total": 0, "orphaned_total": 0}


def feedback_counters() -> Dict[str, int]:
    with _FEEDBACK_LOCK:
        return dict(_FEEDBACK_COUNTS)


class FeedbackLog:
    """``POST /v1/feedback``'s backing store — the data flywheel's feed
    (ROADMAP item 5): a client labels an answer it got
    (``{trace_id, label | score}``), the label is JOINED against the
    structured access log (``DL4J_TPU_ACCESS_LOG=<path>``, ISSUE 15) by
    trace id, and the joined record appends to an append-only
    labeled-example file (``DL4J_TPU_FEEDBACK_FILE``, default
    ``<access_log>.labeled.jsonl``) — model/worker/outcome/latency
    context and the label in one line, usable as training feed.

    A label whose trace id has no access-log line (rotated away, logging
    off, or never served here) is an ORPHAN: counted, not written —
    a labeled-example file must never contain label-only rows.

    The file rotates like the access log (ISSUE 19 satellite): once an
    append would push it past ``DL4J_TPU_FEEDBACK_FILE_MAX_BYTES`` it is
    atomically renamed to ``<path>.1`` (keep-1 rollover) and a fresh
    file starts — a long-running flywheel can never grow the labeled
    feed unbounded, and readers (:func:`iter_feedback_examples`, which
    feeds the scheduler's flywheel job) consult the ``.1`` file too."""

    @staticmethod
    def max_bytes() -> int:
        """``DL4J_TPU_FEEDBACK_FILE_MAX_BYTES``: size-based rotation
        threshold (0 / unset / unparsable = no rotation), mirroring
        ``DL4J_TPU_ACCESS_LOG_MAX_BYTES``."""
        try:
            return max(0, int(os.environ.get(
                "DL4J_TPU_FEEDBACK_FILE_MAX_BYTES", "0")))
        except ValueError:
            return 0

    def __init__(self, access_log_path: Optional[str] = None,
                 out_path: Optional[str] = None):
        if access_log_path is None:
            from deeplearning4j_tpu.runtime import trace
            access_log_path = trace._access_log_path()
        self.access_log_path = access_log_path
        self.out_path = out_path or os.environ.get(
            "DL4J_TPU_FEEDBACK_FILE") or (
                f"{access_log_path}.labeled.jsonl" if access_log_path
                else None)

    def _lookup(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The access-log record for ``trace_id`` (newest wins), scanning
        the live file then its keep-1 rollover."""
        if not self.access_log_path:
            return None
        found = None
        for path in (self.access_log_path, self.access_log_path + ".1"):
            try:
                with open(path) as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if rec.get("trace_id") == trace_id:
                            found = rec
                if found is not None:
                    return found
            except OSError:
                continue
        return None

    def record(self, trace_id: str, label=None, score=None, inputs=None
               ) -> Optional[Dict[str, Any]]:
        """Join one label against the access log; returns the appended
        labeled example, or ``None`` for an orphan. ``inputs`` (the
        request features, re-sent by the labelling client) rides along
        when given — that is what turns a labeled line into a training
        example the flywheel fine-tune can actually fit on."""
        rec = self._lookup(str(trace_id))
        if rec is None or self.out_path is None:
            with _FEEDBACK_LOCK:
                _FEEDBACK_COUNTS["orphaned_total"] += 1
            return None
        example = {k: v for k, v in rec.items() if k != "log"}
        example["label"] = label
        example["score"] = score
        if inputs is not None:
            example["inputs"] = inputs
        example["feedback"] = True
        line = json.dumps(example, default=str) + "\n"
        max_bytes = self.max_bytes()
        with _FEEDBACK_LOCK:
            if max_bytes:
                try:
                    size = os.path.getsize(self.out_path)
                except OSError:
                    size = 0
                if size and size + len(line.encode()) > max_bytes:
                    # atomic keep-1 rollover, same shape as the access log
                    os.replace(self.out_path, self.out_path + ".1")
            with open(self.out_path, "a") as f:
                f.write(line)
            _FEEDBACK_COUNTS["joined_total"] += 1
        return example


def iter_feedback_examples(path: str):
    """Yield labeled examples from a feedback file INCLUDING its keep-1
    rollover (``<path>.1`` first, so lines come out oldest-first across
    the rotation boundary). Malformed lines are skipped, missing files
    are empty — the flywheel's feed must read cleanly mid-rotation."""
    for p in (path + ".1", path):
        try:
            with open(p) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("feedback"):
                        yield rec
        except OSError:
            continue


def handle_feedback(raw: bytes) -> Tuple[int, Dict[str, Any]]:
    """The shared ``POST /v1/feedback`` handler (server AND router mount
    it): 200 with the joined example, 202 for an accepted-but-orphaned
    label, 400 for a malformed body."""
    try:
        body = json.loads(raw.decode() or "{}")
    except ValueError as e:
        return 400, {"error": f"malformed feedback body: {e}"}
    trace_id = body.get("trace_id")
    label, score = body.get("label"), body.get("score")
    if not trace_id:
        return 400, {"error": "feedback requires a trace_id"}
    if label is None and score is None:
        return 400, {"error": "feedback requires a label or a score"}
    example = FeedbackLog().record(trace_id, label=label, score=score,
                                   inputs=body.get("inputs"))
    if example is None:
        return 202, {"joined": False, "trace_id": trace_id,
                     "detail": "no access-log line for this trace id "
                               "(logging off, rotated away, or served "
                               "elsewhere) — label not recorded"}
    return 200, {"joined": True, "example": example}
