"""Production model serving (reference: ``ParallelInference`` + the
konduit/dl4j model-server layer).

The subsystem that puts traffic on this stack:

- :class:`ModelRegistry` (``registry.py``) — named/versioned models loaded
  from live nets, ``ModelSerializer`` archives, or the zoo; hot-swap with
  pre-warmed replacements and graceful drain.
- :class:`ContinuousBatcher` (``batcher.py``) — coalesces concurrent
  requests and pads to a fixed set of power-of-two row buckets, AOT-warmed
  at load, so XLA compilations are bounded by ``buckets x replicas``
  instead of growing with traffic. The executor is a staged pipeline
  (coalesce -> async dispatch -> completion readback) that overlaps host
  batching with device execution; ``parallel.ParallelInference`` is the
  single-model case of this batcher and its ``workers(n)`` means real
  device replicas.
- :class:`ReplicaPool` (``replica.py``) — N device-resident parameter
  copies of one model, least-loaded routing, async per-device dispatch
  through the model's own jitted ``output`` trace (bit-identical results,
  shared compile ledger).
- :class:`AdmissionController` (``admission.py``) — per-request deadlines,
  queue limits, and load shedding with explicit :class:`Overloaded` /
  :class:`DeadlineExceeded` rejections instead of unbounded queueing.
- :class:`ModelServer` (``server.py``) — stdlib-HTTP JSON front end
  (``/v1/models``, ``/v1/models/<name>/predict``, ``/healthz``,
  ``/metrics``).
- :class:`ServingMetrics` (``metrics.py``) — latency percentiles, QPS,
  queue depth, batch occupancy, compile counts, breaker state, retry
  counters; Prometheus text on ``/metrics``; the histogram is reused by
  ``runtime.profiler``.
- :class:`CircuitBreaker` / :class:`RetryPolicy` / :class:`HealthState`
  (``resilience.py``) — per-model failure containment: breaker-shed
  (:class:`CircuitOpen`), bounded retries with full jitter, and the
  health machine surfaced on ``/readyz``. Chaos-hardened via
  ``runtime.chaos`` injection points (``tests/test_chaos.py``).
- :class:`FleetRouter` / :class:`StaticFleet` (``router.py``) and
  :class:`FleetSupervisor` / :class:`WorkerSpec` (``fleet.py``) — the
  fleet tier (ISSUE 7, ``docs/fleet_serving.md``): a front-end HTTP
  router with per-worker health views, consistent rendezvous routing,
  p99-derived request hedging (first bit-identical response wins,
  duplicates suppressed by request id), transparent failover around a
  dead worker, and zero-downtime rolling deploys over N supervised
  ``ModelServer`` worker processes (heartbeat + exit-code watchdog,
  budgeted restarts, manifest-prewarmed relaunches).
- :class:`SLOMonitor` (``slo.py``) and ``capacity.py`` — the telemetry
  pair (ISSUES 9–10): per-model SLO attainment / multi-window burn rates
  and per-model resource accounting (parameter/device bytes by dtype,
  replica utilization, queue headroom, compile footprint) on
  ``/v1/slo`` + ``/v1/capacity``, fleet-aggregated at the router.
- :class:`SLOAutoscaler` (``autoscale.py``) — the closed loop (ISSUE 10,
  ``docs/observability.md``): a control thread at the router consuming
  burn rates + capacity headroom, driving runtime ``ReplicaPool`` resize
  (manifest-warmed, zero on-traffic compiles) and fleet worker count,
  with hysteresis, cooldowns, a capacity guard, and a traced, bounded
  decision log on ``/v1/autoscaler``.
- ``paging.py`` (ISSUE 11, ``docs/fleet_serving.md``) — HBM-budgeted
  model residency: under ``DL4J_TPU_HBM_BUDGET_BYTES`` (or the measured
  device budget) the registry keeps only the highest-value models
  RESIDENT, pages the rest COLD under cost-weighted-LRU eviction
  (bytes x recompile-risk x traffic EWMA, in-flight-safe via pins), and
  rehydrates on demand — single-flight, manifest-prewarmed, with honest
  ``Retry-After`` (:class:`PagingInProgress`) when a deadline cannot
  cover the wait. The router routes cold-model traffic to the worker
  with the model resident (or the most eviction-free headroom), and the
  autoscaler rebalances placement before spawning workers when the wall
  is HBM, not compute.
- ``control_plane.py`` (ISSUE 12, ``docs/fleet_serving.md``) — the
  replicated control plane: :class:`FleetConfig` (the versioned shared
  fleet-config file N routers front one worker roster through, written
  with checkpoint atomics, read with degrade-never-crash semantics),
  :class:`LeaseElection` (file-lock leader election so exactly one
  router's autoscaler acts while the rest shadow-compute),
  :class:`RouterSupervisor` + ``router_main`` (N supervised
  ``FleetRouter`` processes — port-file readiness, heartbeat watchdog,
  budgeted restarts), and :class:`MultiRouterClient` (round-robin +
  connect-fail/5xx failover across routers, so a SIGKILL'd router is
  invisible to callers).
- ``blackbox.py`` (ISSUE 15, ``docs/observability.md`` "Black box") —
  the anomaly watchdog (:class:`AnomalyWatchdog`: journal-rate +
  SLO-ring rules — breaker-flap, restart-storm, page-in-thrash,
  election churn, fast-burn — opening/closing ``incident`` events in
  the fleet event journal, ``runtime/journal.py``) and the one-command
  incident bundle (``GET /v1/debug/bundle``: journal window, traces,
  metrics, capacity, SLO, autoscaler log, config version, per-process
  stack samples, newest crash reports, in one tar.gz).
- :class:`SessionStore` (``sessions.py``, ISSUE 16,
  ``docs/fleet_serving.md`` "Session tier") — server-side
  ``rnnTimeStep`` state for streaming inference: per-session carry
  pinned to a worker via router affinity (never hedged), write-through
  CRC-framed spills with idle-TTL/byte-budget eviction and single-flight
  rehydration, drain-by-migration across rolling deploys, and a
  fixed-bucket batched step path in the batcher that stays bit-identical
  to a serial ``rnn_time_step`` loop.
- ``delivery.py`` (ISSUE 17, ``docs/fleet_serving.md`` "Gated
  delivery") — staged promotion for every deploy:
  :class:`GoldenGate`/:class:`GoldenSet` (the one golden-set gate —
  ``AccuracyGate`` is its quantized face; CRC-framed per-archive
  sidecars, corrupt = refused), :class:`ShadowComparator` (mirrored
  traffic compared off-path, never client-visible),
  :class:`DeliveryController` (shadow -> ramped canary under a
  per-version SLO window -> promote | auto-rollback, every transition a
  journal event), and :class:`FeedbackLog` (``POST /v1/feedback``
  labels joined against the access log into an append-only
  labeled-example file). Driven fleet-wide by
  ``FleetRouter.rolling_deploy(strategy="gated")``.
- :class:`Scheduler` / :class:`JobStore` (``scheduler.py``, ISSUE 19,
  ``docs/fleet_serving.md`` "Background scheduler") — the Arbiter
  analog: preemptible background fine-tunes / golden-set evals / batch
  scoring / random-grid sweeps / the feedback flywheel, run on serving
  workers' measured spare capacity, admission-gated by the live
  capacity/SLO signals, preempted within one control tick with
  bit-exact batch-skip resume, exactly-once claimed through the
  :class:`FleetConfig` ledger, every transition a journal event.
- :class:`WarmupManifest` (``manifest.py``) — persisted record of every
  compiled (bucket, replica, dtype) pair, written next to model archives
  and replayed by registry load / hot-swap so a restart reaches READY
  without compiling on live traffic (with
  ``runtime.compile_cache`` enabled, without compiling at all —
  ``docs/coldstart.md``).

Exports resolve lazily (PEP 562) so that importing one leaf —
``runtime.profiler`` pulling ``serving.metrics.LatencyHistogram`` — does
not drag the batcher/registry/HTTP stack into the training import graph.
"""

import importlib

_EXPORTS = {
    "AdmissionController": "admission",
    "DeadlineExceeded": "admission",
    "HBMBudgetExceeded": "admission",
    "Overloaded": "admission",
    "PagingInProgress": "admission",
    "ServingError": "admission",
    "ServingShutdown": "admission",
    "PagingMetrics": "paging",
    "Residency": "paging",
    "TrafficEWMA": "paging",
    "AutoscalerConfig": "autoscale",
    "SLOAutoscaler": "autoscale",
    "forecast_rate": "autoscale",
    "AnomalyWatchdog": "blackbox",
    "BurnRule": "blackbox",
    "RateRule": "blackbox",
    "FleetConfig": "control_plane",
    "LeaseElection": "control_plane",
    "MultiRouterClient": "control_plane",
    "RouterSpec": "control_plane",
    "RouterSupervisor": "control_plane",
    "ContinuousBatcher": "batcher",
    "default_buckets": "batcher",
    "model_capacity": "capacity",
    "registry_capacity": "capacity",
    "SLOMonitor": "slo",
    "SLOTarget": "slo",
    "LatencyHistogram": "metrics",
    "ServingMetrics": "metrics",
    "ModelRegistry": "registry",
    "ServedModel": "registry",
    "WarmupManifest": "manifest",
    "manifest_path": "manifest",
    "ModelServer": "server",
    "Session": "sessions",
    "SessionLost": "sessions",
    "SessionStepConflict": "sessions",
    "SessionStore": "sessions",
    "FleetRouter": "router",
    "RouterMetrics": "router",
    "StaticFleet": "router",
    "JobStore": "scheduler",
    "Scheduler": "scheduler",
    "SchedulerConfig": "scheduler",
    "FleetSupervisor": "fleet",
    "WorkerSpec": "fleet",
    "Replica": "replica",
    "ReplicaPool": "replica",
    "DeliveryConfig": "delivery",
    "DeliveryController": "delivery",
    "FeedbackLog": "delivery",
    "GateFailed": "delivery",
    "GateRefused": "delivery",
    "GoldenGate": "delivery",
    "GoldenSet": "delivery",
    "ShadowComparator": "delivery",
    "AccuracyGate": "quantize",
    "AccuracyGateFailed": "quantize",
    "CalibrationError": "quantize",
    "DtypePolicy": "quantize",
    "QuantizedModel": "quantize",
    "quantize_archive": "quantize",
    "quantize_requests": "quantize",
    "CircuitBreaker": "resilience",
    "CircuitOpen": "resilience",
    "CircuitState": "resilience",
    "HealthState": "resilience",
    "RetryPolicy": "resilience",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    mod = importlib.import_module(f"{__name__}.{submodule}")
    return getattr(mod, name)


def __dir__():
    return __all__
