"""Fleet worker lifecycle: supervised ``ModelServer`` processes (ISSUE 7).

The :class:`~deeplearning4j_tpu.serving.router.FleetRouter` routes; this
module owns the processes it routes *to*. It is the
:class:`~deeplearning4j_tpu.train.distributed.DistributedSupervisor`
pattern one level up the serving stack — heartbeat-file + exit-code
watchdog, budgeted restarts, conftest-guarded worker pids — with one key
difference: serving workers are independent fault domains, so a dead
worker is restarted *alone* while its peers keep taking traffic (an SPMD
training group, by contrast, restarts whole).

- :class:`WorkerSpec` — everything one worker process needs: archive,
  model name/version, batcher knobs, the shared persistent-compile-cache
  dir, and an optional deterministic straggler schedule (seeded
  ``AddLatency(p=...)`` on ``serving.worker.predict`` — the injected tail
  latency ``bench.py --fleet`` hedges against).
- :class:`FleetSupervisor` — spawns one subprocess per spec (``python -m
  deeplearning4j_tpu.serving.fleet <spec.json>``), waits for each
  worker's port file (written only after the registry is loaded and
  manifest-warmed, so "port known" means "ready"), watches exit codes
  and heartbeat files, and relaunches a crashed or stalled worker within
  a restart budget (`TrainingFailure` escalation when exhausted).
  ``restart_worker`` is the *intentional* relaunch (graceful SIGTERM →
  worker drains its registry and refreshes the warmup manifest → spawn on
  the new archive) that :meth:`FleetRouter.rolling_deploy` drives;
  ``kill_worker`` is the chaos drill's SIGKILL.
- Worker pids launched here register in a module-level table
  (:func:`live_worker_pids` / :func:`kill_stray_workers`) polled by the
  conftest leak guard, so no orphaned serving worker survives a test.

Worker processes run on the CPU backend by default (``JAX_PLATFORMS``
stripped from the inherited env exactly like
``train.distributed.worker_env`` — the sitecustomize TPU bootstrap must
not race the worker's own backend selection).

Multi-host fleets (ISSUE 12): ``WorkerSpec.host`` names the machine a
worker lives on, resolved through a :class:`HostAdapter` — the per-host
spawn/address seam over the ``runtime/mesh.py`` bring-up machinery
(:class:`~deeplearning4j_tpu.runtime.mesh.HostSpec`). The default
``"local"`` adapter is today's behaviour; ``loopback`` adapters are
same-machine stand-ins that let tests and drills exercise the multi-host
spawn/watchdog/endpoint paths without real remote machines; a real
remote adapter needs only ``spawn`` + ``address``. The supervisor can
also PUBLISH its live roster into a shared
:class:`~deeplearning4j_tpu.serving.control_plane.FleetConfig` so N
replicated routers (ISSUE 12 tentpole) discover workers from one
versioned file instead of holding a supervisor reference.
"""

from __future__ import annotations

import copy
import dataclasses
import json
import logging
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.runtime import journal, trace

logger = logging.getLogger(__name__)

# -------------------------------------------------------------------------
# worker-pid registry (the conftest process-leak guard polls this, exactly
# like train.distributed's)
class PidRegistry:
    """Subprocess bookkeeping for one supervised tier (fleet workers
    here; router processes in ``serving/control_plane.py`` instantiate
    their own): track spawned children, poll the live set, kill
    strays/orphans with one wait-and-prune discipline. ``active`` holds
    the tier's RUNNING supervisors (``start()``..``stop()``) — their
    children are MANAGED, not leaked, so the per-test leak guard flags
    only orphans (a module-scoped fixture fleet must survive another
    test's cleanup)."""

    def __init__(self):
        self._lock = threading.Lock()  # guards: _children
        self._children: List[subprocess.Popen] = []
        self.active: List[Any] = []   # running supervisors of this tier

    def track(self, proc: subprocess.Popen) -> None:
        with self._lock:
            self._children.append(proc)

    def live_pids(self) -> List[int]:
        with self._lock:
            self._children[:] = [p for p in self._children
                                 if p.poll() is None]
            return [p.pid for p in self._children]

    def _kill(self, pids: Optional[set] = None) -> List[int]:
        with self._lock:
            stray = [p for p in self._children if p.poll() is None
                     and (pids is None or p.pid in pids)]
            for p in stray:
                try:
                    p.kill()
                except OSError:
                    pass
            for p in stray:
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass
            self._children[:] = [p for p in self._children
                                 if p.poll() is None]
        return [p.pid for p in stray]

    def kill_stray(self) -> List[int]:
        """Kill EVERY still-live tracked child (teardown of last resort)."""
        return self._kill()

    def orphaned_pids(self) -> List[int]:
        """Live tracked pids NOT owned by any active supervisor — what
        the conftest leak guard polls."""
        managed = set()
        for sup in list(self.active):
            managed.update(sup.managed_pids())
        return [pid for pid in self.live_pids() if pid not in managed]

    def kill_orphaned(self) -> List[int]:
        """Kill only the ORPHANED children; never a live supervisor's."""
        return self._kill(set(self.orphaned_pids()))


_registry = PidRegistry()


def _track_child(proc: subprocess.Popen) -> None:
    _registry.track(proc)


def live_worker_pids() -> List[int]:
    """PIDs of fleet worker subprocesses launched through this module that
    are still alive — polled by the conftest leak guard after every test."""
    return _registry.live_pids()


def kill_stray_workers() -> List[int]:
    """Kill any still-live tracked workers (leak-guard teardown); returns
    the PIDs that had to be killed."""
    return _registry.kill_stray()


def orphaned_worker_pids() -> List[int]:
    """Live tracked worker pids NOT owned by any active supervisor — what
    the conftest leak guard polls (a supervised fixture fleet is fine; a
    worker that outlived its supervisor is a leak)."""
    return _registry.orphaned_pids()


def kill_orphaned_workers() -> List[int]:
    """Kill only the ORPHANED tracked workers (leak-guard teardown); a
    managed fixture fleet mid-suite must survive another test's leak, so
    this never touches a live supervisor's children. Returns killed pids."""
    return _registry.kill_orphaned()


#: the tier's running supervisors (see PidRegistry.active)
_active_supervisors = _registry.active


def _worker_env(spec: "WorkerSpec") -> Dict[str, str]:
    """Subprocess env for a fleet worker: strip the TPU bootstrap vars,
    PIN the worker's backend (``python -m`` imports the package — and
    therefore jax — before ``worker_main`` runs, so the platform choice
    must already be in the env or jax may race into TPU-plugin
    initialization), and put the repo on PYTHONPATH — the contract proven
    by the multihost training workers."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith("PALLAS_AXON")}
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = spec.jax_platforms
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                        f"{int(spec.host_device_count)}")
    return env


# -------------------------------------------------------------------------
# host adapters (ISSUE 12): the per-host seam the supervisor spawns and
# watches workers through. An adapter answers two questions — "launch this
# argv on your machine" (returning a Popen-compatible handle the watchdog
# polls/kills) and "at what address are your workers reachable". The
# mesh-level description of the host roster is
# ``runtime.mesh.HostSpec`` / ``runtime.mesh.loopback_hosts`` (kept there,
# next to MeshSpec, because the same roster seeds the multi-host training
# bring-up); this module holds the process-spawning side so it stays
# importable without jax.
class HostAdapter:
    """One machine's process bring-up. ``name`` is what
    :attr:`WorkerSpec.host` references; ``address`` is the host part of
    every endpoint this host's workers serve on."""

    name = "local"
    address = "127.0.0.1"

    def spawn(self, argv: List[str], env: Dict[str, str],
              stdout, stderr) -> subprocess.Popen:
        raise NotImplementedError

    def describe(self) -> Dict[str, str]:
        return {"name": self.name, "address": self.address,
                "kind": type(self).__name__}


class LocalHostAdapter(HostAdapter):
    """This machine (the default): plain subprocess spawn."""

    def spawn(self, argv, env, stdout, stderr) -> subprocess.Popen:
        return subprocess.Popen(argv, env=env, stdout=stdout,
                                stderr=stderr, text=True)


class LoopbackHostAdapter(LocalHostAdapter):
    """A NAMED same-machine "host": processes spawn locally but carry a
    distinct host identity, so tests and drills drive the multi-host
    spawn/watchdog/endpoint paths (per-host adapters, host-qualified
    endpoints, host-spread placement) without remote machines — the
    serving twin of the ``local[N]`` Spark-master trick."""

    def __init__(self, name: str, address: str = "127.0.0.1"):
        self.name = str(name)
        self.address = str(address)


def resolve_host_adapters(specs: List["WorkerSpec"],
                          hosts=None) -> Dict[str, HostAdapter]:
    """The ``{host_name: adapter}`` map for a fleet: ``hosts`` may carry
    :class:`HostAdapter` instances or ``runtime.mesh.HostSpec``-shaped
    records (``.name``/``.address``/``.spawn``); every host a spec
    references must resolve (``"local"`` always does), so a typo'd host
    fails at supervisor construction, not at first relaunch."""
    out: Dict[str, HostAdapter] = {"local": LocalHostAdapter()}
    for h in (hosts or []) if not isinstance(hosts, dict) else hosts.values():
        if isinstance(h, HostAdapter):
            out[h.name] = h
            continue
        name = getattr(h, "name", None)
        spawn = getattr(h, "spawn", "loopback")
        if name is None:
            raise TypeError(f"not a host adapter or HostSpec: {h!r}")
        if spawn in ("loopback", "local"):
            out[str(name)] = LoopbackHostAdapter(
                str(name), getattr(h, "address", "127.0.0.1"))
        else:
            raise NotImplementedError(
                f"host {name!r} wants spawn={spawn!r}; only local/loopback "
                f"adapters ship — a remote adapter implements "
                f"HostAdapter.spawn over its own transport")
    missing = sorted({getattr(s, "host", "local") for s in specs} - set(out))
    if missing:
        raise ValueError(f"worker specs reference unknown host(s) "
                         f"{missing}; pass adapters via hosts=")
    return out


# -------------------------------------------------------------------------
@dataclasses.dataclass
class WorkerSpec:
    """One worker process's configuration (JSON-serializable; the spec
    file IS the worker's argv)."""

    worker_id: str
    model_name: str
    archive: str
    version: Optional[int] = None
    batcher_kw: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: manifest-style input signature ({name|"__single__": {"shape_tail",
    #: "dtype"}}) used to build a zeros warmup example on a FIRST launch,
    #: before any warmup manifest exists next to the archive. Replays of a
    #: recorded manifest take precedence (they know the real bucket set).
    warmup_signature: Optional[Dict[str, Any]] = None
    cache_dir: Optional[str] = None          # shared persistent compile cache
    straggle: Optional[Dict[str, Any]] = None  # {"p", "ms", "seed"[, "point"]}
    #: HBM-budgeted paging (ISSUE 11): resident-byte ceiling for this
    #: worker's registry (None = env knob / measured budget / unbounded)
    hbm_budget_bytes: Optional[int] = None
    #: additional archives registered COLD ({name: archive_path}): zero
    #: HBM until first request, paged in on demand under the budget —
    #: a fleet where every worker KNOWS every model but each is resident
    #: only where traffic placed it
    extra_models: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: session tier (ISSUE 16): spill directory for streaming-session
    #: carries. The WHOLE fleet must share one directory — migration is a
    #: new worker rehydrating a spill some other worker wrote. ``None``
    #: keeps sessions off; ``""`` asks the supervisor for its fleet-shared
    #: default (``run_dir/sessions``). Needs a recurrent primary model.
    session_dir: Optional[str] = None
    #: the one fixed padded batch size every session step executes at
    session_bucket: int = 8
    #: SessionStore knobs (idle_ttl_s, byte_budget_bytes, ...)
    session_kw: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: which machine this worker lives on (ISSUE 12): the name of a
    #: :class:`HostAdapter` registered with the supervisor ("local" =
    #: this machine; loopback adapters are the tests' multi-host stand-in)
    host: str = "local"
    jax_platforms: str = "cpu"
    host_device_count: int = 1
    heartbeat_interval_s: float = 0.5

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class _WorkerHandle:
    def __init__(self, spec: WorkerSpec, run_dir: str):
        self.spec = spec
        self.run_dir = run_dir
        self.spec_path = os.path.join(run_dir, f"{spec.worker_id}.spec.json")
        self.port_file = os.path.join(run_dir, f"{spec.worker_id}.port.json")
        self.heartbeat_file = os.path.join(run_dir, f"{spec.worker_id}.hb")
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.stopping = False    # intentional stop/restart in progress
        self.relaunching = False  # watchdog relaunch in progress
        self.dead = False        # restart budget exhausted; left down
        self.restarts = 0
        self.generation = 0

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class FleetSupervisor:
    """Launch + watch + restart N independent serving workers.

    ``specs`` is a list of :class:`WorkerSpec`. The restart budget
    (``max_restarts`` within ``restart_window_s``, lifetime when None) is
    shared across the fleet — a crash-looping fleet escalates with
    :class:`~deeplearning4j_tpu.train.fault_tolerance.TrainingFailure`
    (surfaced by :meth:`check`) instead of flapping forever. Intentional
    restarts (:meth:`restart_worker`, the rolling-deploy path) do not
    consume the budget.
    """

    #: subprocess entry module + pid/active registries — class seams so
    #: RouterSupervisor (serving/control_plane.py: the same supervisor
    #: pattern one level up, over router processes) reuses this machinery
    #: wholesale while keeping its own leak-guard population
    _worker_module = "deeplearning4j_tpu.serving.fleet"

    @staticmethod
    def _track(proc: subprocess.Popen) -> None:
        _track_child(proc)

    @staticmethod
    def _active_list() -> List["FleetSupervisor"]:
        return _active_supervisors

    def __init__(self, specs: List[WorkerSpec], run_dir: Optional[str] = None,
                 max_restarts: int = 3,
                 restart_window_s: Optional[float] = None,
                 heartbeat_timeout_s: float = 30.0,
                 ready_timeout_s: float = 180.0,
                 poll_s: float = 0.2,
                 hosts=None,
                 config=None):
        ids = [s.worker_id for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate worker ids: {ids}")
        self._hosts = resolve_host_adapters(specs, hosts)
        #: a shared FleetConfig-shaped object (``set_workers(endpoints)``)
        #: the supervisor publishes its live roster into on every change —
        #: what replicated routers (ISSUE 12) read instead of holding a
        #: supervisor reference
        self._config = config
        self._own_run_dir = run_dir is None
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="dl4j-fleet-")
        os.makedirs(self.run_dir, exist_ok=True)
        for s in specs:
            # "" = "the fleet-shared default": every worker spilling into
            # one directory is what makes drain-by-migration work
            if getattr(s, "session_dir", None) == "":
                s.session_dir = os.path.join(self.run_dir, "sessions")
        shared_spills = {s.session_dir for s in specs
                         if getattr(s, "session_dir", None)}
        for d in sorted(shared_spills):
            os.makedirs(d, exist_ok=True)
        self._handles: Dict[str, _WorkerHandle] = {
            s.worker_id: _WorkerHandle(s, self.run_dir) for s in specs}
        self.max_restarts = int(max_restarts)
        self.restart_window_s = restart_window_s
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.poll_s = float(poll_s)
        self.restarts = 0
        self._restart_times: deque = deque()
        self._failure: Optional[BaseException] = None
        # spawn/restart/retire serialization: closes the watchdog-vs-
        # deploy double-spawn race and covers _handles roster mutations
        # guards: (spawn/restart/retire serialization)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    # ------------------------------------------------------------- spawning
    def _spawn(self, handle: _WorkerHandle) -> None:
        for stale in (handle.port_file, handle.heartbeat_file):
            try:
                os.unlink(stale)
            except OSError:
                pass
        spec = handle.spec.to_dict()
        spec["port_file"] = handle.port_file
        spec["heartbeat_file"] = handle.heartbeat_file
        with open(handle.spec_path, "w") as f:
            json.dump(spec, f, indent=2)
        # output to temp FILES, not pipes (a chatty worker must not block
        # on a full pipe buffer and read as a stalled straggler)
        out_f = tempfile.NamedTemporaryFile(
            mode="w+", prefix=f"dl4j-fleet-{handle.spec.worker_id}-out-",
            dir=self.run_dir, delete=False)
        err_f = tempfile.NamedTemporaryFile(
            mode="w+", prefix=f"dl4j-fleet-{handle.spec.worker_id}-err-",
            dir=self.run_dir, delete=False)
        adapter = self._hosts[getattr(handle.spec, "host", "local")]
        proc = adapter.spawn(
            [sys.executable, "-m", self._worker_module, handle.spec_path],
            env=_worker_env(handle.spec), stdout=out_f, stderr=err_f)
        proc._dl4j_capture = (out_f, err_f)  # type: ignore[attr-defined]
        self._track(proc)
        handle.proc = proc
        handle.port = None
        handle.generation += 1
        # every process bring-up is a journal event (ISSUE 15): initial
        # start, watchdog relaunch and deploy restart all leave a record
        journal.emit("fleet.worker_spawn",
                     worker=handle.spec.worker_id, pid=proc.pid,
                     generation=handle.generation,
                     host=getattr(handle.spec, "host", "local"))

    @staticmethod
    def _stderr_tail(handle: _WorkerHandle, n: int = 2000) -> str:
        try:
            _, err_f = getattr(handle.proc, "_dl4j_capture", (None, None))
            err_f.flush()
            err_f.seek(0, os.SEEK_END)
            size = err_f.tell()
            err_f.seek(max(0, size - n))
            return err_f.read()
        except Exception:
            return "<no stderr captured>"

    def _wait_port(self, handle: _WorkerHandle,
                   timeout_s: Optional[float] = None) -> int:
        """Block until the worker writes its port file (it does so only
        AFTER the registry is loaded and warmed — ready, not just alive)."""
        timeout_s = self.ready_timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if handle.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {handle.spec.worker_id!r} exited "
                    f"rc={handle.proc.returncode} before becoming ready:\n"
                    f"{self._stderr_tail(handle)}")
            try:
                with open(handle.port_file) as f:
                    info = json.load(f)
                if info.get("pid") == handle.proc.pid:
                    handle.port = int(info["port"])
                    return handle.port
            except (OSError, ValueError):
                pass
            time.sleep(0.05)
        handle.proc.kill()
        raise RuntimeError(
            f"fleet worker {handle.spec.worker_id!r} not ready after "
            f"{timeout_s:.0f}s:\n{self._stderr_tail(handle)}")

    def start(self) -> "FleetSupervisor":
        """Spawn every worker (concurrently — warmups overlap), wait for
        all to become ready, then start the watchdog. A worker failing to
        come up kills the whole just-spawned group before raising —
        a failed start must not leak processes."""
        with self._lock:
            for handle in self._handles.values():
                self._spawn(handle)
        try:
            for handle in self._handles.values():
                self._wait_port(handle)
        except BaseException:
            for handle in self._handles.values():
                if handle.alive():
                    handle.proc.kill()
                    try:
                        handle.proc.wait(timeout=10)
                    except Exception:
                        pass
                self._close_capture(handle)
            raise
        self._stop.clear()
        self._watchdog = threading.Thread(target=self._watch, daemon=True,
                                          name="FleetSupervisor")
        self._watchdog.start()
        if self not in self._active_list():
            self._active_list().append(self)
        self._publish_roster()
        return self

    # ------------------------------------------------------------ fleet API
    def managed_pids(self) -> List[int]:
        """PIDs of this supervisor's currently-live workers."""
        with self._lock:
            return [h.proc.pid for h in self._handles.values() if h.alive()]

    def endpoints(self) -> Dict[str, str]:
        """``{worker_id: "host:port"}`` for every worker that is alive
        with a known port (the router's view of the fleet). The host part
        comes from the worker's host adapter, so a multi-host fleet's
        endpoints point at the right machines."""
        out = {}
        with self._lock:
            for wid, h in self._handles.items():
                if h.port is not None and h.alive() and not h.stopping:
                    adapter = self._hosts[getattr(h.spec, "host", "local")]
                    out[wid] = f"{adapter.address}:{h.port}"
        return out

    def hosts(self) -> Dict[str, Dict[str, str]]:
        """The resolved host roster (``{name: describe()}``) plus each
        host's live worker ids — the multi-host topology surface."""
        with self._lock:
            per_host: Dict[str, List[str]] = {}
            for wid, h in self._handles.items():
                per_host.setdefault(
                    getattr(h.spec, "host", "local"), []).append(wid)
        return {name: {**adapter.describe(),
                       "workers": sorted(per_host.get(name, []))}
                for name, adapter in sorted(self._hosts.items())}

    def _publish_roster(self) -> None:
        """Best-effort push of the live endpoints into the shared fleet
        config (when attached) — called on every membership change so N
        shared-nothing routers converge on the roster within one config
        read. Publication must never take the fleet down."""
        if self._config is None:
            return
        try:
            self._config.set_workers(self.endpoints())
        except Exception:
            logger.exception("fleet roster publication failed")

    def worker_ids(self) -> List[str]:
        return sorted(self._handles)

    def worker_archive(self, worker_id: str) -> str:
        """The archive ``worker_id`` currently runs (its spec's view) —
        what a gated deploy's rollback restores the canary onto."""
        with self._lock:
            return self._handles[worker_id].spec.archive

    def check(self) -> None:
        """Raise the stored escalation (restart budget exhausted), if any."""
        if self._failure is not None:
            raise self._failure

    def kill_worker(self, worker_id: str) -> int:
        """SIGKILL a worker (the chaos drill). The watchdog notices the
        exit and restarts it within the budget. Returns the killed pid.

        The kill is the first event of an incident timeline (ISSUE 15),
        so it gets its own flagged trace span — the journal event is
        trace-linked like the breaker/failover events that follow it."""
        handle = self._handles[worker_id]
        pid = handle.proc.pid
        sp = trace.span("fleet.kill") if trace.enabled() else trace.NOOP
        with sp:
            if sp.recording:
                sp.flag("fleet")
                sp.set("worker", worker_id)
            journal.emit("fleet.worker_kill", worker=worker_id, pid=pid)
            handle.proc.kill()
        return pid

    def restart_worker(self, worker_id: str, archive: Optional[str] = None,
                       version: Optional[int] = None,
                       stop_timeout_s: float = 30.0) -> int:
        """Intentional relaunch (the rolling-deploy step): graceful
        SIGTERM (the worker drains its registry, refreshing the warmup
        manifest), then spawn — on ``archive``/``version`` when given —
        and wait ready. Does not consume the restart budget."""
        handle = self._handles[worker_id]
        # claim the handle under the lock: the watchdog sets `relaunching`
        # under the same lock before acting on a crash, so exactly one of
        # the two paths owns the handle — no double spawn
        with self._lock:
            handle.stopping = True
        # a watchdog crash-relaunch of this worker may be mid-flight
        # (spawned, waiting for the port file); let it settle before
        # replacing the process, or two children race for one handle
        settle = time.monotonic() + self.ready_timeout_s
        while handle.relaunching and time.monotonic() < settle:
            time.sleep(0.05)
        try:
            if handle.alive():
                handle.proc.terminate()
                try:
                    handle.proc.wait(timeout=stop_timeout_s)
                except subprocess.TimeoutExpired:
                    logger.warning("worker %s ignored SIGTERM; killing",
                                   worker_id)
                    handle.proc.kill()
                    handle.proc.wait(timeout=10)
            self._close_capture(handle)
            if archive is not None:
                handle.spec.archive = archive
            if version is not None:
                handle.spec.version = version
            journal.emit("fleet.worker_restart", worker=worker_id,
                         cause="intentional", archive=archive,
                         version=version)
            with self._lock:
                self._spawn(handle)
            port = self._wait_port(handle)
        finally:
            handle.stopping = False
        self._publish_roster()
        return port

    def clone_spec(self, worker_id: str, new_worker_id: str) -> WorkerSpec:
        """A deep copy of ``worker_id``'s CURRENT spec (post any rolling
        deploy) under a fresh id — what the SLO autoscaler's worker lever
        spawns (ISSUE 10). The clone shares the archive, batcher knobs
        and persistent compile cache, so it comes up manifest-prewarmed
        exactly like a rolling-deploy relaunch."""
        spec = copy.deepcopy(self._handles[worker_id].spec)
        spec.worker_id = str(new_worker_id)
        return spec

    def add_worker(self, spec: WorkerSpec,
                   ready_timeout_s: Optional[float] = None) -> int:
        """Grow the fleet by one worker at runtime (ISSUE 10: the
        autoscaler's fleet lever). Spawns ``spec``, blocks until its port
        file says ready (registry loaded + manifest-warmed), and hands it
        to the running watchdog; the router's ``/readyz`` prober admits
        it on its next cycle. Returns the worker's port."""
        if getattr(spec, "host", "local") not in self._hosts:
            raise ValueError(f"worker spec references unknown host "
                             f"{spec.host!r}; known: {sorted(self._hosts)}")
        with self._lock:
            if spec.worker_id in self._handles:
                raise ValueError(f"worker id {spec.worker_id!r} already "
                                 f"exists in this fleet")
            handle = _WorkerHandle(spec, self.run_dir)
            self._handles[spec.worker_id] = handle
            self._spawn(handle)
        try:
            port = self._wait_port(handle, ready_timeout_s)
            self._publish_roster()
            return port
        except BaseException:
            with self._lock:
                self._handles.pop(spec.worker_id, None)
            if handle.alive():
                handle.proc.kill()
                try:
                    handle.proc.wait(timeout=10)
                except Exception:
                    pass
            self._close_capture(handle)
            raise

    def remove_worker(self, worker_id: str,
                      stop_timeout_s: float = 30.0) -> None:
        """Retire one worker from the fleet (the autoscaler's scale-down
        unwind): graceful SIGTERM — the worker drains its registry and
        refreshes the warmup manifest — escalating to SIGKILL, then the
        handle is dropped so the watchdog never resurrects it. The
        router's view reconciles on its next probe cycle."""
        with self._lock:
            handle = self._handles.get(worker_id)
            if handle is None:
                raise KeyError(f"unknown worker {worker_id!r}")
            handle.stopping = True
        settle = time.monotonic() + self.ready_timeout_s
        while handle.relaunching and time.monotonic() < settle:
            time.sleep(0.05)
        if handle.alive():
            handle.proc.terminate()
            try:
                handle.proc.wait(timeout=stop_timeout_s)
            except subprocess.TimeoutExpired:
                logger.warning("worker %s ignored SIGTERM on retire; "
                               "killing", worker_id)
                handle.proc.kill()
                try:
                    handle.proc.wait(timeout=10)
                except Exception:
                    pass
        self._close_capture(handle)
        with self._lock:
            self._handles.pop(worker_id, None)
        journal.emit("fleet.worker_retire", worker=worker_id)
        self._publish_roster()

    def prewarm_manifest(self, archive: str) -> Optional[str]:
        """Ensure ``archive`` has a warmup manifest before a rolling
        deploy: when it has none, copy a live worker's current-archive
        manifest next to it (same model family — the recorded buckets /
        input signature are what the replacement must pre-warm). This is
        what makes readmission compile-free together with the shared
        persistent executable cache."""
        from deeplearning4j_tpu.serving.manifest import manifest_path
        target = manifest_path(archive)
        if os.path.exists(target):
            return target
        for handle in self._handles.values():
            src = manifest_path(handle.spec.archive)
            if os.path.exists(src) and os.path.abspath(src) != \
                    os.path.abspath(target):
                shutil.copyfile(src, target)
                return target
        return None

    # ------------------------------------------------------------- watchdog
    def _register_restart(self, cause: str) -> None:
        now = time.monotonic()
        self.restarts += 1
        self._restart_times.append(now)
        if self.restart_window_s is not None:
            while (self._restart_times and
                   now - self._restart_times[0] > self.restart_window_s):
                self._restart_times.popleft()
            recent = len(self._restart_times)
            budget = (f"{self.max_restarts} restarts in "
                      f"{self.restart_window_s:.0f}s")
        else:
            recent = self.restarts
            budget = f"{self.max_restarts} restarts"
        if recent > self.max_restarts:
            from deeplearning4j_tpu.train.fault_tolerance import \
                TrainingFailure
            raise TrainingFailure(
                f"fleet giving up after {budget} (last cause: {cause})")
        logger.warning("fleet worker failed (%s); restart %d within "
                       "budget %s", cause, recent, budget)

    @staticmethod
    def _close_capture(handle: _WorkerHandle) -> None:
        for f in getattr(handle.proc, "_dl4j_capture", ()):
            try:
                f.close()
                os.unlink(f.name)
            except (OSError, ValueError):
                pass

    def _heartbeat_stale(self, handle: _WorkerHandle) -> bool:
        if handle.port is None:  # not ready yet; readiness has its own wait
            return False
        try:
            age = time.time() - os.stat(handle.heartbeat_file).st_mtime
        except OSError:
            return False
        return age > self.heartbeat_timeout_s

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            for handle in list(self._handles.values()):
                if handle.stopping or handle.dead or handle.proc is None:
                    continue
                cause = None
                code = handle.proc.poll()
                if code is not None:
                    cause = (f"worker {handle.spec.worker_id} exited "
                             f"rc={code}")
                elif self._heartbeat_stale(handle):
                    cause = (f"worker {handle.spec.worker_id} heartbeat "
                             f"stale > {self.heartbeat_timeout_s:.0f}s")
                    handle.proc.kill()
                    try:
                        handle.proc.wait(timeout=10)
                    except Exception:
                        pass
                if cause is None:
                    continue
                # claim the handle before acting: restart_worker sets
                # `stopping` under this lock, so a crash noticed just as
                # an intentional restart begins is ceded to it instead of
                # racing two spawns onto one handle
                with self._lock:
                    if handle.stopping:
                        continue
                    handle.relaunching = True
                try:
                    self._close_capture(handle)
                    try:
                        self._register_restart(cause)
                    except BaseException as e:
                        self._failure = e
                        handle.dead = True
                        logger.error("fleet restart budget exhausted: %s",
                                     e)
                        continue
                    handle.restarts += 1
                    try:
                        # the crash relaunch is the incident timeline's
                        # recovery leg (ISSUE 15): flagged span so the
                        # journal event is trace-linked
                        sp = (trace.span("fleet.relaunch")
                              if trace.enabled() else trace.NOOP)
                        with sp:
                            if sp.recording:
                                sp.flag("fleet")
                                sp.set("worker", handle.spec.worker_id)
                            journal.emit("fleet.worker_restart",
                                         worker=handle.spec.worker_id,
                                         cause=cause,
                                         restarts=handle.restarts)
                            with self._lock:
                                self._spawn(handle)
                            self._wait_port(handle)
                        self._publish_roster()
                    except Exception:
                        logger.exception("relaunch of %s failed",
                                         handle.spec.worker_id)
                finally:
                    handle.relaunching = False

    # ------------------------------------------------------------ lifecycle
    def stop(self, timeout_s: float = 30.0) -> None:
        """Stop the watchdog, then gracefully stop every worker (SIGTERM →
        drain → manifest refresh → exit 0), escalating to SIGKILL."""
        self._stop.set()
        if self in self._active_list():
            self._active_list().remove(self)
        if self._watchdog is not None:
            self._watchdog.join(timeout=10.0)
            self._watchdog = None
        for handle in self._handles.values():
            handle.stopping = True
            if handle.alive():
                handle.proc.terminate()
        deadline = time.monotonic() + timeout_s
        for handle in self._handles.values():
            if handle.proc is None:
                continue
            try:
                handle.proc.wait(timeout=max(0.1,
                                             deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                handle.proc.kill()
                try:
                    handle.proc.wait(timeout=10)
                except Exception:
                    pass
            self._close_capture(handle)
        self._publish_roster()  # an empty roster, not a stale one

    def __enter__(self) -> "FleetSupervisor":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


# -------------------------------------------------------------------------
# worker process entry point: python -m deeplearning4j_tpu.serving.fleet
# <spec.json>
def worker_main(spec_path: str) -> int:
    with open(spec_path) as f:
        spec = json.load(f)
    # The spawn env already pinned JAX_PLATFORMS/XLA_FLAGS (jax was
    # imported with the package, before this function ran). Re-assert the
    # platform through the config too: a sitecustomize that calls
    # jax.config.update at interpreter start overrides the env var, and
    # this update — legal while backends are uninitialized — overrides it
    # back (the conftest recipe).
    os.environ.setdefault("JAX_PLATFORMS", spec.get("jax_platforms", "cpu"))
    import jax
    jax.config.update("jax_platforms", spec.get("jax_platforms", "cpu"))
    if spec.get("cache_dir"):
        from deeplearning4j_tpu.runtime.environment import get_environment
        get_environment().set_compile_cache(spec["cache_dir"])
    straggle = spec.get("straggle")
    if straggle:
        from deeplearning4j_tpu.runtime.chaos import (AddLatency,
                                                      ChaosController)
        controller = ChaosController(seed=int(straggle.get("seed", 0)))
        controller.on(straggle.get("point", "serving.worker.predict"),
                      AddLatency(float(straggle["ms"]) / 1000.0,
                                 p=float(straggle.get("p", 1.0))))
        controller.__enter__()  # process-lifetime schedule, never exited

    stop = threading.Event()

    def _graceful(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    from deeplearning4j_tpu.serving.manifest import WarmupManifest
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import ModelServer

    batcher_kw = dict(spec.get("batcher_kw") or {})
    sig = spec.get("warmup_signature")
    if sig and "warmup_example" not in batcher_kw and \
            WarmupManifest.load_for_archive(spec["archive"]) is None:
        # first launch of this archive: no manifest to replay yet — build
        # a zeros warmup example from the recorded input signature so the
        # worker still reaches READY fully AOT-warmed
        batcher_kw["warmup_example"] = WarmupManifest(
            inputs={str(k): dict(v) for k, v in sig.items()},
            buckets=[], replicas=1, pairs=[]).example()
    registry = ModelRegistry(hbm_budget_bytes=spec.get("hbm_budget_bytes"))
    served = registry.load(spec["model_name"], spec["archive"],
                           version=spec.get("version"), **batcher_kw)
    # paging catalogue (ISSUE 11): extra archives registered COLD — zero
    # HBM now, rehydrated on demand under the worker's budget with the
    # same batcher knobs as the primary model
    for extra_name, extra_archive in sorted(
            (spec.get("extra_models") or {}).items()):
        registry.load(extra_name, extra_archive, resident=False,
                      **batcher_kw)
    session_dir = spec.get("session_dir")
    if session_dir:
        # session tier (ISSUE 16): warm the fixed-bucket step program
        # BEFORE the port file (readiness) is written, from the same
        # signature the stateless warmup uses — first step never compiles
        man = WarmupManifest.load_for_archive(spec["archive"])
        if man is not None and man.inputs:
            step_example = man.example(rows=1)
        elif sig:
            step_example = WarmupManifest(
                inputs={str(k): dict(v) for k, v in sig.items()},
                buckets=[], replicas=1, pairs=[]).example(rows=1)
        else:
            raise ValueError(
                "session_dir set but neither a warmup manifest nor a "
                "warmup_signature describes the step input shape")
        served.batcher.enable_sessions(
            step_example, session_bucket=int(spec.get("session_bucket", 8)))
    server = ModelServer(registry, worker_id=spec["worker_id"],
                         session_dir=session_dir or None,
                         session_kw=spec.get("session_kw") or None)
    port = server.start(0)
    # the port file is the readiness signal: written only after the
    # registry is loaded, manifest-warmed and serving — atomic so the
    # supervisor never reads a torn record
    info = {"port": port, "pid": os.getpid(),
            "worker_id": spec["worker_id"], "version": served.version}
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(spec["port_file"]))
    with os.fdopen(fd, "w") as f:
        json.dump(info, f)
    os.replace(tmp, spec["port_file"])

    hb = spec["heartbeat_file"]
    interval = float(spec.get("heartbeat_interval_s", 0.5))
    while not stop.wait(interval):
        with open(hb, "a"):
            os.utime(hb)
    # graceful drain: queued requests complete, the warmup manifest is
    # refreshed next to the archive (traffic-minted buckets included) so
    # the NEXT launch of this archive pre-warms what we actually served
    registry.shutdown(drain=True)
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(worker_main(sys.argv[1]))
