"""Anomaly watchdog + one-command incident bundles over the event
journal (ISSUE 15; ``docs/observability.md`` "Black box").

Two consumers of :mod:`deeplearning4j_tpu.runtime.journal`:

- :class:`AnomalyWatchdog` — journal-rate + SLO-ring rules evaluated on
  the router's control cadence (the probe loop calls
  :meth:`AnomalyWatchdog.maybe_tick`; drills call :meth:`tick`
  directly). A firing rule opens an ``incident.open`` journal event
  carrying the rule name, the triggering count and the evidence seqs;
  once the rule stays quiet for ``clear_after_s`` the incident closes
  with an ``incident.close`` event and its duration. The default rule
  set names the fleet's known failure smells: **breaker-flap** (breakers
  tripping repeatedly), **restart-storm** (the supervisor relaunching
  over and over), **page-in-thrash** (the pager evicting and reloading
  in a loop — the budget is too tight for the traffic), **election
  churn** (the autoscaler lease changing hands repeatedly), plus an
  SLO-ring **fast-burn** rule over the router's fleet-wide monitor.
  Clocks are injectable so every rule unit-tests without sleeping.

- :func:`fleet_bundle` / :func:`local_bundle` — ``GET /v1/debug/bundle``:
  ONE tar.gz that makes any drill or outage a self-contained postmortem:
  the fleet-merged journal window, the kept traces, the Prometheus
  ``/metrics`` text, the ``/v1/capacity`` and ``/v1/slo`` payloads, the
  autoscaler decision log, the shared-config version, a
  ``sys._current_frames`` stack sample per process (the router fetches
  each worker's via ``/v1/debug/stacks``), the newest crash-report
  files, and a manifest listing exactly what made it in (a fetch that
  failed is named in the manifest, never silently absent).

This module imports no jax — like the router, it is pure host code.
"""

from __future__ import annotations

import glob
import io
import json
import os
import sys
import tarfile
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.runtime import journal, trace

__all__ = ["RateRule", "BurnRule", "AnomalyWatchdog", "default_rules",
           "stack_sample", "build_bundle", "local_bundle", "fleet_bundle",
           "crash_report_paths"]


# ------------------------------------------------------------------- rules
class RateRule:
    """Journal-rate rule: fires when at least ``threshold`` events of the
    given types landed within the trailing ``window_s`` (wall-anchored,
    so merged multi-process windows evaluate correctly)."""

    def __init__(self, name: str, event_types, threshold: int,
                 window_s: float, description: str = ""):
        self.name = str(name)
        self.event_types = frozenset(event_types)
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.description = description

    def evaluate(self, events: List[Dict[str, Any]], now_wall: float
                 ) -> Optional[Dict[str, Any]]:
        cutoff = now_wall - self.window_s
        hits = [e for e in events
                if e.get("type") in self.event_types
                and (e.get("ts") or 0.0) >= cutoff]
        if len(hits) < self.threshold:
            return None
        return {"count": len(hits), "threshold": self.threshold,
                "window_s": self.window_s,
                "evidence_seqs": [e.get("seq") for e in hits[-16:]],
                "evidence_trace_ids": sorted(
                    {e.get("trace_id") for e in hits
                     if e.get("trace_id")})[:16]}

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": "journal_rate",
                "event_types": sorted(self.event_types),
                "threshold": self.threshold, "window_s": self.window_s,
                "description": self.description}


class BurnRule:
    """SLO-ring rule: fires when any model's fast-window burn rate (the
    max of availability/latency burn, the autoscaler's signal) is at or
    over ``burn`` with at least ``min_requests`` in the window.
    ``monitor`` is an :class:`~deeplearning4j_tpu.serving.slo.SLOMonitor`
    (the router's fleet-wide one)."""

    def __init__(self, monitor, name: str = "slo_fast_burn",
                 window_s: int = 60, burn: float = 2.0,
                 min_requests: int = 8, description: str = ""):
        self.monitor = monitor
        self.name = str(name)
        self.window_s = int(window_s)
        self.burn = float(burn)
        self.min_requests = int(min_requests)
        self.description = description

    def evaluate(self, events, now_wall) -> Optional[Dict[str, Any]]:
        try:
            report = self.monitor.report()
        except Exception:
            return None  # a failing read must not flap an incident
        burning = {}
        for model, rep in sorted(report.items()):
            w = (rep.get("windows") or {}).get(f"{self.window_s}s")
            if not w or int(w.get("requests", 0)) < self.min_requests:
                continue
            b = max(float(w.get("availability_burn_rate", 0.0)),
                    float(w.get("latency_burn_rate", 0.0)))
            if b >= self.burn:
                burning[model] = round(b, 3)
        if not burning:
            return None
        return {"burning_models": burning, "burn_threshold": self.burn,
                "window_s": self.window_s}

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": "slo_burn",
                "window_s": self.window_s, "burn": self.burn,
                "min_requests": self.min_requests,
                "description": self.description}


def default_rules(monitor=None) -> List[Any]:
    """The stock rule set (thresholds sized for production cadences;
    drills shrink them)."""
    rules: List[Any] = [
        RateRule("breaker_flap", {"breaker.open"}, threshold=3,
                 window_s=60.0,
                 description="breakers tripping repeatedly: a worker or "
                             "model is oscillating between dead and "
                             "half-open instead of recovering"),
        RateRule("restart_storm",
                 {"fleet.worker_restart", "fleet.worker_kill"},
                 threshold=3, window_s=120.0,
                 description="the supervisor is relaunching workers in a "
                             "loop: crash loop or heartbeat starvation"),
        RateRule("page_in_thrash", {"registry.page_in", "registry.evict"},
                 threshold=6, window_s=60.0,
                 description="the pager is evicting and reloading in a "
                             "cycle: the HBM budget is too tight for the "
                             "working set"),
        RateRule("election_churn", {"autoscale.election"}, threshold=3,
                 window_s=120.0,
                 description="the autoscaler lease keeps changing hands: "
                             "leader heartbeats are starving or fencing "
                             "is racing"),
    ]
    if monitor is not None:
        rules.append(BurnRule(monitor,
                              description="fast-window burn at page-now "
                                          "levels on at least one model"))
    return rules


# ---------------------------------------------------------------- watchdog
class AnomalyWatchdog:
    """Evaluate rules over the journal on the control cadence; open and
    close ``incident`` journal events.

    ``events_fn`` supplies the event window (default: this process's
    journal — the router process sees breaker/hedge/failover/decision/
    restart events when the supervisor is co-resident, which is the
    drill topology); ``wall_fn``/``mono_fn`` are injectable clocks so
    rule units run without sleeping. ``tick()`` is the drill seam;
    ``maybe_tick()`` rate-limits to ``interval_s`` for the router's
    probe loop."""

    def __init__(self, rules: Optional[List[Any]] = None,
                 events_fn: Optional[Callable[[], List[Dict[str, Any]]]]
                 = None,
                 clear_after_s: float = 30.0, interval_s: float = 0.5,
                 wall_fn: Callable[[], float] = time.time,
                 mono_fn: Callable[[], float] = time.monotonic):
        self.rules = list(rules) if rules is not None else default_rules()
        self._events_fn = events_fn or (lambda: journal.events())
        self.clear_after_s = float(clear_after_s)
        self.interval_s = float(interval_s)
        self._wall = wall_fn
        self._mono = mono_fn
        # guards: _open, incidents_total, ticks, _last_tick
        self._lock = threading.Lock()
        self._open: Dict[str, Dict[str, Any]] = {}
        self.incidents_total = 0
        self.ticks = 0
        self._last_tick = float("-inf")

    def maybe_tick(self) -> None:
        """Tick if at least ``interval_s`` passed since the last one —
        the router probe loop's cheap call."""
        now = self._mono()
        with self._lock:
            if now - self._last_tick < self.interval_s:
                return
            self._last_tick = now
        self.tick()

    def tick(self) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the incident events (open/close)
        emitted this tick."""
        now = self._wall()
        try:
            events = [e for e in self._events_fn()
                      if not str(e.get("type", "")).startswith("incident.")]
        except Exception:
            events = []  # a failing read must not crash the control loop
        emitted: List[Dict[str, Any]] = []
        with self._lock:
            self.ticks += 1
            for rule in self.rules:
                firing = rule.evaluate(events, now)
                state = self._open.get(rule.name)
                if firing is not None:
                    if state is None:
                        self.incidents_total += 1
                        rec = journal.emit("incident.open", rule=rule.name,
                                           **firing)
                        self._open[rule.name] = {
                            "opened_ts": now, "last_firing_ts": now,
                            "open_seq": (rec or {}).get("seq"),
                            "evidence": firing}
                        if rec is not None:
                            emitted.append(rec)
                    else:
                        state["last_firing_ts"] = now
                        state["evidence"] = firing
                elif state is not None and \
                        now - state["last_firing_ts"] >= self.clear_after_s:
                    rec = journal.emit(
                        "incident.close", rule=rule.name,
                        duration_s=round(now - state["opened_ts"], 3),
                        open_seq=state.get("open_seq"))
                    del self._open[rule.name]
                    if rec is not None:
                        emitted.append(rec)
        return emitted

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"rules": [r.describe() for r in self.rules],
                    "open": {k: dict(v) for k, v in self._open.items()},
                    "incidents_total": self.incidents_total,
                    "ticks": self.ticks,
                    "clear_after_s": self.clear_after_s}

    def render_prometheus(self) -> str:
        with self._lock:
            open_rules = set(self._open)
            total = self.incidents_total
        lines = [f"incident_opens_total {total}"]
        for rule in self.rules:
            lines.append(f'incident_open{{rule="{rule.name}"}} '
                         f"{int(rule.name in open_rules)}")
        return "\n".join(lines) + "\n"


# ------------------------------------------------------------------ bundle
def stack_sample() -> Dict[str, List[str]]:
    """``sys._current_frames`` rendered per thread — the "where is every
    thread right now" page of the black box."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        out[f"{names.get(tid, 'unknown')}@{tid}"] = \
            traceback.format_stack(frame)
    return out


def crash_report_paths(n: int = 5,
                       directory: Optional[str] = None) -> List[str]:
    """The newest ``n`` CrashReportingUtil dump files (mtime order,
    newest first) from ``directory`` (default: the configured
    ``crash_dump_dir``, else cwd)."""
    if directory is None:
        from deeplearning4j_tpu.runtime.crash_reporting import \
            CrashReportingUtil
        directory = CrashReportingUtil.crash_dump_dir or os.getcwd()
    paths = glob.glob(os.path.join(directory,
                                   "dl4j-tpu-memory-crash-dump-*.txt"))

    def mtime(p):
        # a dump deleted between glob and stat (tmp reaper racing the
        # bundle pull) must not 500 the whole bundle
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0
    paths.sort(key=mtime, reverse=True)
    return paths[:max(0, int(n))]


def build_bundle(entries: Dict[str, bytes]) -> bytes:
    """Tar.gz the named entries in-memory (sorted, deterministic
    member order)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, data in sorted(entries.items()):
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            info.mtime = int(time.time())
            tf.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def _jsonb(obj: Any) -> bytes:
    return json.dumps(obj, indent=1, sort_keys=True,
                      default=str).encode()


def _collect(entries: Dict[str, bytes], errors: Dict[str, str],
             name: str, fn: Callable[[], bytes]) -> None:
    """One bundle section, best-effort: a failing fetch lands in the
    manifest's ``errors`` map instead of silently missing."""
    try:
        entries[name] = fn()
    except Exception as e:
        errors[name] = repr(e)


def _finish(entries: Dict[str, bytes], errors: Dict[str, str],
            meta: Dict[str, Any]) -> bytes:
    meta = dict(meta)
    meta["created_at"] = time.time()
    meta["incarnation"] = journal.incarnation()
    meta["errors"] = errors
    meta["contents"] = sorted(list(entries) + ["manifest.json"])
    entries["manifest.json"] = _jsonb(meta)
    return build_bundle(entries)


def _crash_report_entries(entries: Dict[str, bytes],
                          errors: Dict[str, str], n: int = 5) -> None:
    for path in crash_report_paths(n):
        def read(p=path):
            with open(p, "rb") as f:
                return f.read()
        _collect(entries, errors,
                 f"crash_reports/{os.path.basename(path)}", read)


def local_bundle(server) -> bytes:
    """One process's bundle (the worker's ``/v1/debug/bundle``):
    journal, kept traces, metrics text, capacity, SLO, stacks, crash
    reports."""
    entries: Dict[str, bytes] = {}
    errors: Dict[str, str] = {}
    evs, truncated = journal.bound_events(journal.events())
    entries["journal.json"] = _jsonb({"events": evs,
                                      "truncated": truncated,
                                      "counters": journal.counters()})
    _collect(entries, errors, "traces.json",
             lambda: _jsonb(trace.collector().traces()))
    _collect(entries, errors, "metrics.txt",
             lambda: server._render_metrics().encode())
    def cap():
        from deeplearning4j_tpu.serving import capacity
        return _jsonb(capacity.registry_capacity(server.registry))
    _collect(entries, errors, "capacity.json", cap)
    _collect(entries, errors, "slo.json", lambda: _jsonb(server.slo.report()))
    _collect(entries, errors, f"stacks/{trace.process_tag()}.json",
             lambda: _jsonb(stack_sample()))
    _crash_report_entries(entries, errors)
    return _finish(entries, errors,
                   {"kind": "worker", "worker": server.worker_id})


def fleet_bundle(router) -> bytes:
    """The fleet bundle (the router's ``/v1/debug/bundle``): the merged
    journal window, merged traces, fleet-aggregated metrics/capacity/SLO,
    the autoscaler log, the shared-config version, a stack sample for
    the router AND every ready worker (scraped via ``/v1/debug/stacks``),
    the watchdog state, and the newest crash reports — one curl away
    from a self-contained postmortem."""
    entries: Dict[str, bytes] = {}
    errors: Dict[str, str] = {}

    def merged_journal():
        evs, truncated = router.fleet_journal()
        return _jsonb({"events": evs, "truncated": truncated,
                       "counters": journal.counters()})
    _collect(entries, errors, "journal.json", merged_journal)

    def traces():
        recs, truncated = router.aggregate_traces_bounded()
        return _jsonb({"traces": recs, "truncated": truncated})
    _collect(entries, errors, "traces.json", traces)
    _collect(entries, errors, "metrics.txt",
             lambda: (router.metrics.render_prometheus(router.workers())
                      + router.render_fleet_metrics()
                      + router._render_blackbox_metrics()).encode())
    _collect(entries, errors, "capacity.json",
             lambda: _jsonb(router.fleet_capacity()))
    _collect(entries, errors, "slo.json",
             lambda: _jsonb(router.slo.report()))
    if router.autoscaler is not None:
        _collect(entries, errors, "autoscaler.json",
                 lambda: _jsonb(router.autoscaler.report()))
    if getattr(router, "watchdog", None) is not None:
        _collect(entries, errors, "watchdog.json",
                 lambda: _jsonb(router.watchdog.snapshot()))
    # the router's own stacks under a router-prefixed name: the process
    # tag can legitimately equal a worker id (an in-process ModelServer
    # set it earlier), and the per-worker scrape below must not be able
    # to collide with (and silently replace) this process's sample
    _collect(entries, errors,
             f"stacks/router-{router.router_id}.json",
             lambda: _jsonb(stack_sample()))

    def worker_stacks():
        return router._scrape_workers("/v1/debug/stacks")
    try:
        for wid, payload in sorted(worker_stacks().items()):
            entries[f"stacks/{wid}.json"] = _jsonb(
                payload.get("stacks", payload))
    except Exception as e:
        errors["stacks/workers"] = repr(e)

    meta: Dict[str, Any] = {"kind": "fleet", "router": router.router_id}
    if router._config is not None:
        try:
            meta["config"] = router._config.counters()
        except Exception as e:
            errors["config"] = repr(e)
    _crash_report_entries(entries, errors)
    return _finish(entries, errors, meta)
