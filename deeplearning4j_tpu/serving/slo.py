"""SLO attainment + multi-window burn rates (ISSUE 9).

The ROADMAP's SLO-feedback autoscaler (item 2 headroom) needs one signal:
per-model SLO attainment and burn rate, computed over the traffic a model
ACTUALLY saw — fleet-wide when fed by the
:class:`~deeplearning4j_tpu.serving.router.FleetRouter` (which sees every
client request regardless of which worker served it), per-worker when fed
by a :class:`~deeplearning4j_tpu.serving.server.ModelServer`.

Definitions (the Google-SRE shape, ``docs/observability.md``):

- an :class:`SLOTarget` declares an **availability** objective (fraction
  of requests answered successfully) and a **latency** objective
  (fraction of successful answers under ``latency_ms``),
- **attainment** over a window is the measured fraction,
- **burn rate** over a window is ``(1 - attainment) / (1 - target)`` —
  the rate at which the error budget is being spent: 1.0 = exactly on
  budget, 14.4 = the classic "page now" fast-burn threshold. Burn is
  reported over SEVERAL windows at once (default 1m / 5m / 1h) because a
  fast window catches an outage in seconds while a slow window catches a
  simmering degradation a fast window forgives.

Implementation: a per-model ring of per-second buckets (same idiom as
``ServingMetrics``'s QPS ring) holding (total, bad, ok, ok_slow) counts;
window sums walk the ring at read time, so recording is O(1) and needs no
timer thread. The clock is injectable (``now_fn``) so burn-rate math is
testable against hand-computed windows without sleeping.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


class SLOTarget:
    """One model's declared objectives. ``availability`` and
    ``latency_target`` are fractions in (0, 1); ``latency_ms`` is the
    per-request threshold the latency objective counts against."""

    __slots__ = ("availability", "latency_ms", "latency_target")

    def __init__(self, availability: float = 0.999,
                 latency_ms: float = 250.0,
                 latency_target: float = 0.99):
        if not 0.0 < availability < 1.0:
            raise ValueError(f"availability must be in (0,1): {availability}")
        if not 0.0 < latency_target < 1.0:
            raise ValueError(
                f"latency_target must be in (0,1): {latency_target}")
        self.availability = float(availability)
        self.latency_ms = float(latency_ms)
        self.latency_target = float(latency_target)

    def to_dict(self) -> Dict[str, float]:
        return {"availability": self.availability,
                "latency_ms": self.latency_ms,
                "latency_target": self.latency_target}


class _ModelWindow:
    """Per-second ring of (total, bad, ok, ok_slow) counts."""

    __slots__ = ("horizon", "times", "total", "bad", "ok", "ok_slow")

    def __init__(self, horizon_s: int):
        self.horizon = int(horizon_s)
        self.times = [-1] * self.horizon
        self.total = [0] * self.horizon
        self.bad = [0] * self.horizon
        self.ok = [0] * self.horizon
        self.ok_slow = [0] * self.horizon

    def record(self, now_s: int, ok: bool, slow: bool) -> None:
        i = now_s % self.horizon
        if self.times[i] != now_s:
            self.times[i] = now_s
            self.total[i] = self.bad[i] = self.ok[i] = self.ok_slow[i] = 0
        self.total[i] += 1
        if ok:
            self.ok[i] += 1
            if slow:
                self.ok_slow[i] += 1
        else:
            self.bad[i] += 1

    def snapshot(self) -> "_ModelWindow":
        """Consistent copy of the ring (C-speed list copies — call under
        the recording lock; the expensive summation walk then runs on
        the copy OUTSIDE it, so a /metrics scrape never stalls the
        request threads feeding :meth:`record`)."""
        snap = _ModelWindow.__new__(_ModelWindow)
        snap.horizon = self.horizon
        snap.times = self.times.copy()
        snap.total = self.total.copy()
        snap.bad = self.bad.copy()
        snap.ok = self.ok.copy()
        snap.ok_slow = self.ok_slow.copy()
        return snap

    def sums(self, now_s: int, window_s: int) -> Tuple[int, int, int, int]:
        return self.multi_sums(now_s, (window_s,))[int(window_s)]

    def multi_sums(self, now_s: int,
                   windows_s: Sequence[int]
                   ) -> Dict[int, Tuple[int, int, int, int]]:
        """Sums for SEVERAL windows in ONE ring walk: each live bucket is
        classified once into the SMALLEST window containing its age, then
        a suffix accumulation folds it into every larger window (a bucket
        younger than w is younger than every w' > w). The read path runs
        under the recording lock, so one pass — with stale/empty slots
        skipped in O(1) — keeps /metrics scrapes from stalling request
        threads."""
        ws = sorted(set(int(w) for w in windows_s))
        acc = [[0, 0, 0, 0] for _ in ws]
        times = self.times
        horizon = ws[-1]
        for i in range(self.horizon):
            age = now_s - times[i]
            if age < 0 or age >= horizon:
                continue  # future-skewed or stale (incl. never-written)
            a = acc[bisect.bisect_right(ws, age)]
            a[0] += self.total[i]
            a[1] += self.bad[i]
            a[2] += self.ok[i]
            a[3] += self.ok_slow[i]
        for j in range(1, len(ws)):  # suffix: larger windows include smaller
            for k in range(4):
                acc[j][k] += acc[j - 1][k]
        return {w: tuple(a) for w, a in zip(ws, acc)}


class SLOMonitor:
    """Fold request outcomes into per-model SLO attainment and
    multi-window burn rates; render on ``/metrics``.

    ``record(model, ok, latency_s)`` is the single feed point (the server
    and the router call it per terminal response). ``windows_s`` are the
    burn-rate windows; the ring horizon is their max.
    """

    def __init__(self, target: Optional[SLOTarget] = None,
                 windows_s: Sequence[int] = (60, 300, 3600),
                 now_fn: Callable[[], float] = time.monotonic,
                 max_models: int = 256):
        self.default_target = target or SLOTarget()
        self.windows_s = tuple(int(w) for w in windows_s)
        if not self.windows_s or min(self.windows_s) <= 0:
            raise ValueError(f"bad windows {windows_s!r}")
        self._horizon = max(self.windows_s)
        self._now_fn = now_fn
        self._lock = threading.Lock()  # guards: _models
        self._models: Dict[str, _ModelWindow] = {}
        self._targets: Dict[str, SLOTarget] = {}
        # hard cap on tracked model names: each window ring is ~5 lists x
        # horizon ints, and the feed point can see arbitrary client-sent
        # names — outcomes for names past the cap are dropped so memory
        # and /metrics cardinality stay bounded no matter the traffic
        self.max_models = int(max_models)

    def set_target(self, model: str, target: SLOTarget) -> None:
        with self._lock:
            self._targets[str(model)] = target

    def target_for(self, model: str) -> SLOTarget:
        return self._targets.get(str(model), self.default_target)

    # ------------------------------------------------------------ recording
    def record(self, model: str, ok: bool,
               latency_s: Optional[float] = None,
               create: bool = True) -> None:
        """One terminal request outcome. ``ok`` is the availability bit
        (served successfully); ``latency_s`` (ok responses only) feeds the
        latency objective. ``create=False`` records only for models
        already tracked — the router passes ``create=(status == 200)`` so
        junk client-sent names that never served cannot occupy slots
        under :attr:`max_models` (once a name HAS served, its failures
        count in full)."""
        now_s = int(self._now_fn())
        target = self.target_for(model)
        slow = (ok and latency_s is not None
                and latency_s * 1e3 > target.latency_ms)
        with self._lock:
            win = self._models.get(model)
            if win is None:
                if not create or len(self._models) >= self.max_models:
                    return  # cardinality cap: never grow without bound
                win = self._models[model] = _ModelWindow(self._horizon)
            win.record(now_s, ok, slow)

    # -------------------------------------------------------------- reading
    def recent_counts(self, model: str, seconds: int) -> list:
        """Per-second request totals for ``model`` over the last
        ``seconds`` FULL seconds, oldest first (the current partial
        second is excluded — it systematically undercounts). This is the
        short-horizon traffic-forecast feed (ISSUE 12): the autoscaler
        fits a trend over these samples to pre-scale BEFORE a burn-rate
        breach. Seconds with no traffic read 0; an untracked model reads
        all zeros."""
        seconds = max(1, min(int(seconds), self._horizon))
        now_s = int(self._now_fn())
        with self._lock:
            win = self._models.get(str(model))
            snap = win.snapshot() if win is not None else None
        out = [0] * seconds
        if snap is None:
            return out
        for i in range(snap.horizon):
            age = now_s - snap.times[i]
            if 1 <= age <= seconds:
                out[seconds - age] += snap.total[i]
        return out

    def report(self, models: Optional[Sequence[str]] = None
               ) -> Dict[str, Dict[str, Any]]:
        """Per-model, per-window attainment + burn rates.

        ``availability_burn = (bad/total) / (1 - availability_target)``;
        ``latency_burn = (ok_slow/ok) / (1 - latency_target)``. Empty
        windows report attainment 1.0 and burn 0.0 (no traffic spends no
        budget). ``models`` restricts the report (and the ring-walk cost)
        to the named models — the autoscaler's per-tick read passes its
        filter so a 256-model fleet does not pay 256 ring walks per
        control tick."""
        now_s = int(self._now_fn())
        wanted = None if models is None else {str(m) for m in models}
        # SNAPSHOT the rings under the lock (record() recycles a stale
        # slot by writing times[i] before zeroing its counts, so an
        # unlocked reader could count an hour-old bucket as current),
        # then run the expensive one-pass walk on the copies OUTSIDE it —
        # a scrape must never stall the request threads feeding record()
        with self._lock:
            snaps = {model: win.snapshot()
                     for model, win in sorted(self._models.items())
                     if wanted is None or model in wanted}
        sums = {model: snap.multi_sums(now_s, self.windows_s)
                for model, snap in snaps.items()}
        out: Dict[str, Dict[str, Any]] = {}
        for model, per_window in sums.items():
            target = self.target_for(model)
            rep: Dict[str, Any] = {"target": target.to_dict(), "windows": {}}
            for w in self.windows_s:
                t, b, o, s = per_window[w]
                avail = 1.0 - (b / t) if t else 1.0
                lat_att = 1.0 - (s / o) if o else 1.0
                rep["windows"][f"{w}s"] = {
                    "requests": t,
                    "availability": round(avail, 6),
                    "availability_burn_rate": round(
                        (1.0 - avail) / (1.0 - target.availability), 4),
                    "latency_attainment": round(lat_att, 6),
                    "latency_burn_rate": round(
                        (1.0 - lat_att) / (1.0 - target.latency_target), 4),
                }
            out[model] = rep
        return out

    def render_prometheus(self, prefix: str = "slo") -> str:
        rep = self.report()
        if not rep:
            return ""
        lines = [f"# TYPE {prefix}_availability_burn_rate gauge"]
        for model, r in rep.items():
            t = r["target"]
            lines.append(f'{prefix}_target_availability{{model="{model}"}} '
                         f"{t['availability']}")
            lines.append(f'{prefix}_target_latency_ms{{model="{model}"}} '
                         f"{t['latency_ms']}")
            for wname, w in r["windows"].items():
                lbl = f'{{model="{model}",window="{wname}"}}'
                lines.append(f"{prefix}_requests_total{lbl} {w['requests']}")
                lines.append(f"{prefix}_availability{lbl} "
                             f"{w['availability']}")
                lines.append(f"{prefix}_availability_burn_rate{lbl} "
                             f"{w['availability_burn_rate']}")
                lines.append(f"{prefix}_latency_attainment{lbl} "
                             f"{w['latency_attainment']}")
                lines.append(f"{prefix}_latency_burn_rate{lbl} "
                             f"{w['latency_burn_rate']}")
        return "\n".join(lines) + "\n"
