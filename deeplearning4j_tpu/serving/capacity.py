"""Per-model capacity and resource accounting (ISSUE 10 tentpole).

PR 9 built the flight recorder — the fleet can SEE that a model is
burning its latency budget — but nothing accounted for the resources a
scaling decision would spend: how many bytes a served model's parameters
occupy (and at which dtype, f32 vs the PR 8 int8 residency), how busy
each device replica actually is, how much admission-queue headroom is
left before shedding, and what the compile caches are holding. This
module is that missing ledger. It is a pure *reader* over the live
serving objects — it owns no state, takes no locks of its own beyond the
metrics snapshots it calls, and never mutates what it measures — so a
``/v1/capacity`` scrape can run at any time without perturbing traffic.

Accounting model (the same one HBM-budgeted model paging will need):

- **Parameter bytes** — every leaf of the model's ``train_state`` summed
  as ``size x itemsize``, broken down per dtype so int8-resident
  quantized archives (PR 8 ``weight_residency="int8"``) show their 4x
  smaller footprint honestly.
- **Device bytes** — each :class:`~deeplearning4j_tpu.serving.replica
  .ReplicaPool` replica holds a ``device_put`` copy of params + model
  state; the total is what replica scale-up actually costs, and what the
  autoscaler's capacity guard checks against the memory budget.
- **Replica utilization** — busy-fraction derived from the existing
  per-batch telemetry (``serving_replica_batches_total`` counts + the
  dispatch-to-completion histogram): the dispatch histogram's *sum* is
  the pipeline's measured busy-seconds, apportioned per replica by its
  batch share. Exported as (busy_s, window_s) PAIRS so a fleet
  aggregation can sum numerators and denominators — a fraction is
  derived at the edge, never averaged across workers.
- **Queue headroom** — admission depth vs limit, with the drain estimate
  reusing the exact :meth:`~deeplearning4j_tpu.serving.admission
  .AdmissionController.retry_after_ms` math the ``Retry-After`` shed
  hints already ship.
- **Compile footprint** — AOT executables behind this model
  (``compile_count``: the buckets x replicas ledger) plus the
  process-wide persistent executable cache's on-disk bytes.

Surfaces: ``GET /v1/capacity`` on :class:`ModelServer` (this registry),
aggregated fleet-wide by :meth:`FleetRouter.fleet_capacity` (sums +
bucket-merged histograms, never averaged percentiles), rendered as
``capacity_*`` / ``fleet_capacity_*`` gauges on the respective
``/metrics``, and reachable without a registry reference through
``runtime.profiler.capacity_stats()``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

__all__ = ["model_capacity", "process_capacity", "registry_capacity",
           "render_prometheus", "persistent_cache_bytes",
           "served_device_bytes", "served_device_dtype_bytes",
           "served_per_device_bytes",
           "attach_harvest", "detach_harvest", "device_utilization"]

# The background scheduler (ISSUE 19) registers a zero-arg provider here
# returning ``{"harvested_busy_s": float, ...}`` — the device-seconds its
# job steps measurably used. ``registry_capacity`` folds that into the
# idle-fraction headline so ``/v1/capacity`` reports what the devices
# actually did, not just what traffic did. One scheduler per process, so
# a single module slot (plain assignment — no lock needed for a swap).
_HARVEST_PROVIDER = None


def attach_harvest(provider) -> None:
    """Register the process's background-harvest provider (a zero-arg
    callable returning at least ``harvested_busy_s``); pass ``None`` or
    call :func:`detach_harvest` to clear it."""
    global _HARVEST_PROVIDER
    _HARVEST_PROVIDER = provider


def detach_harvest() -> None:
    global _HARVEST_PROVIDER
    _HARVEST_PROVIDER = None


def _leaf_bytes(tree) -> Dict[str, int]:
    """Per-dtype byte totals over a pytree of arrays (device or host).

    GLOBAL logical bytes — a sharded array counts its full size once. Use
    :func:`_leaf_device_bytes` for the allocation-true per-device view
    (ISSUE 20: the two differ exactly when a plan shards or replicates a
    tree across a replica's device group)."""
    import jax
    out: Dict[str, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        dt = getattr(leaf, "dtype", None)
        size = getattr(leaf, "size", None)
        if dt is None or size is None:
            continue
        nbytes = int(size) * int(dt.itemsize)
        key = str(dt)
        out[key] = out.get(key, 0) + nbytes
    return out


def _leaf_device_bytes(tree) -> Dict[str, Dict[str, int]]:
    """Allocation-true accounting (ISSUE 20): ``device -> dtype -> bytes``
    over a pytree, from each jax array's actual shards. A plan-sharded
    leaf charges each device only its LOCAL shard; a leaf replicated over
    a replica group charges every copy. Host arrays (numpy fallbacks)
    land under the pseudo-device ``"host"`` at their full size."""
    import jax
    out: Dict[str, Dict[str, int]] = {}

    def charge(dev: str, dt: str, nbytes: int) -> None:
        slot = out.setdefault(dev, {})
        slot[dt] = slot.get(dt, 0) + nbytes

    for leaf in jax.tree_util.tree_leaves(tree):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not hasattr(leaf, "size"):
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            import numpy as _np
            for sh in shards:
                n = int(_np.prod(sh.data.shape)) if sh.data.ndim else 1
                charge(str(sh.device), str(dt), n * int(dt.itemsize))
        else:
            charge("host", str(dt), int(leaf.size) * int(dt.itemsize))
    return out


def _merge_device_bytes(dst: Dict[str, Dict[str, int]],
                        src: Dict[str, Dict[str, int]]) -> None:
    for dev, dts in src.items():
        slot = dst.setdefault(dev, {})
        for dt, b in dts.items():
            slot[dt] = slot.get(dt, 0) + b


def served_per_device_bytes(served) -> Dict[str, int]:
    """Per-device byte map of one served model — the shard-aware ledger
    view (ISSUE 20). Each replica charges each of its devices only the
    bytes that device actually holds (its param shards plus its copy of
    anything replicated over the slice), so a plan-sliced replica of an
    oversized model reads as N small per-device charges instead of the
    full tree on every device. This is the number the per-device HBM
    budget is held against."""
    out: Dict[str, int] = {}
    for dev, dts in _served_device_map(served).items():
        out[dev] = sum(dts.values())
    return out


def served_device_bytes(served) -> int:
    """One served model's total device-resident bytes: every replica's
    ``device_put`` param + model-state copies (the fallback pseudo-replica
    counts the host state that executes). This is the number the
    registry's HBM-budget ledger tracks per model (ISSUE 11) — the same
    per-replica math :func:`model_capacity` reports, so reservation,
    eviction accounting, and the ``/v1/capacity`` scrape all agree. The
    single source of truth for the traversal is
    :func:`served_device_dtype_bytes`; this is its scalar sum."""
    return sum(served_device_dtype_bytes(served).values())


def served_device_dtype_bytes(served) -> Dict[str, int]:
    """Per-dtype breakdown of :func:`served_device_bytes` (ISSUE 12
    satellite; ROADMAP item 3 headroom): the registry records this on the
    model's residency record so the pager's eviction scoring runs on the
    ACTUAL device dtypes — an int8-resident quantized model shows its
    4x-smaller footprint, which is exactly what makes it 4x cheaper to
    keep resident under ``paging.retention_weight``."""
    out: Dict[str, int] = {}
    for dts in _served_device_map(served).values():
        for dt, b in dts.items():
            out[dt] = out.get(dt, 0) + b
    return out


def _served_device_map(served) -> Dict[str, Dict[str, int]]:
    """Shared traversal behind :func:`served_device_dtype_bytes` and
    :func:`served_per_device_bytes`: ``device -> dtype -> bytes`` over
    every replica's actual allocations (shard-aware, ISSUE 20). The
    fallback pseudo-replica charges the model's host state under its
    nominal device — the host state IS what executes there."""
    pool = served.batcher._pool
    ts = getattr(served.model, "train_state", None)
    host: Dict[str, int] = {}
    for part in (getattr(ts, "params", None),
                 getattr(ts, "model_state", None)):
        for dt, b in _leaf_bytes(part).items():
            host[dt] = host.get(dt, 0) + b
    out: Dict[str, Dict[str, int]] = {}
    for rep in list(pool.replicas):
        if rep.params is not None:
            for part in (rep.params, rep.model_state):
                _merge_device_bytes(out, _leaf_device_bytes(part))
        else:
            _merge_device_bytes(out, {str(rep.device): dict(host)})
    return out


def model_capacity(served) -> Dict[str, Any]:
    """One served model's resource accounting (see module docstring).

    ``served`` is a :class:`~deeplearning4j_tpu.serving.registry
    .ServedModel`; this reads its batcher, replica pool and metrics
    in place (same package — capacity is the serving stack's own
    ledger, not an external probe)."""
    batcher = served.batcher
    pool = batcher._pool
    metrics = served.metrics

    ts = getattr(served.model, "train_state", None)
    param_dtype_bytes = _leaf_bytes(getattr(ts, "params", None))
    param_bytes = sum(param_dtype_bytes.values())
    state_bytes = sum(_leaf_bytes(getattr(ts, "model_state", None)).values())

    util = metrics.utilization_snapshot()
    window_s = max(1e-9, util["window_s"])
    busy_s = util["busy_s"]
    batches_total = max(0, util["batches_total"])
    replica_batches = util["replica_batches"]

    per_replica = []
    device_bytes_total = 0
    for rep in list(pool.replicas):
        if rep.params is not None:
            # shard-aware (ISSUE 20): sum of what the replica's devices
            # actually hold — equals the old whole-tree math for classic
            # single-device replicas, and the true allocation for
            # plan-sliced ones (shards once, replication per copy)
            dm: Dict[str, Dict[str, int]] = {}
            for part in (rep.params, rep.model_state):
                _merge_device_bytes(dm, _leaf_device_bytes(part))
            rb = sum(b for dts in dm.values() for b in dts.values())
        else:
            # fallback pseudo-replica: no device_put copy of its own, the
            # model's host state IS what executes
            rb = param_bytes + state_bytes
        device_bytes_total += rb
        share = (replica_batches.get(rep.index, 0) / batches_total
                 if batches_total else 0.0)
        per_replica.append({
            "replica": rep.index,
            "device": str(rep.device),
            "bytes": rb,
            "batches": replica_batches.get(rep.index, 0),
            "busy_s": round(busy_s * share, 6),
            "busy_fraction": round(busy_s * share / window_s, 6),
        })

    queue_depth = batcher._queue.qsize()
    queue_limit = batcher.admission.queue_limit
    drain_ms = batcher._drain_ms_per_request()
    est_drain_ms = (batcher.admission.retry_after_ms(queue_depth, drain_ms)
                    if queue_depth > 0 else 0.0)

    return {
        "param_bytes": param_bytes,
        "param_dtype_bytes": param_dtype_bytes,
        "model_state_bytes": state_bytes,
        "replicas": len(pool),
        "device_bytes_total": device_bytes_total,
        # shard-aware per-device charges (ISSUE 20) — what the per-device
        # HBM budget is held against for plan-sliced replicas
        "per_device_bytes": served_per_device_bytes(served),
        "per_replica": per_replica,
        "utilization": {
            # (busy_s, window_s) pair, NOT a pre-divided fraction: the
            # fleet aggregation sums both and divides once at the edge
            "busy_s": round(busy_s, 6),
            "window_s": round(window_s, 3),
            "busy_fraction": round(busy_s / window_s, 6),
        },
        "queue": {
            "depth": queue_depth,
            "limit": queue_limit,
            "headroom_requests": max(0, queue_limit - queue_depth),
            "drain_ms_per_request": (round(drain_ms, 4)
                                     if drain_ms is not None else None),
            "est_drain_ms": round(est_drain_ms, 2),
        },
        "aot_executables": batcher.compile_count(),
        "warmed_pairs": len(batcher._warmed_pairs),
        "buckets": list(batcher.buckets),
        "max_batch_size": batcher.max_batch_size,
        "dtype_policy": (batcher.dtype_policy.label()
                         if batcher.dtype_policy is not None else None),
        # raw-bucket wire form so the router can MERGE service-time
        # histograms across workers instead of averaging percentiles
        "dispatch_latency": util["dispatch_wire"],
        "version": served.version,
        "health": served.health.value,
    }


def persistent_cache_bytes() -> Optional[int]:
    """On-disk bytes of the persistent XLA executable cache, or ``None``
    when the cache is disabled (never raises — an unreadable entry just
    drops out of the sum)."""
    from deeplearning4j_tpu.runtime import compile_cache
    d = compile_cache.cache_dir()
    if d is None:
        return None
    total = 0
    try:
        for root, _, files in os.walk(d):
            for f in files:
                try:
                    total += os.stat(os.path.join(root, f)).st_size
                except OSError:
                    pass
    except OSError:
        return None
    return total


def process_capacity() -> Dict[str, Any]:
    """Process-level capacity: measured device memory (budget + in-use,
    where the backend reports it — CPU does not) and the compile-cache
    footprint."""
    from deeplearning4j_tpu.runtime import compile_cache, profiler
    devices = profiler.device_memory_stats()
    budget = in_use = None
    for stats in devices.values():
        limit = stats.get("bytes_limit")
        used = stats.get("bytes_in_use")
        if limit is not None:
            budget = (budget or 0) + int(limit)
        if used is not None:
            in_use = (in_use or 0) + int(used)
    cc = compile_cache.stats()
    return {
        "devices": devices,
        "device_budget_bytes": budget,
        "device_in_use_bytes": in_use,
        "compile_cache": {
            "enabled": bool(cc["enabled"]),
            "persistent_bytes": persistent_cache_bytes(),
            "hits": cc["hits"],
            "misses": cc["misses"],
            "aot_executables": cc["aot_compiles"],
        },
    }


def device_utilization(models: Dict[str, Any],
                       harvested_busy_s: float = 0.0) -> Dict[str, Any]:
    """The worker-level busy-window section (ISSUE 19 satellite): sums
    the per-model summable ``(busy_s, window_s)`` pairs into device-time
    terms and derives the ``device_idle_fraction`` headline that was
    previously computed only inside ``bench.py``.

    ``device_window_s`` is the serving-side proxy for available device
    time: each model's metrics window multiplied by its replica count.
    ``harvested_busy_s`` (measured background-job step seconds from the
    scheduler, when one is attached) joins the busy numerator — both
    counters run since their last reset, so an aligned measurement
    resets the serving metrics window and the scheduler's harvest
    counter together (``bench.py --scheduler`` does). The raw terms are
    all exported so the fleet aggregation can sum numerators and
    denominators across workers and divide ONCE at the edge."""
    busy_s = sum(m["utilization"]["busy_s"] for m in models.values())
    device_window_s = sum(m["utilization"]["window_s"] * m["replicas"]
                          for m in models.values())
    replicas = sum(m["replicas"] for m in models.values())
    if device_window_s > 0:
        serving_busy = busy_s / device_window_s
        idle = max(0.0, 1.0 - (busy_s + harvested_busy_s)
                   / device_window_s)
    else:
        serving_busy, idle = 0.0, 1.0
    return {
        "busy_s": round(busy_s, 6),
        "harvested_busy_s": round(harvested_busy_s, 6),
        "device_window_s": round(device_window_s, 3),
        "replicas": replicas,
        "serving_busy_fraction": round(serving_busy, 6),
        "device_idle_fraction": round(idle, 6),
    }


def registry_capacity(registry) -> Dict[str, Any]:
    """The full ``/v1/capacity`` payload for one registry: per-model
    accounting plus the process section, summed totals, and — when the
    registry is a pager (ISSUE 11) — the ``residency`` section: HBM
    budget vs resident bytes, per-name residency state, and the paging
    counters. The residency section is what the fleet router's
    placement-aware ranking and the autoscaler's HBM-vs-compute
    distinction consume."""
    models: Dict[str, Any] = {}
    for name in registry.names():
        try:
            models[name] = model_capacity(registry.get(name))
        except KeyError:
            pass  # cold, or undeployed between listing and snapshot
    harvested = 0.0
    harvest = None
    if _HARVEST_PROVIDER is not None:
        try:
            harvest = _HARVEST_PROVIDER()
            harvested = float(harvest.get("harvested_busy_s", 0.0))
        except Exception:
            harvest = None  # a dying scheduler must not break a scrape
    out = {
        "models": models,
        "process": process_capacity(),
        "totals": {
            "param_bytes": sum(m["param_bytes"] for m in models.values()),
            "device_bytes": sum(m["device_bytes_total"]
                                for m in models.values()),
            "replicas": sum(m["replicas"] for m in models.values()),
        },
        "utilization": device_utilization(models,
                                          harvested_busy_s=harvested),
    }
    if harvest is not None:
        out["scheduler"] = harvest
    snap = getattr(registry, "residency_snapshot", None)
    if snap is not None:
        try:
            out["residency"] = snap()
        except Exception:
            pass  # the ledger must never be able to break a scrape
    return out


def render_prometheus(payload: Dict[str, Any],
                      prefix: str = "capacity") -> str:
    """Render a :func:`registry_capacity` payload as Prometheus gauges
    (the ``/metrics`` view of the same numbers ``/v1/capacity`` serves
    machine-readably)."""
    lines = [f"# TYPE {prefix}_param_bytes gauge"]
    for model, c in sorted((payload.get("models") or {}).items()):
        lbl = f'{{model="{model}"}}'
        lines.append(f"{prefix}_param_bytes{lbl} {c['param_bytes']}")
        lines.append(f"{prefix}_device_bytes{lbl} "
                     f"{c['device_bytes_total']}")
        lines.append(f"{prefix}_replicas{lbl} {c['replicas']}")
        lines.append(f"{prefix}_utilization_busy_fraction{lbl} "
                     f"{c['utilization']['busy_fraction']}")
        lines.append(f"{prefix}_queue_headroom_requests{lbl} "
                     f"{c['queue']['headroom_requests']}")
        lines.append(f"{prefix}_queue_est_drain_ms{lbl} "
                     f"{c['queue']['est_drain_ms']}")
        lines.append(f"{prefix}_aot_executables{lbl} "
                     f"{c['aot_executables']}")
        for dt, b in sorted(c["param_dtype_bytes"].items()):
            lines.append(f'{prefix}_param_dtype_bytes{{model="{model}",'
                         f'dtype="{dt}"}} {b}')
    util = payload.get("utilization")
    if util:
        # the idle-signal headline (ISSUE 19): raw summable terms first,
        # then the edge-derived fractions the scheduler admits against
        lines.append(f"{prefix}_device_busy_s {util['busy_s']}")
        lines.append(f"{prefix}_harvested_busy_s "
                     f"{util['harvested_busy_s']}")
        lines.append(f"{prefix}_device_window_s "
                     f"{util['device_window_s']}")
        lines.append(f"{prefix}_serving_busy_fraction "
                     f"{util['serving_busy_fraction']}")
        lines.append(f"{prefix}_device_idle_fraction "
                     f"{util['device_idle_fraction']}")
    proc = payload.get("process") or {}
    if proc.get("device_budget_bytes") is not None:
        lines.append(f"{prefix}_device_budget_bytes "
                     f"{proc['device_budget_bytes']}")
    if proc.get("device_in_use_bytes") is not None:
        lines.append(f"{prefix}_device_in_use_bytes "
                     f"{proc['device_in_use_bytes']}")
    cc = proc.get("compile_cache") or {}
    if cc.get("persistent_bytes") is not None:
        lines.append(f"{prefix}_compile_cache_bytes "
                     f"{cc['persistent_bytes']}")
    res = payload.get("residency")
    if res:
        # the pager's /metrics view (ISSUE 11): resident bytes vs budget,
        # per-model residency state, and the page-in/eviction counters
        if res.get("hbm_budget_bytes") is not None:
            lines.append(f"{prefix}_hbm_budget_bytes "
                         f"{res['hbm_budget_bytes']}")
        lines.append(f"{prefix}_resident_bytes "
                     f"{res.get('resident_bytes', 0)}")
        for model, m in sorted((res.get("models") or {}).items()):
            lines.append(f'{prefix}_model_resident{{model="{model}"}} '
                         f"{int(m.get('state') == 'resident')}")
            lines.append(f'{prefix}_model_bytes{{model="{model}"}} '
                         f"{m.get('bytes', 0)}")
        pg = res.get("paging") or {}
        for counter in ("page_ins_total", "evictions_total",
                        "page_in_queue_waits_total",
                        "page_in_rejections_total",
                        "page_in_failures_total",
                        "resident_hits_total", "cold_hits_total"):
            if counter in pg:
                lines.append(f"{prefix}_{counter} {pg[counter]}")
        for q, key in ((0.5, "page_in_p50_s"), (0.99, "page_in_p99_s")):
            if key in pg:
                lines.append(f'{prefix}_page_in_seconds{{quantile="{q}"}} '
                             f"{pg[key]}")
    return "\n".join(lines) + "\n"
