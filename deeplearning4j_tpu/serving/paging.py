"""HBM-budgeted model residency: the policy side of the registry pager
(ISSUE 11 tentpole; ROADMAP item 3 — "serve 100x more models than fit in
device memory").

PR 10's capacity ledger measures exactly what each served model costs in
device bytes; this module turns that accounting into a *pager*. A
:class:`~deeplearning4j_tpu.serving.registry.ModelRegistry` under an
explicit HBM budget (``DL4J_TPU_HBM_BUDGET_BYTES``, defaulting to the
measured device budget where the backend reports one) keeps only the
highest-value models RESIDENT; the rest stay COLD — nothing but an
archive path, the warmup manifest, and this module's per-name
:class:`Residency` record (traffic EWMA, measured bytes, measured
page-in cost). A request for a cold model triggers a single-flight
page-in (manifest-prewarmed, so nothing compiles on live traffic) while
concurrent requests wait; a request whose deadline cannot cover the wait
is rejected with an HONEST ``Retry-After`` derived from the measured
page-in cost (:class:`~deeplearning4j_tpu.serving.admission
.PagingInProgress`), never a generic 503.

Eviction is **cost-weighted LRU**: the victim is the resident model with
the lowest *retention weight* —

    ``weight = traffic_ewma x recompile_risk / bytes``

i.e. evict first the model that frees the most bytes per unit of
(traffic it still draws x cost of bringing it back). ``recompile_risk``
is small when a warmup manifest exists next to the archive (the restore
replays it compile-free) and smaller still when the persistent
executable cache is enabled (each replayed warmup compile is a
deserialization hit — ``docs/coldstart.md``); ties break LRU (oldest
``last_used`` first). A model with in-flight requests (a nonzero pin
count) is never a victim, and a model registered from a live net (no
archive to rehydrate from) is never evictable at all.

The registry owns the state machine (``serving/registry.py``); this
module owns the policy pieces so they stay unit-testable without a
model: the budget resolution, the decayed traffic estimate, the
retention weight, and the paging counters/histograms surfaced on
``/v1/capacity`` and ``/metrics`` (``docs/observability.md``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from deeplearning4j_tpu.serving.metrics import LatencyHistogram

logger = logging.getLogger(__name__)

__all__ = ["ENV_BUDGET", "RESIDENT", "COLD", "TrafficEWMA", "Residency",
           "PagingMetrics", "env_hbm_budget", "measured_device_budget",
           "recompile_risk", "retention_weight", "dtype_density",
           "policy_adjusted_archive_bytes"]

ENV_BUDGET = "DL4J_TPU_HBM_BUDGET_BYTES"

#: residency states (strings, not an enum — they ride JSON payloads)
RESIDENT = "resident"
COLD = "cold"


def env_hbm_budget(environ=None) -> Optional[int]:
    """The ``DL4J_TPU_HBM_BUDGET_BYTES`` knob as an int, or ``None`` when
    unset/empty/invalid (a malformed value logs and disables the budget
    rather than crashing the registry at import time)."""
    raw = (environ if environ is not None else os.environ).get(ENV_BUDGET)
    if raw is None or not str(raw).strip():
        return None
    try:
        v = int(str(raw).strip())
    except ValueError:
        logger.warning("ignoring non-integer %s=%r", ENV_BUDGET, raw)
        return None
    if v <= 0:
        logger.warning("ignoring non-positive %s=%r", ENV_BUDGET, raw)
        return None
    return v


def measured_device_budget() -> Optional[int]:
    """The measured device memory budget from the capacity ledger
    (``serving/capacity.py``), or ``None`` on backends that do not report
    one (CPU) — paging is then off unless the env knob sets an explicit
    budget."""
    try:
        from deeplearning4j_tpu.serving import capacity
        return capacity.process_capacity().get("device_budget_bytes")
    except Exception:
        return None


class TrafficEWMA:
    """Exponentially decayed request mass: each :meth:`update` adds one
    request, and the mass halves every ``halflife_s`` seconds of silence
    — a relative traffic weight that forgets, so a model that was hot an
    hour ago does not outrank one that is hot now. Callers synchronize
    (the registry updates under its own lock); ``now`` is injectable so
    the eviction-policy unit tests are deterministic."""

    __slots__ = ("halflife_s", "_mass", "_t")

    def __init__(self, halflife_s: float = 60.0):
        self.halflife_s = float(halflife_s)
        self._mass = 0.0
        self._t: Optional[float] = None

    def _decay(self, now: float) -> None:
        if self._t is None:
            self._t = now
            return
        dt = now - self._t
        if dt > 0:
            self._mass *= 0.5 ** (dt / self.halflife_s)
            self._t = now

    def update(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._decay(now)
        self._mass += 1.0

    def rate(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        self._decay(now)
        return self._mass


def recompile_risk(archive_path: Optional[str]) -> float:
    """How expensive a page-in of this archive would be, as a weight in
    (0, 1]: 1.0 with no warmup manifest (rehydration compiles from
    scratch on the request path's clock), 0.5 with a manifest (the
    restore replays the recorded pairs — bounded compiles, none on
    traffic), 0.25 with a manifest AND the persistent executable cache
    (each replayed compile is a deserialization hit — the sub-second
    restores the ``coldstart`` bench measured). Higher risk = keep
    resident longer."""
    if archive_path is None:
        return 1.0
    from deeplearning4j_tpu.serving.manifest import manifest_path
    if not os.path.exists(manifest_path(archive_path)):
        return 1.0
    try:
        from deeplearning4j_tpu.runtime import compile_cache
        cached = compile_cache.cache_dir() is not None
    except Exception:
        cached = False
    return 0.25 if cached else 0.5


def retention_weight(nbytes: int, traffic: float, risk: float) -> float:
    """Cost-weighted LRU key: how much it hurts, per byte freed, to evict
    this model — ``traffic x recompile_risk / bytes``. The eviction
    victim is the resident model with the MINIMUM weight (big, idle,
    cheap-to-restore models go first); the registry breaks ties by
    ``last_used`` (plain LRU).

    ``nbytes`` must be the model's ACTUAL per-dtype device bytes (ISSUE
    12 satellite; ROADMAP item 3 headroom): an int8-resident quantized
    model occupies 4x fewer device bytes than its f32 twin, so at equal
    traffic and risk its weight is 4x higher — 4x cheaper to keep
    resident, evicted last. The registry feeds measured per-dtype bytes
    for resident models (:meth:`Residency.retention`) and the
    dtype-policy-corrected estimate for cold ones
    (:func:`policy_adjusted_archive_bytes`)."""
    return (float(traffic) + 1e-9) * float(risk) / float(max(1, nbytes))


def _weight_itemsize(policy) -> int:
    """Bytes per weight element at the policy's STORAGE dtype (1 on any
    failure — the conservative, largest-inflation fallback)."""
    try:
        import numpy as np
        return max(1, int(np.dtype(getattr(policy, "weight_dtype",
                                           "int8")).itemsize))
    except Exception:
        return 1


def dtype_density(policy) -> float:
    """Device-byte density of an archive's dtype policy relative to f32,
    in (0, 1]: an ``int8``-resident policy (in-graph dequant) keeps its
    weights on device at 1 byte/param — density 0.25 — while a
    ``dequantized`` policy mints f32 device copies at load (density 1.0
    no matter how small the archive is). ``None`` (no policy: a plain
    f32 archive) is density 1.0."""
    if policy is None:
        return 1.0
    if getattr(policy, "weight_residency", "dequantized") != "int8":
        return 1.0
    return _weight_itemsize(policy) / 4.0


def policy_adjusted_archive_bytes(archive_path: str,
                                  file_bytes: int) -> int:
    """Dtype-policy-aware DEVICE-byte estimate for a cold archive (ISSUE
    12 satellite): the archive's on-disk size reflects its STORAGE dtype
    (int8 payloads are ~4x smaller), but what the budget ledger must
    reserve is the RESIDENCY dtype — a ``dequantized`` policy's device
    copies are f32, so its file size underestimates the page-in cost by
    ~4x (exactly the kind of optimistic estimate that over-admits and
    busts the budget); an ``int8``-resident policy's file size is about
    right. One formula: f32-equivalent bytes (file x 4/storage-itemsize)
    scaled back down by :func:`dtype_density` — the residency rule lives
    in exactly one place. No sidecar = plain archive = file size
    stands."""
    try:
        from deeplearning4j_tpu.serving.quantize import DtypePolicy
        policy = DtypePolicy.load_for_archive(archive_path)
    except Exception:
        policy = None
    if policy is None:
        return int(file_bytes)
    return int(file_bytes * (4.0 / _weight_itemsize(policy))
               * dtype_density(policy))


class Residency:
    """One name's residency record. It outlives evictions: the traffic
    EWMA, measured byte footprint and measured page-in cost carry across
    resident<->cold transitions, so the policy keeps learning while the
    model itself is unloaded."""

    __slots__ = ("name", "state", "evictable", "archive_path", "version",
                 "load_kwargs", "gate_report", "bytes", "bytes_estimated",
                 "dtype_bytes", "device_map", "last_used", "ewma",
                 "page_in_s", "page_ins", "evictions", "risk")

    def __init__(self, name: str, halflife_s: float = 60.0):
        self.name = name
        self.state = COLD
        self.evictable = False          # True once archive-backed
        #: cached :func:`recompile_risk` — refreshed when the manifest is
        #: (re)persisted, so victim selection never stats the filesystem
        #: under the registry lock
        self.risk = 1.0
        self.archive_path: Optional[str] = None
        self.version: Optional[int] = None
        self.load_kwargs: Dict[str, Any] = {}
        self.gate_report = None         # survives deploy_quantized evictions
        self.bytes = 0                  # measured (or estimated) device bytes
        self.bytes_estimated = True
        #: per-dtype breakdown of ``bytes`` when measured (ISSUE 12
        #: satellite): the ACTUAL device dtypes — an int8-resident model
        #: shows {"int8": ...} 4x smaller than its f32 twin — feeding
        #: dtype-aware eviction scoring and the residency snapshot
        self.dtype_bytes: Dict[str, int] = {}
        #: measured per-device byte map (ISSUE 20, shard-aware): what each
        #: device actually holds for this model — a plan-sliced replica
        #: charges each device only its local shards, so the per-device
        #: budget check never sees the full tree on every device
        self.device_map: Dict[str, int] = {}
        self.last_used = 0.0
        self.ewma = TrafficEWMA(halflife_s)
        self.page_in_s = 0.0            # decayed page-in cost estimate
        self.page_ins = 0
        self.evictions = 0

    def record_page_in_cost(self, seconds: float) -> None:
        """Keep a decayed estimate of what paging this model in costs —
        the denominator of the honest ``Retry-After`` hint."""
        self.page_ins += 1
        if self.page_in_s <= 0:
            self.page_in_s = float(seconds)
        else:
            self.page_in_s = 0.5 * self.page_in_s + 0.5 * float(seconds)

    def retention(self, now: Optional[float] = None) -> float:
        """This record's cost-weighted-LRU retention weight from its
        ACTUAL per-dtype device bytes (falls back to the scalar estimate
        while unmeasured) — the dtype-aware eviction score: a 4x-denser
        int8-resident model weighs 4x more per byte, so it is evicted
        last among equals."""
        now = time.monotonic() if now is None else now
        nbytes = (sum(self.dtype_bytes.values()) if self.dtype_bytes
                  else int(self.bytes or 0))
        return retention_weight(nbytes, self.ewma.rate(now), self.risk)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = time.monotonic() if now is None else now
        return {
            "state": self.state,
            "bytes": int(self.bytes or 0),
            "bytes_estimated": bool(self.bytes_estimated),
            "dtype_bytes": dict(self.dtype_bytes),
            "device_map": dict(self.device_map),
            "retention_weight": self.retention(now),
            "evictable": bool(self.evictable),
            "traffic_ewma": round(self.ewma.rate(now), 4),
            "idle_s": (round(now - self.last_used, 3)
                       if self.last_used else None),
            "page_in_s": round(self.page_in_s, 4) if self.page_in_s else None,
            "page_ins": self.page_ins,
            "evictions": self.evictions,
            "version": self.version,
        }


class PagingMetrics:
    """Pager counters + histograms (thread-safe), rendered on
    ``/metrics`` via ``capacity.render_prometheus`` and shipped on
    ``/v1/capacity``'s ``residency.paging`` section so the fleet router
    can sum them."""

    def __init__(self):
        # guards: page_ins_total, evictions_total, page_in_queue_waits_total, page_in_rejections_total, page_in_failures_total, resident_hits_total, cold_hits_total, page_in_seconds, page_in_wait_seconds
        self._lock = threading.Lock()
        self.page_ins_total = 0
        self.page_in_failures_total = 0
        self.evictions_total = 0
        self.page_in_queue_waits_total = 0  # requests that waited on a flight
        self.page_in_rejections_total = 0   # deadline could not cover the wait
        self.resident_hits_total = 0
        self.cold_hits_total = 0
        self.page_in_seconds = LatencyHistogram()
        self.page_in_wait_seconds = LatencyHistogram()

    def record_page_in(self, seconds: float) -> None:
        with self._lock:
            self.page_ins_total += 1
            self.page_in_seconds.observe(seconds)

    def record_page_in_failure(self) -> None:
        with self._lock:
            self.page_in_failures_total += 1

    def record_eviction(self) -> None:
        with self._lock:
            self.evictions_total += 1

    def record_queue_wait(self, seconds: Optional[float] = None) -> None:
        with self._lock:
            self.page_in_queue_waits_total += 1
            if seconds is not None:
                self.page_in_wait_seconds.observe(seconds)

    def record_wait_seconds(self, seconds: float) -> None:
        with self._lock:
            self.page_in_wait_seconds.observe(seconds)

    def record_rejection(self) -> None:
        with self._lock:
            self.page_in_rejections_total += 1

    def record_hit(self, resident: bool) -> None:
        with self._lock:
            if resident:
                self.resident_hits_total += 1
            else:
                self.cold_hits_total += 1

    def hit_rate(self) -> float:
        """Fraction of routed requests that found their model RESIDENT
        (1.0 until the first cold hit)."""
        with self._lock:
            total = self.resident_hits_total + self.cold_hits_total
            return self.resident_hits_total / total if total else 1.0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "page_ins_total": self.page_ins_total,
                "page_in_failures_total": self.page_in_failures_total,
                "evictions_total": self.evictions_total,
                "page_in_queue_waits_total": self.page_in_queue_waits_total,
                "page_in_rejections_total": self.page_in_rejections_total,
                "resident_hits_total": self.resident_hits_total,
                "cold_hits_total": self.cold_hits_total,
                "page_in_p50_s": self.page_in_seconds.percentile(50),
                "page_in_p99_s": self.page_in_seconds.percentile(99),
                "page_in_wait_p99_s": self.page_in_wait_seconds.percentile(99),
            }
