"""Device replicas for the serving pipeline.

The reference's ``ParallelInference`` keeps N model *replicas*, each with a
worker thread, and routes requests to whichever is free. On this stack a
replica is cheaper and stronger: the model's parameters are ``device_put``
onto one local device, and the model's own jitted ``output`` function —
retrieved through the same ``_jitted("output", ...)`` cache the model uses,
so serving and direct ``model.output`` calls share one compile ledger —
executes on whichever device its committed arguments live on. One python
callable, N executables, no per-replica threads: JAX's async dispatch
queues work per device, so a :class:`ReplicaPool` plus the batcher's
dispatch stage is the whole replica machinery.

Placement/compile accounting: a committed-parameter call compiles one
executable per (argument shapes, device) pair, so a warmed pool holds
exactly ``len(buckets) x len(replicas)`` executables. With the AOT fast
path on (``env.aot_dispatch``, the default) those live in the pool's
:class:`~deeplearning4j_tpu.runtime.compile_cache.AotCache` (counted by
:meth:`ReplicaPool.aot_count`); with it off they live in the output
function's jit cache — ``ContinuousBatcher.compile_count`` sums both, so
the ``compiles <= buckets x replicas`` bound holds either way.

Parameters are snapshotted (``device_put`` copies) at pool construction:
a served model's weights are frozen for the lifetime of its batcher, and
the supported update path is the registry's hot-swap (build + warm a new
batcher, then drain the old one).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence, Union

import jax
import numpy as np

from deeplearning4j_tpu.runtime.compile_cache import AotCache
from deeplearning4j_tpu.runtime.state_packing import step_args_signature

ArrayOrDict = Union[np.ndarray, Dict[str, np.ndarray]]

logger = logging.getLogger(__name__)


def _request_signature(x: ArrayOrDict):
    """AOT-cache key component for one padded batch: the shared structural
    signature (shapes + CANONICALIZED dtypes — an f64 JSON request lands
    on the f32 program under jit, so a raw-dtype key would mint a
    duplicate executable and break the compiles <= buckets x replicas
    ledger)."""
    return step_args_signature((x,))


class Replica:
    """One plan-slice-resident copy of the served parameters: one device in
    the classic pool, a device GROUP under a multi-axis
    :class:`~deeplearning4j_tpu.parallel.sharding.ParallelPlan` (pipe/tensor
    slice — ``devices`` lists the group, ``device`` stays its primary for
    single-device consumers). ``fn`` overrides the pool's shared forward for
    replicas whose executable is mesh-bound (the GPipe executor bakes the
    slice mesh into the lowered program). (Per-replica batch counts live in
    :class:`ServingMetrics.replica_batches` — the single source the snapshot
    and Prometheus rendering read.)"""

    __slots__ = ("index", "device", "params", "model_state", "in_flight",
                 "devices", "plan", "fn")

    def __init__(self, index: int, device, params, model_state,
                 devices=None, plan=None, fn=None):
        self.index = int(index)
        self.device = device
        self.params = params
        self.model_state = model_state
        self.in_flight = 0        # dispatched, readback not yet complete
        self.devices = list(devices) if devices is not None else [device]
        self.plan = plan          # per-replica slice plan (None = classic)
        self.fn = fn              # mesh-bound forward (None = pool's shared)


class ReplicaPool:
    """N device replicas of one model with least-loaded routing.

    ``acquire()`` claims the least-loaded replica (round-robin among ties,
    so single-threaded traffic still exercises every replica — and every
    replica's compiled programs stay warm); ``dispatch`` issues the forward
    on the replica's device WITHOUT blocking on the result (JAX async
    dispatch); ``complete`` returns the replica after readback.
    """

    def __init__(self, model, n_replicas: int = 1,
                 devices: Optional[Sequence] = None, plan=None):
        if getattr(model, "train_state", None) is None:
            model.init()
        self.model = model
        devs = list(devices) if devices else list(jax.local_devices())
        n = max(1, int(n_replicas or 1))
        # a plan that spans >1 device per replica (pipe/tensor/fsdp axes)
        # generalizes "replica" to "plan-slice": disjoint device groups of
        # devices_per_replica() each, the plan's ``data`` axis IS the
        # replica fan-out
        self.plan = plan
        self._group_size = plan.devices_per_replica() if plan is not None else 1
        if self._group_size > len(devs):
            raise ValueError(
                f"plan {plan.kind} needs {self._group_size} devices per "
                f"replica, have {len(devs)}")
        max_n = len(devs) // self._group_size
        if n > max_n:
            logger.warning(
                "ReplicaPool: %d replicas requested but only %d local "
                "device(s) (%d per plan-slice); clamping", n, len(devs),
                self._group_size)
            n = max_n
        self._devs = devs
        self._graph_inputs = list(getattr(model.conf, "inputs", []) or [])
        self._fn = self._output_fn(model)
        # AOT fast path (env.aot_dispatch): one lower().compile() executable
        # per (bucket signature, replica device), minted at warmup and
        # called directly from the dispatch stage — counted by aot_count()
        # so the batcher's compile ledger stays truthful
        self._aot = AotCache("replica")
        self._lock = threading.Lock()  # guards: _rr, _next_index
        self._rr = 0
        self.replicas: List[Replica] = []
        if self._fn is None:
            # fallback dispatch ignores replica placement entirely: one
            # pseudo-replica, no device_put copies, honest accounting
            if n > 1:
                logger.warning(
                    "ReplicaPool: %s lacks the MLN/CG internals; serving "
                    "through its own output() on the default device "
                    "(1 replica, %d requested)", type(model).__name__, n)
            self.replicas.append(Replica(0, devs[0], None, None))
            self._next_index = 1
            return
        for i in range(n):
            self.replicas.append(self._mint_replica(i))
        # runtime resize (ISSUE 10) hands out indices from here on; an
        # index is NEVER reused — the AOT cache keys on (index, signature)
        # and a recycled index could hand a new replica an executable
        # compiled for a device its parameters do not live on
        self._next_index = n

    def _replica_group(self, idx: int) -> List:
        """The device group replica ``idx`` lives on: disjoint slices of
        ``_group_size`` while they last, then reuse round-robin (two
        replicas may share a group on a small box, as before)."""
        gs = self._group_size
        n_groups = max(1, len(self._devs) // gs)
        g = idx % n_groups
        return self._devs[g * gs:(g + 1) * gs]

    def _mint_replica(self, idx: int) -> Replica:
        """One plan-slice parameter copy: classic single-device
        ``device_put`` when no plan spans devices; otherwise the slice
        plan's NamedShardings (pipe slices additionally stage-stack the
        trunk through the GPipe executor, whose mesh-bound forward rides
        on the replica)."""
        ts = self.model.train_state
        group = self._replica_group(idx)
        if self._group_size == 1 and self.plan is None:
            dev = group[0]
            return Replica(idx, dev,
                           jax.device_put(ts.params, dev),
                           jax.device_put(ts.model_state, dev))
        slice_plan = self.plan.replica_slice(group)
        if slice_plan.pipe_size > 1:
            from deeplearning4j_tpu.parallel.plan_exec import PipePlanExecutor
            ex = PipePlanExecutor(self.model, slice_plan)
            params = ex.place_packed(ex.pack_params(ts.params))
            fn = ex.make_forward()
        else:
            params = jax.tree.map(jax.device_put, ts.params,
                                  slice_plan.param_sharding(ts.params))
            fn = None  # the pool's shared jit handles committed shardings
        return Replica(idx, group[0], params,
                       jax.device_put(ts.model_state,
                                      slice_plan.replicated()),
                       devices=group, plan=slice_plan, fn=fn)

    def __len__(self) -> int:
        return len(self.replicas)

    def aot_count(self) -> int:
        """XLA executables minted through the AOT fast path (one per
        (bucket, replica) pair when warmed)."""
        return len(self._aot)

    # ------------------------------------------------------------- forward
    def _output_fn(self, model):
        """The model's own jitted inference function, through the same
        ``_jitted("output", ...)`` cache ``model.output`` populates — the
        trace is identical to the model's, so a replica's result is
        bit-identical to ``model.output`` at the same program shape, and
        ``compile_count`` sees every (bucket, device) executable."""
        if self._fallback(model):
            return None
        if self._graph_inputs:
            # mirror ComputationGraph.output's fwd exactly
            def fwd(params, model_state, inputs_):
                acts, _, _ = model._forward_all(params, model_state, inputs_,
                                                training=False, rng=None)
                return [acts[o] for o in model.conf.outputs]
        else:
            # mirror MultiLayerNetwork.output's fwd exactly
            def fwd(params, model_state, x_, m_):
                out, _, _, _ = model._forward(params, model_state, x_,
                                              training=False, rng=None,
                                              fmask=m_)
                return out
        return model._jitted("output", lambda: jax.jit(fwd))

    @staticmethod
    def _fallback(model) -> bool:
        """Duck-typed models without the MLN/CG internals serve through
        their own ``output`` on the default device (single replica, no
        device routing) instead of failing at pool construction."""
        has_fwd = (hasattr(model, "_forward_all")
                   if list(getattr(model.conf, "inputs", []) or [])
                   else hasattr(model, "_forward"))
        return not (has_fwd and hasattr(model, "_jitted"))

    # ------------------------------------------------------------- routing
    def acquire(self) -> Replica:
        """Claim the least-loaded replica (ties broken round-robin) and
        count the dispatch against it."""
        with self._lock:
            low = min(r.in_flight for r in self.replicas)
            ties = [r for r in self.replicas if r.in_flight == low]
            rep = ties[self._rr % len(ties)]
            self._rr += 1
            rep.in_flight += 1
            return rep

    def release(self, replica: Replica) -> None:
        """Un-claim after readback completed OR after a dispatch that
        never executed (chaos/raise)."""
        with self._lock:
            replica.in_flight -= 1

    def total_in_flight(self) -> int:
        with self._lock:
            return sum(r.in_flight for r in self.replicas)

    # ------------------------------------------------------ runtime resize
    def create_replica(self, device=None) -> Replica:
        """Mint a NEW device-resident parameter copy WITHOUT publishing it
        for routing (ISSUE 10: the autoscaler's replica lever). The caller
        warms it — :meth:`forward_blocking` works on an unpublished
        replica — then :meth:`publish_replica` makes it routable, so a
        scaled-up replica never compiles on live traffic. Devices are
        assigned round-robin past the initial set (two replicas may share
        a device on a small box; each still gets its own parameter copy
        and executables, which is what the capacity ledger accounts)."""
        if self._fn is None:
            raise ValueError(
                f"cannot scale a fallback pool ({type(self.model).__name__} "
                f"serves through its own output() with no device routing)")
        with self._lock:
            idx = self._next_index
            self._next_index += 1
        if device is not None and self._group_size == 1 and self.plan is None:
            ts = self.model.train_state
            return Replica(idx, device,
                           jax.device_put(ts.params, device),
                           jax.device_put(ts.model_state, device))
        return self._mint_replica(idx)

    def publish_replica(self, replica: Replica) -> int:
        """Make a warmed replica routable; returns the new pool size."""
        with self._lock:
            self.replicas.append(replica)
            return len(self.replicas)

    def retire_replica(self) -> Optional[Replica]:
        """Remove the NEWEST replica from routing (keeps replica 0 — the
        one direct ``model.output`` calls share a trace with — stable),
        or ``None`` when only one replica remains. In-flight batches hold
        their own reference and complete normally; the retired replica's
        AOT executables are evicted so ``aot_count`` keeps describing the
        live pool. (A dispatch that acquired the replica just before
        retirement may re-mint one executable — a wasted compile, never a
        wrong result.)"""
        with self._lock:
            if len(self.replicas) <= 1:
                return None
            rep = self.replicas.pop()
        self._aot.evict(lambda k: isinstance(k, tuple) and k
                        and k[0] == rep.index)
        return rep

    # ------------------------------------------------------------ dispatch
    def dispatch(self, replica: Replica, x: ArrayOrDict):
        """Issue the forward on ``replica``'s device and return the result
        WITHOUT reading it back — with async dispatch the device executes
        while the host goes on coalescing the next batch. The caller owns
        the eventual blocking readback (``np.asarray``)."""
        if self._fn is None:
            out = (self.model.output(*[x[n] for n in
                                       (self._graph_inputs or sorted(x))])
                   if isinstance(x, dict) else self.model.output(x))
            return out
        if replica.fn is not None:
            # mesh-bound plan-slice executable (GPipe trunk): the plan
            # signature joins the AOT key, so a replica minted under a
            # different plan can never be served a stale executable
            return self._aot.call(
                (replica.index, replica.plan.signature(),
                 _request_signature(x)),
                replica.fn, replica.params, replica.model_state, x, None)
        if self._graph_inputs:
            if not isinstance(x, dict):
                x = {self._graph_inputs[0]: x}
            inputs_ = {n: x[n] for n in self._graph_inputs}
            outs = self._aot.call(
                (replica.index, _request_signature(inputs_)),
                self._fn, replica.params, replica.model_state, inputs_)
            return outs[0] if len(outs) == 1 else outs
        key = ((replica.index, replica.plan.signature(), _request_signature(x))
               if replica.plan is not None
               else (replica.index, _request_signature(x)))
        return self._aot.call(
            key, self._fn, replica.params, replica.model_state, x, None)

    def forward_blocking(self, replica: Replica, x: ArrayOrDict):
        """Dispatch + full readback on one replica (warmup path — forces
        the XLA compile for this shape on this device, bypassing the
        in-flight accounting)."""
        out = self.dispatch(replica, x)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o) for o in out]
        return np.asarray(out)
