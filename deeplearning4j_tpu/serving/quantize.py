"""Post-training quantization for the serving path (ISSUE 8 tentpole).

The reference stack treats reduced precision as a first-class serving lever
(libnd4j ``DataType`` carries FP16/INT8 end to end); here the same lever is
wired through the whole serving subsystem instead of living as two orphan
ops in ``autodiff/ops_registry.py``:

- :func:`quantize_archive` quantizes a ``ModelSerializer`` archive
  **offline**: per-output-channel symmetric int8 weights (``quantize`` /
  ``dequantize`` from the op registry — the ops the round-3 families
  registered and nothing used), input-quantization scales calibrated over a
  representative batch set (CRC-validated through the
  ``serving.quantize.calibrate`` chaos point: corrupt or truncated
  calibration data is a **refused deploy**, never a silently wrong policy),
  and a sidecar :class:`DtypePolicy` manifest
  (``<archive>.dtype_policy.json``) declaring the serving dtypes and the
  accuracy gate the deploy must pass.
- :class:`QuantizedModel` serves a quantized archive through the existing
  executor stack unchanged: it duck-types the MLN/CG internals
  (``_forward``/``_forward_all``/``_jitted``/``output``) that
  :class:`~deeplearning4j_tpu.serving.replica.ReplicaPool` builds its AOT
  executables from, dequantizes **int8 request rows in-graph** (so
  quantized traffic moves 4x fewer host bytes per request through the pad
  buffers and the host→device transfer), and accepts f32 rows on the same
  executables' f32 twins — mixed f32/int8 traffic coalesces separately by
  dtype (the batcher's signature split), pads into separate pooled buffers
  (pools are dtype-keyed), and compiles separate AOT executables (the
  ``AotCache`` signature canonicalizes int8 as int8).
- :class:`AccuracyGate` gates every quantized deploy against the f32 golden
  using the ``evaluation/`` harness: ``ModelRegistry.deploy_quantized``
  runs the gate BEFORE the hot-swap, so a quantization that fails its
  declared gate raises :class:`AccuracyGateFailed` and the f32 version
  keeps serving — the PR 2 rollback guarantee means a bad quantization can
  never take traffic.

Precision policy (honest about backends): weights are **stored** int8
(archives ~4x smaller) and **dequantized at load** into the policy's
``activation_dtype`` (``"auto"`` resolves to the environment compute dtype
— bfloat16 on TPU, float32 on CPU, where XLA's int8/bf16 GEMMs are slower
than the f32 path and in-graph per-call weight dequantization would only
add memory traffic). ``weight_residency="int8"`` keeps the int8 codes
device-resident (4x less HBM per replica — the model-paging trade) and
dequantizes in-graph; both residencies compute identical values
(dequantization is the same arithmetic wherever it runs). The measured
serving speedup on the CPU box comes from the **request path**: int8 rows
are 4x cheaper to coalesce, pad, and transfer (``bench.py --quant``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import struct
import time
import zipfile
import zlib
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from deeplearning4j_tpu.runtime import chaos
from deeplearning4j_tpu.serving import delivery
from deeplearning4j_tpu.serving.manifest import atomic_replace

ArrayOrDict = Union[np.ndarray, Dict[str, np.ndarray]]

logger = logging.getLogger(__name__)

POLICY_SUFFIX = ".dtype_policy.json"
QUANT_MEMBER = "quantization.json"
_CONF = "configuration.json"
_META = "metadata.json"
_WEIGHTS = "qweights.npz"
_STATE = "qstate.npz"
_FORMAT = "dl4j-tpu-quant-v1"

#: Input-spec key for single-array (MultiLayerNetwork-style) models —
#: matches the warmup manifest's convention.
SINGLE = "__single__"

#: Integer code ranges per quantized input dtype (int8 is narrow-range
#: symmetric so the scheme stays sign-symmetric; uint8 is asymmetric).
_CODE_RANGE = {"int8": (-127, 127), "uint8": (0, 255)}


class CalibrationError(RuntimeError):
    """Calibration data was unusable (corrupt, truncated, non-finite, or
    empty) — the quantization is refused; no archive or policy is
    written."""


class AccuracyGateFailed(delivery.GateFailed):
    """A quantized deploy failed its declared accuracy gate; the previous
    (f32) version keeps serving. ``report`` carries the measured deltas.
    (Now a :class:`~deeplearning4j_tpu.serving.delivery.GateFailed`
    subtype — the quantized gate is one face of the shared
    :class:`~deeplearning4j_tpu.serving.delivery.GoldenGate`.)"""


def policy_path(archive_path: str) -> str:
    """Where a quantized archive's dtype-policy sidecar lives."""
    return archive_path + POLICY_SUFFIX


# =========================================================== dtype policy
@dataclasses.dataclass
class DtypePolicy:
    """Per-model (and per-bucket) serving dtype declaration.

    ``inputs`` maps input name (``__single__`` for single-input models) to
    ``{"dtype", "scale", "zero_point", "symmetric"}`` — the calibrated
    affine map clients use to quantize request rows
    (:func:`quantize_requests`) and the server inverts in-graph.
    ``quantized_buckets=None`` means every bucket serves the quantized
    dtype (pre-warmed at load); an explicit list restricts prewarming to
    those buckets (other buckets still serve quantized traffic, minting
    their executable on first use). ``gate`` declares the accuracy bar a
    deploy must clear (``max_delta`` against the f32 golden).
    """

    weight_dtype: str = "int8"
    activation_dtype: str = "auto"  # auto -> environment compute dtype
    weight_residency: str = "dequantized"  # or "int8" (in-graph dequant)
    per_channel: bool = True
    symmetric: bool = True
    inputs: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    quantized_buckets: Optional[List[int]] = None
    gate: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"metric": "top1_agreement",
                                 "max_delta": 0.02})
    created_at: float = 0.0

    # ------------------------------------------------------------- queries
    def label(self) -> str:
        """Compact policy label for the ``serving_dtype_policy`` info
        gauge."""
        per = "per-channel" if self.per_channel else "per-tensor"
        ins = ",".join(sorted({str(s.get("dtype", "?"))
                               for s in self.inputs.values()})) or "none"
        return (f"w:{self.weight_dtype}:{per}:{self.weight_residency}"
                f"/act:{self.activation_dtype}/in:{ins}")

    def input_spec(self, name: Optional[str]) -> Optional[Dict[str, Any]]:
        return self.inputs.get(SINGLE if name is None else name)

    def is_quantized_dtype(self, dtype, name: Optional[str] = None) -> bool:
        spec = self.input_spec(name)
        return spec is not None and np.dtype(dtype) == np.dtype(spec["dtype"])

    def is_quantized_request(self, x: ArrayOrDict) -> bool:
        """Whether a normalized request is quantized traffic under this
        policy (dict requests: every policy-covered input in the policy
        dtype)."""
        if isinstance(x, dict):
            covered = [k for k in x if k in self.inputs]
            return bool(covered) and all(
                self.is_quantized_dtype(x[k].dtype, k) for k in covered)
        return self.is_quantized_dtype(np.asarray(x).dtype)

    def buckets_for(self, buckets) -> List[int]:
        """Buckets pre-warmed at the quantized dtype."""
        if self.quantized_buckets is None:
            return list(buckets)
        allowed = {int(b) for b in self.quantized_buckets}
        return [b for b in buckets if int(b) in allowed]

    def quantized_zeros(self, example: ArrayOrDict) -> Optional[ArrayOrDict]:
        """A zeros example shaped like ``example`` at the policy's
        quantized input dtype(s) — what warmup compiles the quantized
        executables from. ``None`` when the policy quantizes no inputs."""
        if not self.inputs:
            return None
        if isinstance(example, dict):
            out = {}
            for k, v in example.items():
                spec = self.inputs.get(k)
                dt = np.dtype(spec["dtype"]) if spec else v.dtype
                out[k] = np.zeros(v.shape, dt)
            return out
        spec = self.inputs.get(SINGLE)
        if spec is None:
            return None
        return np.zeros(np.asarray(example).shape, np.dtype(spec["dtype"]))

    def resolved_activation_dtype(self):
        if self.activation_dtype == "auto":
            from deeplearning4j_tpu.runtime.environment import get_environment
            return get_environment().compute_dtype
        import jax.numpy as jnp
        return jnp.dtype(self.activation_dtype)

    # --------------------------------------------------------------- serde
    def to_dict(self) -> dict:
        return {"format": _FORMAT,
                "weight_dtype": self.weight_dtype,
                "activation_dtype": self.activation_dtype,
                "weight_residency": self.weight_residency,
                "per_channel": self.per_channel,
                "symmetric": self.symmetric,
                "inputs": self.inputs,
                "quantized_buckets": self.quantized_buckets,
                "gate": self.gate,
                "created_at": self.created_at}

    @staticmethod
    def from_dict(d: dict) -> "DtypePolicy":
        if d.get("format") != _FORMAT:
            raise ValueError(f"not a dtype policy (format="
                             f"{d.get('format')!r}, expected {_FORMAT!r})")
        qb = d.get("quantized_buckets")
        return DtypePolicy(
            weight_dtype=str(d.get("weight_dtype", "int8")),
            activation_dtype=str(d.get("activation_dtype", "auto")),
            weight_residency=str(d.get("weight_residency", "dequantized")),
            per_channel=bool(d.get("per_channel", True)),
            symmetric=bool(d.get("symmetric", True)),
            inputs={str(k): dict(v)
                    for k, v in (d.get("inputs") or {}).items()},
            quantized_buckets=None if qb is None else [int(b) for b in qb],
            gate=dict(d.get("gate") or {}),
            created_at=float(d.get("created_at", 0.0)))

    def save(self, path: str) -> None:
        """Atomic write, same discipline as the warmup manifest — a crash
        mid-save never leaves a torn policy."""
        def write(tmp):
            with open(tmp, "w") as f:
                json.dump(self.to_dict(), f, indent=2)
        atomic_replace(path, write, prefix=".dtype-policy-")

    @staticmethod
    def load(path: str) -> "DtypePolicy":
        with open(path) as f:
            return DtypePolicy.from_dict(json.load(f))

    @staticmethod
    def load_for_archive(archive_path: str) -> Optional["DtypePolicy"]:
        p = policy_path(archive_path)
        if not os.path.exists(p):
            return None
        try:
            return DtypePolicy.load(p)
        except Exception as e:
            logger.warning("ignoring unreadable dtype policy %s (%s: %s)",
                           p, type(e).__name__, e)
            return None


# =========================================================== calibration
def _through_calibration_chaos(arr: np.ndarray) -> np.ndarray:
    """Pass one calibration batch through the ``serving.quantize.calibrate``
    chaos point with CRC framing: ANY injected corruption (bit flips,
    truncation) is caught deterministically and refuses the deploy — a
    corrupt calibration set can degrade the answer to "no", never to a
    silently wrong scale. No-op (no copy) when no controller is
    installed."""
    chaos.inject("serving.quantize.calibrate")
    if not chaos.active():
        return arr
    payload = np.ascontiguousarray(arr, np.float32).tobytes()
    framed = struct.pack("<I", zlib.crc32(payload)) + payload
    out = chaos.transform_bytes("serving.quantize.calibrate", framed)
    if out is framed:
        return arr
    if len(out) < 4:
        raise CalibrationError(
            "calibration batch truncated below its CRC header")
    (crc,), body = struct.unpack("<I", out[:4]), out[4:]
    if len(body) != len(payload) or zlib.crc32(body) != crc:
        raise CalibrationError(
            "calibration batch failed its CRC check (corrupt or truncated "
            "calibration data) — quantization refused")
    return np.frombuffer(body, np.float32).reshape(arr.shape)


def _normalize_calibration(calibration, input_names: List[str]
                           ) -> Dict[str, List[np.ndarray]]:
    """Calibration input → ``{input_name: [batches]}``. Accepts a single
    array, a list of arrays, a dict (multi-input graphs), or a path to an
    ``.npz`` (arrays keyed by input name, or any keys for single-input
    models)."""
    if isinstance(calibration, str):
        with np.load(calibration) as z:
            if input_names:
                calibration = {n: z[n] for n in input_names if n in z.files}
            else:
                calibration = [z[k] for k in z.files]
    if isinstance(calibration, dict):
        out = {}
        for k, v in calibration.items():
            out[str(k)] = ([np.asarray(b) for b in v]
                           if isinstance(v, (list, tuple))
                           else [np.asarray(v)])
        return out
    batches = ([np.asarray(b) for b in calibration]
               if isinstance(calibration, (list, tuple))
               else [np.asarray(calibration)])
    return {SINGLE: batches}


def calibrate_inputs(calibration, input_names: Optional[List[str]] = None,
                     dtype: str = "int8") -> Dict[str, Dict[str, Any]]:
    """Per-input affine quantization specs from a representative batch set.

    int8 is symmetric narrow-range (``scale = amax/127``, zero point 0);
    uint8 is asymmetric (``scale = (hi-lo)/255``). Every batch flows
    through the ``serving.quantize.calibrate`` chaos point; empty,
    non-finite, or corrupt data raises :class:`CalibrationError` — a
    refused deploy, never a silently wrong policy."""
    if dtype not in _CODE_RANGE:
        raise ValueError(f"unsupported quantized input dtype {dtype!r}; "
                         f"have {sorted(_CODE_RANGE)}")
    named = _normalize_calibration(calibration, input_names or [])
    if input_names:
        missing = [n for n in input_names if n not in named]
        if missing:
            raise CalibrationError(
                f"no calibration data for input(s) {missing}")
    specs: Dict[str, Dict[str, Any]] = {}
    for name, batches in named.items():
        if not batches or any(b.size == 0 for b in batches):
            raise CalibrationError(
                f"empty calibration batch set for input {name!r}")
        lo = hi = None
        n_rows = 0
        for b in batches:
            b = _through_calibration_chaos(
                np.asarray(b, np.float32))
            if not np.isfinite(b).all():
                raise CalibrationError(
                    f"non-finite values in calibration data for input "
                    f"{name!r} — quantization refused")
            lo = b.min() if lo is None else min(lo, b.min())
            hi = b.max() if hi is None else max(hi, b.max())
            n_rows += b.shape[0]
        if dtype == "int8":
            amax = max(abs(float(lo)), abs(float(hi)), 1e-12)
            scale, zp = amax / 127.0, 0
        else:  # uint8 asymmetric; range must cover 0 so padding is exact
            lo, hi = min(float(lo), 0.0), max(float(hi), 0.0)
            scale = max((hi - lo) / 255.0, 1e-12)
            zp = int(np.clip(round(-lo / scale), 0, 255))
        if not np.isfinite(scale) or scale <= 0.0:
            raise CalibrationError(
                f"degenerate calibration scale {scale!r} for input "
                f"{name!r} — quantization refused")
        specs[name] = {"dtype": dtype, "scale": float(scale),
                       "zero_point": int(zp),
                       "symmetric": dtype == "int8",
                       "calibration_rows": int(n_rows)}
    return specs


def quantize_requests(x: ArrayOrDict, policy: DtypePolicy) -> ArrayOrDict:
    """Client-side request quantization: f32 rows → the policy's quantized
    input dtype (the 4x-fewer-bytes wire format the serving path inverts
    in-graph). Inputs without a policy spec pass through unchanged."""
    def one(name, a):
        spec = policy.input_spec(name)
        if spec is None:
            return np.asarray(a)
        lo, hi = _CODE_RANGE[spec["dtype"]]
        q = np.round(np.asarray(a, np.float32) / spec["scale"])
        return np.clip(q + spec["zero_point"], lo, hi).astype(spec["dtype"])
    if isinstance(x, dict):
        return {k: one(k, v) for k, v in x.items()}
    return one(None, x)


# ======================================================== weight quant
def _tree_items(tree) -> List[Tuple[str, Any]]:
    """Stable ``(path_key, leaf)`` pairs for an arbitrary params pytree."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(kp), leaf) for kp, leaf in flat]


def _tree_rebuild(template, leaves_by_key: Dict[str, Any]):
    """Rebuild ``template``'s structure with leaves looked up by path key
    (each leaf may be an array OR a quantized-leaf dict subtree)."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for kp, leaf in flat:
        key = jax.tree_util.keystr(kp)
        if key not in leaves_by_key:
            raise ValueError(f"quantized archive is missing leaf {key!r}")
        leaves.append(leaves_by_key[key])
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _quantizable(leaf) -> bool:
    """Weights quantized per-channel: floating leaves of rank >= 2 (dense/
    conv/embedding kernels). Biases, norms, and scalars stay f32 — they are
    a rounding error of the byte budget and all of the fragility."""
    a = np.asarray(leaf)
    return a.ndim >= 2 and np.issubdtype(a.dtype, np.floating)


def quantize_weight(w, per_channel: bool = True
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric narrow-range int8 codes + scale for one weight leaf,
    through the registry's own ``quantize`` op (per-output-channel along
    the last axis — ``W`` is ``(nIn, nOut)`` here, conv kernels
    ``(..., out)``). Round-trip error is bounded by ``scale/2``
    (property-tested in ``tests/test_ops_quantize.py``)."""
    from deeplearning4j_tpu.autodiff.ops_registry import OPS
    w = np.asarray(w, np.float32)
    if per_channel and w.ndim >= 2:
        amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
        axis = -1
    else:
        amax, axis = np.max(np.abs(w)), None
    scale = np.maximum(np.asarray(amax, np.float32) / 127.0,
                       np.float32(1e-12))
    q = OPS["quantize"](w, scale=scale, zero_point=0, dtype="int8",
                        axis=axis, narrow_range=True)
    return np.asarray(q), np.asarray(scale, np.float32)


def dequantize_weight(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    from deeplearning4j_tpu.autodiff.ops_registry import OPS
    axis = -1 if np.asarray(scale).ndim == 1 else None
    return np.asarray(OPS["dequantize"](q, scale=scale, axis=axis))


# ===================================================== archive quantize
def quantize_archive(src: str, dst: str, calibration, *,
                     input_dtype: str = "int8",
                     per_channel: bool = True,
                     activation_dtype: str = "auto",
                     weight_residency: str = "dequantized",
                     max_accuracy_delta: float = 0.02,
                     quantized_buckets: Optional[List[int]] = None
                     ) -> Tuple[DtypePolicy, Dict[str, Any]]:
    """Quantize a ``ModelSerializer`` archive offline: per-channel int8
    weights, calibrated input scales, and a sidecar dtype-policy manifest
    (``<dst>.dtype_policy.json``) declaring dtypes and the accuracy gate.

    The output archive is written atomically AFTER calibration succeeds:
    a :class:`CalibrationError` (corrupt/truncated/non-finite calibration
    data, including injected ``serving.quantize.calibrate`` faults) leaves
    no archive and no policy behind — a refused deploy. Returns
    ``(policy, report)`` where ``report`` records byte savings and
    quantized-leaf counts."""
    if weight_residency not in ("dequantized", "int8"):
        raise ValueError(f"weight_residency must be 'dequantized' or "
                         f"'int8', got {weight_residency!r}")
    with zipfile.ZipFile(src) as zf:
        names = zf.namelist()
        if QUANT_MEMBER in names:
            raise ValueError(f"{src!r} is already a quantized archive")
        conf_json = zf.read(_CONF).decode()
        meta = (json.loads(zf.read(_META).decode())
                if _META in names else {})
    from deeplearning4j_tpu.models.serializer import ModelSerializer
    model = ModelSerializer.restore_model(src, load_updater=False)
    graph_inputs = list(getattr(model.conf, "inputs", []) or [])

    # calibration FIRST: nothing is written unless it succeeds
    input_specs = calibrate_inputs(calibration, graph_inputs or None,
                                   dtype=input_dtype)

    ts = model.train_state
    arrays: Dict[str, np.ndarray] = {}
    qmeta: Dict[str, Dict[str, Any]] = {}
    n_quant = n_total = 0
    f32_bytes = q_bytes = 0
    for key, leaf in _tree_items(ts.params):
        a = np.asarray(leaf)
        n_total += 1
        f32_bytes += a.nbytes
        if _quantizable(a):
            q, scale = quantize_weight(a, per_channel=per_channel)
            arrays["q|" + key] = q
            arrays["s|" + key] = scale
            qmeta[key] = {"dtype": "int8", "axis": -1,
                          "per_channel": bool(scale.ndim == 1)}
            q_bytes += q.nbytes + scale.nbytes
            n_quant += 1
        else:
            arrays["f|" + key] = a.astype(np.float32)
            q_bytes += a.nbytes
    state_arrays = {"m|" + key: np.asarray(leaf)
                    for key, leaf in _tree_items(ts.model_state)}

    policy = DtypePolicy(
        weight_dtype="int8", activation_dtype=activation_dtype,
        weight_residency=weight_residency, per_channel=per_channel,
        symmetric=True, inputs=input_specs,
        quantized_buckets=quantized_buckets,
        gate={"metric": "top1_agreement",
              "max_delta": float(max_accuracy_delta)},
        created_at=time.time())

    meta = dict(meta)
    meta["quantized"] = True
    def write_archive(tmp):
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(_CONF, conf_json)
            zf.writestr(_META, json.dumps(meta))
            zf.writestr(QUANT_MEMBER, json.dumps(
                {"format": _FORMAT, "leaves": qmeta,
                 "policy": policy.to_dict()}))
            import io
            for member, payload in ((_WEIGHTS, arrays),
                                    (_STATE, state_arrays)):
                buf = io.BytesIO()
                np.savez(buf, **payload)
                zf.writestr(member, buf.getvalue())
    atomic_replace(dst, write_archive, prefix=".quant-", suffix=".zip")
    policy.save(policy_path(dst))
    report = {"weights_quantized": n_quant, "leaves_total": n_total,
              "params_bytes_f32": int(f32_bytes),
              "params_bytes_quantized": int(q_bytes),
              "archive_bytes_src": os.path.getsize(src),
              "archive_bytes_dst": os.path.getsize(dst),
              "inputs": {k: {kk: v[kk] for kk in
                             ("dtype", "scale", "zero_point")}
                         for k, v in input_specs.items()}}
    return policy, report


# ======================================================= quantized model
def _is_qleaf(node) -> bool:
    return isinstance(node, dict) and "__q__" in node


class QuantizedModel:
    """A quantized archive served as a first-class model.

    Duck-types the MLN/ComputationGraph internals the serving stack builds
    on (``conf``/``train_state``/``_forward``/``_forward_all``/``_jitted``/
    ``output``), so :class:`~deeplearning4j_tpu.serving.replica.ReplicaPool`
    AOT-compiles its executables, the batcher buckets its traffic, and the
    registry hot-swaps it exactly like an f32 model. Int8 request rows are
    dequantized **in-graph** per the policy's calibrated input specs; f32
    rows pass through untouched — one wrapper, two dtype worlds, separate
    executables per dtype (the AOT signature sees the real dtype).
    """

    def __init__(self, base, params, model_state, policy: DtypePolicy):
        import dataclasses as _dc
        self.base = base
        self.conf = base.conf
        self.rng = base.rng
        self.dtype_policy = policy
        self._graph_inputs = list(getattr(base.conf, "inputs", []) or [])
        self._jit_cache: Dict[str, Any] = {}
        self.train_state = _dc.replace(
            base.train_state, params=params, model_state=model_state)

    def init(self) -> "QuantizedModel":
        return self  # restored fully-initialised; nothing to draw

    # ------------------------------------------------------------ restore
    @staticmethod
    def restore(path: str) -> "QuantizedModel":
        """Load a :func:`quantize_archive` output. The embedded policy is
        authoritative; the sidecar exists for fleet tooling and humans."""
        with zipfile.ZipFile(path) as zf:
            qinfo = json.loads(zf.read(QUANT_MEMBER).decode())
            conf_json = zf.read(_CONF).decode()
            meta = (json.loads(zf.read(_META).decode())
                    if _META in zf.namelist() else {})
            import io
            with np.load(io.BytesIO(zf.read(_WEIGHTS))) as z:
                arrays = {k: z[k] for k in z.files}
            with np.load(io.BytesIO(zf.read(_STATE))) as z:
                state_arrays = {k: z[k] for k in z.files}
        policy = DtypePolicy.from_dict(qinfo["policy"])
        if meta.get("model_type") == "ComputationGraph":
            from deeplearning4j_tpu.models.computation_graph import (
                ComputationGraph, ComputationGraphConfiguration)
            base = ComputationGraph(
                ComputationGraphConfiguration.from_json(conf_json)).init()
        else:
            from deeplearning4j_tpu.models.multi_layer_network import \
                MultiLayerNetwork
            from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
            base = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(conf_json)).init()

        act_dt = policy.resolved_activation_dtype()
        import jax.numpy as jnp
        by_key: Dict[str, Any] = {}
        for key, _ in _tree_items(base.train_state.params):
            if ("q|" + key) in arrays:
                q, s = arrays["q|" + key], arrays["s|" + key]
                if policy.weight_residency == "int8":
                    by_key[key] = {"__q__": jnp.asarray(q),
                                   "__scale__": jnp.asarray(s)}
                else:
                    w = dequantize_weight(q, s)
                    by_key[key] = (jnp.asarray(w, act_dt)
                                   if jnp.dtype(act_dt) != jnp.float32
                                   else jnp.asarray(w))
            elif ("f|" + key) in arrays:
                by_key[key] = jnp.asarray(arrays["f|" + key])
            else:
                raise ValueError(
                    f"quantized archive {path!r} is missing leaf {key!r}")
        params = _tree_rebuild(base.train_state.params, by_key)
        state_by_key = {}
        for key, _ in _tree_items(base.train_state.model_state):
            state_by_key[key] = jnp.asarray(state_arrays["m|" + key])
        model_state = _tree_rebuild(base.train_state.model_state,
                                    state_by_key)
        return QuantizedModel(base, params, model_state, policy)

    # ----------------------------------------------------------- plumbing
    def _jitted(self, name: str, factory):
        if name not in self._jit_cache:
            self._jit_cache[name] = factory()
        return self._jit_cache[name]

    def _serve_params(self, params):
        """Dequantize any device-resident int8 leaves to the activation
        dtype (traced; a no-op tree walk for ``dequantized`` residency)."""
        import jax.numpy as jnp
        act_dt = self.dtype_policy.resolved_activation_dtype()

        def walk(node):
            if _is_qleaf(node):
                return (node["__q__"].astype(act_dt)
                        * node["__scale__"].astype(act_dt))
            if isinstance(node, dict):
                return {k: walk(v) for k, v in node.items()}
            return node
        return walk(params)

    def _dequant_one(self, name: Optional[str], x):
        """Invert the calibrated input map for request rows arriving in
        the policy's EXACT wire dtype (traced). Everything else — floats,
        but also plain int64/int32 feature rows that merely happen to be
        integers — passes through untouched, mirroring
        ``DtypePolicy.is_quantized_request``: only rows a client
        deliberately quantized carry codes, and applying the affine map
        to ordinary integer features would silently corrupt them."""
        import jax.numpy as jnp
        x = jnp.asarray(x)
        spec = self.dtype_policy.input_spec(name)
        if spec is None or np.dtype(x.dtype) != np.dtype(spec["dtype"]):
            return x
        act_dt = self.dtype_policy.resolved_activation_dtype()
        zp = spec.get("zero_point", 0)
        x = x.astype(act_dt)
        if zp:
            x = x - jnp.asarray(zp, act_dt)
        return x * jnp.asarray(spec["scale"], act_dt)

    # ------------------------------------------------------ forward duck
    def _forward(self, params, model_state, x, *, training: bool = False,
                 rng=None, fmask=None, carries=None):
        return self.base._forward(
            self._serve_params(params), model_state,
            self._dequant_one(None, x), training=training, rng=rng,
            fmask=fmask, carries=carries)

    def _forward_all(self, params, model_state, inputs, *,
                     training: bool = False, rng=None, masks=None,
                     carries=None):
        deq = {k: self._dequant_one(k, v) for k, v in inputs.items()}
        return self.base._forward_all(
            self._serve_params(params), model_state, deq,
            training=training, rng=rng, masks=masks, carries=carries)

    def output(self, *xs, training: bool = False, mask=None):
        """Inference mirroring MLN/CG ``output`` through this wrapper's
        forward (so direct calls, the gate, and the replica executables
        share one trace per input signature)."""
        ts = self.train_state
        if self._graph_inputs:
            if len(xs) == 1 and isinstance(xs[0], dict):
                inputs = dict(xs[0])
            else:
                inputs = {n: x for n, x in zip(self._graph_inputs, xs)}

            def fwd(params, model_state, inputs_):
                acts, _, _ = self._forward_all(params, model_state, inputs_,
                                               training=False, rng=None)
                return [acts[o] for o in self.conf.outputs]
            import jax
            fn = self._jitted("output", lambda: jax.jit(fwd))
            outs = fn(ts.params, ts.model_state, inputs)
            return outs[0] if len(outs) == 1 else outs

        def fwd(params, model_state, x_, m_):
            out, _, _, _ = self._forward(params, model_state, x_,
                                         training=False, rng=None, fmask=m_)
            return out
        import jax
        fn = self._jitted("output", lambda: jax.jit(fwd))
        return fn(ts.params, ts.model_state, xs[0], mask)


# ========================================================= accuracy gate
class AccuracyGate(delivery.GoldenGate):
    """The quantized-deploy bar, now THE ONE
    :class:`~deeplearning4j_tpu.serving.delivery.GoldenGate`
    implementation wearing its quantized face (ISSUE 17's "exactly one
    gate" fix): quantized accuracy may trail the f32 golden by at most
    ``max_delta`` on the evaluation set, the quantized model sees inputs
    **through the policy's request quantization** (the real serving
    path — int8 rows, in-graph dequant — handled by the base class via
    ``dtype_policy``), and failure raises :class:`AccuracyGateFailed`
    while the previous version keeps serving."""

    chaos_point = "serving.quantize.gate"
    failure_exc = AccuracyGateFailed
