"""Stdlib-HTTP JSON model server (the konduit/dl4j model-server role).

Same dependency-free ``ThreadingHTTPServer`` pattern as ``ui/server.py``
(offline environment — no web framework). Endpoints:

- ``GET  /v1/models``                  — registry listing + per-model metrics
- ``GET  /v1/models/<name>``           — one model's description
- ``POST /v1/models/<name>/predict``   — JSON inference (pages a COLD
  model in first — ISSUE 11; the request waits, and a deadline that
  cannot cover the wait gets 503 ``paging_in`` with an honest
  ``Retry-After`` from the measured page-in cost)
- ``POST /v1/models/<name>/residency`` — explicit paging lever:
  ``{"state": "resident"|"cold"}`` pages in / evicts (409 while pinned)
- Session tier (ISSUE 16, requires ``session_dir``): ``POST
  /v1/models/<name>/sessions`` opens a stream (server-side
  ``rnnTimeStep`` carry), ``POST /v1/models/<name>/sessions/<id>/step``
  advances it one chunk (``{"inputs": ..., "step": k}`` — the step index
  makes failover retries exactly-once; 410 ``session_lost`` when the
  spilled carry is damaged, 409 ``step_conflict`` on a position
  mismatch), ``POST /v1/models/<name>/sessions/<id>/stream`` runs many
  steps over one connection with Server-Sent-Events framing, ``DELETE
  /v1/models/<name>/sessions/<id>`` closes, and ``POST
  /v1/sessions/drain`` is the rolling-deploy migration fence (spill all
  resident carries to the shared spill dir)
- ``GET  /healthz``                    — liveness (the process serves HTTP)
- ``GET  /readyz``                     — readiness (every model READY; a
  DEGRADED breaker-open model or an empty registry returns 503 so an
  orchestrator routes traffic elsewhere)
- ``GET  /metrics``                    — Prometheus text format, incl. the
  pipeline gauges (ISSUE 3): ``serving_inflight_depth`` (dispatched
  batches awaiting readback), ``serving_replica_batches_total`` per device
  replica, and the ``serving_dispatch_to_completion_seconds`` histogram

Predict request body::

    {"inputs": [[...], ...]}                       # single-input model
    {"inputs": {"in_a": [[...]], "in_b": [[...]]}} # multi-input graph
    {"inputs": ..., "timeout_ms": 50}              # per-request deadline
    {"inputs": [[...]], "dtype": "int8"}           # wire dtype (ISSUE 8)

The optional ``dtype`` field (a numpy dtype name, or a per-input-name map
for graphs) pins the parsed arrays' dtype — JSON integers otherwise parse
as int64, which would miss the int8 executables a quantized model's
dtype policy pre-warmed. Clients serving a quantized model send rows
through :func:`~deeplearning4j_tpu.serving.quantize.quantize_requests`
and declare ``"dtype": "int8"`` (``docs/quantization.md``).

Admission-control semantics map onto status codes: ``503`` for
``Overloaded`` (queue full — shed, retry elsewhere) and for
``CircuitOpen`` (breaker shedding a failing model, ``reason`` field
disambiguates), ``504`` for ``DeadlineExceeded``, ``404`` unknown model,
``400`` malformed body. Every response is explicit; nothing queues
unboundedly behind the socket.

Fleet-tier contract (ISSUE 7, ``docs/fleet_serving.md``) — the headers a
:class:`~deeplearning4j_tpu.serving.router.FleetRouter` in front of this
worker relies on:

- ``X-Deadline-Ms`` (request): the caller's REMAINING deadline budget.
  Honored as an upper bound on the body's ``timeout_ms``, so a hedged or
  failed-over retry arriving late in a request's life never gets a fresh
  full deadline (deadlines used to be process-local only).
- ``Retry-After`` / ``Retry-After-Ms`` (503 ``Overloaded`` response): the
  shedding worker's queue-depth-derived drain estimate
  (:meth:`~deeplearning4j_tpu.serving.admission.AdmissionController
  .retry_after_ms`) — the router routes around this worker until the
  window passes instead of hammering it.
- ``X-Request-Id`` (both ways): echoed verbatim so duplicate hedge
  completions are attributable; ``X-Worker-Id`` / ``X-Model-Version``
  (response) identify who actually served.

``chaos.inject("serving.worker.predict")`` fires at the top of every
predict so a drill (or ``bench.py --fleet``'s straggler schedule) can
slow or fail an individual worker process.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

import numpy as np

from deeplearning4j_tpu.runtime import chaos, journal, trace
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.serving.admission import (
    DeadlineExceeded,
    Overloaded,
    PagingInProgress,
    ServingError,
)
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.resilience import CircuitOpen
from deeplearning4j_tpu.serving.sessions import (SessionLost,
                                                 SessionStepConflict)
from deeplearning4j_tpu.serving.slo import SLOMonitor


def _to_jsonable(out):
    if isinstance(out, (list, tuple)):
        return [np.asarray(o).tolist() for o in out]
    return np.asarray(out).tolist()


class ModelServer:
    """``ModelServer(registry).start(port)`` — serve a registry over HTTP.

    ``worker_id`` names this process in a fleet (stamped on responses as
    ``X-Worker-Id`` so the router's hedge/failover accounting and the
    bit-identity drills can attribute every answer)."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 worker_id: Optional[str] = None,
                 slo: Optional[SLOMonitor] = None,
                 session_dir: Optional[str] = None,
                 session_kw: Optional[dict] = None,
                 wire_enabled: Optional[bool] = None):
        self.registry = registry or ModelRegistry()
        self.worker_id = worker_id
        # binary wire protocol (ISSUE 18): on by default; the
        # DL4J_TPU_FORCE_JSON runbook knob (or wire_enabled=False) makes
        # this worker answer 415 to binary frames so every sender
        # transcodes to JSON — the negotiated compatibility fallback
        if wire_enabled is None:
            wire_enabled = not os.environ.get("DL4J_TPU_FORCE_JSON")
        self.wire_enabled = bool(wire_enabled)
        # per-worker SLO attainment + burn rates (ISSUE 9); the router
        # keeps its own fleet-wide monitor over the same outcomes
        self.slo = slo or SLOMonitor()
        # session tier (ISSUE 16): enabled by pointing the worker at the
        # fleet's SHARED spill directory — sharing it is what makes a
        # session survive failover and rolling deploys (migration =
        # rehydrate the spill on the newly pinned worker)
        self.sessions = None
        if session_dir is not None:
            from deeplearning4j_tpu.serving.sessions import SessionStore
            self.sessions = SessionStore(self.registry, session_dir,
                                         worker_id=worker_id or "",
                                         **(session_kw or {}))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._capacity_provider = None  # our profiler attachment (stop)
        # background-job scheduler (ISSUE 19): attach one to surface
        # GET /v1/scheduler and the scheduler_* /metrics section; the
        # owner starts/stops it (the server only reads snapshots)
        self.scheduler = None
        self.port: Optional[int] = None

    # ------------------------------------------------------------ handlers
    @staticmethod
    def _effective_timeout_ms(body_timeout_ms, header_deadline_ms):
        """The request's deadline budget: the body's ``timeout_ms`` capped
        by the forwarded ``X-Deadline-Ms`` remaining budget — a retry that
        arrives with 40 ms left gets 40 ms, never a fresh full window."""
        values = [float(v) for v in (body_timeout_ms, header_deadline_ms)
                  if v is not None]
        return min(values) if values else None

    def _handle_predict(self, name: str, raw: bytes, headers=None,
                        wire_proto: bool = False):
        """Returns ``(status, body, extra_headers)`` — ``body`` is a
        jsonable dict, or an encoded wire frame (bytes) for a binary
        request's 200 (errors stay JSON on both protocols so a damaged
        frame can never masquerade as a tensor).

        Tracing (ISSUE 9): when enabled, the whole predict runs inside a
        ``worker.predict`` span continuing the caller's trace off the
        ``X-Trace-Id`` / ``X-Parent-Span-Id`` headers (the router's
        attempt span id), so the router's ``/v1/traces`` aggregation can
        merge this worker's spans — including the batcher stage spans the
        request's span parents — into one tree. Terminal outcomes feed
        the worker's :class:`SLOMonitor` and, behind the
        ``DL4J_TPU_ACCESS_LOG`` knob, one structured JSON log line."""
        h = headers or {}
        if trace.enabled():
            sp = trace.server_span("worker.predict",
                                   trace_id=h.get("X-Trace-Id"),
                                   parent_id=h.get("X-Parent-Span-Id"))
            # a caller that already knows this trace is interesting (the
            # router's hedge attempt) says so — tail sampling is decided
            # per process, so the hint is what keeps THIS process's half
            flags = h.get("X-Trace-Flags")
            if flags and sp.recording:
                for f in str(flags).split(","):
                    if f.strip():
                        sp.flag(f.strip())
        else:
            sp = trace.NOOP
        t0 = time.monotonic()
        with sp:
            if sp.recording:
                sp.set("model", name)
                if self.worker_id is not None:
                    sp.set("worker", self.worker_id)
            status, obj, hdrs = self._predict_inner(name, raw, h,
                                                    wire_proto=wire_proto)
            latency_s = time.monotonic() - t0
            if sp.recording:
                sp.set("status", status)
                if status == 503:
                    sp.flag("shed")
                elif status == 504:
                    sp.flag("deadline")
                elif status >= 500:
                    sp.flag("fault")
                hdrs["X-Trace-Id"] = sp.trace_id
        if status != 404:
            # 404 = the model name does not exist here; recording it
            # would let arbitrary client-sent names grow SLO state
            self.slo.record(name, ok=status == 200, latency_s=latency_s)
        if trace.access_log_enabled():  # don't build the record otherwise
            trace.emit_access_log({
                "trace_id": sp.trace_id,
                "request_id": h.get("X-Request-Id"),
                "worker": self.worker_id,
                "model": name,
                "bucket": sp.annotations.get("bucket"),
                "dtype": sp.annotations.get("dtype"),
                "outcome": status,
                "latency_ms": round(latency_s * 1e3, 3),
            })
        return status, obj, hdrs

    def _predict_inner(self, name: str, raw: bytes, headers,
                       wire_proto: bool = False):
        chaos.inject("serving.worker.predict")
        if wire_proto:
            return self._predict_wire(name, raw, headers)
        hdrs = {}
        try:
            body = json.loads(raw.decode() or "{}")
            inputs = body["inputs"]
            timeout_ms = self._effective_timeout_ms(
                body.get("timeout_ms"),
                (headers or {}).get("X-Deadline-Ms"))
            dtype = body.get("dtype")
            if dtype is not None:
                trace.annotate_current(
                    "dtype", dtype if isinstance(dtype, str) else dict(dtype))

            def _dt(name):
                if dtype is None:
                    return None
                if isinstance(dtype, dict):
                    if name not in dtype:
                        return None
                    dt = np.dtype(dtype[name])
                else:
                    dt = np.dtype(dtype)
                if dt.kind not in "biuf":
                    # object/str/datetime dtypes would defeat the
                    # ragged-row guard below (np.asarray(..., object)
                    # accepts ragged input) and fail inside the model,
                    # feeding the circuit breaker instead of returning 400
                    raise ValueError(f"unsupported request dtype {dt!s}")
                return dt
            if isinstance(inputs, dict):
                x = {k: np.asarray(v, dtype=_dt(k))
                     for k, v in inputs.items()}
            else:
                x = np.asarray(inputs, dtype=_dt(None))  # ragged rows -> 400
        except Exception as e:
            return 400, {"error": f"malformed request body: {e}"}, hdrs
        status, obj, hdrs, out = self._serve(name, x, timeout_ms, hdrs)
        if status == 200:
            obj = dict(obj, outputs=_to_jsonable(out))
        return status, obj, hdrs

    def _predict_wire(self, name: str, raw: bytes, headers):
        """The binary-frame twin of the JSON parse path.  A frame that
        fails validation is an EXPLICIT protocol error: 503 with reason
        ``wire_protocol_error`` (retryable at the router — 400 would be
        terminal), never a silently wrong tensor."""
        hdrs = {}
        try:
            x, body_timeout_ms, fields, fr = wire.decode_predict_request(raw)
        except wire.WireProtocolError as e:
            trace.flag_current("fault")
            return 503, {"error": "bad wire frame",
                         "reason": "wire_protocol_error",
                         "detail": str(e)}, hdrs
        try:
            # frame fields carry the control headers 1:1; an ACTUAL HTTP
            # header wins (the router stamps the per-attempt shrunken
            # X-Deadline-Ms on the hop itself)
            eff = wire.fields_to_headers(fields)
            eff.update({str(k): v for k, v in dict(headers or {}).items()})
            timeout_ms = self._effective_timeout_ms(
                body_timeout_ms, eff.get("X-Deadline-Ms"))
            status, obj, hdrs, out = self._serve(name, x, timeout_ms, hdrs)
        finally:
            x = None  # drop tensor views so a shm-backed frame can close
            fr.close()
        if status == 200:
            frame = wire.encode_predict_response(
                name, obj.get("version"), out,
                fields=wire.headers_to_fields(
                    dict(hdrs, **({"X-Worker-Id": self.worker_id}
                                  if self.worker_id is not None else {}))))
            return 200, frame, hdrs
        return status, obj, hdrs

    def _serve(self, name, x, timeout_ms, hdrs):
        """acquire -> predict -> classify, shared by both protocols.
        Returns ``(status, obj, hdrs, out)`` where ``out`` is the raw
        model output on 200 (the caller marshals it per protocol)."""
        # resolve the model OUTSIDE the submit try: a KeyError raised by a
        # multi-input forward (wrong input name) must not read as 404.
        # acquire() also PAGES IN a cold model (ISSUE 11) — the request
        # waits in the page-in queue instead of failing — and pins the
        # entry so eviction can never unload it mid-request.
        acquire = getattr(self.registry, "acquire", None)
        # the deadline is spent ONCE: time the request waits on a page-in
        # is deducted from the budget the batcher sees afterwards
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1000.0)
        try:
            if acquire is not None:
                served = acquire(name, timeout_ms=timeout_ms)
            else:  # duck-typed stub registry (tests): resident-only lookup
                served = self.registry.get(name)
        except KeyError:
            return 404, {"error": f"model {name!r} not found",
                         "models": self.registry.names()}, hdrs, None
        except PagingInProgress as e:
            # the deadline provably cannot cover the page-in: an HONEST
            # Retry-After from the measured page-in cost, not a generic 503
            retry_ms = e.retry_after_ms
            if retry_ms is not None:
                hdrs["Retry-After"] = str(int(math.ceil(retry_ms / 1000.0)))
                hdrs["Retry-After-Ms"] = f"{retry_ms:.0f}"
            trace.flag_current("shed")
            return 503, {"error": "paging in", "reason": "paging_in",
                         "retry_after_ms": retry_ms,
                         "detail": str(e)}, hdrs, None
        except ServingError as e:
            # e.g. HBMBudgetExceeded mid-page-in: transient, retryable
            return 503, {"error": "unavailable", "reason": "paging_failed",
                         "detail": str(e)}, hdrs, None
        except Exception as e:
            # a corrupt archive mid-page-in must not read as model fault 500
            return 503, {"error": "unavailable", "reason": "paging_failed",
                         "detail": repr(e)}, hdrs, None
        if deadline is not None:
            timeout_ms = max(0.0, (deadline - time.monotonic()) * 1000.0)
        try:
            out = served.predict(x, timeout_ms=timeout_ms)
        except CircuitOpen as e:
            return 503, {"error": "unavailable", "reason": "circuit_open",
                         "detail": str(e)}, hdrs, None
        except Overloaded as e:
            retry_ms = getattr(e, "retry_after_ms", None)
            if retry_ms is not None:
                # standard header is integer seconds; the -Ms twin keeps
                # sub-second hints honest for the router
                hdrs["Retry-After"] = str(int(math.ceil(retry_ms / 1000.0)))
                hdrs["Retry-After-Ms"] = f"{retry_ms:.0f}"
            return 503, {"error": "overloaded", "reason": "overloaded",
                         "retry_after_ms": retry_ms,
                         "detail": str(e)}, hdrs, None
        except DeadlineExceeded as e:
            return (504, {"error": "deadline exceeded", "detail": str(e)},
                    hdrs, None)
        except Exception as e:
            return 500, {"error": repr(e)}, hdrs, None
        finally:
            unpin = getattr(served, "unpin", None)
            if unpin is not None:  # stubs have no pin ledger
                unpin()
        hdrs["X-Model-Version"] = str(served.version)
        return (200, {"model": name, "version": served.version}, hdrs, out)

    def _handle_get(self, path: str):
        if path.startswith("/v1/journal"):
            # this process's slice of the black box (ISSUE 15): the
            # router merges it fleet-wide; same bounded-read contract
            # as /v1/traces
            q = parse_qs(urlsplit(path).query)
            try:
                limit = (int(q["limit"][0]) if "limit" in q else None)
                since = (float(q["since"][0]) if "since" in q else None)
            except ValueError as e:
                return 400, {"error": f"bad limit/since query param: {e}"}
            types = None
            if "type" in q:
                types = {t for v in q["type"] for t in v.split(",") if t}
            events, truncated = journal.bound_events(
                journal.events(), since=since, limit=limit, types=types)
            return 200, {"worker": self.worker_id, "events": events,
                         "truncated": truncated,
                         "counters": journal.counters()}
        if path == "/v1/debug/stacks":
            # per-process stack sample: what the router's fleet bundle
            # scrapes so the postmortem shows where EVERY process was
            from deeplearning4j_tpu.serving import blackbox
            return 200, {"worker": self.worker_id,
                         "stacks": blackbox.stack_sample()}
        if path.startswith("/v1/traces"):
            # this process's kept traces (tail-sampled flight recorder);
            # ?trace_id= filters, ?format=chrome renders Perfetto-loadable
            # trace-event JSON (ISSUE 9, docs/observability.md).
            # Responses are BOUNDED (ISSUE 10): ?limit=N keeps the newest
            # N, ?since=<unix ts> filters by span start, and a hard
            # serialized-size cap applies regardless — a scrape of a full
            # ring can never produce an unbounded HTTP body.
            q = parse_qs(urlsplit(path).query)
            recs = trace.collector().traces()
            tid = q.get("trace_id", [None])[0]
            if tid:
                recs = [r for r in recs if r.get("trace_id") == tid]
            try:
                limit = (int(q["limit"][0]) if "limit" in q else None)
                since = (float(q["since"][0]) if "since" in q else None)
            except ValueError as e:
                return 400, {"error": f"bad limit/since query param: {e}"}
            recs, truncated = trace.bound_traces(recs, limit=limit,
                                                 since=since)
            if q.get("format", [None])[0] == "chrome":
                return 200, trace.to_chrome_trace(recs)
            return 200, {"traces": recs,
                         "truncated": truncated,
                         "kept": trace.collector().kept,
                         "dropped": trace.collector().dropped,
                         "worker": self.worker_id}
        if path == "/v1/slo":
            # machine-readable twin of the /metrics slo_* section: the
            # SLOMonitor report dict — what the autoscaler drill and
            # external dashboards consume instead of parsing Prometheus
            # text (ISSUE 10)
            return 200, {"worker": self.worker_id,
                         "windows_s": list(self.slo.windows_s),
                         "slo": self.slo.report()}
        if path == "/v1/capacity":
            # per-model resource accounting (ISSUE 10 tentpole): parameter
            # /device bytes by dtype, replica utilization, queue headroom,
            # compile footprint — the ledger the autoscaler's capacity
            # guard consults (aggregated fleet-wide by the router)
            from deeplearning4j_tpu.serving import capacity
            payload = {"worker": self.worker_id,
                       **capacity.registry_capacity(self.registry)}
            if self.sessions is not None:
                # session-tier residency (ISSUE 16): counts/bytes +
                # rehydrate percentiles, fleet-aggregated by the router
                payload["sessions"] = self.sessions.snapshot()
            return 200, payload
        if path == "/v1/scheduler":
            # background-job scheduler (ISSUE 19): harvest counters,
            # admission config and the shared job store's records — the
            # machine-readable twin of the scheduler_* /metrics section
            if self.scheduler is None:
                return 404, {"error": "no scheduler attached"}
            return 200, {"worker": self.worker_id,
                         "scheduler": self.scheduler.harvest_snapshot(),
                         "jobs": self.scheduler.store.jobs()}
        if path == "/v1/metricsz":
            # machine-readable twin of /metrics: summable counters + raw
            # bucket histograms so the router can aggregate fleet-wide
            models = {}
            for name in self.registry.names():
                try:
                    models[name] = \
                        self.registry.get(name).metrics.wire_snapshot()
                except KeyError:
                    pass  # undeployed between listing and snapshot
            return 200, {"worker": self.worker_id, "models": models}
        if path == "/healthz":
            # liveness only: the process is up and serving HTTP; "wire"
            # advertises whether binary frames are accepted (ISSUE 18)
            return 200, {"status": "ok", "models": self.registry.names(),
                         "wire": self.wire_enabled}
        if path == "/readyz":
            # one snapshot for both fields so they can never disagree
            health = self.registry.health()
            ready = self.registry.ready_from(health)
            return (200 if ready else 503), {"ready": ready,
                                             "models": health}
        if path == "/v1/models":
            return 200, {"models": self.registry.describe()}
        if path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):].strip("/")
            try:
                return 200, self.registry.get(name).describe()
            except KeyError:
                # a COLD model is registered, not gone (ISSUE 11): serve
                # its catalogue description instead of a false 404
                for d in self.registry.describe():
                    if d.get("name") == name:
                        return 200, d
                return 404, {"error": f"model {name!r} not found"}
        return 404, {"error": f"unknown path {path!r}"}

    def _handle_scale(self, name: str, raw: bytes, headers=None):
        """``POST /v1/models/<name>/replicas`` — runtime ReplicaPool
        resize (ISSUE 10: the autoscaler's replica lever; also a manual
        operator action). Body ``{"replicas": n}`` (absolute) or
        ``{"delta": d}`` (relative to the LIVE count — what the
        autoscaler sends, so a stale capacity scrape can never turn a
        scale-up into an absolute scale-down; delta targets clamp to the
        one-replica floor instead of erroring). Grows via
        :meth:`ContinuousBatcher.add_replica` (each new replica warmed
        from the live warmup manifest BEFORE routing — zero on-traffic
        compiles) or shrinks via :meth:`remove_replica`; concurrent
        resizes serialize on the batcher's resize lock (two racing
        target-chasing loops would otherwise overshoot and thrash,
        paying warmup compiles for replicas immediately removed). Joins
        the caller's trace off the standard headers so the scaling
        decision and its execution are ONE tree."""
        h = headers or {}
        sp = (trace.server_span("worker.scale_replicas",
                                trace_id=h.get("X-Trace-Id"),
                                parent_id=h.get("X-Parent-Span-Id"))
              if trace.enabled() else trace.NOOP)
        with sp:
            if sp.recording:
                sp.flag("autoscale")
                sp.set("model", name)
            try:
                body = json.loads(raw.decode() or "{}")
                if ("replicas" in body) == ("delta" in body):
                    raise ValueError(
                        "body must carry exactly one of 'replicas' "
                        "(absolute) or 'delta' (relative)")
                delta = int(body["delta"]) if "delta" in body else None
                n = int(body["replicas"]) if "replicas" in body else None
                if n is not None and not 1 <= n <= 64:
                    raise ValueError(f"replicas must be in [1, 64], got {n}")
                # optional floor for delta requests (the autoscaler sends
                # its min_replicas): downward deltas clamp against it
                floor = int(body.get("floor", 1))
                if not 1 <= floor <= 64:
                    raise ValueError(f"floor must be in [1, 64], got {floor}")
                if floor != 1 and delta is None:
                    raise ValueError("'floor' is only valid with 'delta'")
            except Exception as e:
                return 400, {"error": f"malformed scale request: {e}"}, {}
            try:
                served = self.registry.get(name)
            except KeyError:
                if name in self.registry.names():
                    # registered but COLD: a resize has no pool to act on
                    return 409, {"error": f"model {name!r} is cold; page "
                                          f"it in before resizing"}, {}
                return 404, {"error": f"model {name!r} not found"}, {}
            batcher = served.batcher
            with batcher.resize_lock:
                before = batcher.replica_count
                if delta is not None:
                    n = min(64, max(floor, before + delta))
                try:
                    while batcher.replica_count < n:
                        batcher.add_replica()
                    while batcher.replica_count > n:
                        batcher.remove_replica()
                except Exception as e:
                    return 500, {"error": repr(e),
                                 "replicas": batcher.replica_count}, {}
            if sp.recording:
                sp.set("replicas_before", before)
                sp.set("replicas_after", batcher.replica_count)
            refresh = getattr(self.registry, "refresh_device_bytes", None)
            if refresh is not None:
                # the resize minted/dropped device_put copies: the HBM
                # ledger must see the new footprint (and page others out
                # if it overshot the budget) — ISSUE 11
                refresh(name)
            try:
                # persist the resized warm set so a restart pre-warms it
                self.registry.save_manifest(name)
            except Exception:
                pass  # best effort, same as graceful-shutdown refresh
            return 200, {"model": name, "replicas": batcher.replica_count,
                         "replicas_before": before,
                         "compile_count": batcher.compile_count(),
                         "warmed_pairs": len(batcher._warmed_pairs)}, {}

    def _handle_residency(self, name: str, raw: bytes, headers=None):
        """``POST /v1/models/<name>/residency`` — explicit paging lever
        (ISSUE 11): body ``{"state": "resident"}`` pages a cold model in
        (manifest-prewarmed, single-flight with any request-triggered
        page-in underway), ``{"state": "cold"}`` evicts (refused with 409
        while in-flight requests pin the model — eviction is never
        unsafe, only deferred). Drives the autoscaler's placement
        rebalancing and operator runbooks; joins the caller's trace so a
        rebalance decision and its page-in are one tree."""
        h = headers or {}
        sp = (trace.server_span("worker.residency",
                                trace_id=h.get("X-Trace-Id"),
                                parent_id=h.get("X-Parent-Span-Id"))
              if trace.enabled() else trace.NOOP)
        with sp:
            if sp.recording:
                sp.flag("page_in")
                sp.set("model", name)
            try:
                body = json.loads(raw.decode() or "{}")
                state = body["state"]
                if state not in ("resident", "cold"):
                    raise ValueError(f"state must be 'resident' or 'cold', "
                                     f"got {state!r}")
            except Exception as e:
                return 400, {"error": f"malformed residency request: "
                                      f"{e}"}, {}
            if sp.recording:
                sp.set("target_state", state)
            # the explicit lever is a journal event either way (ISSUE 15):
            # an autoscaler rebalance and an operator runbook leave the
            # same black-box record
            journal.emit("registry.residency_lever", model=name,
                         target_state=state)
            if state == "resident":
                try:
                    served = self.registry.page_in(name)
                except KeyError:
                    return 404, {"error": f"no archive-backed model "
                                          f"{name!r}"}, {}
                except Exception as e:
                    return 500, {"error": repr(e)}, {}
                return 200, {"model": name, "state": "resident",
                             "version": served.version,
                             "device_bytes": served.device_bytes}, {}
            if name not in self.registry.names():
                return 404, {"error": f"model {name!r} not found"}, {}
            if self.registry.evict(name):
                return 200, {"model": name, "state": "cold"}, {}
            # idempotence: asking for a state the model is already in is
            # a no-op 200, not a 409 (retried runbooks must not alert)
            if name not in self.registry.resident_names():
                return 200, {"model": name, "state": "cold",
                             "already": True}, {}
            return 409, {"error": f"cannot evict {name!r}: pinned by "
                                  f"in-flight requests or not "
                                  f"archive-backed"}, {}

    # ------------------------------------------------------ session tier
    def _session_store_or_503(self):
        if self.sessions is None:
            return None, (503, {"error": "sessions disabled",
                                "reason": "sessions_disabled",
                                "detail": "this worker was started without "
                                          "a session spill directory"}, {})
        return self.sessions, None

    def _handle_session_create(self, name: str, raw: bytes, headers=None):
        """``POST /v1/models/<name>/sessions`` — open a stream. Body
        ``{"session_id"?: str, "timeout_ms"?: ms}``; the router normally
        generates the id so it can pin before forwarding."""
        store, err = self._session_store_or_503()
        if err is not None:
            return err
        h = headers or {}
        try:
            body = json.loads(raw.decode() or "{}")
            timeout_ms = self._effective_timeout_ms(
                body.get("timeout_ms"), h.get("X-Deadline-Ms"))
        except Exception as e:
            return 400, {"error": f"malformed request body: {e}"}, {}
        try:
            sess = store.create(name, body.get("session_id"),
                                timeout_ms=timeout_ms)
        except KeyError:
            return 404, {"error": f"model {name!r} not found"}, {}
        except ValueError as e:
            # duplicate id, invalid id, or a model without the session
            # path warmed — a client error either way
            return 409, {"error": str(e)}, {}
        except ServingError as e:
            return 503, {"error": "unavailable", "detail": str(e)}, {}
        except Exception as e:
            return 500, {"error": repr(e)}, {}
        return 200, {"model": name, "session": sess.session_id,
                     "step": sess.step, "worker": self.worker_id}, {}

    def _session_step_inner(self, name, sid, body, timeout_ms, hdrs):
        """Shared by the unary step endpoint and the SSE stream: returns
        ``(status, json_obj)`` for ONE step of session ``sid``."""
        store = self.sessions
        try:
            dtype = body.get("dtype")
            x = np.asarray(body["inputs"],
                           dtype=None if dtype is None else np.dtype(dtype))
        except Exception as e:
            return 400, {"error": f"malformed request body: {e}"}
        t0 = time.monotonic()
        try:
            out, step, replayed = store.step(
                name, sid, x, timeout_ms=timeout_ms,
                client_step=body.get("step"))
        except KeyError:
            return 404, {"error": f"unknown session {sid!r} for model "
                                  f"{name!r}"}
        except SessionLost as e:
            # 410 Gone: the stream is unrecoverable — carry was damaged
            # on disk; the client must open a new session
            return 410, {"error": "session lost", "reason": "session_lost",
                         "detail": str(e)}
        except SessionStepConflict as e:
            return 409, {"error": "step conflict", "reason": "step_conflict",
                         "detail": str(e)}
        except Overloaded as e:
            retry_ms = getattr(e, "retry_after_ms", None)
            if retry_ms is not None:
                hdrs["Retry-After"] = str(int(math.ceil(retry_ms / 1000.0)))
                hdrs["Retry-After-Ms"] = f"{retry_ms:.0f}"
            return 503, {"error": "overloaded", "reason": "overloaded",
                         "retry_after_ms": retry_ms, "detail": str(e)}
        except DeadlineExceeded as e:
            return 504, {"error": "deadline exceeded", "detail": str(e)}
        except ServingError as e:
            return 503, {"error": "unavailable", "detail": str(e)}
        except Exception as e:
            return 500, {"error": repr(e)}
        self.slo.record(name, ok=True, latency_s=time.monotonic() - t0)
        return 200, {"model": name, "session": sid, "step": step,
                     "replayed": replayed, "outputs": _to_jsonable(out)}

    def _handle_session_step(self, name: str, sid: str, raw: bytes,
                             headers=None):
        """``POST /v1/models/<name>/sessions/<id>/step`` — advance the
        stream one input chunk. Body ``{"inputs": [[...]], "step"?: k,
        "timeout_ms"?: ms, "dtype"?: name}``; ``step`` (the client's
        0-based index for THIS call) makes failover retries exactly-once —
        a replay of the last acked step returns its persisted output
        without advancing the carry."""
        store, err = self._session_store_or_503()
        if err is not None:
            return err
        h = headers or {}
        hdrs = {}
        try:
            body = json.loads(raw.decode() or "{}")
            timeout_ms = self._effective_timeout_ms(
                body.get("timeout_ms"), h.get("X-Deadline-Ms"))
        except Exception as e:
            return 400, {"error": f"malformed request body: {e}"}, hdrs
        status, obj = self._session_step_inner(name, sid, body, timeout_ms,
                                               hdrs)
        if status == 200:
            hdrs["X-Session-Step"] = str(obj["step"])
        return status, obj, hdrs

    def _handle_session_stream(self, name: str, sid: str, raw: bytes,
                               handler) -> None:
        """``POST /v1/models/<name>/sessions/<id>/stream`` — multi-step
        generation over ONE connection, Server-Sent-Events framing. Body
        ``{"inputs": [chunk, ...], "step"?: k0, "timeout_ms"?: ms}``:
        each chunk is one step input; one ``data:`` event per step, then
        ``event: end`` (or ``event: error`` carrying the same JSON the
        unary endpoint would have returned). The response is
        close-delimited (no Content-Length); a writer thread decouples
        device stepping from a slow client socket and is ALWAYS joined
        before the handler returns."""
        import queue as _queue
        h = handler.headers
        try:
            body = json.loads(raw.decode() or "{}")
            chunks = body["inputs"]
            if not isinstance(chunks, list) or not chunks:
                raise ValueError("'inputs' must be a non-empty list of "
                                 "per-step input chunks")
            timeout_ms = self._effective_timeout_ms(
                body.get("timeout_ms"), h.get("X-Deadline-Ms"))
        except Exception as e:
            payload = json.dumps(
                {"error": f"malformed request body: {e}"}).encode()
            handler._send(400, payload, "application/json")
            return
        store, err = self._session_store_or_503()
        if err is not None:
            handler._send(err[0], json.dumps(err[1]).encode(),
                          "application/json")
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-store")
        handler.send_header("Connection", "close")
        if self.worker_id is not None:
            handler.send_header("X-Worker-Id", self.worker_id)
        handler.end_headers()
        q: "_queue.Queue" = _queue.Queue()

        def _writer():
            while True:
                frame = q.get()
                if frame is None:
                    return
                try:
                    handler.wfile.write(frame)
                    handler.wfile.flush()
                except OSError:
                    # client went away; keep draining so the stepper
                    # never blocks on an unbounded queue put
                    pass

        wt = threading.Thread(target=_writer, daemon=True,
                              name=f"stream-writer-{sid}")
        wt.start()
        deadline = (None if timeout_ms is None
                    else time.monotonic() + timeout_ms / 1000.0)
        step0 = body.get("step")
        try:
            for i, chunk in enumerate(chunks):
                remaining_ms = (None if deadline is None
                                else max(0.0, (deadline - time.monotonic())
                                         * 1000.0))
                step_body = {"inputs": chunk, "dtype": body.get("dtype")}
                if step0 is not None:
                    step_body["step"] = int(step0) + i
                status, obj = self._session_step_inner(
                    name, sid, step_body, remaining_ms, {})
                if status != 200:
                    obj["status"] = status
                    q.put(b"event: error\ndata: "
                          + json.dumps(obj).encode() + b"\n\n")
                    return
                q.put(b"data: " + json.dumps(obj).encode() + b"\n\n")
            q.put(b"event: end\ndata: "
                  + json.dumps({"steps": len(chunks)}).encode() + b"\n\n")
        finally:
            q.put(None)
            wt.join()

    def _handle_session_close(self, name: str, sid: str):
        """``DELETE /v1/models/<name>/sessions/<id>`` — end the stream
        and delete its spill file."""
        store, err = self._session_store_or_503()
        if err is not None:
            return err
        try:
            store.close(name, sid)
        except KeyError:
            return 404, {"error": f"unknown session {sid!r} for model "
                                  f"{name!r}"}, {}
        except Exception as e:
            return 500, {"error": repr(e)}, {}
        return 200, {"model": name, "session": sid, "closed": True}, {}

    def _handle_sessions_drain(self, raw: bytes = b""):
        """``POST /v1/sessions/drain`` — the rolling-deploy migration
        fence: push every resident session cold so its state is on the
        shared spill dir before this worker restarts. Steps arriving
        after the drain simply rehydrate (here or on the repinned
        worker); nothing is dropped."""
        store, err = self._session_store_or_503()
        if err is not None:
            return err
        try:
            n = store.spill_all(reason="drain")
        except Exception as e:
            return 500, {"error": repr(e)}, {}
        return 200, {"worker": self.worker_id, "spilled": n}, {}

    def _render_sessions(self) -> str:
        """``/metrics`` session-tier section (ISSUE 16)."""
        snap = self.sessions.snapshot()
        c = snap["counters"]
        reh = snap["rehydrate"]
        return "\n".join([
            f"serving_sessions_tracked {snap['tracked']}",
            f"serving_sessions_resident {snap['resident']}",
            f"serving_sessions_resident_bytes {snap['resident_bytes']}",
            f"serving_sessions_spilled_files {snap['spilled_files']}",
            f"serving_session_steps_total {c['steps_total']}",
            f"serving_session_replays_total {c['replays_total']}",
            f"serving_session_rehydrates_total {c['rehydrates_total']}",
            f"serving_session_migrations_total {c['migrations_total']}",
            f"serving_session_evictions_total {c['evictions_total']}",
            f"serving_session_lost_total {c['lost_total']}",
            "serving_session_rehydrate_seconds{quantile=\"0.5\"} "
            + f"{reh['p50_s']}",
            "serving_session_rehydrate_seconds{quantile=\"0.99\"} "
            + f"{reh['p99_s']}",
        ])

    def _render_metrics(self) -> str:
        parts = ["# TYPE serving_latency_seconds summary",
                 "# TYPE serving_dispatch_to_completion_seconds summary",
                 "# TYPE serving_inflight_depth gauge",
                 "# TYPE serving_warmup_seconds gauge",
                 "# TYPE serving_replica_batches_total counter"]
        for name in self.registry.names():
            try:
                parts.append(self.registry.get(name).metrics
                             .render_prometheus(name))
            except KeyError:
                pass  # undeployed between listing and render
        parts.append(self._render_compile_cache())
        slo_text = self.slo.render_prometheus()
        if slo_text:
            parts.append(slo_text.rstrip("\n"))
        try:
            # the capacity ledger's /metrics view (ISSUE 10): same numbers
            # /v1/capacity serves machine-readably
            from deeplearning4j_tpu.serving import capacity
            parts.append(capacity.render_prometheus(
                capacity.registry_capacity(self.registry)).rstrip("\n"))
        except Exception:
            pass  # capacity must never be able to break a scrape
        if self.sessions is not None:
            parts.append(self._render_sessions())
        if self.scheduler is not None:
            # the harvest ledger's /metrics view (ISSUE 19)
            from deeplearning4j_tpu.serving import scheduler as _sched
            try:
                parts.append(_sched.render_prometheus(
                    self.scheduler.harvest_snapshot()).rstrip("\n"))
            except Exception:
                pass  # the scheduler must never break a scrape
        # binary transport frame/error counters (ISSUE 18)
        parts.append("\n".join(wire.render_prometheus()))
        # the black box's ring health (ISSUE 15): journal_* gauges
        parts.append(journal.render_prometheus().rstrip("\n"))
        # the flywheel's label-join counters (ISSUE 17)
        from deeplearning4j_tpu.serving import delivery
        fb = delivery.feedback_counters()
        parts.append(
            f"serving_feedback_joined_total {fb['joined_total']}\n"
            f"serving_feedback_orphaned_total {fb['orphaned_total']}")
        return "\n".join(parts) + "\n"

    @staticmethod
    def _render_compile_cache() -> str:
        """Process-global persistent-executable-cache + AOT counters
        (ISSUE 5 cold-start observability) — unlabelled: one XLA process,
        one cache, shared by every served model."""
        from deeplearning4j_tpu.runtime.compile_cache import stats
        s = stats()
        return "\n".join([
            f"compile_cache_enabled {int(bool(s['enabled']))}",
            f"compile_cache_hits_total {s['hits']}",
            f"compile_cache_misses_total {s['misses']}",
            f"compile_cache_corrupt_entries_total {s['corrupt_entries']}",
            f"compile_cache_compile_seconds_total {s['compile_seconds']}",
            f"compile_cache_retrieval_seconds_total {s['retrieval_seconds']}",
            f"aot_dispatch_executables_total {s['aot_compiles']}",
            f"aot_dispatch_fallbacks_total {s['aot_fallbacks']}",
        ])

    # ------------------------------------------------------------ plumbing
    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        srv = self
        if self.worker_id is not None:
            trace.set_process_tag(self.worker_id)
        # profiling tooling reads this registry's capacity ledger without
        # holding a registry reference (ISSUE 10; newest server wins,
        # mirroring profiler.attach_router)
        from deeplearning4j_tpu.runtime import profiler

        def _capacity_provider():
            from deeplearning4j_tpu.serving import capacity
            return capacity.registry_capacity(srv.registry)
        self._capacity_provider = _capacity_provider
        profiler.attach_capacity(_capacity_provider)

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive (ISSUE 18): the router's and client's
            # connection pools reuse this socket across requests instead
            # of paying TCP setup per hop (the 1.0 default closes every
            # time).  Every _send sets Content-Length, which 1.1
            # requires; ``timeout`` bounds how long an idle keep-alive
            # connection may pin its handler thread.
            protocol_version = "HTTP/1.1"
            timeout = 20.0
            # headers and body go out in separate writes; without
            # NODELAY, Nagle + delayed ACK stalls each response ~40ms
            disable_nagle_algorithm = True

            def _send(self, code: int, body: bytes, ctype: str,
                      extra=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if srv.worker_id is not None:
                    self.send_header("X-Worker-Id", srv.worker_id)
                rid = self.headers.get("X-Request-Id")
                if rid:
                    self.send_header("X-Request-Id", rid)
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, srv._render_metrics().encode(),
                               "text/plain; version=0.0.4")
                    return
                if self.path.startswith("/v1/debug/bundle"):
                    # the worker's local incident bundle (ISSUE 15); the
                    # router's twin merges the whole fleet
                    from deeplearning4j_tpu.serving import blackbox
                    try:
                        data = blackbox.local_bundle(srv)
                    except Exception as e:
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode(),
                            "application/json")
                        return
                    self._send(200, data, "application/gzip")
                    return
                code, obj = srv._handle_get(self.path)
                self._send(code, json.dumps(obj).encode(), "application/json")

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                if (self.path.startswith("/v1/models/")
                        and self.path.endswith("/predict")):
                    name = self.path[len("/v1/models/"):-len("/predict")]
                    ctype = (self.headers.get("Content-Type") or
                             "").split(";")[0].strip()
                    if ctype == wire.CONTENT_TYPE and not srv.wire_enabled:
                        # negotiation: 415 tells the sender to transcode
                        # to JSON and downgrade this endpoint
                        code, obj, extra = 415, {
                            "error": "binary wire protocol disabled",
                            "reason": "wire_disabled"}, {}
                    else:
                        code, obj, extra = srv._handle_predict(
                            name, raw, headers=self.headers,
                            wire_proto=ctype == wire.CONTENT_TYPE)
                elif (self.path.startswith("/v1/models/")
                        and self.path.endswith("/replicas")):
                    name = self.path[len("/v1/models/"):-len("/replicas")]
                    code, obj, extra = srv._handle_scale(
                        name, raw, headers=self.headers)
                elif (self.path.startswith("/v1/models/")
                        and self.path.endswith("/residency")):
                    name = self.path[len("/v1/models/"):-len("/residency")]
                    code, obj, extra = srv._handle_residency(
                        name, raw, headers=self.headers)
                elif (self.path.startswith("/v1/models/")
                        and "/sessions" in self.path):
                    name, _, tail = (self.path[len("/v1/models/"):]
                                     .partition("/sessions"))
                    tail = tail.strip("/")
                    if not tail:
                        code, obj, extra = srv._handle_session_create(
                            name, raw, headers=self.headers)
                    else:
                        parts = tail.split("/")
                        if len(parts) == 2 and parts[1] == "step":
                            code, obj, extra = srv._handle_session_step(
                                name, parts[0], raw, headers=self.headers)
                        elif len(parts) == 2 and parts[1] == "stream":
                            # SSE: the handler writes the (close-
                            # delimited) response itself
                            srv._handle_session_stream(
                                name, parts[0], raw, self)
                            return
                        else:
                            code, obj, extra = (
                                404, {"error": f"unknown path "
                                               f"{self.path!r}"}, {})
                elif self.path == "/v1/sessions/drain":
                    code, obj, extra = srv._handle_sessions_drain(raw)
                elif self.path == "/v1/feedback":
                    # label intake (ISSUE 17): a client grades an answer
                    # by trace id; the label joins the access log into
                    # the append-only labeled-example file
                    from deeplearning4j_tpu.serving import delivery
                    code, obj = delivery.handle_feedback(raw)
                    extra = {}
                else:
                    code, obj, extra = (404,
                                        {"error": f"unknown path "
                                                  f"{self.path!r}"}, {})
                if isinstance(obj, bytes):  # a 200 wire frame
                    self._send(code, obj, wire.CONTENT_TYPE, extra=extra)
                else:
                    self._send(code, json.dumps(obj).encode(),
                               "application/json", extra=extra)

            def do_DELETE(self):
                if (self.path.startswith("/v1/models/")
                        and "/sessions/" in self.path):
                    name, _, sid = (self.path[len("/v1/models/"):]
                                    .partition("/sessions/"))
                    code, obj, extra = srv._handle_session_close(
                        name, sid.strip("/"))
                else:
                    code, obj, extra = (404,
                                        {"error": f"unknown path "
                                                  f"{self.path!r}"}, {})
                self._send(code, json.dumps(obj).encode(),
                           "application/json", extra=extra)

            def log_message(self, *a):
                pass

        # KeepAliveHTTPServer: stop() must sever parked keep-alive
        # connections, or pooled routers keep talking to a dead worker
        self._httpd = wire.KeepAliveHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="ModelServer")
        self._thread.start()
        return self.port

    def stop(self, shutdown_registry: bool = False) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()  # release the listener fd promptly
            self._httpd = None
        if self.sessions is not None:
            # spill-at-exit: a graceful stop leaves every stream
            # resumable from the shared spill dir
            self.sessions.shutdown(spill=True)
        if self._capacity_provider is not None:
            # detach only OUR provider — a newer server's stays attached
            from deeplearning4j_tpu.runtime import profiler
            profiler.detach_capacity(self._capacity_provider)
            self._capacity_provider = None
        if shutdown_registry:
            self.registry.shutdown()
