"""Stdlib-HTTP JSON model server (the konduit/dl4j model-server role).

Same dependency-free ``ThreadingHTTPServer`` pattern as ``ui/server.py``
(offline environment — no web framework). Endpoints:

- ``GET  /v1/models``                  — registry listing + per-model metrics
- ``GET  /v1/models/<name>``           — one model's description
- ``POST /v1/models/<name>/predict``   — JSON inference
- ``GET  /healthz``                    — liveness (the process serves HTTP)
- ``GET  /readyz``                     — readiness (every model READY; a
  DEGRADED breaker-open model or an empty registry returns 503 so an
  orchestrator routes traffic elsewhere)
- ``GET  /metrics``                    — Prometheus text format, incl. the
  pipeline gauges (ISSUE 3): ``serving_inflight_depth`` (dispatched
  batches awaiting readback), ``serving_replica_batches_total`` per device
  replica, and the ``serving_dispatch_to_completion_seconds`` histogram

Predict request body::

    {"inputs": [[...], ...]}                       # single-input model
    {"inputs": {"in_a": [[...]], "in_b": [[...]]}} # multi-input graph
    {"inputs": ..., "timeout_ms": 50}              # per-request deadline

Admission-control semantics map onto status codes: ``503`` for
``Overloaded`` (queue full — shed, retry elsewhere) and for
``CircuitOpen`` (breaker shedding a failing model, ``reason`` field
disambiguates), ``504`` for ``DeadlineExceeded``, ``404`` unknown model,
``400`` malformed body. Every response is explicit; nothing queues
unboundedly behind the socket.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.serving.admission import DeadlineExceeded, Overloaded
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.resilience import CircuitOpen


def _to_jsonable(out):
    if isinstance(out, (list, tuple)):
        return [np.asarray(o).tolist() for o in out]
    return np.asarray(out).tolist()


class ModelServer:
    """``ModelServer(registry).start(port)`` — serve a registry over HTTP."""

    def __init__(self, registry: Optional[ModelRegistry] = None):
        self.registry = registry or ModelRegistry()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    # ------------------------------------------------------------ handlers
    def _handle_predict(self, name: str, raw: bytes):
        try:
            body = json.loads(raw.decode() or "{}")
            inputs = body["inputs"]
            timeout_ms = body.get("timeout_ms")
            if isinstance(inputs, dict):
                x = {k: np.asarray(v) for k, v in inputs.items()}
            else:
                x = np.asarray(inputs)  # ragged rows raise -> 400
        except Exception as e:
            return 400, {"error": f"malformed request body: {e}"}
        # resolve the model OUTSIDE the submit try: a KeyError raised by a
        # multi-input forward (wrong input name) must not read as 404
        try:
            served = self.registry.get(name)
        except KeyError:
            return 404, {"error": f"model {name!r} not found",
                         "models": self.registry.names()}
        try:
            out = served.predict(x, timeout_ms=timeout_ms)
        except CircuitOpen as e:
            return 503, {"error": "unavailable", "reason": "circuit_open",
                         "detail": str(e)}
        except Overloaded as e:
            return 503, {"error": "overloaded", "reason": "overloaded",
                         "detail": str(e)}
        except DeadlineExceeded as e:
            return 504, {"error": "deadline exceeded", "detail": str(e)}
        except Exception as e:
            return 500, {"error": repr(e)}
        return 200, {"model": name, "version": served.version,
                     "outputs": _to_jsonable(out)}

    def _handle_get(self, path: str):
        if path == "/healthz":
            # liveness only: the process is up and serving HTTP
            return 200, {"status": "ok", "models": self.registry.names()}
        if path == "/readyz":
            # one snapshot for both fields so they can never disagree
            health = self.registry.health()
            ready = self.registry.ready_from(health)
            return (200 if ready else 503), {"ready": ready,
                                             "models": health}
        if path == "/v1/models":
            return 200, {"models": self.registry.describe()}
        if path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):].strip("/")
            try:
                return 200, self.registry.get(name).describe()
            except KeyError:
                return 404, {"error": f"model {name!r} not found"}
        return 404, {"error": f"unknown path {path!r}"}

    def _render_metrics(self) -> str:
        parts = ["# TYPE serving_latency_seconds summary",
                 "# TYPE serving_dispatch_to_completion_seconds summary",
                 "# TYPE serving_inflight_depth gauge",
                 "# TYPE serving_warmup_seconds gauge",
                 "# TYPE serving_replica_batches_total counter"]
        for name in self.registry.names():
            try:
                parts.append(self.registry.get(name).metrics
                             .render_prometheus(name))
            except KeyError:
                pass  # undeployed between listing and render
        parts.append(self._render_compile_cache())
        return "\n".join(parts) + "\n"

    @staticmethod
    def _render_compile_cache() -> str:
        """Process-global persistent-executable-cache + AOT counters
        (ISSUE 5 cold-start observability) — unlabelled: one XLA process,
        one cache, shared by every served model."""
        from deeplearning4j_tpu.runtime.compile_cache import stats
        s = stats()
        return "\n".join([
            f"compile_cache_enabled {int(bool(s['enabled']))}",
            f"compile_cache_hits_total {s['hits']}",
            f"compile_cache_misses_total {s['misses']}",
            f"compile_cache_corrupt_entries_total {s['corrupt_entries']}",
            f"compile_cache_compile_seconds_total {s['compile_seconds']}",
            f"compile_cache_retrieval_seconds_total {s['retrieval_seconds']}",
            f"aot_dispatch_executables_total {s['aot_compiles']}",
            f"aot_dispatch_fallbacks_total {s['aot_fallbacks']}",
        ])

    # ------------------------------------------------------------ plumbing
    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, srv._render_metrics().encode(),
                               "text/plain; version=0.0.4")
                    return
                code, obj = srv._handle_get(self.path)
                self._send(code, json.dumps(obj).encode(), "application/json")

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                if (self.path.startswith("/v1/models/")
                        and self.path.endswith("/predict")):
                    name = self.path[len("/v1/models/"):-len("/predict")]
                    code, obj = srv._handle_predict(name, raw)
                else:
                    code, obj = 404, {"error": f"unknown path {self.path!r}"}
                self._send(code, json.dumps(obj).encode(), "application/json")

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="ModelServer")
        self._thread.start()
        return self.port

    def stop(self, shutdown_registry: bool = False) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd = None
        if shutdown_registry:
            self.registry.shutdown()
