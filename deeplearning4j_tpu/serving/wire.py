"""Binary framed wire protocol for the serving tier (ISSUE 18).

The JSON serving path marshals every row through ``tolist()`` /
``json.dumps`` / ``json.loads`` and opens a fresh TCP connection per
router->worker hop; the serving bench shows the device idle ~40% of the
wall while the host shovels text.  This module is the serving-side
answer, riding the same framing discipline as ``native.TreeCodec`` and
the checkpoint writer: a magic + version + CRC-framed binary frame
carrying dtype/shape-tagged ndarray payloads, so a corrupt frame is an
explicit :class:`WireProtocolError` — never a silently wrong tensor.

Frame layout (little-endian)::

    magic    4s   b"DWF1"
    version  B    1
    kind     B    1=request  2=response
    flags    H    bit0: payload rides a shared-memory segment
    meta_len I    length of the JSON meta block
    payload_len Q length of the tensor payload (inline OR in shm)
    crc32    I    zlib.crc32 over meta + payload
    meta     ...  compact JSON: tensors [{name,dtype,shape,offset,nbytes}],
                  fields (control headers), model/version, timeout_ms,
                  shm {name,size,pid} when flags bit0 is set
    payload  ...  concatenated C-contiguous tensor bytes (absent for shm)

Every control header the router forwards has a registered frame-field
mapping in :data:`HEADER_FIELDS` (lint-enforced: WIRE-UNMAPPED-HEADER),
so hedging, deadlines, shed windows, sessions, and shadow mirroring are
protocol-invariant.  Negotiation is per-connection content-type: a
worker that cannot (or is configured not to) speak binary answers 415
and the sender transcodes to JSON and downgrades that endpoint.

Also here: :class:`ConnectionPool`, the bounded keep-alive pool shared
by the router, the control-plane client, and the bench — so the legacy
JSON path stops paying per-request TCP setup too.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import socket
import time
import zlib
from collections import deque
from http.client import HTTPConnection
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..runtime import chaos

MAGIC = b"DWF1"
VERSION = 1
KIND_REQUEST = 1
KIND_RESPONSE = 2
FLAG_SHM = 0x0001

#: content type that negotiates the binary protocol on an HTTP hop
CONTENT_TYPE = "application/x-dl4j-wire"

#: payloads below this many bytes are not worth a shared-memory segment
SHM_MIN_BYTES = 32768

_HEADER = struct.Struct("<4sBBHIQI")

# Every control header forwarded on the HTTP path, mapped 1:1 into a
# frame field so the binary protocol carries identical semantics.  The
# lint cross-check (WIRE-UNMAPPED-HEADER / WIRE-STALE-FIELD) diffs this
# registry against the header literals in the serving sources: a future
# header cannot silently lose its meaning on the binary path.
HEADER_FIELDS: Dict[str, str] = {
    "X-Request-Id": "request_id",
    "X-Deadline-Ms": "deadline_ms",
    "X-Trace-Id": "trace_id",
    "X-Parent-Span-Id": "parent_span_id",
    "X-Trace-Flags": "trace_flags",
    "X-Worker-Id": "worker_id",
    "X-Model-Version": "model_version",
    "X-Session-Step": "session_step",
    "X-Shadow": "shadow",
    "Retry-After": "retry_after",
    "Retry-After-Ms": "retry_after_ms",
}

_FIELD_HEADERS = {v: k for k, v in HEADER_FIELDS.items()}
_LOWER_HEADERS = {k.lower(): k for k in HEADER_FIELDS}


class WireProtocolError(RuntimeError):
    """A frame failed validation (bad magic/version/CRC/bounds/dtype).

    Always an explicit, counted error — the decode path never hands a
    partially-valid tensor to the model.
    """


def headers_to_fields(headers) -> Dict[str, str]:
    """Project the registered control headers out of an HTTP header map
    into their frame-field names (unregistered headers are dropped)."""
    fields = {}
    for key, value in dict(headers or {}).items():
        canon = _LOWER_HEADERS.get(str(key).lower())
        if canon is not None:
            fields[HEADER_FIELDS[canon]] = str(value)
    return fields


def fields_to_headers(fields) -> Dict[str, str]:
    """Inverse of :func:`headers_to_fields`; unknown fields are dropped
    (forward compatibility: a newer sender's extra fields are ignored,
    never misinterpreted)."""
    headers = {}
    for field, value in dict(fields or {}).items():
        header = _FIELD_HEADERS.get(field)
        if header is not None:
            headers[header] = str(value)
    return headers


# ------------------------------------------------------------------ counters
class _Counters:
    """Process-wide wire counters, rendered into /v1/metricsz."""

    def __init__(self):
        self._lock = threading.Lock()  # guards: all counter attributes
        self.reset()

    def reset(self):
        with self._lock:
            self.frames_encoded_total = 0
            self.frames_decoded_total = 0
            self.protocol_errors_total = 0
            self.shm_frames_total = 0
            self.bytes_encoded_total = 0

    def inc(self, name, n=1):
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "frames_encoded_total": self.frames_encoded_total,
                "frames_decoded_total": self.frames_decoded_total,
                "protocol_errors_total": self.protocol_errors_total,
                "shm_frames_total": self.shm_frames_total,
                "bytes_encoded_total": self.bytes_encoded_total,
            }


_counters = _Counters()


def counters() -> Dict[str, int]:
    """Snapshot of the process-wide wire counters."""
    return _counters.snapshot()


def reset_counters():
    """Zero the process-wide wire counters (bench/test isolation)."""
    _counters.reset()


def render_prometheus() -> List[str]:
    """``serving_wire_*`` rows for a worker's /v1/metricsz."""
    snap = _counters.snapshot()
    return [f"serving_wire_{name} {value}" for name, value in snap.items()]


# --------------------------------------------------------------- frame codec
def _check_dtype(dt: np.dtype) -> np.dtype:
    if dt.kind not in "biuf" or dt.hasobject:
        raise WireProtocolError(f"dtype {dt} not wire-encodable")
    return dt


def _pack_tensors(arrays) -> Tuple[List[dict], List[Any], int]:
    metas, parts, offset = [], [], 0
    for name, arr in arrays:
        arr = np.ascontiguousarray(arr)
        _check_dtype(arr.dtype)
        parts.append(arr.data.cast("B") if arr.nbytes else b"")
        metas.append({"name": name, "dtype": arr.dtype.str,
                      "shape": list(arr.shape), "offset": offset,
                      "nbytes": arr.nbytes})
        offset += arr.nbytes
    return metas, parts, offset


def encode_frame(kind: int, meta: dict, payload_parts=(), flags: int = 0,
                 inline_payload: bool = True) -> bytes:
    """Assemble a frame; fires the ``serving.wire.frame`` chaos point
    (call + byte point) so drills can corrupt/truncate/flip the encoded
    bytes and prove damage is always a counted protocol error.

    ``inline_payload=False`` builds a shm frame: the CRC and
    ``payload_len`` still cover the parts, but the bytes themselves ride
    the shared-memory segment instead of the socket.
    """
    meta_b = json.dumps(meta, separators=(",", ":")).encode()
    crc = zlib.crc32(meta_b)
    payload_len = 0
    for part in payload_parts:
        crc = zlib.crc32(part, crc)
        payload_len += len(part)
    header = _HEADER.pack(MAGIC, VERSION, kind, flags, len(meta_b),
                          payload_len, crc & 0xFFFFFFFF)
    parts = [header, meta_b]
    if inline_payload:
        parts.extend(payload_parts)  # join accepts buffers: single copy
    frame = b"".join(parts)
    chaos.inject("serving.wire.frame")
    frame = chaos.transform_bytes("serving.wire.frame", frame)
    _counters.inc("frames_encoded_total")
    _counters.inc("bytes_encoded_total", len(frame))
    return frame


class DecodedFrame:
    """A validated frame: ``meta`` dict plus a zero-copy ``payload``
    view (over the inline bytes, or an attached shm segment).  Call
    :meth:`close` when the tensors are no longer needed."""

    def __init__(self, kind, flags, meta, payload, shm=None):
        self.kind = kind
        self.flags = flags
        self.meta = meta
        self.payload = payload
        self._shm = shm

    def tensors(self):
        """Decode the tagged tensors as READ-ONLY zero-copy views into
        the payload — the single copy on the serving path is the
        batcher's pad-buffer gather."""
        out = []
        for t in self.meta.get("tensors", []):
            try:
                dt = _check_dtype(np.dtype(t["dtype"]))
                ofs, nbytes = int(t["offset"]), int(t["nbytes"])
                shape = tuple(int(d) for d in t["shape"])
            except WireProtocolError:
                raise
            except Exception as e:
                raise WireProtocolError(f"bad tensor meta: {e}") from e
            if ofs < 0 or nbytes < 0 or ofs + nbytes > len(self.payload):
                raise WireProtocolError("tensor bounds exceed payload")
            arr = np.frombuffer(self.payload[ofs:ofs + nbytes], dtype=dt)
            try:
                arr = arr.reshape(shape)
            except ValueError as e:
                raise WireProtocolError(f"tensor shape mismatch: {e}") from e
            arr.flags.writeable = False
            out.append((t.get("name"), arr))
        return out

    def close(self):
        if self._shm is None:
            return
        try:
            self._shm.close()
        except BufferError:
            # a numpy view still exports the buffer: keep the handle so
            # a later close() (after the caller drops its tensors) can
            # finish the job; the creator owns the unlink either way
            return
        self._shm = None


def decode_frame(buf, expect_kind: Optional[int] = None) -> DecodedFrame:
    """Validate and open a frame.  Any damage — wrong magic, truncated
    body, flipped bits (CRC), nonsense tensor tags — raises
    :class:`WireProtocolError` after counting it."""
    try:
        return _decode_frame(buf, expect_kind)
    except WireProtocolError:
        _counters.inc("protocol_errors_total")
        raise


def _decode_frame(buf, expect_kind):
    view = memoryview(buf)
    if len(view) < _HEADER.size:
        raise WireProtocolError(f"frame truncated: {len(view)} bytes")
    magic, version, kind, flags, meta_len, payload_len, crc = \
        _HEADER.unpack_from(view)
    if magic != MAGIC:
        raise WireProtocolError(f"bad magic {bytes(magic)!r}")
    if version != VERSION:
        raise WireProtocolError(f"unsupported wire version {version}")
    if expect_kind is not None and kind != expect_kind:
        raise WireProtocolError(f"unexpected frame kind {kind}")
    meta_end = _HEADER.size + meta_len
    shm = None
    if flags & FLAG_SHM:
        if len(view) != meta_end:
            raise WireProtocolError("shm frame carries inline payload")
    elif len(view) != meta_end + payload_len:
        raise WireProtocolError(
            f"frame length {len(view)} != header + {meta_len} + "
            f"{payload_len}")
    meta_b = view[_HEADER.size:meta_end]
    try:
        meta = json.loads(bytes(meta_b))
    except Exception as e:
        raise WireProtocolError(f"bad meta block: {e}") from e
    if not isinstance(meta, dict):
        raise WireProtocolError("meta block is not an object")
    if flags & FLAG_SHM:
        shm, payload = _attach_shm(meta, payload_len)
    else:
        payload = view[meta_end:]
    actual = zlib.crc32(payload, zlib.crc32(meta_b)) & 0xFFFFFFFF
    if actual != crc:
        if shm is not None:
            shm.close()
        raise WireProtocolError(
            f"CRC mismatch: frame says {crc:#010x}, payload is "
            f"{actual:#010x}")
    _counters.inc("frames_decoded_total")
    return DecodedFrame(kind, flags, meta, payload, shm=shm)


# ------------------------------------------------------------ predict frames
def _as_arrays(inputs, dtype=None):
    if isinstance(inputs, dict):
        return True, [(str(k), np.asarray(v, dtype=dtype))
                      for k, v in inputs.items()]
    return False, [(None, np.asarray(inputs, dtype=dtype))]


def encode_predict_request(inputs, timeout_ms=None, headers=None,
                           fields=None, dtype=None) -> bytes:
    """Frame a predict request: ``inputs`` is an ndarray (or dict of
    named ndarrays, mirroring the JSON multi-input form)."""
    multi, arrays = _as_arrays(inputs, dtype=dtype)
    metas, parts, _total = _pack_tensors(arrays)
    meta: Dict[str, Any] = {"tensors": metas,
                            "fields": dict(fields or
                                           headers_to_fields(headers))}
    if multi:
        meta["multi"] = True
    if timeout_ms is not None:
        meta["timeout_ms"] = float(timeout_ms)
    return encode_frame(KIND_REQUEST, meta, parts)


def decode_predict_request(raw):
    """Returns ``(inputs, timeout_ms, fields, frame)`` — inputs are
    read-only zero-copy views; close ``frame`` once served."""
    fr = decode_frame(raw, expect_kind=KIND_REQUEST)
    try:
        tensors = fr.tensors()
        if not tensors:
            raise WireProtocolError("request frame has no tensors")
        if fr.meta.get("multi"):
            x = {name: arr for name, arr in tensors}
        else:
            x = tensors[0][1]
    except WireProtocolError:
        fr.close()
        _counters.inc("protocol_errors_total")
        raise
    return x, fr.meta.get("timeout_ms"), fr.meta.get("fields") or {}, fr


def encode_predict_response(model, version, outputs, fields=None) -> bytes:
    """Frame a predict response; ``outputs`` is an ndarray or a
    list/tuple of ndarrays (multi-output heads)."""
    multi = isinstance(outputs, (list, tuple))
    arrays = [(None, np.asarray(o)) for o in
              (outputs if multi else [outputs])]
    metas, parts, _total = _pack_tensors(arrays)
    meta: Dict[str, Any] = {"model": model, "version": version,
                            "tensors": metas, "fields": dict(fields or {})}
    if multi:
        meta["multi"] = True
    return encode_frame(KIND_RESPONSE, meta, parts)


def decode_predict_response(raw):
    """Returns ``(model, version, outputs, frame)``; outputs mirror the
    encoder's single-vs-list shape.  Close ``frame`` after use."""
    fr = decode_frame(raw, expect_kind=KIND_RESPONSE)
    try:
        tensors = fr.tensors()
    except WireProtocolError:
        fr.close()
        _counters.inc("protocol_errors_total")
        raise
    outs = [arr for _name, arr in tensors]
    outputs = outs if fr.meta.get("multi") else (outs[0] if outs else None)
    return fr.meta.get("model"), fr.meta.get("version"), outputs, fr


def frame_to_json_body(raw) -> Tuple[bytes, Optional[float]]:
    """Transcode a binary predict request into the equivalent JSON body
    (the mid-stream downgrade path for JSON-only workers).  The dtype is
    pinned in the body so the downgraded request produces bit-identical
    outputs to the binary path."""
    x, timeout_ms, _fields, fr = decode_predict_request(raw)
    try:
        if isinstance(x, dict):
            body: Dict[str, Any] = {
                "inputs": {k: np.asarray(v).tolist() for k, v in x.items()}}
            dtypes = {np.asarray(v).dtype.name for v in x.values()}
            if len(dtypes) == 1:
                body["dtype"] = dtypes.pop()
        else:
            body = {"inputs": np.asarray(x).tolist(),
                    "dtype": np.asarray(x).dtype.name}
        if timeout_ms is not None:
            body["timeout_ms"] = timeout_ms
    finally:
        fr.close()
    return json.dumps(body).encode(), timeout_ms


def response_to_jsonable(raw) -> dict:
    """Decode a binary predict response into the JSON response shape
    (used by shadow-mirror comparison so gated delivery sees identical
    structures whichever protocol carried the traffic)."""
    model, version, outputs, fr = decode_predict_response(raw)
    try:
        if isinstance(outputs, list):
            out = [np.asarray(o).tolist() for o in outputs]
        else:
            out = np.asarray(outputs).tolist()
    finally:
        fr.close()
    return {"model": model, "version": version, "outputs": out}


# ------------------------------------------------------- shared-memory hop
def _attach_shm(meta, payload_len):
    info = meta.get("shm")
    if not isinstance(info, dict) or "name" not in info:
        raise WireProtocolError("shm frame missing segment name")
    try:
        from multiprocessing import resource_tracker, shared_memory
        seg = shared_memory.SharedMemory(name=str(info["name"]))
        if int(info.get("pid", -1)) != os.getpid():
            # attaching registered the segment with OUR resource
            # tracker; the creator owns unlink, so unregister here or
            # the tracker reaps (and warns about) a foreign segment
            resource_tracker.unregister(seg._name, "shared_memory")
    except WireProtocolError:
        raise
    except Exception as e:
        raise WireProtocolError(f"cannot attach shm segment: {e}") from e
    if payload_len > seg.size:
        seg.close()
        raise WireProtocolError("shm segment smaller than payload_len")
    return seg, memoryview(seg.buf)[:payload_len]


def frame_to_shm(raw, min_bytes: int = SHM_MIN_BYTES):
    """Re-frame an inline frame so its payload rides a shared-memory
    segment (the colocated router->worker fast path).  Returns
    ``(frame_bytes, shm)`` — the caller owns ``shm`` and must
    ``close()`` + ``unlink()`` it once the hop completes — or
    ``(raw, None)`` when the payload is too small to bother.  Any
    failure here is the caller's cue to fall back to the socket path."""
    fr = decode_frame(raw)
    if len(fr.payload) < min_bytes:
        return raw, None
    from multiprocessing import shared_memory
    seg = shared_memory.SharedMemory(create=True, size=len(fr.payload))
    try:
        seg.buf[:len(fr.payload)] = fr.payload
        meta = dict(fr.meta)
        meta["shm"] = {"name": seg.name.lstrip("/"),
                       "size": len(fr.payload), "pid": os.getpid()}
        frame = encode_frame(fr.kind, meta, [fr.payload],
                             flags=fr.flags | FLAG_SHM,
                             inline_payload=False)
    except Exception:
        seg.close()
        seg.unlink()
        raise
    _counters.inc("shm_frames_total")
    return frame, seg


def release_shm(seg):
    """Creator-side teardown of a fast-path segment (close + unlink);
    tolerant of the receiver having raced us to the unlink."""
    if seg is None:
        return
    try:
        seg.close()
    except BufferError:
        pass
    try:
        seg.unlink()
    except FileNotFoundError:
        pass


# ---------------------------------------------------------- connection pool
class KeepAliveHTTPServer(ThreadingHTTPServer):
    """``ThreadingHTTPServer`` that force-closes every accepted socket on
    ``server_close()``.  With HTTP/1.1 pooled clients, a daemon handler
    thread parked in a keep-alive read would otherwise keep serving a
    "stopped" server through the already-open socket — stop must look
    like process death to connected peers, or failover paths that fire
    on connection faults (router death, worker kill) never trigger."""

    daemon_threads = True
    # without this, server_close() would join the handler threads — i.e.
    # block stop() on every idle keep-alive connection's read timeout
    block_on_close = False

    def __init__(self, *args, **kwargs):
        # guards: _conns
        self._conn_lock = threading.Lock()
        self._conns: set = set()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conn_lock:
            conns, self._conns = list(self._conns), set()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class _NoDelayConnection(HTTPConnection):
    """HTTPConnection with TCP_NODELAY: http.client writes headers and
    body in separate sends, and Nagle + delayed ACK turns that into a
    ~40ms stall per request on loopback."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # non-TCP transports (tests may stub the socket)


class ConnectionPool:
    """Bounded per-endpoint keep-alive HTTP connection pool.

    Health-aware recycling keeps breaker/failover semantics unchanged: a
    request on a REUSED connection that fails at the socket layer is
    retried exactly once on a fresh connection (the idle keep-alive may
    simply have expired); a fresh-connection failure propagates — that
    is the same signal the old one-connection-per-request path produced,
    so ``_classify`` and the breakers see identical evidence.
    """

    def __init__(self, max_idle_per_endpoint: int = 8,
                 max_idle_s: float = 30.0):
        self.max_idle_per_endpoint = max_idle_per_endpoint
        self.max_idle_s = max_idle_s
        # guards: _idle, _closed, created_total, reused_total, discarded_total, invalidated_total
        self._lock = threading.Lock()
        self._idle: Dict[str, deque] = {}
        self._closed = False
        self.created_total = 0
        self.reused_total = 0
        self.discarded_total = 0
        self.invalidated_total = 0

    def _checkout(self, address, timeout):
        now = time.monotonic()
        with self._lock:
            dq = self._idle.get(address)
            while dq:
                conn, parked_at = dq.pop()  # LIFO: warmest first
                if now - parked_at <= self.max_idle_s:
                    self.reused_total += 1
                    break
                self.discarded_total += 1
                _close_quiet(conn)
            else:
                conn = None
        if conn is not None:
            conn.timeout = timeout
            if conn.sock is not None:
                try:
                    conn.sock.settimeout(timeout)
                except OSError:
                    pass
            return conn, True
        host, _, port = address.partition(":")
        conn = _NoDelayConnection(host, int(port or 80), timeout=timeout)
        with self._lock:
            self.created_total += 1
        return conn, False

    def _checkin(self, address, conn):
        with self._lock:
            if not self._closed:
                dq = self._idle.setdefault(address, deque())
                if len(dq) < self.max_idle_per_endpoint:
                    dq.append((conn, time.monotonic()))
                    return
        _close_quiet(conn)

    def request(self, address, method, path, body=None, headers=None,
                timeout=None):
        """Issue one HTTP request over a pooled connection.  Returns
        ``(status, headers_dict, body_bytes)``; socket-layer failures
        raise exactly as the unpooled path did."""
        for _attempt in (0, 1):
            conn, reused = self._checkout(address, timeout)
            try:
                conn.request(method, path, body=body,
                             headers=dict(headers or {}))
                resp = conn.getresponse()
                data = resp.read()
            except Exception:
                _close_quiet(conn)
                with self._lock:
                    self.discarded_total += 1
                if reused:
                    continue  # stale keep-alive: one retry on a fresh conn
                raise
            hdrs = dict(resp.getheaders())
            if resp.will_close:
                _close_quiet(conn)
            else:
                self._checkin(address, conn)
            return resp.status, hdrs, data
        raise AssertionError("unreachable")  # pragma: no cover

    def invalidate(self, address):
        """Drop every idle connection to an endpoint (breaker opened,
        worker restarted, address changed)."""
        with self._lock:
            dq = self._idle.pop(address, None) or ()
            self.invalidated_total += len(dq)
        for conn, _t in dq:
            _close_quiet(conn)

    def idle_count(self, address=None) -> int:
        with self._lock:
            if address is not None:
                return len(self._idle.get(address, ()))
            return sum(len(dq) for dq in self._idle.values())

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "idle_connections": sum(len(dq)
                                        for dq in self._idle.values()),
                "created_total": self.created_total,
                "reused_total": self.reused_total,
                "discarded_total": self.discarded_total,
                "invalidated_total": self.invalidated_total,
            }

    def close(self):
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, {}
        for dq in idle.values():
            for conn, _t in dq:
                _close_quiet(conn)


def _close_quiet(conn):
    try:
        conn.close()
    except Exception:
        pass
